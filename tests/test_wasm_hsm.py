"""WASM gas-metering pass + HSM provider seam tests."""

import struct

import pytest

from fisco_bcos_tpu.crypto.hsm import HsmKeyPair, SoftHsmProvider
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor.wasm import (GasMeteredModule, WasmEngine,
                                          WasmUnavailable, is_wasm)


def _leb(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tiny_module() -> bytes:
    """Minimal valid-enough module with one function body:
    i32.const 1; i32.const 2; i32.add; call 0; end"""
    body_code = b"\x41\x01\x41\x02\x6a\x10\x00\x0b"
    body = _leb(0) + body_code  # 0 local decls
    code_section = _leb(1) + _leb(len(body)) + body
    sec = bytes([10]) + _leb(len(code_section)) + code_section
    return b"\x00asm\x01\x00\x00\x00" + sec


def test_gas_metering_plan():
    mod = _tiny_module()
    assert is_wasm(mod)
    m = GasMeteredModule(mod)
    assert m.blocks, "no metering blocks found"
    # const+const+add (3) then call (5) + end block accounting
    assert m.static_cost() >= 8


def test_wasm_gated_without_backend():
    eng = WasmEngine()
    WasmEngine.set_backend(None)
    assert not WasmEngine.available()
    with pytest.raises(WasmUnavailable):
        eng.execute(_tiny_module(), "main", b"", 100000)


def test_wasm_backend_seam():
    calls = []

    def backend(code, func, args, gas, module):
        calls.append((func, args, module.static_cost()))
        return b"\x2a", gas - module.static_cost()

    WasmEngine.set_backend(backend)
    try:
        out, gas_left = WasmEngine().execute(_tiny_module(), "main",
                                             b"\x04", 1000)
        assert out == b"\x2a" and gas_left < 1000
        assert calls and calls[0][0] == "main"
    finally:
        WasmEngine.set_backend(None)


def test_soft_hsm_sign_verify(tmp_path):
    prov = SoftHsmProvider(str(tmp_path / "keystore"), b"pin1234")
    pub = prov.generate_key(1)
    assert len(pub) == 64
    suite = make_suite(sm_crypto=True, backend="host")
    digest = suite.hash(b"hsm message")
    sig = prov.sign(1, digest)
    assert prov.verify(1, digest, sig)
    # the suite verifies HSM-produced signatures identically
    assert suite.verify(pub, digest, sig)

    kp = HsmKeyPair(prov, 1, suite)
    assert kp.secret is None
    assert kp.pub_bytes == pub
    sig2 = kp.sign_digest(digest)
    assert suite.verify(pub, digest, sig2)

    # keystore survives reopen with the right pin, rejects a wrong one
    prov2 = SoftHsmProvider(str(tmp_path / "keystore"), b"pin1234")
    assert prov2.public_key(1) == pub
    with pytest.raises(ValueError):
        SoftHsmProvider(str(tmp_path / "keystore"), b"wrong")


def test_wasm_malformed_module_rejected():
    with pytest.raises(ValueError, match="malformed"):
        GasMeteredModule(b"\x00asm\x01\x00\x00\x00" + bytes([10])
                         + b"\x05\x01\x03\x00\x41")


def test_wasm_blocktype_and_br_table_immediates():
    # block 0x40; br_table [0] 0; end — immediates must not be read as ops
    body_code = b"\x02\x40\x41\x00\x0e\x01\x00\x00\x0b\x0b"
    body = _leb(0) + body_code
    code_section = _leb(1) + _leb(len(body)) + body
    sec = bytes([10]) + _leb(len(code_section)) + code_section
    mod = b"\x00asm\x01\x00\x00\x00" + sec
    m = GasMeteredModule(mod)
    # ops: block, i32.const, br_table, end, end = 5 default-cost ops
    assert m.static_cost() == 5


def test_hsm_sign_through_suite(tmp_path):
    prov = SoftHsmProvider(str(tmp_path / "ks2"), b"pin")
    prov.generate_key(7)
    suite = make_suite(sm_crypto=True, backend="host")
    kp = HsmKeyPair(prov, 7, suite)
    digest = suite.hash(b"via-suite")
    sig = suite.sign(kp, digest)  # must dispatch to the provider
    assert suite.verify(kp.pub_bytes, digest, sig)
