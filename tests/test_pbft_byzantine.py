"""Byzantine-behavior PBFT tests: equivocation, tampered seals, garbage.

Reference scenarios: bcos-pbft's PBFTEngineTest exercises faulty packets
and view changes; these tests inject adversarial traffic through the
FakeGateway filter (the fixture-level fault injection the reference does
with faked nodes)."""

import time

from fisco_bcos_tpu.codec.wire import Reader, Writer
from fisco_bcos_tpu.consensus.pbft.messages import (
    PacketType,
    PBFTMessage,
    make_packet,
    pack_messages,
)
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import ConsensusNode
from fisco_bcos_tpu.net.gateway import FakeGateway
from fisco_bcos_tpu.net.moduleid import ModuleID
from fisco_bcos_tpu.protocol import Block, Transaction, TransactionStatus


def wait_until(pred, timeout=25.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


def _cluster(view_timeout=2.0):
    suite = make_suite(backend="host")
    gateway = FakeGateway()
    keypairs = [suite.generate_keypair(bytes([i + 21]) * 16)
                for i in range(4)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    nodes = []
    for kp in keypairs:
        node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0,
                               view_timeout=view_timeout),
                    keypair=kp, gateway=gateway)
        node.build_genesis(sealers)
        nodes.append(node)
    return suite, gateway, keypairs, nodes


def _tx(suite, kp, nonce):
    return Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "register", lambda w: w.blob(nonce.encode())
                           .u64(1)),
                       nonce=nonce, block_limit=100).sign(suite, kp)


def _front_pack(payload: bytes) -> bytes:
    return (Writer().u16(int(ModuleID.PBFT)).u8(0).u64(0)
            .blob(payload).bytes())


def _parse_pbft(data: bytes):
    r = Reader(data)
    module, _, _ = r.u16(), r.u8(), r.u64()
    if module != int(ModuleID.PBFT):
        return None
    try:
        return PBFTMessage.decode(r.blob())
    except Exception:
        return None


def test_equivocating_leader_does_not_fork(tmp_path):
    """The height-1 leader sends DIFFERENT proposals to different nodes;
    the chain must never fork — all nodes converge on one header."""
    suite, gateway, keypairs, nodes = _cluster()
    # leader for height 1, view 0: index (1 // leader_period + 0) % 4 in
    # the engine's sorted node-id ordering (engine.py leader_for)
    sorted_ids = sorted(kp.pub_bytes for kp in keypairs)
    leader_kp = next(kp for kp in keypairs
                     if kp.pub_bytes == sorted_ids[1 % 4])
    victim_id = next(i for i in sorted_ids if i != leader_kp.pub_bytes)

    def equivocate(src, dst, data):
        if src != leader_kp.pub_bytes or dst != victim_id:
            return True
        msg = _parse_pbft(data)
        if msg is None or msg.packet_type != int(PacketType.PRE_PREPARE):
            return True
        # substitute a CONFLICTING, validly-signed proposal for the victim
        try:
            block = Block.decode(msg.payload)
        except Exception:
            return True
        block.header.timestamp += 1  # different content -> different hash
        block.header.invalidate()
        phash = block.header.hash(suite)
        forged = make_packet(PacketType.PRE_PREPARE, msg.view, msg.number,
                             msg.from_idx, phash, block.encode())
        forged.sign(suite, leader_kp)
        gateway.send(src, dst, _front_pack(forged.encode()))
        return False  # drop the original toward the victim

    gateway.set_filter(equivocate)
    try:
        kp = suite.generate_keypair(b"byz-user")
        for node in nodes:
            node.start()
        res = nodes[0].send_transaction(_tx(suite, kp, "bz1"))
        assert res.status == TransactionStatus.OK

        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes)), \
            [n.ledger.current_number() for n in nodes]
        headers = [n.ledger.header_by_number(1) for n in nodes]
        assert len({h.hash(suite) for h in headers}) == 1, "chain forked"
    finally:
        for n in nodes:
            n.stop()
        gateway.stop()


def test_tampered_checkpoint_seal_rejected_but_chain_commits(tmp_path):
    """One node's checkpoint seal is corrupted in flight: the batch seal
    verification must reject it while the honest quorum still commits, and
    the committed header must carry only VALID seals."""
    suite, gateway, keypairs, nodes = _cluster(view_timeout=8.0)
    tampered = {"n": 0}

    def corrupt_one_seal(src, dst, data):
        msg = _parse_pbft(data)
        if (msg is not None
                and msg.packet_type == int(PacketType.CHECKPOINT)
                and msg.from_idx == 3):
            # flip bits in the seal payload; packet signature stays intact
            bad = bytes([msg.payload[0] ^ 0xFF]) + msg.payload[1:]
            msg.payload = bad
            msg._hash = None
            msg.sign(suite, keypairs[3])  # re-signed packet, garbage seal
            tampered["n"] += 1
            gateway.send(src, dst, _front_pack(msg.encode()))
            return False
        return True

    gateway.set_filter(corrupt_one_seal)
    try:
        kp = suite.generate_keypair(b"byz-user2")
        for node in nodes:
            node.start()
        res = nodes[0].send_transaction(_tx(suite, kp, "bz2"))
        assert res.status == TransactionStatus.OK
        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes)), \
            [n.ledger.current_number() for n in nodes]
        assert tampered["n"] > 0, "filter never fired"
        for node in nodes:
            header = node.ledger.header_by_number(1)
            ehash = header.hash(suite)
            # drop the self-added signature_list then re-verify each seal
            for idx, seal in header.signature_list:
                pub = sorted(k.pub_bytes for k in keypairs)[idx]
                assert suite.verify(pub, ehash, seal), \
                    "committed header carries an invalid seal"
            assert len(header.signature_list) >= 3
    finally:
        for n in nodes:
            n.stop()
        gateway.stop()


def test_garbage_and_replayed_packets_ignored(tmp_path):
    """Random garbage and stale replayed packets on the PBFT module must
    not disturb consensus."""
    suite, gateway, keypairs, nodes = _cluster()
    try:
        for node in nodes:
            node.start()
        kp = suite.generate_keypair(b"byz-user3")
        res = nodes[0].send_transaction(_tx(suite, kp, "bz3"))
        assert res.status == TransactionStatus.OK
        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes))

        # blast garbage + replays at every node from a non-member identity
        intruder = suite.generate_keypair(b"intruder").pub_bytes
        stale = make_packet(PacketType.PRE_PREPARE, 0, 1, 0, b"\x00" * 32,
                            b"not-a-block")
        stale.sign(suite, keypairs[0])
        for node_kp in keypairs:
            gateway.register_front(intruder, type("F", (), {
                "on_network_message": staticmethod(lambda s, d: None)})())
            gateway.send(intruder, node_kp.pub_bytes,
                         _front_pack(b"\xde\xad\xbe\xef"))
            gateway.send(intruder, node_kp.pub_bytes,
                         _front_pack(stale.encode()))

        res = nodes[1].send_transaction(_tx(suite, kp, "bz4"))
        assert res.status == TransactionStatus.OK
        assert wait_until(
            lambda: all(n.ledger.current_number() >= 2 for n in nodes)), \
            [n.ledger.current_number() for n in nodes]
        headers = [n.ledger.header_by_number(2) for n in nodes]
        assert len({h.hash(suite) for h in headers}) == 1
    finally:
        for n in nodes:
            n.stop()
        gateway.stop()


def test_forged_carried_preprepare_rejected():
    """A single Byzantine member forges a carried pre-prepare inside its
    VIEW_CHANGE payload, claiming a HIGHER view than the genuinely prepared
    proposal so it would displace it on re-propose. The new-view leader's
    carried-proposal selection must verify each inner pre-prepare's leader
    identity and signature and keep the legitimate one."""
    suite, gateway, keypairs, nodes = _cluster(view_timeout=60.0)
    try:
        for node in nodes:
            node.start()
        eng = next(n.consensus for n in nodes if n.consensus is not None)
        by_pub = {kp.pub_bytes: kp for kp in keypairs}

        def kp_of(idx):
            return by_pub[eng.nodes[idx]]

        new_view = 2
        leader0 = eng.leader_for(1, 0)
        leader1 = eng.leader_for(1, 1)
        byz_idx = next(i for i in range(eng.n)
                       if i not in (leader0, leader1))

        # the legitimate prepared proposal: height 1 sealed in view 0,
        # carried with its leader's authentic inner signature AND the
        # prepare quorum certificate that made it prepared
        block = Block()
        block.header.number = 1
        block.header.timestamp = 1234
        phash = block.header.hash(suite)
        legit = make_packet(PacketType.PRE_PREPARE, 0, 1, leader0,
                            phash, block.encode())
        legit.sign(suite, kp_of(leader0))
        legit_qc = []
        for i in range(eng.quorum):
            pv = make_packet(PacketType.PREPARE, 0, 1, i, phash)
            pv.sign(suite, kp_of(i))
            legit_qc.append(pv)

        forged_block = Block()
        forged_block.header.number = 1
        forged_block.header.timestamp = 9999
        fhash = forged_block.header.hash(suite)
        # forgery A: claims view 1 (displaces view 0) under view 1's leader
        # index, but only the Byzantine node's key signed it
        forged_sig = make_packet(PacketType.PRE_PREPARE, 1, 1, leader1,
                                 fhash, forged_block.encode())
        forged_sig.sign(suite, kp_of(byz_idx))
        # forgery B: validly signed by the Byzantine node under its OWN
        # index — but it never led round (1, view 1)
        forged_leader = make_packet(PacketType.PRE_PREPARE, 1, 1, byz_idx,
                                    fhash, forged_block.encode())
        forged_leader.sign(suite, kp_of(byz_idx))
        # forgery C: validly signed by the NEW view's leader claiming the
        # view being entered — a carried proposal must predate it
        leader_new = eng.leader_for(1, new_view)
        forged_view = make_packet(PacketType.PRE_PREPARE, new_view, 1,
                                  leader_new, fhash, forged_block.encode())
        forged_view.sign(suite, kp_of(leader_new))
        # forgery D: the ex-leader attack — the node that legitimately LED
        # (1, view 1) fabricates a "carried" pre-prepare for that round at
        # view-change time with its own VALID signature, but can forge no
        # prepare quorum (plus a lone self-prepare, far short of quorum)
        ex_leader = make_packet(PacketType.PRE_PREPARE, 1, 1, leader1,
                                fhash, forged_block.encode())
        ex_leader.sign(suite, kp_of(leader1))
        ex_leader_pv = make_packet(PacketType.PREPARE, 1, 1, leader1, fhash)
        ex_leader_pv.sign(suite, kp_of(leader1))

        payloads = [
            pack_messages([legit] + legit_qc),
            pack_messages([forged_sig]),
            pack_messages([forged_leader]),
            pack_messages([forged_view, ex_leader, ex_leader_pv]),
        ]
        vcs = []
        for i, payload in enumerate(payloads):
            vc = make_packet(PacketType.VIEW_CHANGE, new_view, 1, i,
                             b"\x00" * 32, payload)
            vc.sign(suite, kp_of(i))
            vcs.append(vc)

        carried = eng._carried_by_height(vcs, new_view)
        assert 1 in carried, "legitimate carried proposal was lost"
        assert carried[1].header.hash(suite) == phash, \
            "a forged carried pre-prepare displaced the prepared proposal"

        # and without its quorum certificate even the authentic carried
        # proposal is not re-proposed (it provably never prepared)
        vc_noqc = make_packet(PacketType.VIEW_CHANGE, new_view, 1, 0,
                              b"\x00" * 32, pack_messages([legit]))
        vc_noqc.sign(suite, kp_of(0))
        assert eng._carried_by_height([vc_noqc], new_view) == {}
    finally:
        for n in nodes:
            n.stop()
        gateway.stop()


def test_pipelined_double_include_cannot_fork_or_wedge(tmp_path):
    """A Byzantine leader for a pipelined height proposes a block that
    DOUBLE-INCLUDES a tx already carried by the in-flight previous height
    (honest leaders cannot: accepted proposals mark their txs sealed, and
    pre-seal tombstones cover gossip stragglers). When the earlier height
    commits, the duplicate proposal becomes unexecutable everywhere (its
    tx was pruned); the cluster must neither fork nor wedge — a view
    change re-proposes and every tx commits exactly once."""
    suite, gateway, keypairs, nodes = _cluster(view_timeout=2.0)
    sorted_ids = sorted(kp.pub_bytes for kp in keypairs)
    # leader of height 2 in view 0 forges the duplicate proposal
    leader2_kp = next(kp for kp in keypairs
                      if kp.pub_bytes == sorted_ids[2 % 4])
    seen_h1_tx = {}

    def inject(src, dst, data):
        msg = _parse_pbft(data)
        if msg is None or msg.packet_type != int(PacketType.PRE_PREPARE):
            return True
        if msg.number == 1 and not seen_h1_tx:
            try:
                seen_h1_tx["block"] = Block.decode(msg.payload)
            except Exception:
                pass
            return True
        if (msg.number == 2 and msg.from_idx == 2
                and "block" in seen_h1_tx and "forged" not in seen_h1_tx):
            # replace the legitimate height-2 proposal with one that
            # re-includes height 1's txs (validly signed by leader 2)
            seen_h1_tx["forged"] = True
            b1 = seen_h1_tx["block"]
            dup = Block.decode(msg.payload)
            dup.tx_hashes = list(b1.tx_hashes) + list(dup.tx_hashes)
            dup.transactions = []
            dup.header.invalidate()
            phash = dup.header.hash(suite)
            forged = make_packet(PacketType.PRE_PREPARE, msg.view,
                                 msg.number, msg.from_idx, phash,
                                 dup.encode())
            forged.sign(suite, leader2_kp)
            for peer in sorted_ids:
                if peer != src:
                    gateway.send(src, peer, _front_pack(forged.encode()))
            return False
        return True

    gateway.set_filter(inject)
    try:
        kp = suite.generate_keypair(b"dup-user")
        for node in nodes:
            node.start()
        txs = [_tx(suite, kp, f"dup-{i}") for i in range(6)]
        nodes[0].txpool.submit_batch(txs[:3])
        assert wait_until(lambda: all(
            n.ledger.current_number() >= 1 for n in nodes), timeout=20)
        nodes[1].txpool.submit_batch(txs[3:])
        # liveness: everything commits despite the forged duplicate
        assert wait_until(lambda: all(
            n.ledger.total_tx_count() >= 6 for n in nodes), timeout=60), \
            [n.ledger.total_tx_count() for n in nodes]
        # safety: exactly once, identical chain
        for n in nodes:
            assert n.ledger.total_tx_count() == 6
        head = nodes[0].ledger.current_number()
        for b in range(1, head + 1):
            hh = {n.ledger.header_by_number(b).hash(suite) for n in nodes}
            assert len(hh) == 1, f"fork at height {b}"
    finally:
        for n in nodes:
            n.stop()
        gateway.stop()
