"""Golden-value tests for 256-bit limb arithmetic vs Python ints."""

import random

import pytest

import jax.numpy as jnp
import numpy as np

from fisco_bcos_tpu.ops import bigint as bi

SECP_P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
SM2_P = 0xFFFFFFFEFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF00000000FFFFFFFFFFFFFFFF

rng = random.Random(1234)


def rand256(below=1 << 256):
    return rng.randrange(below)


def test_roundtrip():
    for _ in range(20):
        x = rand256()
        assert bi.from_limbs(bi.to_limbs(x)) == x


def test_add_sub_carry():
    xs = [rand256() for _ in range(64)] + [0, 1, (1 << 256) - 1]
    ys = [rand256() for _ in range(64)] + [(1 << 256) - 1, (1 << 256) - 1, 1]
    a = jnp.asarray(np.stack([bi.to_limbs(x) for x in xs]))
    b = jnp.asarray(np.stack([bi.to_limbs(y) for y in ys]))
    s, c = bi.add(a, b)
    d, brw = bi.sub(a, b)
    for i, (x, y) in enumerate(zip(xs, ys)):
        assert bi.from_limbs(s[i]) == (x + y) % (1 << 256)
        assert int(c[i]) == (x + y) >> 256
        assert bi.from_limbs(d[i]) == (x - y) % (1 << 256)
        assert int(brw[i]) == (1 if x < y else 0)
    assert bool(bi.geq(a, b)[0]) == (xs[0] >= ys[0])


def test_mod_ring_ops():
    for p in (SECP_P, SECP_N, SM2_P):
        m = bi.Mod(p)
        xs = [rand256(p) for _ in range(32)] + [0, 1, p - 1]
        ys = [rand256(p) for _ in range(32)] + [p - 1, p - 1, p - 1]
        a = jnp.asarray(np.stack([bi.to_limbs(x) for x in xs]))
        b = jnp.asarray(np.stack([bi.to_limbs(y) for y in ys]))
        s = m.add(a, b)
        d = m.sub(a, b)
        n = m.neg(a)
        h = m.half(a)
        for i, (x, y) in enumerate(zip(xs, ys)):
            assert bi.from_limbs(s[i]) == (x + y) % p, (i, hex(p))
            assert bi.from_limbs(d[i]) == (x - y) % p
            assert bi.from_limbs(n[i]) == (-x) % p
            assert bi.from_limbs(h[i]) == (x * pow(2, -1, p)) % p


def test_mont_mul():
    for p in (SECP_P, SECP_N, SM2_P):
        m = bi.Mod(p)
        xs = [rand256(p) for _ in range(32)] + [0, 1, p - 1]
        ys = [rand256(p) for _ in range(32)] + [p - 1, 1, p - 1]
        a = jnp.asarray(np.stack([bi.to_limbs(x) for x in xs]))
        b = jnp.asarray(np.stack([bi.to_limbs(y) for y in ys]))
        am = m.to_mont(a)
        bm = m.to_mont(b)
        prod = m.from_mont(m.mul(am, bm))
        for i, (x, y) in enumerate(zip(xs, ys)):
            assert bi.from_limbs(prod[i]) == (x * y) % p, (i, hex(p))
        # round-trip
        back = m.from_mont(am)
        for i, x in enumerate(xs):
            assert bi.from_limbs(back[i]) == x


def test_pow_inv():
    for p in (SECP_P, SECP_N):
        m = bi.Mod(p)
        xs = [rand256(p - 1) + 1 for _ in range(8)]
        a = m.to_mont(jnp.asarray(np.stack([bi.to_limbs(x) for x in xs])))
        inv = m.from_mont(m.inv(a))
        cube = m.from_mont(m.pow_const(m.to_mont(
            jnp.asarray(np.stack([bi.to_limbs(x) for x in xs]))), 3))
        for i, x in enumerate(xs):
            assert bi.from_limbs(inv[i]) == pow(x, -1, p)
            assert bi.from_limbs(cube[i]) == pow(x, 3, p)


def test_window_digits():
    x = rand256()
    a = jnp.asarray(bi.to_limbs(x))
    d = bi.window_digits(a, 4)
    for i in range(64):
        assert int(d[i]) == (x >> (4 * i)) & 0xF


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_inv_batch_matches_fermat_and_handles_zeros():
    import numpy as np

    from fisco_bcos_tpu.crypto import refimpl
    from fisco_bcos_tpu.ops import fp

    for F, mod in ((fp.SolinasField(refimpl.SECP256K1.p, "p"),
                    refimpl.SECP256K1.p),
                   (fp.MontField(refimpl.SECP256K1.n, "n"),
                    refimpl.SECP256K1.n)):
        vals = [pow(3, i + 1, mod) for i in range(14)] + [0, mod - 1]
        a = np.stack([fp.to_limbs(v) for v in vals], axis=1)  # [16, 16]
        rep = F.to_rep(a)
        out = F.from_rep(F.inv_batch(rep))
        got = [fp.from_limbs_np(np.asarray(out)[:, j])
               for j in range(len(vals))]
        exp = [pow(v, -1, mod) if v else 0 for v in vals]
        assert got == exp, F.name
