"""Tracing plane tests: otrace core, W3C context propagation across a
4-node chain, slow-span capture at sample_rate=0, the /metrics //trace
//status ops routes on the event-loop edge, and getTrace/getSystemStatus
RPC (HTTP + WS parity)."""

import http.client
import json
import time

import pytest

from fisco_bcos_tpu.utils import otrace
from fisco_bcos_tpu.utils.otrace import (SpanContext, Tracer,
                                         parse_traceparent, unpack_ctx)


# -- core ------------------------------------------------------------------
def test_traceparent_roundtrip():
    ctx = SpanContext(bytes(range(16)), bytes(range(8)), True)
    tp = ctx.traceparent()
    assert tp == ("00-000102030405060708090a0b0c0d0e0f-"
                  "0001020304050607-01")
    back = parse_traceparent(tp)
    assert back is not None
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id
    assert back.sampled is True
    # unsampled flag honored
    assert parse_traceparent(tp[:-2] + "00").sampled is False
    # malformed inputs -> None, never an exception
    for bad in (None, "", "garbage", "00-zz-xx-01", "00-" + "0" * 32 +
                "-" + "0" * 16 + "-01", 42, "00-abc-def-01"):
        assert parse_traceparent(bad) is None


def test_wire_context_roundtrip():
    ctx = SpanContext(b"\x11" * 16, b"\x22" * 8, True)
    back = unpack_ctx(ctx.pack())
    assert back.trace_id == ctx.trace_id
    assert back.span_id == ctx.span_id and back.sampled
    assert unpack_ctx(b"short") is None
    assert unpack_ctx(bytes(25)) is None  # all-zero ids invalid


def test_ctx_scope_stack():
    assert otrace.current() is None
    a = SpanContext(b"\xaa" * 16, b"\x01" * 8, True)
    b = SpanContext(b"\xbb" * 16, b"\x02" * 8, True)
    with otrace.ctx_scope(a):
        assert otrace.current() is a
        with otrace.ctx_scope(None):  # no-op scope
            assert otrace.current() is a
        with otrace.ctx_scope(b):
            assert otrace.current() is b
        assert otrace.current() is a
    assert otrace.current() is None


def test_sampling_ring_and_queries():
    tr = Tracer(sample_rate=1.0, ring_size=64, slow_ms=0.0)
    roots = []
    for i in range(3):
        root = tr.new_root()
        assert root.sampled
        roots.append(root)
        with tr.span("outer", parent=root, attrs={"i": i}) as sp:
            # the span scopes its context: children nest automatically
            with tr.span("inner"):
                pass
            sp.set_attr("extra", True)
    spans = tr.get_trace(roots[0].trace_id.hex())
    assert {s["name"] for s in spans} == {"outer", "inner"}
    outer = next(s for s in spans if s["name"] == "outer")
    inner = next(s for s in spans if s["name"] == "inner")
    assert inner["parentSpanId"] == outer["spanId"]
    assert outer["attrs"] == {"i": 0, "extra": True}
    summaries = tr.list_traces()
    assert len(summaries) == 3
    assert all(t["spans"] == 2 for t in summaries)
    # ring stays bounded
    for _ in range(200):
        tr.record("x", tr.new_root(), time.monotonic())
    assert tr.stats()["ring_spans"] == 64
    assert tr.stats()["dropped_total"] > 0


def test_sample_rate_zero_is_empty_but_slow_capture_fires():
    tr = Tracer(sample_rate=0.0, ring_size=64, slow_ms=5.0)
    root = tr.new_root()
    assert not root.sampled
    with tr.span("fast", parent=root):
        pass
    with tr.span("slow-one", parent=root):
        time.sleep(0.02)
    st = tr.stats()
    assert st["ring_spans"] == 0  # nothing sampled into the main ring
    assert st["slow_spans"] == 1  # the slow span was retained anyway
    spans = tr.get_trace(root.trace_id.hex())
    assert [s["name"] for s in spans] == ["slow-one"]
    assert spans[0]["slow"] is True
    # observe_slow (the no-context seam) also lands in the slow ring only
    tr.observe_slow("stage.commit", 0.5, attrs={"number": 9})
    assert tr.stats()["slow_spans"] == 2
    assert tr.stats()["ring_spans"] == 0
    # fully idle tracer short-circuits to the null span
    idle = Tracer(sample_rate=0.0, ring_size=64, slow_ms=0.0)
    assert idle.idle()
    assert idle.span("anything") is otrace._NULL_SPAN


# -- ops server (satellite: /metrics off the event-loop edge) --------------
def test_ops_server_routes():
    from fisco_bcos_tpu.utils.metrics import MetricsRegistry, MetricsServer

    reg = MetricsRegistry()
    reg.inc("up")
    tr = Tracer(sample_rate=1.0, ring_size=64, slow_ms=0.0)
    root = tr.new_root()
    tr.record("hello", root, time.monotonic() - 0.01)
    srv = MetricsServer(reg, port=0, tracer=tr,
                        status_fn=lambda: {"blockNumber": 7})
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        assert r.status == 200
        assert "version=0.0.4" in r.getheader("Content-Type")
        assert "up 1.0" in r.read().decode()
        # keep-alive: same connection serves every route
        conn.request("GET", "/status")
        st = json.loads(conn.getresponse().read())
        assert st["blockNumber"] == 7
        conn.request("GET", f"/trace?id={root.trace_id.hex()}")
        doc = json.loads(conn.getresponse().read())
        assert [s["name"] for s in doc["spans"]] == ["hello"]
        conn.request("GET", "/traces?limit=10")
        lst = json.loads(conn.getresponse().read())
        assert lst["traces"][0]["traceId"] == root.trace_id.hex()
        conn.request("GET", "/nope")
        r = conn.getresponse()
        assert r.status == 404
        r.read()
        # POST on an ops-only server is refused, session survives
        conn.request("POST", "/metrics", body=b"{}")
        r = conn.getresponse()
        assert r.status == 405
        r.read()
        conn.close()
    finally:
        srv.stop()


# -- label escaping (satellite: Prometheus exposition validity) ------------
def _parse_exposition(text: str) -> dict:
    """Minimal Prometheus text-format parser: {(name, (label kv...)):
    value}. Raises on any malformed line — the round-trip assertion."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            labelpart, value = rest.rsplit("} ", 1)
            labels = []
            i = 0
            while i < len(labelpart):
                eq = labelpart.index('="', i)
                key = labelpart[i:eq]
                j = eq + 2
                val = []
                while labelpart[j] != '"':
                    if labelpart[j] == "\\":
                        nxt = labelpart[j + 1]
                        val.append({"\\": "\\", '"': '"',
                                    "n": "\n"}[nxt])
                        j += 2
                    else:
                        val.append(labelpart[j])
                        j += 1
                labels.append((key, "".join(val)))
                i = j + 2 if j + 1 < len(labelpart) and \
                    labelpart[j + 1] == "," else j + 1
        else:
            name, value = line.rsplit(" ", 1)
            labels = []
        out[(name, tuple(labels))] = float(value)
    return out


def test_label_value_escaping_round_trips():
    from fisco_bcos_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    nasty = 'gr"oup\\one\nline'
    reg.inc("bcos_evil_total", labels={"group": nasty})
    reg.set_gauge("bcos_plain", 1.0, labels={"group": "g0"})
    reg.observe("bcos_evil_seconds", 0.25, labels={"group": nasty})
    text = reg.prometheus_text()
    assert "\n\n" not in text.strip()  # raw newline would split a line
    parsed = _parse_exposition(text)
    assert parsed[("bcos_evil_total", (("group", nasty),))] == 1.0
    assert parsed[("bcos_plain", (("group", "g0"),))] == 1.0
    # histogram series carry the escaped label too
    assert any(n == "bcos_evil_seconds_count" and dict(ls)["group"] == nasty
               for n, ls in parsed)


# -- chain fixtures --------------------------------------------------------
def _chain(sample_rate: float, slow_ms: float = 0.0, n: int = 4,
           rpc_on_first: bool = False, ws_on_first: bool = False):
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.ledger.ledger import ConsensusNode
    from fisco_bcos_tpu.net.gateway import FakeGateway

    suite = make_suite(False, backend="host")
    kps = [suite.generate_keypair(bytes([i + 1]) * 16) for i in range(n)]
    gw = FakeGateway()
    sealers = [ConsensusNode(kp.pub_bytes) for kp in kps]
    nodes = []
    for i, kp in enumerate(kps):
        node = Node(NodeConfig(
            consensus="pbft", crypto_backend="host", min_seal_time=0.0,
            view_timeout=30.0, trace_sample_rate=sample_rate,
            trace_slow_ms=slow_ms,
            rpc_port=0 if rpc_on_first and i == 0 else None,
            ws_port=0 if ws_on_first and i == 0 else None),
            keypair=kp, gateway=gw)
        node.build_genesis(sealers)
        nodes.append(node)
    otrace.TRACER.reset()
    for node in nodes:
        node.start()
    return nodes, gw


def _stop(nodes, gw):
    for node in nodes:
        node.stop()
    gw.stop()


def _signed_tx(suite, i: int):
    from fisco_bcos_tpu.executor import precompiled as pc
    from fisco_bcos_tpu.protocol import Transaction

    kp = suite.generate_keypair(b"otrace-client")
    return Transaction(
        to=pc.BALANCE_ADDRESS,
        input=pc.encode_call("register",
                             lambda w: w.blob(b"ot%d" % i).u64(10 + i)),
        nonce=f"ot{i}", block_limit=400).sign(suite, kp)


# -- end-to-end propagation (satellite: 4-node trace coverage) -------------
def test_chain_trace_propagation_4node():
    """One submitted tx yields ONE trace whose spans cover admission ->
    receipt, with PBFT spans from follower nodes carrying the leader's
    trace context via the p2p envelope."""
    nodes, gw = _chain(sample_rate=1.0)
    try:
        tx = _signed_tx(nodes[0].suite, 0)
        root = otrace.TRACER.new_root()
        assert root.sampled
        tx._otrace = root
        res = nodes[0].send_transaction(tx)
        rc = nodes[0].txpool.wait_for_receipt(res.tx_hash, 30)
        assert rc is not None and rc.status == 0
        deadline = time.monotonic() + 5
        names: set = set()
        while time.monotonic() < deadline:
            spans = otrace.TRACER.get_trace(root.trace_id.hex())
            names = {s["name"] for s in spans}
            if {"pbft.consensus", "stage.notify"} <= names and len(
                    [s for s in spans
                     if s["name"] == "pbft.consensus"]) >= 3:
                break
            time.sleep(0.05)
        # ONE trace id covering admission -> seal -> consensus ->
        # execute -> commit -> receipt notify
        assert len({s["traceId"] for s in spans}) == 1
        for expected in ("ingest.admit", "txpool.admit", "seal",
                         "pbft.consensus", "stage.execute", "stage.commit",
                         "stage.notify"):
            assert expected in names, (expected, sorted(names))
        # consensus spans from >= 2 DISTINCT nodes, stitched by the p2p
        # envelope (followers adopted the leader's context)
        pbft_nodes = {s["attrs"]["node_idx"] for s in spans
                      if s["name"] == "pbft.consensus"}
        assert len(pbft_nodes) >= 2, pbft_nodes
        stage_nodes = {s["attrs"]["node"] for s in spans
                       if s["name"] == "stage.commit"}
        assert len(stage_nodes) >= 2, stage_nodes
        # parent chain: every span's trace matches the client root
        assert all(s["traceId"] == root.trace_id.hex() for s in spans)
    finally:
        _stop(nodes, gw)


def test_chain_sample_rate_zero_empty_ring_slow_fires():
    """[trace] sample_rate=0 leaves ZERO entries in the span ring while
    slow-span capture still fires (threshold set below a block stage)."""
    nodes, gw = _chain(sample_rate=0.0, slow_ms=0.0001)
    try:
        tx = _signed_tx(nodes[0].suite, 1)
        res = nodes[0].send_transaction(tx)
        rc = nodes[0].txpool.wait_for_receipt(res.tx_hash, 30)
        assert rc is not None and rc.status == 0
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and \
                otrace.TRACER.stats()["slow_spans"] == 0:
            time.sleep(0.05)
        st = otrace.TRACER.stats()
        assert st["ring_spans"] == 0, st  # nothing sampled
        assert st["slow_spans"] > 0, st   # slow capture still fired
        assert otrace.TRACER.list_traces(slow_only=True)
    finally:
        _stop(nodes, gw)


# -- RPC/ops surface on a live node ---------------------------------------
@pytest.fixture(scope="module")
def rpc_node():
    nodes, gw = _chain(sample_rate=1.0, rpc_on_first=True,
                       ws_on_first=True)
    yield nodes
    _stop(nodes, gw)


def _http_rpc(node, payload, headers=None):
    conn = http.client.HTTPConnection(node.config.rpc_host, node.rpc.port,
                                      timeout=15)
    try:
        conn.request("POST", "/", body=json.dumps(payload).encode(),
                     headers=headers or {})
        r = conn.getresponse()
        return json.loads(r.read()), dict(r.getheaders())
    finally:
        conn.close()


def test_traceparent_http_e2e_get_trace(rpc_node):
    """Client-supplied traceparent: the submission's spans join the
    client's trace (sampled flag honored), the response echoes the
    header, and getTrace returns the stitched spans by id."""
    nodes = rpc_node
    node = nodes[0]
    otrace.TRACER.reset()
    tid = "11d1c0de" * 4
    tp = f"00-{tid}-00f067aa0ba902b7-01"
    tx = _signed_tx(node.suite, 2)
    resp, headers = _http_rpc(
        node,
        {"jsonrpc": "2.0", "id": 1, "method": "sendTransaction",
         "params": ["group0", "", "0x" + tx.encode().hex()]},
        headers={"traceparent": tp})
    assert "result" in resp, resp
    assert headers.get("traceparent") == tp  # echoed for correlation
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        got, _ = _http_rpc(node, {
            "jsonrpc": "2.0", "id": 2, "method": "getTrace",
            "params": ["group0", "", tid]})
        names = {s["name"] for s in got["result"]["spans"]}
        if "stage.notify" in names and "rpc.sendTransaction" in names:
            break
        time.sleep(0.05)
    assert got["result"]["traceId"] == tid
    assert "rpc.sendTransaction" in names, names
    assert "pbft.consensus" in names, names
    # listTraces sees the same trace
    lst, _ = _http_rpc(node, {"jsonrpc": "2.0", "id": 3,
                              "method": "listTraces",
                              "params": ["group0", "", 10]})
    assert any(t["traceId"] == tid for t in lst["result"]["traces"])


def test_rpc_edge_serves_ops_routes(rpc_node):
    """GET /metrics, /status and /trace come from the SAME event-loop
    edge that serves JSON-RPC POSTs (no dedicated scrape thread)."""
    node = rpc_node[0]
    conn = http.client.HTTPConnection(node.config.rpc_host, node.rpc.port,
                                      timeout=15)
    try:
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        assert r.status == 200
        body = r.read().decode()
        assert "bcos_tx_stage_seconds" in body
        # a POST on the same keep-alive connection still serves RPC
        conn.request("POST", "/", body=json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": "getBlockNumber",
             "params": ["group0", ""]}).encode())
        assert "result" in json.loads(conn.getresponse().read())
        conn.request("GET", "/status")
        st = json.loads(conn.getresponse().read())
        assert st["group"] == "group0" and "pipeline" in st
    finally:
        conn.close()


def test_get_system_status_http_ws_parity(rpc_node):
    """getSystemStatus aggregates the scattered operational state into
    one group-labeled document, identically shaped over HTTP and WS."""
    node = rpc_node[0]
    http_resp, _ = _http_rpc(node, {
        "jsonrpc": "2.0", "id": 1, "method": "getSystemStatus",
        "params": ["group0", ""]})
    doc = http_resp["result"]
    for key in ("group", "node", "blockNumber", "syncMode", "txpool",
                "ingest", "pipeline", "storage", "snapshot", "groups",
                "trace", "consensus"):
        assert key in doc, key
    assert doc["group"] == "group0"
    assert doc["groups"] == ["group0"]
    assert doc["pipeline"]["stages"] is not None
    assert doc["trace"]["ring_size"] > 0

    from fisco_bcos_tpu.net.websocket import ws_connect
    conn = ws_connect(node.config.rpc_host, node.ws.port)
    try:
        conn.send_text(json.dumps({
            "jsonrpc": "2.0", "id": 9, "method": "getSystemStatus",
            "params": ["group0", ""]}))
        _op, payload = conn.recv()
        ws_doc = json.loads(payload)["result"]
    finally:
        conn.close()
    # parity: same shape and same identity over both transports
    assert set(ws_doc) == set(doc)
    assert ws_doc["group"] == doc["group"]
    assert ws_doc["node"] == doc["node"]


def test_ws_traceparent_member(rpc_node):
    """WS has no per-message headers: a `traceparent` MEMBER on the
    request object carries the context instead."""
    node = rpc_node[0]
    otrace.TRACER.reset()
    tid = "22d1c0de" * 4
    from fisco_bcos_tpu.net.websocket import ws_connect
    conn = ws_connect(node.config.rpc_host, node.ws.port)
    try:
        conn.send_text(json.dumps({
            "jsonrpc": "2.0", "id": 4, "method": "getBlockNumber",
            "params": ["group0", ""],
            "traceparent": f"00-{tid}-00f067aa0ba902b7-01"}))
        _op, payload = conn.recv()
        assert "result" in json.loads(payload)
    finally:
        conn.close()
    spans = otrace.TRACER.get_trace(tid)
    assert any(s["name"] == "rpc.getBlockNumber" for s in spans), spans
