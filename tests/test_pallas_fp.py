"""Pallas-fused field multiplies: bit-parity with the XLA path.

Runs the pallas kernels in interpreter mode (CPU CI); on a real TPU the
same bodies lower through Mosaic. The EC kernel suite (test_ec.py) then
covers the full verify/recover pipeline with the dispatch active.
"""

import numpy as np
import pytest

from fisco_bcos_tpu.ops import fp, pallas_fp

SECP_P = 2**256 - 2**32 - 977
SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
SM2_P = 0xFFFFFFFEFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFF00000000FFFFFFFFFFFFFFFF

FIELDS = [
    fp.SolinasField(SECP_P, "secp.p"),
    fp.MontField(SECP_N, "secp.n"),
    fp.MontField(SM2_P, "sm2.p"),
]


def _rand_cols(rng, n, below):
    vals = [int.from_bytes(rng.bytes(32), "big") % below for _ in range(n)]
    return np.stack([fp.to_limbs(v) for v in vals], axis=1)


@pytest.mark.parametrize("field", FIELDS, ids=lambda f: f.name)
def test_mul_matches_xla(field):
    rng = np.random.default_rng(7)
    a = _rand_cols(rng, 256, field.n_int)
    b = _rand_cols(rng, 256, field.n_int)
    want = np.asarray(field.mul_xla(a, b))
    got = np.asarray(pallas_fp.mul(field, a, b, interpret=True))
    assert (want == got).all()


@pytest.mark.parametrize("field", FIELDS[:2], ids=lambda f: f.name)
def test_mul_edge_values(field):
    vals = [0, 1, 2, field.n_int - 1, field.n_int - 2, (1 << 255) % field.n_int]
    vals = (vals * 22)[:128]
    a = np.stack([fp.to_limbs(v) for v in vals], axis=1)
    b = np.ascontiguousarray(a[:, ::-1])
    want = np.asarray(field.mul_xla(a, b))
    got = np.asarray(pallas_fp.mul(field, a, b, interpret=True))
    assert (want == got).all()


def test_mul_stacked_matches_xla():
    field = FIELDS[0]
    rng = np.random.default_rng(9)
    a = np.stack([_rand_cols(rng, 128, field.n_int) for _ in range(3)])
    b = np.stack([_rand_cols(rng, 128, field.n_int) for _ in range(3)])
    want = np.asarray(field.mul_xla(a, b))
    got = np.asarray(pallas_fp.mul_stacked(field, a, b, interpret=True))
    assert (want == got).all()


def test_pallas_ok_gating():
    assert pallas_fp.pallas_ok((16, 128))
    assert pallas_fp.pallas_ok((16, 65536))
    assert not pallas_fp.pallas_ok((16, 100))  # not lane-aligned
    assert not pallas_fp.pallas_ok((16, 1))    # scalar column
    assert not pallas_fp.pallas_ok((8, 128))   # wrong limb count
    assert not pallas_fp.pallas_ok((3, 16, 128))  # stacked handled upstream


def test_mul_non_blk_multiple_covers_all_lanes():
    """B = 640 (a 128-multiple, NOT a 512-multiple) must compute every
    lane — regression for the floor-divided grid dropping the tail."""
    field = FIELDS[0]
    rng = np.random.default_rng(13)
    a = _rand_cols(rng, 640, field.n_int)
    b = _rand_cols(rng, 640, field.n_int)
    want = np.asarray(field.mul_xla(a, b))
    got = np.asarray(pallas_fp.mul(field, a, b, interpret=True))
    assert (want == got).all()  # esp. lanes 512..639


@pytest.mark.parametrize("field", FIELDS[:2], ids=lambda f: f.name)
def test_mul_const_column(field):
    """[16, B] x [16, 1] goes through the constant-column kernel."""
    rng = np.random.default_rng(15)
    a = _rand_cols(rng, 256, field.n_int)
    c = _rand_cols(rng, 1, field.n_int)
    want = np.asarray(field.mul_xla(a, np.broadcast_to(c, a.shape)))
    got = np.asarray(pallas_fp.mul_const(field, a, c, interpret=True))
    assert (want == got).all()


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
@pytest.mark.parametrize("field", FIELDS[:2], ids=lambda f: f.name)
def test_pow_const_fused(field):
    """Fused exponentiation matches the XLA scan path (small exponents in
    CI; the (p+1)/4 sqrt exponent is covered by the offline harness and
    the device sweep's recover assertions)."""
    rng = np.random.default_rng(17)
    a = _rand_cols(rng, 128, field.n_int)
    if isinstance(field, fp.MontField):
        a = np.asarray(field.to_rep(a))
    prior = list(fp._PALLAS_CACHE)
    try:
        for e in (1, 2, 3, 0x1234, 0xFFFF):
            fp._PALLAS_CACHE[:] = [False]
            want = np.asarray(field.pow_const(a, e))
            fp._PALLAS_CACHE[:] = []
            got = np.asarray(pallas_fp.pow_const(field, a, e,
                                                 interpret=True))
            assert (want == got).all(), hex(e)
    finally:
        fp._PALLAS_CACHE[:] = prior


def test_host_value_parity():
    """Pallas product agrees with Python big-int arithmetic, not just the
    XLA path (guards against a shared systematic error)."""
    field = FIELDS[0]
    rng = np.random.default_rng(11)
    vals_a = [int.from_bytes(rng.bytes(32), "big") % SECP_P for _ in range(128)]
    vals_b = [int.from_bytes(rng.bytes(32), "big") % SECP_P for _ in range(128)]
    a = np.stack([fp.to_limbs(v) for v in vals_a], axis=1)
    b = np.stack([fp.to_limbs(v) for v in vals_b], axis=1)
    got = np.asarray(pallas_fp.mul(field, a, b, interpret=True))
    for i in (0, 17, 127):
        want = vals_a[i] * vals_b[i] % SECP_P
        assert fp.from_limbs_np(got[:, i]) == want
