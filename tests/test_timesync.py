"""Peer clock-skew maintenance (NodeTimeMaintenance.cpp analogue)."""

import time

from fisco_bcos_tpu.tool.timesync import (
    MAX_TIME_OFFSET_MS,
    MIN_TIME_OFFSET_MS,
    NodeTimeMaintenance,
    utc_ms,
)


def test_median_offset_alignment():
    tm = NodeTimeMaintenance()
    now = utc_ms()
    # three peers: +10min, +12min, -2min -> median +10min
    tm.update_peer_time(b"p1", now + 600_000, local_time_ms=now)
    tm.update_peer_time(b"p2", now + 720_000, local_time_ms=now)
    tm.update_peer_time(b"p3", now - 120_000, local_time_ms=now)
    assert tm.median_offset_ms() == 600_000
    aligned = tm.aligned_time_ms()
    assert abs(aligned - (utc_ms() + 600_000)) < 2_000


def test_small_jitter_ignored():
    tm = NodeTimeMaintenance()
    now = utc_ms()
    tm.update_peer_time(b"p1", now + 500_000, local_time_ms=now)
    # sub-threshold wobble: estimate unchanged
    tm.update_peer_time(b"p1", now + 500_000 + MIN_TIME_OFFSET_MS - 1,
                        local_time_ms=now)
    assert tm.median_offset_ms() == 500_000
    # above-threshold move: estimate updates
    tm.update_peer_time(b"p1", now + 500_000 + MIN_TIME_OFFSET_MS + 1000,
                        local_time_ms=now)
    assert tm.median_offset_ms() == 500_000 + MIN_TIME_OFFSET_MS + 1000


def test_single_drifter_does_not_move_median():
    tm = NodeTimeMaintenance()
    now = utc_ms()
    for i, p in enumerate((b"a", b"b", b"c", b"d")):
        tm.update_peer_time(p, now + i, local_time_ms=now)
    tm.update_peer_time(b"evil", now + MAX_TIME_OFFSET_MS * 3,
                        local_time_ms=now)
    assert tm.median_offset_ms() < 1_000  # robust to one far-off peer


def test_forget_peer():
    tm = NodeTimeMaintenance()
    now = utc_ms()
    tm.update_peer_time(b"p1", now + 900_000, local_time_ms=now)
    assert tm.median_offset_ms() == 900_000
    tm.forget_peer(b"p1")
    assert tm.median_offset_ms() == 0


def _two_node_gossip_pair(seed_base: int):
    """(gateway, [node, node]) wired over a FakeGateway, started."""
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.ledger.ledger import ConsensusNode
    from fisco_bcos_tpu.net.gateway import FakeGateway

    suite = make_suite(backend="host")
    gateway = FakeGateway()
    kps = [suite.generate_keypair(bytes([i + seed_base]) * 16)
           for i in range(2)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in kps]
    nodes = []
    for kp in kps:
        n = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                            min_seal_time=0.0), keypair=kp,
                 gateway=gateway)
        n.build_genesis(sealers)
        nodes.append(n)
    for n in nodes:
        n.start()
    return gateway, nodes


def test_status_gossip_feeds_timesync():
    """Two gateway-connected nodes exchange sync status; each learns the
    other's clock and the sealer's clock source follows the median."""
    gateway, nodes = _two_node_gossip_pair(71)
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            if all(len(n.timesync._offsets) >= 1 for n in nodes):
                break
            time.sleep(0.1)
        assert all(len(n.timesync._offsets) >= 1 for n in nodes)
        # same-machine clocks: offsets near zero, sealer clock sane
        for n in nodes:
            assert abs(n.timesync.median_offset_ms()) < 5_000
            assert abs(n.sealer.clock_ms() - utc_ms()) < 5_000
    finally:
        for n in nodes:
            n.stop()
        gateway.stop()


def test_silent_peer_pruned_from_sync_and_median():
    """A departed peer stops pinning the sync download target and drops
    out of the timesync median after PEER_TTL_INTERVALS silent periods."""
    gateway, nodes = _two_node_gossip_pair(81)
    for n in nodes:
        # fast status cadence so the prune TTL elapses quickly
        n.blocksync.status_interval = 0.1
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            if all(len(n.blocksync._peers) >= 1 for n in nodes):
                break
            time.sleep(0.05)
        assert all(len(n.blocksync._peers) >= 1 for n in nodes)
        assert all(len(n.timesync._offsets) >= 1 for n in nodes)
        # "crash" node 1: stop gossip; node 0 must forget it
        nodes[1].stop()
        ttl = nodes[0].blocksync.status_interval * \
            nodes[0].blocksync.PEER_TTL_INTERVALS
        deadline = time.time() + ttl * 10 + 10
        while time.time() < deadline:
            # wait on the MEDIAN too: forget_peer recomputes it after the
            # offsets pop, so polling offsets alone races the recompute
            if (len(nodes[0].blocksync._peers) == 0
                    and len(nodes[0].timesync._offsets) == 0
                    and nodes[0].timesync.median_offset_ms() == 0):
                break
            time.sleep(0.1)
        assert len(nodes[0].blocksync._peers) == 0
        assert len(nodes[0].timesync._offsets) == 0
        assert nodes[0].timesync.median_offset_ms() == 0
    finally:
        for n in nodes:
            try:
                n.stop()
            except Exception:
                pass
        gateway.stop()
