"""Disk-engine chaos gate: kill -9 a REAL daemon running `[storage]
backend = disk` while the engine is continuously flushing and compacting,
restart it, and require byte-identical state (the c_* rule: head hash AND
every `c_balance` row compared raw across nodes) with no full-log replay —
boot recovers from manifest + WAL tail only.

The memtable cap is forced to 0 so EVERY commit flushes a segment and
compaction runs every couple of flushes: a kill -9 at a random moment
lands inside (or between) flush/compaction edges with high probability,
and the deterministic per-edge crash points are unit-tested in
tests/test_storage_engine.py. `tools/sanitize_ci.sh --storage` runs the
single-node smoke; this is the full multi-process gate (marked slow).
"""

import os
import re

import pytest

from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.sdk.client import TransactionBuilder
from fisco_bcos_tpu.testing.chaos import ChaosHarness

pytestmark = pytest.mark.slow

# flush on every commit, merge every ~3 segments: maximal crash surface
DISK_OVERRIDES = {"storage_backend": "disk", "storage_memtable_mb": 0,
                  "storage_compact_segments": 2}


def _read_balance_rows(node_dir: str) -> dict:
    """Open a STOPPED node's engine offline and dump c_balance raw. With
    key_page_size on by default for the disk backend, the raw rows are
    pages — read through the page layer when the meta row is present so
    the cross-node comparison stays at the logical row level."""
    from fisco_bcos_tpu.storage.engine import DiskStorage
    from fisco_bcos_tpu.storage.keypage import META_KEY, KeyPageStorage

    st = DiskStorage(os.path.join(node_dir, "data"), auto_compact=False)
    try:
        view = KeyPageStorage(st) \
            if st.get("c_balance", META_KEY) is not None else st
        return {k: view.get("c_balance", k) for k in view.keys("c_balance")}
    finally:
        st.close()


def test_kill9_mid_flush_compaction_rejoins_byte_identical(tmp_path):
    with ChaosHarness(str(tmp_path / "chain"), tls=False,
                      config_overrides=DISK_OVERRIDES) as h:
        h.start_all()
        for i in range(h.n):
            h.wait_rpc_up(i)
        suite = h.suite()
        kp = suite.generate_keypair(b"disk-chaos")
        builder = TransactionBuilder(suite, None,
                                     chain_id=h.info["chain_id"],
                                     group_id=h.info["group_id"])
        sent = 0

        def burst(n, via):
            nonlocal sent
            for k in range(n):
                tx = builder.build(
                    kp, pc.BALANCE_ADDRESS,
                    pc.encode_call("register",
                                   lambda w: w.blob(b"acct%d" % sent).u64(1)),
                    nonce=f"dc-{sent}", block_limit=500)
                h.client(via[k % len(via)]).send_transaction(tx, wait=False)
                sent += 1

        survivors = [0, 1, 2]
        burst(8, via=survivors)
        h.wait_until(lambda: min(h.total_txs(i) for i in range(h.n)) >= 4,
                     timeout=180, what="pre-kill commits on every node")
        # the victim must genuinely have been flushing/compacting segments
        log3 = h.read_daemon_log(3)
        assert "[ENGINE][flushed]" in log3, \
            "disk engine never flushed before the kill — overrides not live?"
        h.kill(3)  # SIGKILL mid-stream: flush-per-commit makes mid-flush
        #            and mid-compaction windows the common case
        burst(8, via=survivors)
        h.wait_until(
            lambda: min(h.total_txs(i) for i in survivors) >= sent,
            timeout=180, what="survivor commits after kill -9")

        h.start(3)
        h.wait_rpc_up(3)
        log3 = h.read_daemon_log(3)
        # boot recovered from manifest + WAL tail, not a full-log replay:
        # the engine reports what it replayed, and with flush-per-commit
        # the durable tail above the floor is at most a handful of records
        recov = re.findall(r"\[ENGINE\]\[recovered\].*?segments=(\d+)"
                           r".*?wal_records=(\d+)", log3)
        assert recov, "no engine recovery badge in the restarted daemon log"
        segments, wal_records = map(int, recov[-1])
        assert segments >= 1, "restart found no durable segments"
        assert wal_records <= 8, \
            f"boot replayed {wal_records} WAL records — not a tail"
        # the daemon must report a non-genesis height straight from disk
        ups = re.findall(r"\[DAEMON\]\[up\].*?number=(-?\d+)", log3)
        assert ups and int(ups[-1]) >= 1, \
            "restart came up at genesis — engine recovery restored nothing"

        h.wait_until(lambda: h.total_txs(3) >= sent, timeout=180,
                     what="node3 catch-up after restart")
        height = h.wait_converged(range(h.n), min_height=1, timeout=120)
        hashes = {h.block_hash(i, height) for i in range(h.n)}
        assert len(hashes) == 1, f"head hash diverged at {height}: {hashes}"

        # byte-identical c_balance rows, read RAW from each node's engine
        # after a clean stop (per-changeset state_root alone does not prove
        # full-state equality — the PR 4 c_ prefix lesson)
        for i in range(h.n):
            h.terminate(i)
        rows = [_read_balance_rows(h.info["nodes"][i]["dir"])
                for i in range(h.n)]
        assert rows[0] and len(rows[0]) >= sent // 2, \
            f"suspiciously few balance rows: {len(rows[0])}"
        for i in range(1, h.n):
            assert rows[i] == rows[0], \
                f"node{i} c_balance diverged from node0"
