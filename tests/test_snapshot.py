"""Snapshot/checkpoint subsystem: export, verify, install, prune, serve.

Covers the acceptance contract of the subsystem in-process: a snapshot is
one batched `suite.hash_batch` call per manifest (asserted by counting
instrumentation on export AND import), any tampering is rejected whole, a
pruned chain answers range requests with a pruned-below marker, and a
joining node more than `snap_sync_threshold` blocks behind installs the
snapshot + replays only the tail (sync_mode == "snap").
"""

import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
from fisco_bcos_tpu.net.gateway import FakeGateway
from fisco_bcos_tpu.protocol import BlockHeader, Transaction
from fisco_bcos_tpu.snapshot import (SnapshotManifest, SnapshotStore,
                                     SnapshotVerifyError, export_snapshot,
                                     install_snapshot, verify_snapshot)
from fisco_bcos_tpu.snapshot.manifest import pack_chunks, unpack_chunk
from fisco_bcos_tpu.storage.memory import MemoryStorage


def wait_until(pred, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


def make_tx(suite, kp, i):
    return Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "register",
                           lambda w: w.blob(b"acct%d" % i).u64(5)),
                       nonce=f"snap-{i}", block_limit=500).sign(suite, kp)


def commit_blocks(node, n, start=0):
    kp = node.suite.generate_keypair(b"snap-user")
    for i in range(start, start + n):
        res = node.send_transaction(make_tx(node.suite, kp, i))
        rc = node.txpool.wait_for_receipt(res.tx_hash, 15)
        assert rc is not None and rc.status == 0


@pytest.fixture()
def solo_node():
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0))
    node.start()
    yield node
    node.stop()


def make_verify_seals(suite, sealer_pubs):
    """Standalone seal verifier for import-side tests (what BlockSync's
    _verify_seals does, without needing a BlockSync)."""
    import numpy as np

    def verify(header: BlockHeader) -> bool:
        sealer_set = sorted(sealer_pubs)
        if list(header.sealer_list) != sealer_set:
            return False
        hh = header.hash(suite)
        by_idx = {i: s for i, s in header.signature_list
                  if 0 <= i < len(sealer_set)}
        quorum = 2 * ((len(sealer_set) - 1) // 3) + 1
        if len(by_idx) < quorum:
            return False
        idxs = sorted(by_idx)
        ok = np.asarray(suite.verify_batch(
            [hh] * len(idxs), [by_idx[i] for i in idxs],
            [sealer_set[i] for i in idxs]))
        return int(ok.sum()) >= quorum

    return verify


# -- codec ------------------------------------------------------------------

def test_manifest_and_chunk_codec_roundtrip():
    rows = [("t_a", b"k1", b"v1"), ("t_a", b"k2", b"v" * 100),
            ("t_b", b"", b""), ("t_c", b"k" * 40, b"x" * 3000)]
    chunks = pack_chunks(rows, chunk_bytes=256)
    assert len(chunks) > 1  # budget forces a split
    assert [r for c in chunks for r in unpack_chunk(c)] == rows
    m = SnapshotManifest(height=7, header_bytes=b"hdr", root=b"r" * 32,
                         chunk_hashes=[b"h" * 32, b"i" * 32],
                         total_bytes=123)
    m2 = SnapshotManifest.decode(m.encode())
    assert m2 == m


def test_pack_chunks_oversized_row_never_wedges():
    rows = [("t", b"k", b"v" * 10_000)]
    chunks = pack_chunks(rows, chunk_bytes=64)
    assert len(chunks) == 1  # at least one row per chunk


# -- export / install -------------------------------------------------------

def test_export_install_roundtrip(solo_node):
    node = solo_node
    commit_blocks(node, 3)
    manifest, chunks = export_snapshot(node.storage, node.ledger,
                                       node.suite, chunk_bytes=512)
    assert manifest.height == node.ledger.current_number()
    assert manifest.chunk_count == len(chunks) > 1

    fresh = MemoryStorage()
    verify = make_verify_seals(node.suite, [node.keypair.pub_bytes])
    header = install_snapshot(manifest, chunks, fresh, node.suite, verify)
    led2 = Ledger(fresh, node.suite)
    assert led2.current_number() == manifest.height
    assert (led2.header_by_number(manifest.height).hash(node.suite)
            == header.hash(node.suite))
    # every public row travelled (spot check: receipts + config)
    for n in range(1, manifest.height + 1):
        assert led2.tx_hashes_by_number(n) == \
            node.ledger.tx_hashes_by_number(n)
    assert led2.system_config("tx_count_limit") == \
        node.ledger.system_config("tx_count_limit")
    # chain-state c_* tables travel (c_balance is written by the register
    # precompile) — only the consensus-PRIVATE log is excluded
    src_bal = list(node.storage.keys("c_balance"))
    assert src_bal, "fixture no longer touches c_balance"
    assert list(fresh.keys("c_balance")) == src_bal
    for k in src_bal:
        assert fresh.get("c_balance", k) == node.storage.get("c_balance", k)


def test_private_tables_never_exported(solo_node):
    node = solo_node
    commit_blocks(node, 1)
    node.storage.set("c_pbft_log", b"view", b"\x00" * 8)  # consensus-private
    manifest, chunks = export_snapshot(node.storage, node.ledger, node.suite)
    tables = {t for c in chunks for t, _, _ in unpack_chunk(c)}
    assert "c_pbft_log" not in tables
    assert "s_number_2_header" in tables
    # c_ is NOT a private prefix: replicated chain state under c_* (the
    # balance/auth/account precompile tables) must be snapshotted
    assert "c_balance" in tables


def test_one_batched_hash_call_per_manifest(solo_node):
    """The acceptance instrumentation: ALL chunk hashing is one
    suite.hash_batch call on export, and one on verify."""
    node = solo_node
    commit_blocks(node, 2)
    calls = []
    orig = node.suite.hash_batch

    def counted(msgs, _orig=orig):
        calls.append(len(msgs))
        return _orig(msgs)

    node.suite.hash_batch = counted
    try:
        manifest, chunks = export_snapshot(node.storage, node.ledger,
                                           node.suite, chunk_bytes=256)
        assert calls == [len(chunks)]  # exactly ONE call, all chunks in it
        calls.clear()
        verify = make_verify_seals(node.suite, [node.keypair.pub_bytes])
        verify_snapshot(manifest, chunks, node.suite, verify)
        assert calls == [len(chunks)]
    finally:
        node.suite.hash_batch = orig


def test_tampered_snapshot_rejected(solo_node):
    node = solo_node
    commit_blocks(node, 2)
    manifest, chunks = export_snapshot(node.storage, node.ledger,
                                       node.suite, chunk_bytes=256)
    verify = make_verify_seals(node.suite, [node.keypair.pub_bytes])
    fresh = MemoryStorage()

    # 1. flipped chunk byte
    bad = list(chunks)
    bad[0] = bytes([bad[0][0] ^ 0xFF]) + bad[0][1:]
    with pytest.raises(SnapshotVerifyError):
        install_snapshot(manifest, bad, fresh, node.suite, verify)
    # 2. root mismatch
    m2 = SnapshotManifest.decode(manifest.encode())
    m2.root = bytes(32)
    with pytest.raises(SnapshotVerifyError):
        install_snapshot(m2, chunks, fresh, node.suite, verify)
    # 3. missing chunk
    with pytest.raises(SnapshotVerifyError):
        install_snapshot(manifest, chunks[:-1], fresh, node.suite, verify)
    # 4. forged header (seals won't cover it)
    m3 = SnapshotManifest.decode(manifest.encode())
    hdr = BlockHeader.decode(m3.header_bytes)
    hdr.timestamp += 1
    hdr.invalidate()
    m3.header_bytes = hdr.encode()
    with pytest.raises(SnapshotVerifyError):
        install_snapshot(m3, chunks, fresh, node.suite, verify)
    # 5. seal-verifier rejection propagates
    with pytest.raises(SnapshotVerifyError):
        install_snapshot(manifest, chunks, fresh, node.suite,
                         lambda h: False)
    # nothing was installed by any failed attempt
    assert Ledger(fresh, node.suite).current_number() == -1
    # and the untampered snapshot still installs
    install_snapshot(manifest, chunks, fresh, node.suite, verify)
    assert Ledger(fresh, node.suite).current_number() == manifest.height


def test_malformed_chunk_content_is_verify_error(solo_node):
    """Review fix: a Byzantine peer can serve chunks whose hashes MATCH its
    own manifest but whose bytes are garbage — the decode failure must
    surface as SnapshotVerifyError (reject-whole + snap backoff), not as a
    plain ValueError that escapes to the worker loop with sync_mode stuck
    on "snap"."""
    node = solo_node
    commit_blocks(node, 1)
    manifest, chunks = export_snapshot(node.storage, node.ledger, node.suite)
    verify = make_verify_seals(node.suite, [node.keypair.pub_bytes])

    garbage = [b"\xff\x07not-a-chunk-record"]
    forged = SnapshotManifest.decode(manifest.encode())
    forged.chunk_hashes = node.suite.hash_batch(garbage)
    forged.root = node.suite.merkle_root(forged.chunk_hashes)
    forged.total_bytes = sum(len(c) for c in garbage)
    fresh = MemoryStorage()
    with pytest.raises(SnapshotVerifyError):
        install_snapshot(forged, garbage, fresh, node.suite, verify)
    assert not list(fresh.keys("s_current_state"))

    # same attack through snap_sync: returns None (backoff path), no raise
    from fisco_bcos_tpu.snapshot import importer as imp

    class Front:
        def request(self, module, peer, payload, timeout=5.0):
            from fisco_bcos_tpu.codec.wire import Reader
            r = Reader(payload)
            op = r.u8()
            return forged.encode() if op == imp.OP_MANIFEST else garbage[0]

    assert imp.snap_sync(Front(), b"P" * 64, fresh, node.suite, verify,
                         current_number=-1) is None


def test_install_removes_stale_genesis_rows(solo_node):
    node = solo_node
    commit_blocks(node, 1)
    manifest, chunks = export_snapshot(node.storage, node.ledger, node.suite)
    fresh = MemoryStorage()
    # a divergent local row that is NOT in the snapshot must not survive
    fresh.set("s_current_state", b"bogus_key", b"stale")
    verify = make_verify_seals(node.suite, [node.keypair.pub_bytes])
    install_snapshot(manifest, chunks, fresh, node.suite, verify)
    assert fresh.get("s_current_state", b"bogus_key") is None


def test_snap_sync_authenticates_before_fetching(solo_node, monkeypatch):
    """A peer-supplied manifest must not drive chunk downloads until its
    header seals verified and its declared size passed the resource caps."""
    from fisco_bcos_tpu.snapshot import importer as imp

    node = solo_node
    commit_blocks(node, 2)
    manifest, chunks = export_snapshot(node.storage, node.ledger, node.suite,
                                       chunk_bytes=256)
    assert manifest.chunk_count > 1
    verify = make_verify_seals(node.suite, [node.keypair.pub_bytes])

    class Front:
        def __init__(self, manifest_bytes):
            self.manifest_bytes = manifest_bytes
            self.chunk_requests = 0

        def request(self, module, peer, payload, timeout=5.0):
            from fisco_bcos_tpu.codec.wire import Reader
            r = Reader(payload)
            op, height, index = r.u8(), r.i64(), r.u32()
            if op == imp.OP_MANIFEST:
                return self.manifest_bytes
            self.chunk_requests += 1
            return chunks[index]

    fresh = MemoryStorage()
    # 1. forged seals: rejected with ZERO chunk fetches
    forged = SnapshotManifest.decode(manifest.encode())
    hdr = BlockHeader.decode(forged.header_bytes)
    hdr.signature_list = [(0, b"\x00" * 65)]
    forged.header_bytes = hdr.encode()
    front = Front(forged.encode())
    assert imp.snap_sync(front, b"P" * 64, fresh, node.suite, verify,
                         current_number=-1) is None
    assert front.chunk_requests == 0
    # 2. declared size beyond the cap: rejected with ZERO chunk fetches
    monkeypatch.setattr(imp, "MAX_SNAPSHOT_CHUNKS", 1)
    front = Front(manifest.encode())
    assert imp.snap_sync(front, b"P" * 64, fresh, node.suite, verify,
                         current_number=-1) is None
    assert front.chunk_requests == 0
    # 3. caps restored: the same wire path installs fine — and the 2f+1
    # quorum is batch-verified exactly ONCE per join (pre-fetch; install
    # must not pay for the same expensive check again)
    monkeypatch.setattr(imp, "MAX_SNAPSHOT_CHUNKS", 1 << 16)
    front = Front(manifest.encode())
    seal_checks = []

    def counting_verify(header, _v=verify):
        seal_checks.append(header.number)
        return _v(header)

    res = imp.snap_sync(front, b"P" * 64, fresh, node.suite,
                        counting_verify, current_number=-1)
    assert res is not None
    assert seal_checks == [manifest.height]
    assert Ledger(fresh, node.suite).current_number() == manifest.height


def test_snap_sync_fetch_deadline_aborts(solo_node, monkeypatch):
    """A peer with a genuinely sealed header but a forged/dribbled chunk
    inventory is cut off at the wall-clock fetch deadline instead of
    wedging the download worker for chunk_count * request_timeout."""
    from fisco_bcos_tpu.snapshot import importer as imp

    node = solo_node
    commit_blocks(node, 2)
    manifest, chunks = export_snapshot(node.storage, node.ledger, node.suite,
                                       chunk_bytes=256)
    verify = make_verify_seals(node.suite, [node.keypair.pub_bytes])

    class Front:
        def __init__(self):
            self.chunk_requests = 0

        def request(self, module, peer, payload, timeout=5.0):
            from fisco_bcos_tpu.codec.wire import Reader
            r = Reader(payload)
            op, height, index = r.u8(), r.i64(), r.u32()
            if op == imp.OP_MANIFEST:
                return manifest.encode()
            self.chunk_requests += 1
            return chunks[index]

    # an already-expired deadline models the dribbling peer: abort before
    # a single chunk is fetched, so the caller can move to another peer
    monkeypatch.setattr(imp, "SNAP_FETCH_MIN_SECONDS", -1.0)
    monkeypatch.setattr(imp, "MIN_FETCH_BYTES_PER_SEC", float("inf"))
    front = Front()
    fresh = MemoryStorage()
    assert imp.snap_sync(front, b"P" * 64, fresh, node.suite, verify,
                         current_number=-1) is None
    assert front.chunk_requests == 0


def test_snap_sync_aborts_on_stop_signal(solo_node):
    """Review fix: the chunk-fetch loop must yield to shutdown — a stop
    signal raised mid-fetch aborts before the next chunk, and one raised
    after the fetch aborts BEFORE any storage write (an abandoned download
    thread must never commit into a WAL the daemon already closed)."""
    from fisco_bcos_tpu.snapshot import importer as imp

    node = solo_node
    commit_blocks(node, 2)
    manifest, chunks = export_snapshot(node.storage, node.ledger, node.suite,
                                       chunk_bytes=256)
    assert manifest.chunk_count > 1
    verify = make_verify_seals(node.suite, [node.keypair.pub_bytes])

    class Front:
        def __init__(self):
            self.chunk_requests = 0

        def request(self, module, peer, payload, timeout=5.0):
            from fisco_bcos_tpu.codec.wire import Reader
            r = Reader(payload)
            op, height, index = r.u8(), r.i64(), r.u32()
            if op == imp.OP_MANIFEST:
                return manifest.encode()
            self.chunk_requests += 1
            return chunks[index]

    # stop raised before the first chunk: zero fetches, nothing installed
    front = Front()
    fresh = MemoryStorage()
    assert imp.snap_sync(front, b"P" * 64, fresh, node.suite, verify,
                         current_number=-1,
                         should_abort=lambda: True) is None
    assert front.chunk_requests == 0
    assert not list(fresh.keys("s_current_state"))
    # stop raised after the last chunk: fetch completes but the install
    # must still bail before touching storage
    front = Front()
    fresh = MemoryStorage()
    polls = []

    def abort_after_fetch():
        polls.append(True)
        return len(polls) > manifest.chunk_count  # True only pre-install

    assert imp.snap_sync(front, b"P" * 64, fresh, node.suite, verify,
                         current_number=-1,
                         should_abort=abort_after_fetch) is None
    assert front.chunk_requests == manifest.chunk_count
    assert not list(fresh.keys("s_current_state"))


def test_prune_sweeps_in_bounded_batches(solo_node, monkeypatch):
    """The first prune of a long chain must not hold every historical tx
    hash in one remove_batch (O(history) memory + one giant WAL record) —
    the sweep runs in PRUNE_SWEEP_BLOCKS rounds, same end state."""
    from fisco_bcos_tpu.ledger.ledger import T_NUM2TXS

    node = solo_node
    commit_blocks(node, 4)
    head = node.ledger.current_number()
    monkeypatch.setattr(type(node.ledger), "PRUNE_SWEEP_BLOCKS", 1)
    calls = []
    orig = node.ledger.storage.remove_batch

    def counting(table, keys, _o=orig):
        calls.append((table, len(keys)))
        return _o(table, keys)

    monkeypatch.setattr(node.ledger.storage, "remove_batch", counting)
    assert node.ledger.prune_block_data(head, keep_nonces=0) == head - 1
    rounds = [n for t, n in calls if t == T_NUM2TXS]
    assert rounds == [1] * (head - 1)  # bounded rounds, never one sweep
    for n in range(1, head):
        assert node.ledger.tx_hashes_by_number(n) == []
        assert node.ledger.nonces_by_number(n) == []
    assert node.ledger.tx_hashes_by_number(head)


def test_txpool_reconciled_after_snap_install(solo_node):
    """A tx the snapshot's chain already committed must leave the joiner's
    pool after the install jump (and its nonce must stay rejected) — the
    per-block commit notifications never ran for the jumped range."""
    node = solo_node
    commit_blocks(node, 2)
    manifest, chunks = export_snapshot(node.storage, node.ledger, node.suite)
    committed_hash = node.ledger.tx_hashes_by_number(1)[0]
    committed_tx = node.ledger.transaction(committed_hash)

    joiner = Node(NodeConfig(crypto_backend="host"), suite=node.suite)
    joiner.build_genesis([ConsensusNode(node.keypair.pub_bytes)])
    res = joiner.txpool.submit(committed_tx)  # pending on the joiner
    assert res.status == 0
    # a second pending tx the snapshot chain does NOT contain: it must
    # survive the reconciliation WITH its nonce still blocking duplicates
    kp2 = node.suite.generate_keypair(b"still-pending")
    fresh_tx = Transaction(to=pc.BALANCE_ADDRESS,
                           input=pc.encode_call(
                               "register",
                               lambda w: w.blob(b"fresh").u64(1)),
                           nonce="keep-me",
                           block_limit=500).sign(node.suite, kp2)
    assert joiner.txpool.submit(fresh_tx).status == 0
    assert joiner.txpool.pending_count() == 2

    verify = make_verify_seals(node.suite, [node.keypair.pub_bytes])
    install_snapshot(manifest, chunks, joiner.storage, node.suite, verify)
    joiner.scheduler.external_commit(manifest.height)
    assert joiner.txpool.pending_count() == 1  # fresh_tx survived
    rc = joiner.txpool.wait_for_receipt(committed_hash, 5)
    assert rc is not None and rc.status == 0  # waiter settled from ledger
    # nonce filter rebuilt from the installed nonce tables: resubmitting
    # the already-committed tx is refused
    from fisco_bcos_tpu.protocol import TransactionStatus
    dup = node.ledger.transaction(committed_hash)
    assert joiner.txpool.submit(dup).status in (
        TransactionStatus.NONCE_CHECK_FAIL, TransactionStatus.ALREADY_KNOWN)
    # review fix: the surviving pending tx's nonce must also still be in
    # the rebuilt filter — a conflicting tx reusing it is refused
    conflict = Transaction(to=pc.BALANCE_ADDRESS,
                           input=pc.encode_call(
                               "register",
                               lambda w: w.blob(b"conflict").u64(2)),
                           nonce="keep-me",
                           block_limit=500).sign(node.suite, kp2)
    assert joiner.txpool.submit(conflict).status == \
        TransactionStatus.NONCE_CHECK_FAIL


# -- store ------------------------------------------------------------------

def test_store_fs_roundtrip_and_retention(tmp_path):
    store = SnapshotStore(str(tmp_path / "snaps"))
    for h in (4, 8, 12):
        m = SnapshotManifest(height=h, header_bytes=b"hdr", root=b"r" * 32,
                             chunk_hashes=[b"h" * 32], total_bytes=3)
        store.save(m, [b"abc"])
    assert store.heights() == [4, 8, 12]
    assert store.latest_height() == 12
    assert store.manifest(8).height == 8
    assert store.chunk(8, 0) == b"abc"
    assert store.chunk(8, 1) is None
    assert store.retain(2) == [4]
    assert store.heights() == [8, 12]
    # reopen: crash-swept, same content
    store2 = SnapshotStore(str(tmp_path / "snaps"))
    assert store2.heights() == [8, 12]
    assert store2.chunk(12, 0) == b"abc"


def test_store_memory_mode():
    store = SnapshotStore(None)
    m = SnapshotManifest(height=2, header_bytes=b"h", root=b"r" * 32,
                         chunk_hashes=[b"h" * 32], total_bytes=1)
    store.save(m, [b"x"])
    assert store.latest().height == 2
    assert store.chunk(2, 0) == b"x"
    store.retain(0)
    assert store.heights() == []


# -- pruning + worker -------------------------------------------------------

def test_prune_keeps_headers_drops_bodies(solo_node):
    node = solo_node
    commit_blocks(node, 3)
    head = node.ledger.current_number()
    tx_hash = node.ledger.tx_hashes_by_number(1)[0]
    # head-1 body rows swept (genesis has no body row); keep_nonces=0 so
    # the nonce sweep is visible at this tiny height (the retention window
    # has its own test below)
    assert node.ledger.prune_block_data(head, keep_nonces=0) == head - 1
    assert node.ledger.pruned_below() == head
    assert node.ledger.prune_block_data(head, keep_nonces=0) == 0
    for n in range(1, head):
        assert node.ledger.header_by_number(n) is not None
        assert node.ledger.tx_hashes_by_number(n) == []
        assert node.ledger.nonces_by_number(n) == []
    assert node.ledger.transaction(tx_hash) is None
    assert node.ledger.receipt(tx_hash) is None
    # head block's own body is kept
    assert node.ledger.tx_hashes_by_number(head)


def test_prune_nonce_retention_window(solo_node):
    """Nonce rows outlive pruned bodies by keep_nonces blocks: the txpool's
    duplicate-nonce filter is rebuilt from T_NONCES after a snap jump, so
    a recently-committed tx must not become re-admittable."""
    node = solo_node
    commit_blocks(node, 4)
    head = node.ledger.current_number()
    assert node.ledger.prune_block_data(head, keep_nonces=2) == head - 1
    for n in range(1, head):
        assert node.ledger.tx_hashes_by_number(n) == []  # bodies swept
    kept = [n for n in range(1, head) if node.ledger.nonces_by_number(n)]
    assert kept == list(range(max(1, head - 2), head))
    assert node.ledger.prune_block_data(head, keep_nonces=2) == 0


def test_checkpoint_keep_tail_leaves_replay_window(solo_node):
    """Pruning stops keep_tail blocks below the checkpoint, so a peer only
    a few blocks behind catches up by cheap tail replay instead of being
    forced into a full O(state) snap-sync."""
    from fisco_bcos_tpu.snapshot.service import SnapshotService
    node = solo_node
    commit_blocks(node, 5)
    head = node.ledger.current_number()
    svc = SnapshotService(node.storage, node.ledger, node.suite,
                          prune=True, keep_tail=2)
    manifest = svc.checkpoint()
    assert manifest.height == head
    assert node.ledger.pruned_below() == head - 2
    for n in range(head - 2, head + 1):  # the tail stays replayable
        assert node.ledger.tx_hashes_by_number(n)


def test_snapshot_worker_checkpoints_prunes_and_retains(tmp_path):
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           storage_path=str(tmp_path / "data"),
                           snapshot_interval=2, snapshot_retention=1,
                           snapshot_prune=True, snapshot_keep_tail=0,
                           snapshot_chunk_bytes=1024))
    node.start()
    try:
        commit_blocks(node, 2)
        assert wait_until(
            lambda: node.snapshot.store.latest_height() is not None)
        commit_blocks(node, 2, start=10)
        assert wait_until(
            lambda: (node.snapshot.store.latest_height() or 0) >= 4
            and len(node.snapshot.store.heights()) == 1
            and node.ledger.pruned_below()
            == node.snapshot.store.latest_height(), timeout=20)
        st = node.snapshot.status()
        assert st["enabled"] and st["prune"]
        assert st["lastSnapshotNumber"] == node.snapshot.store.latest_height()
        assert st["prunedBelow"] > 0
    finally:
        node.stop()
    # WAL compaction after prune: a reboot comes back at the same height
    node2 = Node(NodeConfig(crypto_backend="host",
                            storage_path=str(tmp_path / "data")))
    assert node2.ledger.current_number() >= 4
    assert node2.ledger.pruned_below() > 0


def test_get_snapshot_status_rpc(solo_node):
    from fisco_bcos_tpu.rpc.server import JsonRpcImpl
    node = solo_node
    commit_blocks(node, 1)
    node.snapshot.checkpoint()
    impl = JsonRpcImpl(node)
    resp = impl.handle({"jsonrpc": "2.0", "id": 1,
                        "method": "getSnapshotStatus",
                        "params": [node.config.group_id, ""]})
    st = resp["result"]
    assert st["lastSnapshotNumber"] == node.ledger.current_number()
    assert st["syncMode"] == "replay"  # no gateway: never snap-synced
    assert st["root"].startswith("0x")


# -- snap-sync join (in-process, full network path) -------------------------

def _single_sealer_chain(tmp_path=None, **cfg):
    suite = make_suite(backend="host")
    gw = FakeGateway()
    kp = suite.generate_keypair(b"\x01" * 16)
    sealers = [ConsensusNode(kp.pub_bytes)]
    node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                           min_seal_time=0.0, **cfg),
                keypair=kp, gateway=gw)
    node.build_genesis(sealers)
    node.start()
    return suite, gw, node, sealers


def test_snap_sync_join_and_pruned_peer_serves():
    """A far-behind joiner snap-syncs from a PRUNED peer: manifest + chunks
    over SnapshotSync, one batched verify, tail replay only — and the
    joiner adopts the snapshot so it can serve the next joiner."""
    suite, gw, src, sealers = _single_sealer_chain(
        snapshot_interval=3, snapshot_prune=True, snapshot_keep_tail=0,
        snapshot_chunk_bytes=1024)
    joiners = []
    try:
        commit_blocks(src, 6)
        assert wait_until(
            lambda: (src.snapshot.store.latest_height() or 0) >= 3
            and src.ledger.pruned_below() > 0, timeout=20)
        floor = src.ledger.pruned_below()

        obs = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                              snap_sync_threshold=2),
                   keypair=suite.generate_keypair(b"obs-1"), gateway=gw)
        obs.build_genesis(sealers)
        replayed = []
        orig_exec = obs.scheduler.execute_block

        def traced(block, *a, _orig=orig_exec, **kw):
            replayed.append(block.header.number)
            return _orig(block, *a, **kw)

        obs.scheduler.execute_block = traced
        obs.start()
        joiners.append(obs)
        assert wait_until(lambda: obs.ledger.current_number()
                          >= src.ledger.current_number(), timeout=40)
        assert obs.blocksync.sync_mode == "snap"
        # NO pruned block was replayed — only the tail above the checkpoint
        assert replayed == [] or min(replayed) > floor
        h = src.ledger.current_number()
        assert (obs.ledger.header_by_number(h).hash(suite)
                == src.ledger.header_by_number(h).hash(suite))
        assert (obs.ledger.header_by_number(h).state_root
                == src.ledger.header_by_number(h).state_root)
        # the joiner adopted the snapshot and can now serve it itself
        assert obs.snapshot.store.latest_height() == floor
    finally:
        for j in joiners:
            j.stop()
        src.stop()
        gw.stop()


def test_snap_sync_threshold_zero_keeps_replay():
    suite, gw, src, sealers = _single_sealer_chain(
        snapshot_interval=2, snapshot_chunk_bytes=1024)
    obs = None
    try:
        commit_blocks(src, 3)
        assert wait_until(
            lambda: src.snapshot.store.latest_height() is not None)
        obs = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                              snap_sync_threshold=0),
                   keypair=suite.generate_keypair(b"obs-2"), gateway=gw)
        obs.build_genesis(sealers)
        obs.start()
        assert wait_until(lambda: obs.ledger.current_number()
                          >= src.ledger.current_number(), timeout=40)
        assert obs.blocksync.sync_mode == "replay"
    finally:
        if obs is not None:
            obs.stop()
        src.stop()
        gw.stop()


# -- quorum-certificate checkpoint binding ----------------------------------

def make_cert_node():
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           seal_mode="cert"))
    node.start()
    return node


def make_qc_verify(suite, sealer_pubs):
    """The production import-side judge: qc.verify_spans, the same ONE
    seal admission path sync and the light client ride."""
    from fisco_bcos_tpu.consensus import qc

    def verify(header):
        return bool(qc.verify_spans([header], sorted(sealer_pubs),
                                    suite)[0])

    return verify


class _CountingSuite:
    def __init__(self, suite):
        self._suite = suite
        self.verify_calls = 0

    def __getattr__(self, name):
        return getattr(self._suite, name)

    def verify_batch(self, digests, sigs, pubs):
        self.verify_calls += 1
        return self._suite.verify_batch(digests, sigs, pubs)


def test_cert_mode_snapshot_installs_with_one_lane_call():
    """A seal_mode=cert chain's snapshot manifest binds the checkpoint
    QuorumCert; install verifies it as exactly ONE verify_batch call."""
    from fisco_bcos_tpu.consensus import qc

    node = make_cert_node()
    try:
        commit_blocks(node, 2)
        manifest, chunks = export_snapshot(node.storage, node.ledger,
                                           node.suite, chunk_bytes=512)
        header = BlockHeader.decode(manifest.header_bytes)
        cert = qc.extract(header)
        assert cert is not None and cert.mode == qc.MODE_CERT
        counting = _CountingSuite(node.suite)
        fresh = MemoryStorage()
        verify = make_qc_verify(counting, [node.keypair.pub_bytes])
        installed = install_snapshot(manifest, chunks, fresh, node.suite,
                                     verify)
        assert installed.number == manifest.height
        assert counting.verify_calls == 1
        led2 = Ledger(fresh, node.suite)
        assert led2.current_number() == manifest.height
    finally:
        node.stop()


def test_forged_checkpoint_cert_rejected_whole():
    """Tampering the manifest-bound certificate (payload bit-flip OR
    sentinel-mixing loose seals into the carriage) fails install."""
    from fisco_bcos_tpu.consensus import qc

    node = make_cert_node()
    try:
        commit_blocks(node, 2)
        manifest, chunks = export_snapshot(node.storage, node.ledger,
                                           node.suite, chunk_bytes=512)
        verify = make_qc_verify(node.suite, [node.keypair.pub_bytes])

        header = BlockHeader.decode(manifest.header_bytes)
        cert = qc.extract(header)
        cert.payload = bytes([cert.payload[0] ^ 1]) + cert.payload[1:]
        qc.attach(header, cert)
        m_tampered = SnapshotManifest(
            height=manifest.height, header_bytes=header.encode(),
            root=manifest.root, chunk_hashes=manifest.chunk_hashes,
            total_bytes=manifest.total_bytes)
        fresh = MemoryStorage()
        with pytest.raises(SnapshotVerifyError):
            install_snapshot(m_tampered, chunks, fresh, node.suite, verify)

        header2 = BlockHeader.decode(manifest.header_bytes)
        header2.signature_list = (header2.signature_list
                                  + [(0, b"\x00" * 65)])
        m_mixed = SnapshotManifest(
            height=manifest.height, header_bytes=header2.encode(),
            root=manifest.root, chunk_hashes=manifest.chunk_hashes,
            total_bytes=manifest.total_bytes)
        with pytest.raises(SnapshotVerifyError):
            install_snapshot(m_mixed, chunks, fresh, node.suite, verify)

        # the untampered manifest still installs cleanly afterwards
        install_snapshot(manifest, chunks, fresh, node.suite, verify)
    finally:
        node.stop()
