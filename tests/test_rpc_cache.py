"""Commit-coherent query cache (rpc/cache.py) — amortization + coherence.

The amortization claims: N identical getBlock-with-txs requests cost at
most ONE sender-recover batch (computed at commit or first touch, then
reused), and identical queries serve byte-for-byte identical responses.

The coherence claims (the reason a blockchain can cache at all): a
storage-commit ROLLBACK and a snap-sync SNAPSHOT INSTALL both wipe the
cache before any reader can observe the new state. State is verified via
`call` balance reads and c_balance rows, NOT state_root — the root is
per-changeset, so matching roots do not prove matching state.
"""

import http.client
import json
import time

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.sdk.client import SdkClient


class CountingSuite:
    """Delegating wrapper counting batch-recover crossings (the
    instrument behind the '<= 1 recover batch' assertion)."""

    def __init__(self, suite):
        self._suite = suite
        self.recover_calls = 0

    def __getattr__(self, name):
        return getattr(self._suite, name)

    def recover_addresses(self, hashes, sigs):
        self.recover_calls += 1
        return self._suite.recover_addresses(hashes, sigs)


def _register(node, kp, name: bytes, value: int, nonce: str):
    tx = Transaction(to=pc.BALANCE_ADDRESS,
                     input=pc.encode_call(
                         "register",
                         lambda w: w.blob(name).u64(value)),
                     nonce=nonce, block_limit=100).sign(node.suite, kp)
    rc = node.txpool.wait_for_receipt(node.send_transaction(tx).tx_hash, 30)
    assert rc is not None and rc.status == 0, rc
    return rc


def _balance(client: SdkClient, name: bytes) -> int:
    out = client.call(pc.BALANCE_ADDRESS,
                      pc.encode_call("balanceOf", lambda w: w.blob(name)))
    assert out["status"] == 0, out
    from fisco_bcos_tpu.codec.wire import Reader
    return Reader(bytes.fromhex(out["output"][2:])).u64()


def _post_fixed_id(node, method: str, params: list, rid: int = 424242
                   ) -> bytes:
    """Raw POST with a FIXED request id -> full response body bytes (the
    byte-for-byte comparison needs identical envelopes)."""
    body = json.dumps({"jsonrpc": "2.0", "id": rid, "method": method,
                       "params": params}).encode()
    conn = http.client.HTTPConnection(node.rpc.host, node.rpc.port,
                                      timeout=30)
    try:
        conn.request("POST", "/", body=body,
                     headers={"Content-Type": "application/json"})
        return conn.getresponse().read()
    finally:
        conn.close()


def test_identical_getblock_requests_cost_at_most_one_recover():
    """Satellite: the per-request `batch_recover_senders` is gone — the
    senders row is rendered once (commit prime or first touch) and N
    identical getBlockByNumber --includeTxs requests reuse it."""
    counting = CountingSuite(make_suite(False, backend="host"))
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           rpc_port=0), suite=counting)
    node.start()
    try:
        kp = counting.generate_keypair(b"cache-recover")
        txs = [Transaction(to=pc.BALANCE_ADDRESS,
                           input=pc.encode_call(
                               "register",
                               lambda w, i=i: w.blob(b"cr%d" % i).u64(1)),
                           nonce=f"cr-{i}", block_limit=100
                           ).sign(counting, kp) for i in range(8)]
        node.txpool.submit_batch(txs)
        hashes = [tx.hash(counting) for tx in txs]
        for h in hashes:
            assert node.txpool.wait_for_receipt(h, 30) is not None
        head = node.ledger.current_number()
        assert head >= 1
        time.sleep(0.3)  # let the commit-prime observer finish rendering
        sdk = SdkClient(f"http://{node.rpc.host}:{node.rpc.port}")
        counting.recover_calls = 0
        blocks = [sdk.get_block_by_number(head) for _ in range(6)]
        assert all(b == blocks[0] for b in blocks)
        assert any("from" in tj for tj in blocks[0]["transactions"])
        assert counting.recover_calls <= 1, (
            f"{counting.recover_calls} recover batches for 6 identical "
            "getBlock requests — sender recovery is not amortized")
        stats = node.query_cache.stats()
        assert stats["hits"] >= 5
    finally:
        node.stop()


def test_commit_prime_reuses_admission_senders():
    """Priming must not re-pay the recover the admission batch already
    ran: the scheduler hands its LIVE (sender-populated) tx objects to
    prime_block, so submit -> seal -> commit -> prime costs exactly ONE
    recover batch end to end."""
    counting = CountingSuite(make_suite(False, backend="host"))
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           rpc_port=0), suite=counting)
    node.start()
    try:
        kp = counting.generate_keypair(b"prime-reuse")
        counting.recover_calls = 0
        txs = [Transaction(to=pc.BALANCE_ADDRESS,
                           input=pc.encode_call(
                               "register",
                               lambda w, i=i: w.blob(b"pr%d" % i).u64(1)),
                           nonce=f"pr-{i}", block_limit=100
                           ).sign(counting, kp) for i in range(6)]
        # submit WIRE-decoded copies (sign() pre-populates _sender on the
        # local objects; a real client's txs arrive sender-less)
        txs = [Transaction.decode(tx.encode()) for tx in txs]
        node.txpool.submit_batch(txs)
        for tx in txs:
            assert node.txpool.wait_for_receipt(tx.hash(counting), 30)
        deadline = time.monotonic() + 5
        head = node.ledger.current_number()
        while time.monotonic() < deadline:  # prime observer settling
            if node.query_cache.get(("senders", head)) is not None:
                break
            time.sleep(0.05)
        assert node.query_cache.get(("senders", head)) is not None, \
            "commit prime never rendered the senders row"
        assert counting.recover_calls == 1, (
            f"{counting.recover_calls} recover batches for submit->commit"
            "->prime — priming re-recovered the admission's senders")
        # and the served block reuses that row too
        sdk = SdkClient(f"http://{node.rpc.host}:{node.rpc.port}")
        blk = sdk.get_block_by_number(head)
        assert all("from" in tj for tj in blk["transactions"])
        assert counting.recover_calls == 1
    finally:
        node.stop()


def test_send_transaction_retry_is_idempotent():
    """A client re-POSTing sendTransaction after a connection reset (the
    SdkClient bounded retry) must get the receipt back, not an
    ALREADY_IN_TXPOOL/ALREADY_KNOWN error — the duplicate statuses are
    benign on this path."""
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           rpc_port=0))
    node.start()
    try:
        kp = node.suite.generate_keypair(b"retry-idem")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"ri").u64(3)),
                         nonce="ri-0", block_limit=100).sign(node.suite, kp)
        sdk = SdkClient(f"http://{node.rpc.host}:{node.rpc.port}")
        wire = "0x" + tx.encode().hex()
        first = sdk.request("sendTransaction", ["group0", "", wire, False])
        assert first["status"] == 0
        # the "retry": same bytes again, after the tx committed
        second = sdk.request("sendTransaction", ["group0", "", wire, False])
        assert second["status"] == 0
        assert second["transactionHash"] == first["transactionHash"]
    finally:
        node.stop()


def test_identical_queries_serve_identical_bytes():
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           rpc_port=0))
    node.start()
    try:
        kp = node.suite.generate_keypair(b"cache-bytes")
        rc = _register(node, kp, b"bytes", 5, "cb-0")
        n = rc.block_number
        r1 = _post_fixed_id(node, "getBlockByNumber",
                            ["group0", "", n, False, False])
        r2 = _post_fixed_id(node, "getBlockByNumber",
                            ["group0", "", n, False, False])
        assert r1 == r2 and b'"result"' in r1
        # receipts too
        tx_hash = json.loads(r1)["result"]["transactions"][0]["hash"]
        a = _post_fixed_id(node, "getTransactionReceipt",
                           ["group0", "", tx_hash, False])
        b = _post_fixed_id(node, "getTransactionReceipt",
                           ["group0", "", tx_hash, False])
        assert a == b
    finally:
        node.stop()


def test_no_stale_read_after_commit_rollback():
    """Satellite: a storage 2PC rollback invalidates the cache (the
    scheduler's on_invalidate hook), the chain retries and commits, and
    every post-rollback read reflects the really-committed state
    (balance spot-checks via RPC `call`)."""
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           rpc_port=0))
    node.start()
    try:
        kp = node.suite.generate_keypair(b"cache-rb")
        _register(node, kp, b"rb-a", 7, "rb-0")
        sdk = SdkClient(f"http://{node.rpc.host}:{node.rpc.port}")
        blk1 = sdk.get_block_by_number(1)
        assert _balance(sdk, b"rb-a") == 7
        inv0 = node.query_cache.stats()["invalidations"]

        # inject ONE commit failure: scheduler rolls back, solo retries
        orig_commit = node.storage.commit
        state = {"tripped": False}

        def flaky(number):
            if not state["tripped"]:
                state["tripped"] = True
                raise RuntimeError("injected commit failure")
            return orig_commit(number)

        node.storage.commit = flaky
        _register(node, kp, b"rb-b", 9, "rb-1")  # survives the rollback
        node.storage.commit = orig_commit
        assert state["tripped"], "injection never fired"

        assert node.query_cache.stats()["invalidations"] > inv0, \
            "rollback did not invalidate the query cache"
        # post-rollback reads: committed state, not cached pre-rollback junk
        assert _balance(sdk, b"rb-b") == 9
        assert _balance(sdk, b"rb-a") == 7
        blk1_again = sdk.get_block_by_number(1)
        want = node.ledger.header_by_number(1).hash(node.suite)
        assert blk1_again["hash"] == "0x" + want.hex() == blk1["hash"]
    finally:
        node.stop()


def test_no_stale_read_after_snapshot_install():
    """Satellite: a snap-sync install jumps the head over WIPED tables;
    `Scheduler.external_commit` must invalidate the cache so neither the
    block JSON nor the balance reads serve the pre-install chain."""
    from fisco_bcos_tpu.snapshot import export_snapshot, install_snapshot

    suite = make_suite(False, backend="host")
    # source chain A: probe = 42, three blocks committed
    a = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0),
             suite=suite)
    a.start()
    kp_a = suite.generate_keypair(b"snap-a")
    _register(a, kp_a, b"probe", 42, "sa-0")
    _register(a, kp_a, b"other", 1, "sa-1")
    _register(a, kp_a, b"third", 2, "sa-2")
    a.stop()
    a_head = a.ledger.current_number()
    a_hash1 = "0x" + a.ledger.header_by_number(1).hash(suite).hex()
    manifest, chunks = export_snapshot(a.storage, a.ledger, suite,
                                       chunk_bytes=4096)

    # serving node B: DIFFERENT chain, same table names — probe = 7
    b = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                        rpc_port=0), suite=suite)
    b.start()
    try:
        kp_b = suite.generate_keypair(b"snap-b")
        _register(b, kp_b, b"probe", 7, "sb-0")
        assert b.ledger.current_number() < a_head
        sdk = SdkClient(f"http://{b.rpc.host}:{b.rpc.port}")
        # populate the cache with B's chain and verify B's state
        pre = sdk.get_block_by_number(1)
        assert pre["hash"] != a_hash1
        assert _balance(sdk, b"probe") == 7

        # snap-sync install (the sync/sync.py _try_snap_sync sequence:
        # invalidate BEFORE the install commit publishes the new state,
        # install into the LIVE storage, then external_commit — whose
        # second invalidation fences out renders in flight across it)
        b.scheduler.invalidate_caches(b.ledger.current_number())
        install_snapshot(manifest, chunks, b.storage, suite,
                         lambda header: True)
        b.scheduler.external_commit(manifest.height)

        # no stale reads: balance via RPC call AND the c_balance row
        assert _balance(sdk, b"probe") == 42, \
            "stale balance served after snapshot install"
        key = next(iter(a.storage.keys("c_balance")))
        assert b.storage.get("c_balance", key) == \
            a.storage.get("c_balance", key)
        post = sdk.get_block_by_number(1)
        assert post["hash"] == a_hash1, \
            "stale block JSON served after snapshot install"
        assert sdk.get_block_number() == a_head
    finally:
        b.stop()


def test_cache_lru_and_generation_fencing():
    """Unit: LRU eviction respects the entry bound; a put carrying a
    pre-invalidation generation is dropped (in-flight render fencing)."""
    from fisco_bcos_tpu.rpc.cache import QueryCache

    c = QueryCache(max_entries=2)
    g = c.generation()
    c.put("a", {"v": 1}, g)
    c.put("b", {"v": 2}, g)
    assert c.get("a") == {"v": 1}  # refresh a: b is now LRU
    c.put("c", {"v": 3}, g)
    assert c.get("b") is None and c.get("a") is not None
    # generation fencing
    stale_gen = c.generation()
    c.invalidate()
    c.put("d", {"v": 4}, stale_gen)
    assert c.get("d") is None, "stale-generation render entered the cache"
    c.put("e", {"v": 5}, c.generation())
    assert c.get("e") == {"v": 5}
    stats = c.stats()
    assert stats["invalidations"] == 1 and stats["entries"] == 1
