"""Golden-value tests: TPU EC kernels vs the pure-Python oracle.

SURVEY §4: "golden-value crypto tests CPU<->TPU (same sigs must verify
identically)". Runs on the CPU backend (conftest) with tiny batches; the
same kernels run unchanged on TPU.
"""

import numpy as np
import pytest

from fisco_bcos_tpu.crypto import refimpl
from fisco_bcos_tpu.ops import bigint, ec, fp


def _limbs_col(xs):
    """ints -> lane-major [NLIMBS, B] uint32."""
    return np.stack([fp.to_limbs(int(x)) for x in xs], axis=1)


def _from_col(a):
    return [fp.from_limbs_np(np.asarray(a)[:, i]) for i in range(a.shape[1])]


# ---------------------------------------------------------------------------
# field arithmetic vs Python ints
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("field,mod", [
    (ec.SECP256K1.fp, refimpl.SECP256K1.p),
    (ec.SM2P256V1.fp, refimpl.SM2P256V1.p),
    (ec.SECP256K1.fn, refimpl.SECP256K1.n),
    (ec.SM2P256V1.fn, refimpl.SM2P256V1.n),
])
def test_field_ops_golden(field, mod):
    rng = np.random.default_rng(42)
    xs = [int.from_bytes(rng.bytes(32), "big") % mod for _ in range(6)]
    ys = [int.from_bytes(rng.bytes(32), "big") % mod for _ in range(6)]
    xs[0], ys[0] = 0, 0
    xs[1], ys[1] = mod - 1, mod - 1
    a, b = _limbs_col(xs), _limbs_col(ys)

    got = _from_col(field.add(a, b))
    assert got == [(x + y) % mod for x, y in zip(xs, ys)]
    got = _from_col(field.sub(a, b))
    assert got == [(x - y) % mod for x, y in zip(xs, ys)]
    got = _from_col(field.neg(a))
    assert got == [(-x) % mod for x in xs]
    got = _from_col(field.half(a))
    assert got == [x * pow(2, -1, mod) % mod for x in xs]

    # mul/inv in the internal domain: encode -> op -> decode
    ar = np.stack([field.encode_int(x) for x in xs], axis=1)
    br = np.stack([field.encode_int(y) for y in ys], axis=1)
    got = _from_col(field.from_rep(field.mul(ar, br)))
    assert got == [x * y % mod for x, y in zip(xs, ys)]
    inv_in = [x if x else 1 for x in xs]  # 0 has no inverse
    ar2 = np.stack([field.encode_int(x) for x in inv_in], axis=1)
    got = _from_col(field.from_rep(field.inv(ar2)))
    assert got == [pow(x, -1, mod) for x in inv_in]


def test_reduce_loose_and_to_rep():
    f = ec.SECP256K1.fp
    mod = refimpl.SECP256K1.p
    vals = [0, 1, mod - 1, mod, mod + 12345, (1 << 256) - 1]
    a = _limbs_col(vals)
    got = _from_col(f.from_rep(f.to_rep(a)))
    assert got == [v % mod for v in vals]
    fn = ec.SECP256K1.fn
    got = _from_col(fn.from_rep(fn.to_rep(a)))
    assert got == [v % refimpl.SECP256K1.n for v in vals]


# ---------------------------------------------------------------------------
# ECDSA verify / recover vs oracle
# ---------------------------------------------------------------------------

def _sign_batch(params, count, seed=0):
    rng = np.random.default_rng(seed)
    es, rs, ss, vs, pubs = [], [], [], [], []
    for i in range(count):
        sk, pub = refimpl.keygen(params, bytes([seed + i + 1]) * 32)
        digest = refimpl.keccak256(rng.bytes(48))
        r, s, v = refimpl.ecdsa_sign(params, sk, digest)
        es.append(int.from_bytes(digest, "big"))
        rs.append(r)
        ss.append(s)
        vs.append(v)
        pubs.append(pub)
    return es, rs, ss, vs, pubs


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_ecdsa_verify_batch_golden():
    params = refimpl.SECP256K1
    es, rs, ss, vs, pubs = _sign_batch(params, 5)
    # adversarial rows: bad s; swapped pub; r = 0; off-curve pub; r >= n
    es2 = es + [es[0], es[1], es[2], es[3], es[4]]
    rs2 = rs + [rs[0], rs[1], 0, rs[3], params.n + 5]
    ss2 = ss + [(ss[0] + 1) % params.n, ss[1], ss[2], ss[3], ss[4]]
    pubs2 = pubs + [pubs[0], pubs[2], pubs[2], (pubs[3][0], pubs[3][1] ^ 1),
                    pubs[4]]
    e = ec.limbs(es2)
    r = ec.limbs(rs2)
    s = ec.limbs(ss2)
    qx = ec.limbs([p[0] for p in pubs2])
    qy = ec.limbs([p[1] for p in pubs2])
    ok = np.asarray(ec.ecdsa_verify_batch(ec.SECP256K1, e, r, s, qx, qy))
    want = [refimpl.ecdsa_verify(params, p, int(d).to_bytes(32, "big"), rr, sv)
            for p, d, rr, sv in zip(pubs2, es2, rs2, ss2)]
    assert ok.tolist() == want
    assert ok.tolist() == [True] * 5 + [False] * 5


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_ecdsa_recover_batch_golden():
    params = refimpl.SECP256K1
    es, rs, ss, vs, pubs = _sign_batch(params, 6, seed=9)
    # two bad rows: v out of range; s = 0
    es2 = es + [es[0], es[1]]
    rs2 = rs + [rs[0], rs[1]]
    ss2 = ss + [ss[0], 0]
    vs2 = vs + [255, vs[1]]
    e = ec.limbs(es2)
    r = ec.limbs(rs2)
    s = ec.limbs(ss2)
    v = np.asarray(vs2, np.uint32)
    qx, qy, ok = ec.ecdsa_recover_batch(ec.SECP256K1, e, r, s, v)
    qx, qy, ok = np.asarray(qx), np.asarray(qy), np.asarray(ok)
    assert ok.tolist() == [True] * 6 + [False] * 2
    for i in range(6):
        assert bigint.from_limbs(qx[i]) == pubs[i][0]
        assert bigint.from_limbs(qy[i]) == pubs[i][1]


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_sm2_verify_batch_golden():
    params = refimpl.SM2P256V1
    rng = np.random.default_rng(3)
    es, rs, ss, pubs = [], [], [], []
    for i in range(4):
        sk, pub = refimpl.keygen(params, bytes([i + 40]) * 32)
        digest = refimpl.sm3(rng.bytes(48))
        r, s = refimpl.sm2_sign(sk, digest)
        es.append(int.from_bytes(digest, "big"))
        rs.append(r)
        ss.append(s)
        pubs.append(pub)
    # bad rows: tampered digest; r+s == 0 construction is impractical, use s=0
    es2 = es + [es[0] ^ 1, es[1]]
    rs2 = rs + [rs[0], rs[1]]
    ss2 = ss + [ss[0], 0]
    pubs2 = pubs + [pubs[0], pubs[1]]
    e = ec.limbs(es2)
    r = ec.limbs(rs2)
    s = ec.limbs(ss2)
    qx = ec.limbs([p[0] for p in pubs2])
    qy = ec.limbs([p[1] for p in pubs2])
    ok = np.asarray(ec.sm2_verify_batch(ec.SM2P256V1, e, r, s, qx, qy))
    want = [refimpl.sm2_verify(p, int(d).to_bytes(32, "big"), rr, sv)
            for p, d, rr, sv in zip(pubs2, es2, rs2, ss2)]
    assert ok.tolist() == want
    assert ok.tolist() == [True] * 4 + [False] * 2


def test_glv_split_device_matches_oracle():
    """Device GLV decomposition: identity k1 + lambda*k2 == k (mod n) and
    signed magnitudes within the 34-window budget, vs refimpl.glv_split."""
    import jax
    import jax.numpy as jnp
    from fisco_bcos_tpu.ops.ec import _glv_split_device

    cv = ec.SECP256K1
    assert cv.has_endo
    n = cv.params.n
    rng = np.random.default_rng(17)
    ks = [int.from_bytes(rng.bytes(32), "big") % n for _ in range(6)] + [0, 1]
    k = jnp.transpose(ec.limbs(ks))
    m1, n1, m2, n2 = jax.jit(lambda kk: _glv_split_device(cv, kk))(k)
    m1, n1 = np.asarray(m1), np.asarray(n1)
    m2, n2 = np.asarray(m2), np.asarray(n2)
    for i, kv in enumerate(ks):
        mag1 = int(bigint.from_limbs(m1[:, i]))
        mag2 = int(bigint.from_limbs(m2[:, i]))
        k1 = n - mag1 if n1[i] else mag1
        k2 = n - mag2 if n2[i] else mag2
        assert (k1 + k2 * refimpl.GLV_LAMBDA) % n == kv
        assert mag1.bit_length() <= 4 * ec.GLV_DIGITS
        assert mag2.bit_length() <= 4 * ec.GLV_DIGITS
        # the device decomposition IS the documented mul-shift formula:
        # it must agree with the host oracle exactly, not just satisfy
        # the identity
        ok1, ok2 = refimpl.glv_split(kv)
        assert (k1 % n, k2 % n) == (ok1, ok2)


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_glv_ladder_matches_plain_shamir():
    """The endomorphism ladder and the plain Shamir ladder compute the
    same affine points for random (k1, k2, Q)."""
    import jax
    import jax.numpy as jnp
    from fisco_bcos_tpu.ops.ec import (_unpack, glv_shamir_mult,
                                       shamir_mult)

    cv = ec.SECP256K1
    params = cv.params
    rng = np.random.default_rng(23)
    k1s, k2s, qxs, qys = [], [], [], []
    for i in range(4):
        _, pub = refimpl.keygen(params, bytes([i + 70]) * 32)
        k1s.append(int.from_bytes(rng.bytes(32), "big") % params.n)
        k2s.append(int.from_bytes(rng.bytes(32), "big") % params.n)
        qxs.append(pub[0])
        qys.append(pub[1])
    # edge rows: zero scalars
    k1s += [0, 5]
    k2s += [7, 0]
    qxs += qxs[:2]
    qys += qys[:2]

    k1 = jnp.transpose(ec.limbs(k1s))
    k2 = jnp.transpose(ec.limbs(k2s))
    qx = cv.fp.to_rep(jnp.transpose(ec.limbs(qxs)))
    qy = cv.fp.to_rep(jnp.transpose(ec.limbs(qys)))

    def affine(P):
        X, Y, Z = _unpack(P)
        X, Y, Z = (np.asarray(v) for v in (X, Y, Z))
        out = []
        f = cv.fp
        for i in range(X.shape[-1]):
            xi = int(bigint.from_limbs(np.asarray(
                f.from_rep(X[:, i:i + 1]))[:, 0]))
            yi = int(bigint.from_limbs(np.asarray(
                f.from_rep(Y[:, i:i + 1]))[:, 0]))
            zi = int(bigint.from_limbs(np.asarray(
                f.from_rep(Z[:, i:i + 1]))[:, 0]))
            if zi == 0:
                out.append(None)
                continue
            zinv = pow(zi, -1, params.p)
            out.append((xi * zinv * zinv % params.p,
                        yi * zinv * zinv * zinv % params.p))
        return out

    Pg = jax.jit(lambda *a: glv_shamir_mult(cv, *a))(k1, k2, qx, qy)
    Pp = jax.jit(lambda *a: shamir_mult(cv, *a))(k1, k2, qx, qy)
    got, want = affine(Pg), affine(Pp)
    assert got == want
    # and against the host oracle
    for i in range(len(k1s)):
        exp = refimpl.ec_add(
            params,
            refimpl.ec_mul(params, k1s[i], (params.gx, params.gy)),
            refimpl.ec_mul(params, k2s[i], (qxs[i], qys[i])))
        assert got[i] == exp
