"""End-to-end slice (SURVEY §7 step 5): submit -> txpool validate -> seal ->
execute (precompiles, DAG) -> Merkle roots -> 2PC commit -> receipts/proofs.

Host crypto backend keeps this fast; kernel golden tests cover the device
paths separately. Mirrors the reference's module tests with fakes
(bcos-framework testutils/faker) driving real txpool/sealer/scheduler."""

import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.executor.executor import TransactionExecutor
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import Ledger
from fisco_bcos_tpu.ops import merkle as merkle_mod
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage


def make_tx(suite, kp, to, payload, nonce, block_limit=100):
    return Transaction(to=to, input=payload, nonce=nonce,
                       block_limit=block_limit).sign(suite, kp)


@pytest.fixture()
def node():
    n = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0))
    n.start()
    yield n
    n.stop()


def wait_until(pred, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_solo_chain_transfer_flow(node):
    suite = node.suite
    kp = suite.generate_keypair(b"alice")
    reg = make_tx(suite, kp, pc.BALANCE_ADDRESS,
                  pc.encode_call("register",
                                 lambda w: w.blob(b"alice").u64(1000)),
                  nonce="r1")
    res = node.send_transaction(reg)
    assert res.status == TransactionStatus.OK
    rc = node.txpool.wait_for_receipt(res.tx_hash, timeout=10)
    assert rc is not None and rc.status == 0

    reg2 = make_tx(suite, kp, pc.BALANCE_ADDRESS,
                   pc.encode_call("register",
                                  lambda w: w.blob(b"bob").u64(10)),
                   nonce="r2")
    xfer = make_tx(suite, kp, pc.BALANCE_ADDRESS,
                   pc.encode_call("transfer",
                                  lambda w: w.blob(b"alice").blob(b"bob").u64(250)),
                   nonce="x1")
    r2 = node.txpool.submit_batch([reg2, xfer])
    assert all(r.status == TransactionStatus.OK for r in r2)
    rc2 = node.txpool.wait_for_receipt(r2[1].tx_hash, timeout=10)
    assert rc2 is not None

    # read balance via call
    q = Transaction(to=pc.BALANCE_ADDRESS,
                    input=pc.encode_call("balanceOf", lambda w: w.blob(b"bob")))
    out = node.call(q)
    assert out.status == 0
    from fisco_bcos_tpu.codec.wire import Reader
    assert Reader(out.output).u64() == 260

    # chain advanced; block structure checks
    n = node.ledger.current_number()
    assert n >= 1
    hdr = node.ledger.header_by_number(1)
    assert hdr is not None
    assert hdr.parent_info[0].number == 0
    # hash->number index must hold the FINAL header hash (post state-root)
    assert node.ledger.number_by_hash(hdr.hash(suite)) == 1
    blk = node.ledger.block_by_number(1)
    assert blk.header.txs_root == blk.calculate_txs_root(suite)
    # commit seal present and valid (solo signs its own header)
    assert hdr.signature_list
    idx, sig = hdr.signature_list[0]
    assert suite.verify(node.keypair.pub_bytes, hdr.hash(suite), sig)


def test_receipt_and_tx_merkle_proofs(node):
    suite = node.suite
    kp = suite.generate_keypair(b"proofacct")
    txs = [make_tx(suite, kp, pc.BALANCE_ADDRESS,
                   pc.encode_call("register",
                                  lambda w, i=i: w.blob(f"acct{i}".encode()).u64(5)),
                   nonce=f"p{i}") for i in range(6)]
    results = node.txpool.submit_batch(txs)
    assert all(r.status == TransactionStatus.OK for r in results)
    assert node.txpool.wait_for_receipt(results[-1].tx_hash, 10) is not None

    th = results[2].tx_hash
    proof, root = node.ledger.tx_proof(th)
    leaf = th
    assert merkle_mod.verify_merkle_proof(leaf, proof, root, suite.hash_name)

    rproof, rroot = node.ledger.receipt_proof(th)
    rc = node.ledger.receipt(th)
    assert merkle_mod.verify_merkle_proof(rc.hash(suite), rproof, rroot,
                                          suite.hash_name)


def test_txpool_rejections(node):
    suite = node.suite
    kp = suite.generate_keypair(b"rej")
    good = make_tx(suite, kp, pc.BALANCE_ADDRESS, b"", nonce="g1")
    dup = Transaction.decode(good.encode())
    r1 = node.txpool.submit_batch([good, dup])
    assert r1[0].status == TransactionStatus.OK
    assert r1[1].status == TransactionStatus.ALREADY_IN_TXPOOL

    wrong_chain = Transaction(chain_id="other", nonce="c1", block_limit=100,
                              to=pc.BALANCE_ADDRESS).sign(suite, kp)
    assert node.txpool.submit(wrong_chain).status == TransactionStatus.INVALID_CHAINID

    expired = Transaction(nonce="e1", block_limit=0,
                          to=pc.BALANCE_ADDRESS).sign(suite, kp)
    assert node.txpool.submit(expired).status == TransactionStatus.BLOCK_LIMIT_CHECK_FAIL

    bad_sig = make_tx(suite, kp, pc.BALANCE_ADDRESS, b"", nonce="b1")
    sig = bytearray(bad_sig.signature)
    sig[40] ^= 0x55
    bad_sig.signature = bytes(sig)
    bad_sig._sender = None
    st = node.txpool.submit(bad_sig).status
    assert st in (TransactionStatus.INVALID_SIGNATURE, TransactionStatus.OK)
    if st == TransactionStatus.OK:
        # recovered a different key: sender must not equal the real signer
        assert bad_sig.sender(suite) != kp.address

    nonce_reuse = make_tx(suite, kp, pc.BALANCE_ADDRESS, b"x", nonce="g1")
    assert node.txpool.submit(nonce_reuse).status == TransactionStatus.NONCE_CHECK_FAIL


def test_executor_revert_isolation():
    suite = make_suite(backend="host")
    storage = MemoryStorage()
    ex = TransactionExecutor(suite)
    state = StateStorage(storage)
    kp = suite.generate_keypair(b"iso")
    ok_tx = make_tx(suite, kp, pc.BALANCE_ADDRESS,
                    pc.encode_call("register", lambda w: w.blob(b"a").u64(100)),
                    nonce="1")
    # transfer more than balance -> REVERT, but must not undo ok_tx's write
    bad_tx = make_tx(suite, kp, pc.BALANCE_ADDRESS,
                     pc.encode_call("transfer",
                                    lambda w: w.blob(b"a").blob(b"b").u64(999)),
                     nonce="2")
    rcs = ex.execute_block_serial([ok_tx, bad_tx], state, 1, 0)
    assert rcs[0].status == 0
    assert rcs[1].status == int(TransactionStatus.REVERT)
    assert state.get(pc.T_BALANCE, b"a") is not None
    # the failed tx's writes are rolled back
    assert state.get(pc.T_BALANCE, b"b") is None


def test_dag_waves_match_serial():
    suite = make_suite(backend="host")
    ex = TransactionExecutor(suite)
    kp = suite.generate_keypair(b"dag")

    def xfer(src, dst, amt, nonce):
        return make_tx(suite, kp, pc.BALANCE_ADDRESS,
                       pc.encode_call("transfer",
                                      lambda w: w.blob(src).blob(dst).u64(amt)),
                       nonce=nonce)

    def reg(name, amt, nonce):
        return make_tx(suite, kp, pc.BALANCE_ADDRESS,
                       pc.encode_call("register",
                                      lambda w: w.blob(name).u64(amt)),
                       nonce=nonce)

    txs = [reg(b"a", 100, "1"), reg(b"b", 100, "2"), reg(b"c", 100, "3"),
           reg(b"d", 100, "4"),
           xfer(b"a", b"b", 10, "5"),   # conflicts with a,b
           xfer(b"c", b"d", 20, "6"),   # independent of 5 -> same wave
           xfer(b"b", b"c", 5, "7")]    # conflicts with both

    st_serial = StateStorage(MemoryStorage())
    rs = ex.execute_block_serial(txs, st_serial, 1, 0)
    st_dag = StateStorage(MemoryStorage())
    rd = ex.execute_block_dag(txs, st_dag, 1, 0)
    assert [r.status for r in rs] == [r.status for r in rd]
    assert st_serial.changeset().keys() == st_dag.changeset().keys()
    for k in st_serial.changeset():
        assert st_serial.changeset()[k].value == st_dag.changeset()[k].value
    waves = ex.plan_dag(txs)
    # the two independent transfers share a wave
    w5 = next(i for i, w in enumerate(waves) if 4 in w)
    w6 = next(i for i, w in enumerate(waves) if 5 in w)
    assert w5 == w6


def test_system_config_governance(node):
    suite = node.suite
    kp = suite.generate_keypair(b"gov")
    tx = make_tx(suite, kp, pc.SYS_CONFIG_ADDRESS,
                 pc.encode_call("setValueByKey",
                                lambda w: w.text("tx_count_limit").text("500")),
                 nonce="cfg1")
    r = node.send_transaction(tx)
    assert r.status == TransactionStatus.OK
    assert node.txpool.wait_for_receipt(r.tx_hash, 10) is not None
    v = node.ledger.system_config("tx_count_limit")
    assert v[0] == "500"
    cfg = node.ledger.ledger_config()
    assert cfg.block_tx_count_limit == 500


def test_wal_backed_node_restart(tmp_path):
    p = str(tmp_path / "chaindb")
    cfg = NodeConfig(crypto_backend="host", storage_path=p, min_seal_time=0.0)
    n1 = Node(cfg)
    n1.start()
    suite = n1.suite
    kp = suite.generate_keypair(b"persist")
    tx = make_tx(suite, kp, pc.BALANCE_ADDRESS,
                 pc.encode_call("register", lambda w: w.blob(b"p").u64(42)),
                 nonce="w1")
    r = n1.send_transaction(tx)
    assert n1.txpool.wait_for_receipt(r.tx_hash, 10) is not None
    committed = n1.ledger.current_number()
    n1.stop()
    n1.storage.close()

    n2 = Node(NodeConfig(crypto_backend="host", storage_path=p))
    assert n2.ledger.current_number() == committed
    assert n2.ledger.receipt(r.tx_hash) is not None
    hdr = n2.ledger.header_by_number(committed)
    assert hdr is not None
    n2.storage.close()


def test_compat_version_raise_not_active_same_block(node):
    """Next-block governance semantics: a compatibility_version raise and a
    gated-feature call landing in the SAME block must execute against the
    block-START version — the raise activates one block later (the
    executor's block-start snapshot; LedgerTypeDef.h:42 semantics)."""
    suite = node.suite
    # chain born at 1.0.0 would be ideal, but the fixture chain is 1.1.0;
    # build a dedicated node at 1.0.0
    n = Node(NodeConfig(crypto_backend="host", min_seal_time=0.2,
                        compatibility_version="1.0.0"))
    n.start()
    try:
        suite = n.suite
        kp = suite.generate_keypair(b"sameblock")
        runtime = bytes.fromhex("3660006000376020600036600060006008"
                                "5af16020526040" "6000f3")
        init = bytes.fromhex("601b600c600039601b6000f3") + runtime
        tx = make_tx(suite, kp, b"", init, nonce="d1")
        r = n.send_transaction(tx)
        rc = n.txpool.wait_for_receipt(r.tx_hash, 15)
        assert rc is not None and rc.status == 0
        proxy = rc.contract_address

        g2 = (
            10857046999023057135944570762232829481370756359578518086990519993285655852781,
            11559732032986387107991004021392285783925812861821192530917403151452391805634,
            8495653923123431417604973247489272438418190587263600148770280649306958101930,
            4082367875863433681332203403145435568316851327593401208105741076214120093531)
        pair_input = b"".join(v.to_bytes(32, "big") for v in
                              (0, 0, g2[1], g2[0], g2[3], g2[2]))
        raise_tx = make_tx(
            suite, kp, pc.SYS_CONFIG_ADDRESS,
            pc.encode_call("setValueByKey",
                           lambda w: w.text("compatibility_version")
                           .text("1.1.0")), nonce="g1")
        call_tx = make_tx(suite, kp, proxy, pair_input, nonce="c1")
        results = n.txpool.submit_batch([raise_tx, call_tx])
        assert all(int(x.status) == 0 for x in results)
        rc_raise = n.txpool.wait_for_receipt(raise_tx.hash(suite), 15)
        rc_call = n.txpool.wait_for_receipt(call_tx.hash(suite), 15)
        assert rc_raise.status == 0
        if rc_raise.block_number == rc_call.block_number:
            # same block: the call ran under 1.0.0 — inner CALL failed
            assert int.from_bytes(rc_call.output[32:64], "big") == 0
        # one block later the feature is live everywhere
        call2 = make_tx(suite, kp, proxy, pair_input, nonce="c2")
        r2 = n.send_transaction(call2)
        rc2 = n.txpool.wait_for_receipt(r2.tx_hash, 15)
        assert rc2.status == 0
        assert int.from_bytes(rc2.output[32:64], "big") == 1
    finally:
        n.stop()
