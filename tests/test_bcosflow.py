"""bcosflow (tools/bcosflow.py): per-pass fixture tests over the
interprocedural analyzer, plus self-checks against the real repo
(resolution floor, CI time budget, zero jax import, baseline gate)."""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import textwrap
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "bcosflow", os.path.join(_REPO, "tools", "bcosflow.py"))
bcosflow = importlib.util.module_from_spec(_spec)
sys.modules.setdefault("bcosflow", bcosflow)
_spec.loader.exec_module(bcosflow)


def flow(sources: dict[str, str]):
    """{relpath: src} -> (findings, graph), with dedented sources."""
    return bcosflow.analyze_sources(
        {rel: textwrap.dedent(src) for rel, src in sources.items()})


def rules_of(findings):
    return sorted(v.rule for v in findings)


# -- pass: plane-blocking (thread-spawn roots) ------------------------------

_INGEST_FSYNC = {
    "fisco_bcos_tpu/txpool/mini.py": """
    import os
    import threading

    class MiniLane:
        def __init__(self):
            self._thread = threading.Thread(target=self._run,
                                            name="tx-ingest", daemon=True)

        def _run(self):
            self._persist()

        def _persist(self):
            os.fsync(3)
    """,
}


def test_plane_blocking_interprocedural_from_spawn_root():
    # the fsync is one call HOP below the thread body: only transitive
    # effect propagation can see it from the ingest plane
    findings, graph = flow(_INGEST_FSYNC)
    pb = [v for v in findings if v.rule == "plane-blocking"]
    assert len(pb) == 1
    assert pb[0].scope == "MiniLane._persist"
    assert "'ingest' plane" in pb[0].message
    assert any(p == "ingest" for _, p, _ in graph.roots)


def test_plane_blocking_suppression_comment():
    srcs = {k: v.replace("os.fsync(3)",
                         "os.fsync(3)  # bcosflow: disable=plane-blocking")
            for k, v in _INGEST_FSYNC.items()}
    findings, _ = flow(srcs)
    assert "plane-blocking" not in rules_of(findings)


def test_plane_blocking_callback_registration():
    # the PR-13 shape: a commit observer reaches a socket send through
    # one indirection layer — the callback-registration edge must carry
    # the 'notify' plane onto the registered function
    findings, _ = flow({
        "fisco_bcos_tpu/rpc/pump.py": """
        class Pump:
            def __init__(self, sched, sock):
                self.sock = sock
                sched.add_commit_observer(self._on_commit)

            def _on_commit(self, number):
                self._push(number)

            def _push(self, number):
                self.sock.sendall(b"x")
        """,
    })
    pb = [v for v in findings if v.rule == "plane-blocking"]
    assert len(pb) == 1
    assert pb[0].scope == "Pump._push"
    assert "'notify' plane" in pb[0].message


# -- pass: lock-blocking-interproc ------------------------------------------

_LOCK_FIXTURE = """
import os
from ..analysis import lockcheck as lc

class Pool:
    def __init__(self):
        self._lock = lc.make_lock("txpool.state")

    def admit(self):
        with self._lock:
            self._flush()

    def _flush(self):
        os.fsync(3)

    def direct(self):
        with self._lock:
            os.fsync(3)
"""


def test_lock_blocking_across_calls():
    findings, _ = flow({"fisco_bcos_tpu/txpool/pool2.py": _LOCK_FIXTURE})
    lb = [v for v in findings if v.rule == "lock-blocking-interproc"]
    assert len(lb) == 1
    assert lb[0].scope == "Pool._flush"
    assert "txpool.state" in lb[0].message


def test_lock_blocking_depth_zero_left_to_bcoslint():
    # `direct` blocks INSIDE its own with-block: that is bcoslint's
    # lexical blocking-under-lock rule, not an interprocedural finding —
    # the analyzer must not double-report it
    findings, _ = flow({"fisco_bcos_tpu/txpool/pool2.py": _LOCK_FIXTURE})
    lb = [v for v in findings if v.rule == "lock-blocking-interproc"]
    assert all(v.scope != "Pool.direct" for v in lb)


# -- pass: lock-order-interproc ---------------------------------------------

def test_lock_order_inversion_across_calls():
    # txpool.state ranks INSIDE scheduler.state: acquiring the scheduler
    # lock in a callee while the pool lock is held inverts the canonical
    # order one call away from the `with`
    findings, _ = flow({
        "fisco_bcos_tpu/txpool/pool3.py": """
        from ..analysis import lockcheck as lc

        class P:
            def __init__(self):
                self._lock = lc.make_lock("txpool.state")
                self._sched = lc.make_lock("scheduler.state")

            def outer(self):
                with self._lock:
                    self.inner()

            def inner(self):
                with self._sched:
                    pass
        """,
    })
    lo = [v for v in findings if v.rule == "lock-order-interproc"]
    assert len(lo) == 1
    assert lo[0].scope == "P.inner"
    assert "scheduler.state" in lo[0].message


def test_lock_order_correct_nesting_not_flagged():
    findings, _ = flow({
        "fisco_bcos_tpu/txpool/pool4.py": """
        from ..analysis import lockcheck as lc

        class P:
            def __init__(self):
                self._lock = lc.make_lock("txpool.state")
                self._sched = lc.make_lock("scheduler.state")

            def outer(self):
                with self._sched:
                    self.inner()

            def inner(self):
                with self._lock:
                    pass
        """,
    })
    assert "lock-order-interproc" not in rules_of(findings)


# -- pass: fsync-path-unarmed -----------------------------------------------

_FSYNC_LEAF = """
import os

def write_segment(fd):
    os.fsync(fd)
"""


def test_fsync_unarmed_entry_chain_flagged():
    findings, _ = flow({"fisco_bcos_tpu/storage/seg.py": _FSYNC_LEAF})
    fu = [v for v in findings if v.rule == "fsync-path-unarmed"]
    assert len(fu) == 1
    assert fu[0].scope == "write_segment"


def test_fsync_covered_when_every_caller_is_armed():
    findings, _ = flow({
        "fisco_bcos_tpu/storage/seg2.py": _FSYNC_LEAF + """

    def append(fd):
        fire("storage.append.pre")
        write_segment(fd)
    """,
    })
    assert "fsync-path-unarmed" not in rules_of(findings)


def test_fsync_outside_storage_scope_ignored():
    findings, _ = flow({"fisco_bcos_tpu/utils/misc.py": _FSYNC_LEAF})
    assert "fsync-path-unarmed" not in rules_of(findings)


# -- pass: lane-host-sync ---------------------------------------------------

_LANE_SRC = """
import threading

class Dispatcher:
    def __init__(self):
        self._thread = threading.Thread(target=self._run,
                                        name="crypto-lane", daemon=True)

    def _run(self):
        from ..ops.mix import merge
        merge(None)
"""


def test_lane_host_sync_outside_boundary_flagged():
    findings, _ = flow({
        "fisco_bcos_tpu/crypto/lane9.py": _LANE_SRC,
        "fisco_bcos_tpu/ops/mix.py": """
        def merge(x):
            x.block_until_ready()
        """,
    })
    hs = [v for v in findings if v.rule == "lane-host-sync"]
    assert len(hs) == 1
    assert hs[0].path == "fisco_bcos_tpu/ops/mix.py"


def test_lane_host_sync_inside_crypto_boundary_sanctioned():
    # crypto/ IS the sanctioned demux boundary: materialising a merged
    # batch there is the dispatcher's job, not a mid-pipeline stall
    findings, _ = flow({
        "fisco_bcos_tpu/crypto/lane9.py": """
        import threading

        class Dispatcher:
            def __init__(self):
                self._thread = threading.Thread(target=self._run,
                                                name="crypto-lane",
                                                daemon=True)

            def _run(self):
                self.demux(None)

            def demux(self, x):
                x.block_until_ready()
        """,
    })
    assert "lane-host-sync" not in rules_of(findings)


# -- pass: jit purity -------------------------------------------------------

def test_jit_impure_and_shape_branch():
    findings, _ = flow({
        "fisco_bcos_tpu/ops/kern.py": """
        import os
        import jax

        @jax.jit
        def kernel(x):
            os.fsync(3)
            if x.shape[0] > 4:
                return x
            return x

        def plain(x):
            os.fsync(3)
            if x.shape[0] > 4:
                return x
            return x
        """,
    })
    by_rule = rules_of(findings)
    assert "jit-impure" in by_rule
    assert "jit-shape-branch" in by_rule
    # the un-jitted twin triggers NEITHER rule
    assert all(v.scope == "kernel" for v in findings
               if v.rule in ("jit-impure", "jit-shape-branch"))


# -- pass: hot-loop-alloc ---------------------------------------------------

def test_hot_loop_alloc_on_ingest_path():
    findings, _ = flow({
        "fisco_bcos_tpu/txpool/mini2.py": """
        import threading

        class Item:
            def __init__(self, x):
                self.x = x

        class MiniLane:
            def __init__(self):
                self._thread = threading.Thread(target=self._run,
                                                name="tx-ingest",
                                                daemon=True)

            def _run(self):
                out = []
                for x in range(4):
                    out.append(Item(x))
        """,
    })
    ha = [v for v in findings if v.rule == "hot-loop-alloc"]
    assert len(ha) == 1
    assert ha[0].scope == "MiniLane._run"


def test_alloc_in_raise_is_loop_exit_not_per_item():
    findings, _ = flow({
        "fisco_bcos_tpu/txpool/mini3.py": """
        import threading

        class PoolFull(Exception):
            def __init__(self, x):
                super().__init__(x)

        class MiniLane:
            def __init__(self):
                self._thread = threading.Thread(target=self._run,
                                                name="tx-ingest",
                                                daemon=True)

            def _run(self):
                for x in range(4):
                    if x > 2:
                        raise PoolFull(x)
        """,
    })
    assert "hot-loop-alloc" not in rules_of(findings)


# -- graph dump shape -------------------------------------------------------

def test_graph_dump_structure():
    _, graph = flow(_INGEST_FSYNC)
    d = graph.dump()
    assert set(d) == {"stats", "roots", "functions", "edges", "ref_edges"}
    assert any(r["plane"] == "ingest" for r in d["roots"])
    quals = {f["qual"] for f in d["functions"]}
    assert any(q.endswith("MiniLane._persist") for q in quals)
    assert any(s.endswith("._run") and t.endswith("._persist")
               for s, t in d["edges"])


# -- self-checks against the real repo --------------------------------------

def test_repo_resolution_floor_and_roots():
    paths = [os.path.join(_REPO, "fisco_bcos_tpu")]
    summaries, _ = bcosflow.load_summaries(paths, None)
    graph = bcosflow.Graph(summaries)
    assert graph.resolution_rate() >= 0.90, (
        f"call-edge resolution fell to {graph.resolution_rate():.1%} — "
        "new code defeats the receiver-typing heuristics; extend "
        "tools/bcosflow.py resolution before baselining around it")
    assert len(graph.roots) >= 10  # plane roots, not a degenerate graph


def test_cli_green_vs_committed_baseline_within_budget():
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "bcosflow.py"),
         "--no-cache"],
        capture_output=True, text=True, timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new finding(s)" in proc.stdout + proc.stderr
    assert elapsed < 30.0, f"bcosflow took {elapsed:.1f}s (CI budget 30s)"


def test_analysis_never_imports_jax():
    # the lint gate must stay runnable on machines with no accelerator
    # stack; loading planes/profiler/lockorder happens by file path
    code = textwrap.dedent(f"""
        import importlib.util, os, sys
        spec = importlib.util.spec_from_file_location(
            "bcosflow", {os.path.join(_REPO, 'tools', 'bcosflow.py')!r})
        m = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(m)
        summaries, _ = m.load_summaries(
            [{os.path.join(_REPO, 'fisco_bcos_tpu')!r}], None)
        m.Analyzer(m.Graph(summaries)).run()
        assert "jax" not in sys.modules, "analysis imported jax"
        assert "jaxlib" not in sys.modules, "analysis imported jaxlib"
    """)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
