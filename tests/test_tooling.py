"""build_chain + config round-trip: generate a 4-node PBFT chain directory
and boot it in-process over a FakeGateway (the reference's
build_chain.sh -> Air chain flow)."""

import sys
import os
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from build_chain import build_chain  # noqa: E402

from fisco_bcos_tpu.net.gateway import FakeGateway  # noqa: E402
from fisco_bcos_tpu.tool import ChainConfig, load_node  # noqa: E402
from fisco_bcos_tpu.tool.config import (node_config_from_ini,  # noqa: E402
                                        node_config_to_ini)
from fisco_bcos_tpu.init.node import NodeConfig  # noqa: E402
from fisco_bcos_tpu.protocol import Transaction  # noqa: E402
from fisco_bcos_tpu.executor import precompiled as pc  # noqa: E402


def test_node_config_ini_roundtrip(tmp_path):
    cfg = NodeConfig(chain_id="c9", group_id="g7", sm_crypto=True,
                     storage_path=str(tmp_path / "d"), consensus="pbft",
                     min_seal_time=0.2, view_timeout=7.5, leader_period=3,
                     crypto_backend="host", rpc_port=1234)
    back = node_config_from_ini(node_config_to_ini(cfg))
    assert back.chain_id == "c9" and back.group_id == "g7"
    assert back.sm_crypto and back.consensus == "pbft"
    assert back.view_timeout == 7.5 and back.leader_period == 3
    assert back.rpc_port == 1234 and back.crypto_backend == "host"


def test_chain_config_roundtrip():
    chain = ChainConfig(sealers=[b"\x01" * 64, b"\x02" * 64],
                        block_tx_count_limit=500)
    back = ChainConfig.from_ini(chain.to_ini())
    assert back.sealers == chain.sealers
    assert back.block_tx_count_limit == 500


def test_build_and_boot_pbft_chain(tmp_path):
    out = str(tmp_path / "chain")
    info = build_chain(out, 4, consensus="pbft", crypto_backend="host")
    assert len(info["nodes"]) == 4
    assert os.path.exists(os.path.join(out, "node0", "config.ini"))

    gw = FakeGateway()
    nodes = [load_node(os.path.join(out, f"node{i}"), gateway=gw)
             for i in range(4)]
    try:
        for n in nodes:
            n.start()
        lead = nodes[0]
        kp = lead.suite.generate_keypair(b"tool-user")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"t").u64(11)),
                         nonce="t1",
                         block_limit=lead.ledger.current_number() + 100
                         ).sign(lead.suite, kp)
        # submit to every node's pool via gossip-free direct submit
        res = lead.send_transaction(tx)
        assert int(res.status) == 0
        deadline = time.time() + 30
        while time.time() < deadline and any(
                n.ledger.current_number() < 1 for n in nodes):
            time.sleep(0.05)
        heights = [n.ledger.current_number() for n in nodes]
        assert all(h >= 1 for h in heights), heights
        rc = lead.txpool.wait_for_receipt(res.tx_hash, 10)
        assert rc is not None and rc.status == 0
    finally:
        for n in nodes:
            n.stop()
            if hasattr(n.storage, "close"):
                n.storage.close()
        gw.stop()


def test_encrypted_node_key(tmp_path):
    out = str(tmp_path / "encchain")
    build_chain(out, 1, consensus="solo", crypto_backend="host",
                encrypt_passphrase=b"hunter2")
    assert os.path.exists(os.path.join(out, "node0", "node.key.enc"))
    assert not os.path.exists(os.path.join(out, "node0", "node.key"))
    import pytest
    with pytest.raises(ValueError):
        load_node(os.path.join(out, "node0"))
    node = load_node(os.path.join(out, "node0"),
                     storage_passphrase=b"hunter2")
    assert node.ledger.current_number() == 0
    node.storage.close()


def test_genesis_mismatch_rejected_on_restart(tmp_path):
    out = str(tmp_path / "gchain")
    build_chain(out, 1, consensus="solo", crypto_backend="host")
    node = load_node(os.path.join(out, "node0"))
    node.build_genesis() if node.ledger.current_number() < 0 else None
    node.storage.close()
    # tamper with the genesis sealer list
    import re
    gpath = os.path.join(out, "node0", "genesis")
    text = open(gpath).read()
    text = re.sub(r"node\.0=[0-9a-f]+", "node.0=" + "ab" * 64, text)
    open(gpath, "w").write(text)
    import pytest
    with pytest.raises(ValueError, match="genesis block"):
        load_node(os.path.join(out, "node0"))


def test_build_chain_monitor_and_smtls(tmp_path):
    """--metrics-base-port emits per-node Prometheus ports + the monitor
    bundle; --sm-tls issues loadable dual-cert credentials; a booted node
    serves the pending-tx gauge on its metrics endpoint."""
    import json
    import urllib.request

    from fisco_bcos_tpu.net.smtls import SMTLSContext
    from fisco_bcos_tpu.tool.config import load_smtls_context

    out = str(tmp_path / "chain")
    info = build_chain(out, 2, consensus="pbft", crypto_backend="host",
                       metrics_base_port=0, sm_tls=True)
    assert info["sm_tls"] and info["nodes"][0]["metrics_port"] == 0

    # monitor bundle materialized with rewritten scrape targets
    assert os.path.exists(os.path.join(out, "monitor", "Dashboard.json"))
    with open(os.path.join(out, "monitor", "prometheus.yml")) as f:
        assert "127.0.0.1:0" in f.read()
    with open(os.path.join(out, "monitor", "Dashboard.json")) as f:
        dash = json.load(f)
    assert any("bcos_txpool_pending" in t.get("expr", "")
               for p in dash["panels"] for t in p.get("targets", []))

    # SM-TLS credentials load into contexts whose subjects chain to the CA
    ctx0 = load_smtls_context(os.path.join(out, "node0"))
    ctx1 = load_smtls_context(os.path.join(out, "node1"))
    assert isinstance(ctx0, SMTLSContext) and isinstance(ctx1, SMTLSContext)
    assert ctx0.cred.sign_cert.subject == "node0"

    # a booted node serves Prometheus text incl. the pending gauge
    node = load_node(os.path.join(out, "node0"), gateway=FakeGateway())
    node.config.consensus = "solo"  # lone boot for the scrape check
    node.start()
    try:
        node.txpool._update_pending_gauge()
        url = f"http://127.0.0.1:{node.metrics.port}/metrics"
        body = urllib.request.urlopen(url, timeout=10).read().decode()
        assert "bcos_txpool_pending" in body
    finally:
        node.stop()


def test_restart_after_governance_membership_change(tmp_path):
    """Live addSealer governance diverges the consensus set from the
    genesis file; a restart must still boot (the check pins the IMMUTABLE
    genesis block, not the evolving set)."""
    from fisco_bcos_tpu.ledger.ledger import ConsensusNode

    out = str(tmp_path / "chain")
    build_chain(out, 1, consensus="solo", crypto_backend="host")
    node = load_node(os.path.join(out, "node0"))
    # a governance-added sealer (what the Consensus precompile writes)
    node.ledger._set_consensus_direct(ConsensusNode(b"\xaa" * 64))
    assert len(node.ledger.ledger_config().consensus_nodes) == 2

    # restart with the original genesis file: must NOT refuse
    node2 = load_node(os.path.join(out, "node0"))
    assert len(node2.ledger.ledger_config().consensus_nodes) == 2

    # a genuinely different genesis file must still be rejected
    import configparser
    with open(os.path.join(out, "node0", "genesis")) as f:
        text = f.read()
    other = ChainConfig.from_ini(text)
    other.sealers = [b"\xbb" * 64]
    with open(os.path.join(out, "node0", "genesis"), "w") as f:
        f.write(other.to_ini())
    try:
        load_node(os.path.join(out, "node0"))
        raised = False
    except ValueError:
        raised = True
    assert raised
