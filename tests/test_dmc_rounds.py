"""DMC message rounds: cross-shard call routing, nesting, deadlock revert.

Reference scenarios: bcos-scheduler/test/testDmcExecutor.cpp — executives
pause at cross-contract calls, the scheduler routes ExecutionMessages
between (remote) executors in rounds, and lock cycles revert the higher
context (BlockExecutive.cpp:861-978, GraphKeyLocks.cpp).
"""

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor.evm import T_CODE, T_STORE
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.scheduler.dmc_rounds import (
    DmcRoundScheduler,
    ShardExecutor,
)
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage


def _push_addr(addr: bytes) -> bytes:
    return b"\x73" + addr  # PUSH20


def _call_forward(target: bytes) -> bytes:
    """CALL target (no args), SSTORE(0, success), return its 32-byte out."""
    return (
        b"\x60\x20\x5f\x5f\x5f\x5f"  # out_size=32 out_off in_size in_off val
        + _push_addr(target)
        + b"\x61\xff\xff"  # gas
        + b"\xf1"          # CALL -> success
        + b"\x5f\x55"      # SSTORE(slot 0, success)
        + b"\x60\x20\x5f\xf3"  # RETURN(0, 32)
    )


LEAF = (b"\x60\x07\x5f\x55"          # SSTORE(0, 7)
        b"\x60\x2a\x5f\x52"          # MSTORE(0, 42)
        b"\x60\x20\x5f\xf3")         # RETURN(0, 32)


def _setup(partition):
    """-> (suite, base_state, scheduler, shards) with an address partition
    fn mapping addr -> shard index."""
    suite = make_suite(backend="host")
    base = StateStorage(MemoryStorage())
    shards = [
        ShardExecutor(b"shard-%d" % i, suite,
                      owns=lambda a, i=i: partition(a) == i)
        for i in range(2)
    ]
    return suite, base, DmcRoundScheduler(shards), shards


def _tx(suite, kp, to, nonce, data=b""):
    return Transaction(to=to, input=data, nonce=nonce,
                       block_limit=100).sign(suite, kp)


def test_cross_shard_call_roundtrip():
    # A (shard 0) calls B (shard 1); B writes storage and returns 42
    A, B = b"\xaa" * 20, b"\xbb" * 20
    suite, base, sched, _ = _setup(lambda a: 0 if a == A else 1)
    kp = suite.generate_keypair(b"dmc-user")
    base.set(T_CODE, A, _call_forward(B))
    base.set(T_CODE, B, LEAF)

    [rc] = sched.execute_block([_tx(suite, kp, A, "r1")], base, 1, 0)
    assert rc.status == 0, rc.message
    assert int.from_bytes(rc.output, "big") == 42  # B's return, via A
    # B's write landed (on shard 1's partition, merged into base)
    assert base.get(T_STORE, B + (0).to_bytes(32, "big")) == (7).to_bytes(32, "big")
    # A recorded the call success flag
    assert base.get(T_STORE, A + (0).to_bytes(32, "big")) == (1).to_bytes(32, "big")


def test_nested_reentrant_chain_across_shards():
    # A (shard 0) -> B (shard 1) -> C (shard 0): the sub-call re-enters the
    # origin shard while the root frame is paused there
    A, B, C = b"\xaa" * 20, b"\xbb" * 20, b"\xcc" * 20
    suite, base, sched, _ = _setup(lambda a: 1 if a == B else 0)
    kp = suite.generate_keypair(b"dmc-user2")
    base.set(T_CODE, A, _call_forward(B))
    base.set(T_CODE, B, _call_forward(C))
    base.set(T_CODE, C, LEAF)

    [rc] = sched.execute_block([_tx(suite, kp, A, "n1")], base, 1, 0)
    assert rc.status == 0, rc.message
    assert int.from_bytes(rc.output, "big") == 42  # C -> B -> A
    assert base.get(T_STORE, C + (0).to_bytes(32, "big")) == (7).to_bytes(32, "big")


def test_two_contexts_opposite_shards_no_conflict():
    # tx0 runs entirely on shard 0, tx1 on shard 1 — both succeed
    A, B = b"\xaa" * 20, b"\xbb" * 20
    suite, base, sched, _ = _setup(lambda a: 0 if a == A else 1)
    kp = suite.generate_keypair(b"dmc-user3")
    base.set(T_CODE, A, LEAF)
    base.set(T_CODE, B, LEAF)
    rcs = sched.execute_block(
        [_tx(suite, kp, A, "p1"), _tx(suite, kp, B, "p2")], base, 1, 0)
    assert all(rc.status == 0 for rc in rcs)
    assert base.get(T_STORE, A + (0).to_bytes(32, "big")) == (7).to_bytes(32, "big")
    assert base.get(T_STORE, B + (0).to_bytes(32, "big")) == (7).to_bytes(32, "big")


def test_deadlock_reverts_higher_context_and_completes():
    # ctx0: A1 (shard0) -> B1 (shard1); ctx1: B2 (shard1) -> A2 (shard0).
    # FIFO processing: ctx0 takes shard0 and pauses; ctx1 takes shard1 and
    # pauses; each waits on the other's shard -> deadlock. ctx1 (higher id)
    # reverts and re-runs after ctx0 completes. Both must end successful
    # with all four stores visible.
    A1, B1 = b"\xa1" * 20, b"\xb1" * 20
    B2, A2 = b"\xb2" * 20, b"\xa2" * 20
    shard_of = lambda a: 0 if a in (A1, A2) else 1  # noqa: E731
    suite, base, sched, _ = _setup(shard_of)
    kp = suite.generate_keypair(b"dmc-user4")
    base.set(T_CODE, A1, _call_forward(B1))
    base.set(T_CODE, B1, LEAF)
    base.set(T_CODE, B2, _call_forward(A2))
    base.set(T_CODE, A2, LEAF)

    rcs = sched.execute_block(
        [_tx(suite, kp, A1, "d1"), _tx(suite, kp, B2, "d2")], base, 1, 0)
    assert all(rc.status == 0 for rc in rcs), [
        (rc.status, rc.message) for rc in rcs]
    for addr in (B1, A2):
        assert base.get(T_STORE, addr + (0).to_bytes(32, "big")) == (7).to_bytes(32, "big")
    for addr in (A1, B2):  # call success flags
        assert base.get(T_STORE, addr + (0).to_bytes(32, "big")) == (1).to_bytes(32, "big")


def test_deterministic_across_runs():
    """Same block twice on fresh state -> identical receipts + changesets."""
    A1, B1 = b"\xa1" * 20, b"\xb1" * 20
    B2, A2 = b"\xb2" * 20, b"\xa2" * 20
    shard_of = lambda a: 0 if a in (A1, A2) else 1  # noqa: E731
    suite = make_suite(backend="host")
    kp = suite.generate_keypair(b"dmc-user5")

    def run_once():
        base = StateStorage(MemoryStorage())
        shards = [ShardExecutor(b"s%d" % i, suite,
                                owns=lambda a, i=i: shard_of(a) == i)
                  for i in range(2)]
        sched = DmcRoundScheduler(shards)
        base.set(T_CODE, A1, _call_forward(B1))
        base.set(T_CODE, B1, LEAF)
        base.set(T_CODE, B2, _call_forward(A2))
        base.set(T_CODE, A2, LEAF)
        rcs = sched.execute_block(
            [_tx(suite, kp, A1, "x1"), _tx(suite, kp, B2, "x2")],
            base, 1, 0)
        return ([(rc.status, rc.output, rc.gas_used) for rc in rcs],
                sorted((t, k, e.value) for (t, k), e
                       in base.changeset().items()))

    assert run_once() == run_once()


REVERT_AFTER_CALL = (
    # CALL target, then unconditionally REVERT(0, 0)
    b"\x60\x20\x5f\x5f\x5f\x5f")  # placeholder assembled in the test


def test_reverted_tx_discards_remote_shard_writes():
    """A calls B cross-shard (B SSTOREs), then A REVERTs: B's write must
    NOT merge into block state — tx atomicity spans shards."""
    A, B = b"\xaa" * 20, b"\xbb" * 20
    suite, base, sched, _ = _setup(lambda a: 0 if a == A else 1)
    kp = suite.generate_keypair(b"dmc-rv")
    code_a = (
        b"\x60\x20\x5f\x5f\x5f\x5f" + _push_addr(B) + b"\x61\xff\xff\xf1"
        + b"\x50"          # pop call success
        + b"\x5f\x5f\xfd"  # REVERT(0, 0)
    )
    base.set(T_CODE, A, code_a)
    base.set(T_CODE, B, LEAF)
    [rc] = sched.execute_block([_tx(suite, kp, A, "rv1")], base, 1, 0)
    assert rc.status != 0
    assert base.get(T_STORE, B + (0).to_bytes(32, "big")) is None, \
        "reverted tx leaked remote shard writes"


def test_precompile_routed_to_home_shard():
    """Root txs to system precompiles and in-EVM precompile CALLs both run
    on the deterministic precompile-home shard (single writer)."""
    from fisco_bcos_tpu.executor import precompiled as pc
    from fisco_bcos_tpu.codec.wire import Reader

    A = b"\xaa" * 20
    suite, base, sched, shards = _setup(lambda a: 1 if a == A else -1)
    assert shards[0].precompile_home
    kp = suite.generate_keypair(b"dmc-pc")
    tx = Transaction(to=pc.BALANCE_ADDRESS,
                     input=pc.encode_call(
                         "register", lambda w: w.blob(b"dmcacct").u64(9)),
                     nonce="pc1", block_limit=100).sign(suite, kp)
    [rc] = sched.execute_block([tx], base, 1, 0)
    assert rc.status == 0, rc.message
    tx2 = Transaction(to=pc.BALANCE_ADDRESS,
                      input=pc.encode_call(
                          "balanceOf", lambda w: w.blob(b"dmcacct")),
                      nonce="pc2", block_limit=100).sign(suite, kp)
    [rc2] = sched.execute_block([tx2], base, 1, 0)
    assert Reader(rc2.output).u64() == 9
