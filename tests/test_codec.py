"""Codec tests: Solidity ABI v2 + SCALE, including spec golden vectors."""

import pytest

from fisco_bcos_tpu.codec import abi, scale
from fisco_bcos_tpu.crypto import refimpl


# ---------------------------------------------------------------------------
# ABI — golden vectors from the public Solidity ABI spec examples
# ---------------------------------------------------------------------------

def test_abi_spec_baz():
    # baz(uint32,bool) with (69, true)
    enc = abi.encode_call("baz(uint32,bool)", [69, True], refimpl.keccak256)
    assert enc.hex() == (
        "cdcd77c0"
        "0000000000000000000000000000000000000000000000000000000000000045"
        "0000000000000000000000000000000000000000000000000000000000000001")


def test_abi_spec_sam():
    # sam(bytes,bool,uint256[]) with ("dave", true, [1,2,3])
    enc = abi.encode_call("sam(bytes,bool,uint256[])",
                          [b"dave", True, [1, 2, 3]], refimpl.keccak256)
    assert enc.hex() == (
        "a5643bf2"
        "0000000000000000000000000000000000000000000000000000000000000060"
        "0000000000000000000000000000000000000000000000000000000000000001"
        "00000000000000000000000000000000000000000000000000000000000000a0"
        "0000000000000000000000000000000000000000000000000000000000000004"
        "6461766500000000000000000000000000000000000000000000000000000000"
        "0000000000000000000000000000000000000000000000000000000000000003"
        "0000000000000000000000000000000000000000000000000000000000000001"
        "0000000000000000000000000000000000000000000000000000000000000002"
        "0000000000000000000000000000000000000000000000000000000000000003")


def test_abi_spec_f_dynamic():
    # f(uint256,uint32[],bytes10,bytes) — the spec's worked example
    enc = abi.encode(["uint256", "uint32[]", "bytes10", "bytes"],
                     [0x123, [0x456, 0x789], b"1234567890", b"Hello, world!"])
    assert enc.hex() == (
        "0000000000000000000000000000000000000000000000000000000000000123"
        "0000000000000000000000000000000000000000000000000000000000000080"
        "3132333435363738393000000000000000000000000000000000000000000000"
        "00000000000000000000000000000000000000000000000000000000000000e0"
        "0000000000000000000000000000000000000000000000000000000000000002"
        "0000000000000000000000000000000000000000000000000000000000000456"
        "0000000000000000000000000000000000000000000000000000000000000789"
        "000000000000000000000000000000000000000000000000000000000000000d"
        "48656c6c6f2c20776f726c642100000000000000000000000000000000000000")


@pytest.mark.parametrize("types,values", [
    (["uint256", "bool"], [123456789, True]),
    (["int64"], [-42]),
    (["address"], [b"\x11" * 20]),
    (["bytes32"], [b"\xaa" * 32]),
    (["string", "bytes"], ["héllo", b"\x00\x01\x02"]),
    (["uint8[3]"], [[1, 2, 3]]),
    (["uint256[]", "string[]"], [[7, 8], ["a", "bc"]]),
    (["(uint256,string)"], [(5, "x")]),
    (["(uint256,string)[]"], [[(1, "a"), (2, "b")]]),
    (["uint256[2][]"], [[[1, 2], [3, 4]]]),
])
def test_abi_roundtrip(types, values):
    enc = abi.encode(types, values)
    dec = abi.decode(types, enc)
    norm = [list(v) if isinstance(v, tuple) else v for v in dec]
    want = [list(v) if isinstance(v, tuple) else v for v in values]
    # tuples decode as tuples; nested lists compare after normalisation
    def deep(x):
        if isinstance(x, (list, tuple)):
            return [deep(i) for i in x]
        return x
    assert deep(norm) == deep(want)


def test_abi_selector_canonicalisation():
    a = abi.selector("transfer(address,uint)", refimpl.keccak256)
    b = abi.selector("transfer(address,uint256)", refimpl.keccak256)
    assert a == b == bytes.fromhex("a9059cbb")


def test_abi_errors():
    with pytest.raises(abi.ABIError):
        abi.encode(["uint8"], [256])
    with pytest.raises(abi.ABIError):
        abi.encode(["bytes4"], [b"12345"])
    with pytest.raises(abi.ABIError):
        abi.decode(["uint256"], b"\x00" * 31)


# ---------------------------------------------------------------------------
# SCALE — golden vectors from the public SCALE spec
# ---------------------------------------------------------------------------

def test_scale_compact_golden():
    for v, want in [(0, "00"), (1, "04"), (42, "a8"), (63, "fc"),
                    (69, "1501"), (16383, "fdff"), (16384, "02000100"),
                    (1073741823, "feffffff"),
                    (1073741824, "0300000040"),
                    ((1 << 32) - 1, "03ffffffff")]:
        assert scale.Encoder().compact(v).bytes().hex() == want
        assert scale.Decoder(bytes.fromhex(want)).compact() == v


def test_scale_fixed_ints():
    assert scale.Encoder().u16(42).bytes().hex() == "2a00"
    assert scale.Encoder().u32(16777215).bytes().hex() == "ffffff00"
    assert scale.Encoder().int_(-127, 1).bytes().hex() == "81"
    assert scale.Decoder(bytes.fromhex("81")).int_(1) == -127


def test_scale_roundtrip_composites():
    e = scale.Encoder()
    e.string("Hamlet").boolean(True).option(None, scale.Encoder.u32)
    e.option(7, lambda enc, v: enc.u32(v))
    e.vec([4, 8, 15], lambda enc, v: enc.u64(v))
    e.u256(2**255 + 1)
    d = scale.Decoder(e.bytes())
    assert d.string() == "Hamlet"
    assert d.boolean() is True
    assert d.option(scale.Decoder.u32) is None
    assert d.option(lambda dec: dec.u32()) == 7
    assert d.vec(lambda dec: dec.u64()) == [4, 8, 15]
    assert d.u256() == 2**255 + 1
    assert d.remaining() == 0


def test_scale_errors():
    with pytest.raises(scale.ScaleError):
        scale.Decoder(b"\x02").boolean()
    with pytest.raises(scale.ScaleError):
        scale.Decoder(b"").u32()
    with pytest.raises(scale.ScaleError):
        scale.Encoder().u8(300)
