"""Push-based subscription plane (rpc/eventsub.SubHub) — correctness
under adversity, and the zero-extra-render claim.

The plane's contract: commit-time fan-out sources the SAME serialized
fragment bytes the QueryCache primed, so a notification costs buffer
joins — zero extra `json.dumps`, zero recover batches beyond the
existing prime — and the cache-generation fence means a rollback or
snapshot install can never push a stale fragment. Delivery rides the
bounded per-session outbox: a never-draining subscriber sheds (droppable
streams) or is killed (lossless) without delaying anyone else.
"""

import itertools
import json
import threading
import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.rpc.eventsub import EventFilter, SubLimitError


def wait_until(pred, timeout=15.0, tick=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(tick)
    return pred()


def _mk_node(**kw):
    cfg = NodeConfig(crypto_backend="host", min_seal_time=0.0,
                     rpc_port=0, **kw)
    node = Node(cfg)
    node.start()
    return node


def _register(node, kp, name: bytes, value: int, nonce: str):
    """-> (receipt, tx_hash)"""
    tx = Transaction(to=pc.BALANCE_ADDRESS,
                     input=pc.encode_call(
                         "register", lambda w: w.blob(name).u64(value)),
                     nonce=nonce, block_limit=100).sign(node.suite, kp)
    h = tx.hash(node.suite)
    rc = node.txpool.wait_for_receipt(node.send_transaction(tx).tx_hash, 30)
    assert rc is not None and rc.status == 0, rc
    return rc, h


def _transfer(node, kp, src: bytes, dst: bytes, amount: int, nonce: str):
    tx = Transaction(to=pc.BALANCE_ADDRESS,
                     input=pc.encode_call(
                         "transfer", lambda w: w.blob(src).blob(dst)
                         .u64(amount)),
                     nonce=nonce, block_limit=100).sign(node.suite, kp)
    rc = node.txpool.wait_for_receipt(node.send_transaction(tx).tx_hash, 30)
    assert rc is not None and rc.status == 0, rc
    return rc


class _Sink:
    """In-process subscriber: records decoded notification frames."""

    def __init__(self):
        self.frames: list[dict] = []
        self.ok = True

    def __call__(self, frame: bytes, lossless: bool, t0) -> bool:
        if not self.ok:
            return False
        self.frames.append(json.loads(frame))
        return True

    def results(self):
        return [f["params"]["result"] for f in self.frames]


# ---------------------------------------------------------------------------
# staleness: rollback + generation fence
# ---------------------------------------------------------------------------

def test_rollback_pushes_nothing_stale():
    """A storage 2PC rollback between fan-outs: every header the
    subscriber ever receives must be a header of the REAL committed
    chain (the retry's block), never the rolled-back attempt's bytes."""
    node = _mk_node()
    try:
        sink = _Sink()
        node.subhub.subscribe("newBlockHeaders", sink, owner=object())
        kp = node.suite.generate_keypair(b"sub-rb")
        _register(node, kp, b"rb-a", 7, "rb-0")

        orig_commit = node.storage.commit
        state = {"tripped": False}

        def flaky(number):
            if not state["tripped"]:
                state["tripped"] = True
                raise RuntimeError("injected commit failure")
            return orig_commit(number)

        node.storage.commit = flaky
        _register(node, kp, b"rb-b", 9, "rb-1")  # survives the rollback
        node.storage.commit = orig_commit
        assert state["tripped"], "injection never fired"

        head = node.ledger.current_number()
        assert wait_until(lambda: any(
            r.get("number") == head for r in sink.results()))
        for r in sink.results():
            want = node.ledger.header_by_number(r["number"])
            assert want is not None, f"pushed header for unknown #{r}"
            assert r["hash"] == "0x" + want.hash(node.suite).hex(), (
                f"stale header pushed for block {r['number']}")
    finally:
        node.stop()


def test_fanout_generation_fence_gives_up_on_racing_invalidation():
    """White-box: when the cache generation keeps moving under the
    fan-out's fragment reads (an invalidation storm — rollback or
    snapshot install racing the worker), the batch is DROPPED after one
    retry rather than pushing bytes read across a wipe."""
    node = _mk_node()
    try:
        hub = node.subhub
        sink = _Sink()
        hub.subscribe("newBlockHeaders", sink, owner=object())
        kp = node.suite.generate_keypair(b"sub-fence")
        _register(node, kp, b"fence", 1, "fe-0")
        assert wait_until(lambda: len(sink.frames) >= 1)
        got = len(sink.frames)

        class EverMoving:
            """Delegates to the real cache but every generation() call
            observes a new generation — no read window can close."""

            def __init__(self, real):
                self._real = real
                self._g = itertools.count()

            def generation(self):
                return next(self._g)

            def __getattr__(self, name):
                return getattr(self._real, name)

        hub.cache = EverMoving(node.query_cache)
        hub.on_commit(node.ledger.current_number())
        time.sleep(0.5)  # worker runs, fence trips twice, batch dropped
        assert len(sink.frames) == got, \
            "fan-out pushed a batch whose reads raced an invalidation"
        hub.cache = node.query_cache  # heal: pushes resume
        _register(node, kp, b"fence2", 1, "fe-1")
        assert wait_until(lambda: len(sink.frames) > got)
    finally:
        node.stop()


# ---------------------------------------------------------------------------
# delivery: slow subscribers shed without delaying anyone
# ---------------------------------------------------------------------------

def test_never_draining_subscriber_sheds_without_delaying_others():
    """One subscriber whose outbox never drains: droppable frames evict
    oldest-first (counted), the healthy subscriber keeps receiving every
    head promptly, and the fan-out worker never blocks on the stuck one
    (push() is enqueue-only)."""
    from fisco_bcos_tpu.rpc.ws_server import _Session
    from fisco_bcos_tpu.utils.metrics import REGISTRY

    class FakeSock:
        closed = False

        def close(self):
            self.closed = True

    class StuckConn:
        peer = "stuck"

        def __init__(self):
            self._gate = threading.Event()
            self.sock = FakeSock()

        def send_text(self, text):
            self._gate.wait(30)  # writer parks: outbox never drains

    node = _mk_node()
    try:
        stuck = _Session(StuckConn())
        stuck.MAX_OUTBOX = 4
        healthy = _Sink()
        hub = node.subhub
        hub.subscribe("newBlockHeaders", stuck.push, owner=stuck)
        hub.subscribe("newBlockHeaders", healthy, owner=object())
        before = REGISTRY.snapshot()["counters"].get(
            "bcos_ws_push_dropped_total", 0.0)
        kp = node.suite.generate_keypair(b"sub-stuck")
        for i in range(10):
            _register(node, kp, b"st%d" % i, 1, f"st-{i}")
        head = node.ledger.current_number()
        # the healthy subscriber saw the final head promptly...
        assert wait_until(lambda: any(
            r.get("number") == head for r in healthy.results()))
        # ...while the stuck one overflowed its bounded outbox
        assert wait_until(lambda: REGISTRY.snapshot()["counters"].get(
            "bcos_ws_push_dropped_total", 0.0) > before), \
            "stuck subscriber's overflow was never shed/counted"
        assert not stuck.conn.sock.closed  # droppable stream: shed, not
        stuck.close_push()  # killed
    finally:
        node.stop()


def test_dead_sink_is_evicted_from_the_hub():
    """A sink that reports death (session killed by lossless overflow,
    socket gone) is unsubscribed by the fan-out — no zombie streams."""
    node = _mk_node()
    try:
        hub = node.subhub
        sink = _Sink()
        hub.subscribe("newBlockHeaders", sink, owner=object())
        kp = node.suite.generate_keypair(b"sub-dead")
        _register(node, kp, b"dd", 1, "dd-0")
        assert wait_until(lambda: len(sink.frames) >= 1)
        sink.ok = False  # session died
        _register(node, kp, b"dd2", 1, "dd-1")
        assert wait_until(
            lambda: hub.stats()["byKind"]["newBlockHeaders"] == 0), \
            "dead sink never evicted"
    finally:
        node.stop()


def test_unsubscribe_races_commit_fanout_cleanly():
    """unsubscribe concurrent with a storm of fan-outs: no exception, the
    registry converges to empty, and the worker stays healthy (a fresh
    subscriber still receives pushes afterwards)."""
    node = _mk_node()
    try:
        hub = node.subhub
        kp = node.suite.generate_keypair(b"sub-race")
        _register(node, kp, b"race", 1, "ra-0")
        head = node.ledger.current_number()
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                hub.on_commit(head)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            for i in range(50):
                sid = hub.subscribe("newBlockHeaders", _Sink(),
                                    owner=object())
                hub.unsubscribe(sid)
        finally:
            stop.set()
            t.join(timeout=5)
        assert hub.stats()["byKind"]["newBlockHeaders"] == 0
        late = _Sink()
        hub.subscribe("newBlockHeaders", late, owner=object())
        _register(node, kp, b"race2", 1, "ra-1")
        assert wait_until(lambda: len(late.frames) >= 1), \
            "fan-out worker died during the unsubscribe race"
    finally:
        node.stop()


# ---------------------------------------------------------------------------
# filtering + admission
# ---------------------------------------------------------------------------

def test_log_filters_match_topics_exactly():
    """logs streams filter per-position topic OR-sets exactly: the
    matching filter sees the transfer log, the non-matching one sees
    NOTHING (and an address mismatch also excludes)."""
    node = _mk_node()
    try:
        hub = node.subhub
        kp = node.suite.generate_keypair(b"sub-filter")
        _register(node, kp, b"fa", 100, "fl-0")
        _register(node, kp, b"fb", 0, "fl-1")

        match = _Sink()
        wrong_topic = _Sink()
        wrong_addr = _Sink()
        both = _Sink()  # no filter: sees everything
        hub.subscribe("logs", match, owner=object(),
                      flt=EventFilter(topics=[{b"transfer"}]))
        hub.subscribe("logs", wrong_topic, owner=object(),
                      flt=EventFilter(topics=[{b"not-a-topic"}]))
        hub.subscribe("logs", wrong_addr, owner=object(),
                      flt=EventFilter(addresses={b"\xde\xad" * 10},
                                      topics=[{b"transfer"}]))
        hub.subscribe("logs", both, owner=object())

        _transfer(node, kp, b"fa", b"fb", 7, "fl-2")
        assert wait_until(lambda: len(match.frames) >= 1), \
            "matching filter never saw the transfer log"
        row = match.results()[0]
        assert row["topics"][0] == "0x" + b"transfer".hex()
        assert row["address"] == "0x" + pc.BALANCE_ADDRESS.hex()
        assert wait_until(lambda: len(both.frames) >= 1)
        time.sleep(0.3)  # give any wrong push time to surface
        assert wrong_topic.frames == [], "topic filter leaked a log"
        assert wrong_addr.frames == [], "address filter leaked a log"
    finally:
        node.stop()


def test_subscription_storm_sheds_with_typed_error():
    """Beyond the caps the hub answers SubLimitError (wire: -32006) —
    a storm sheds with a TYPED reject, it does not grow unbounded."""
    node = _mk_node(sub_max_sessions=2)
    try:
        hub = node.subhub
        assert hub.max_sessions == 2
        hub.subscribe("newBlockHeaders", _Sink(), owner="s1")
        hub.subscribe("newBlockHeaders", _Sink(), owner="s2")
        with pytest.raises(SubLimitError):
            hub.subscribe("newBlockHeaders", _Sink(), owner="s3")
        # existing sessions may still add streams; new sessions may not
        hub.subscribe("logs", _Sink(), owner="s1")
        assert hub.stats()["rejects"] == 1
    finally:
        node.stop()


def test_receipt_subscription_is_lossless_one_shot():
    """A receipt stream for an ALREADY-committed hash completes at
    subscribe time (lossless), and the stream auto-closes after the
    single frame."""
    node = _mk_node()
    try:
        hub = node.subhub
        kp = node.suite.generate_keypair(b"sub-rc")
        _, h = _register(node, kp, b"rc1", 5, "rc-0")
        sink = _Sink()
        hub.subscribe("receipt", sink, owner=object(), tx_hash=h)
        assert wait_until(lambda: len(sink.frames) >= 1)
        assert sink.frames[0]["params"]["kind"] == "receipt"
        assert int(sink.results()[0]["status"]) == 0
        assert hub.stats()["byKind"]["receipt"] == 0  # one-shot closed
    finally:
        node.stop()


# ---------------------------------------------------------------------------
# the zero-extra-render claim (acceptance criterion)
# ---------------------------------------------------------------------------

class _DumpsCounter:
    """Counts json.dumps calls whose argument is a CONTAINER (fragment
    renders); id-only dumps (ints/strings, the envelope splice) are
    free by design and not counted."""

    def __init__(self):
        self.container_calls = 0
        self._orig = json.dumps

    def __enter__(self):
        def counting(obj, *a, **k):
            if isinstance(obj, (dict, list, tuple)):
                self.container_calls += 1
            return self._orig(obj, *a, **k)

        json.dumps = counting
        return self

    def __exit__(self, *exc):
        json.dumps = self._orig


def test_notification_render_cost_is_independent_of_subscriber_count():
    """The acceptance instrument: a commit's dumps count with 8
    subscribers equals the count with 1 — every extra subscriber costs
    buffer joins only, zero extra fragment renders beyond the prime."""
    node = _mk_node()
    try:
        hub = node.subhub
        kp = node.suite.generate_keypair(b"sub-zero")
        _register(node, kp, b"z-warm", 1, "zw-0")  # warm the planes

        def measured_commit(n_subs: int, tag: str) -> int:
            sinks = [_Sink() for _ in range(n_subs)]
            sids = [hub.subscribe("newBlockHeaders", s, owner=object())
                    for s in sinks]
            time.sleep(0.2)  # quiesce prior prime/fan-out work
            with _DumpsCounter() as dc:
                _register(node, kp, b"z-" + tag.encode(), 1, f"z-{tag}")
                head = node.ledger.current_number()
                assert wait_until(lambda: all(
                    any(r.get("number") == head for r in s.results())
                    for s in sinks))
                # let the prime observer finish rendering this block
                assert wait_until(lambda: node.query_cache.get(
                    ("senders", head)) is not None)
                time.sleep(0.3)  # zk/proof prime tail settles
                count = dc.container_calls
            for sid in sids:
                hub.unsubscribe(sid)
            return count

        one = measured_commit(1, "a")
        eight = measured_commit(8, "b")
        assert one > 0  # the prime itself renders fragments
        assert eight <= one + 1, (
            f"{eight} container dumps with 8 subscribers vs {one} with 1 "
            "— notifications are paying per-subscriber renders")
    finally:
        node.stop()


def test_polled_hits_reuse_primed_fragment_bytes():
    """Satellite: N identical polled getBlockByNumber /
    getTransactionReceipt hits after one commit perform ZERO further
    fragment dumps — the envelope writer splices the bytes rendered
    once at prime time (the only dumps per hit is the response id)."""
    import http.client

    node = _mk_node()
    try:
        kp = node.suite.generate_keypair(b"sub-poll")
        rc, h = _register(node, kp, b"poll", 5, "po-0")
        n = rc.block_number
        tx_hash = "0x" + h.hex()
        assert wait_until(lambda: node.query_cache.get(
            ("senders", n)) is not None)  # prime settled

        # pre-serialize request bodies: the client must not dump either
        blk_body = json.dumps({"jsonrpc": "2.0", "id": 1,
                               "method": "getBlockByNumber",
                               "params": ["group0", "", n, False, False]
                               }).encode()
        rc_body = json.dumps({"jsonrpc": "2.0", "id": 2,
                              "method": "getTransactionReceipt",
                              "params": ["group0", "", tx_hash, False]
                              }).encode()

        def post(body: bytes) -> dict:
            conn = http.client.HTTPConnection(node.rpc.host, node.rpc.port,
                                              timeout=30)
            try:
                conn.request("POST", "/", body=body,
                             headers={"Content-Type": "application/json"})
                return json.loads(conn.getresponse().read())
            finally:
                conn.close()

        warm = post(blk_body)  # first touch may lazily render
        assert warm["result"]["number"] == n
        post(rc_body)
        with _DumpsCounter() as dc:
            for _ in range(6):
                blk = post(blk_body)
                assert blk["result"]["number"] == n
                rcj = post(rc_body)
                assert int(rcj["result"]["status"]) == 0
            assert dc.container_calls == 0, (
                f"{dc.container_calls} fragment dumps across 12 cached "
                "hits — the envelope splice path is not being used")
    finally:
        node.stop()
