"""Disk engine (storage/engine.py): 2PC contract parity vs MemoryStorage,
crash recovery (incl. injected kill -9 points mid-flush/mid-compaction),
tombstone semantics across flush+compaction, prefix scans spanning
memtable+segments, WAL segment rotation/retirement, namespace isolation on
one engine, and the snapshot capture/install fast paths."""

import os
import random
import threading

import pytest

from fisco_bcos_tpu.storage import MemoryStorage, NamespacedStorage
from fisco_bcos_tpu.storage.engine import DiskStorage
from fisco_bcos_tpu.storage.interface import Entry, EntryStatus
from fisco_bcos_tpu.storage.wal import SegmentedWal


def _engine(tmp_path, name="db", **kw):
    kw.setdefault("auto_compact", False)
    kw.setdefault("memtable_bytes", 1 << 20)
    return DiskStorage(str(tmp_path / name), **kw)


def _dump(st, tables=("t", "u")):
    out = {}
    for table in tables:
        for k in st.keys(table):
            out[(table, k)] = st.get(table, k)
    return out


# -- 2PC contract parity ----------------------------------------------------
def test_2pc_contract_basics(tmp_path):
    st = _engine(tmp_path)
    st.set("t", b"k0", b"v0")
    cs = {("t", b"k1"): Entry(b"v1"),
          ("t", b"k0"): Entry(b"", EntryStatus.DELETED)}
    st.prepare(1, cs)
    assert st.get("t", b"k1") is None  # not visible before commit
    st.commit(1)
    assert st.get("t", b"k1") == b"v1"
    assert st.get("t", b"k0") is None
    st.prepare(2, {("t", b"k2"): Entry(b"v2")})
    st.rollback(2)
    assert st.get("t", b"k2") is None
    st.close()


def test_randomized_parity_vs_memory(tmp_path):
    """The same op stream applied to MemoryStorage and DiskStorage (with
    flushes and compactions interleaved) must be observationally equal."""
    rng = random.Random(1109)
    mem = MemoryStorage()
    disk = _engine(tmp_path)
    keys = [b"k%03d" % i for i in range(60)]
    block = 1
    for step in range(600):
        op = rng.random()
        table = rng.choice(["t", "u"])
        if op < 0.45:
            k, v = rng.choice(keys), b"v%d" % step
            mem.set(table, k, v)
            disk.set(table, k, v)
        elif op < 0.6:
            k = rng.choice(keys)
            mem.remove(table, k)
            disk.remove(table, k)
        elif op < 0.8:
            cs = {(table, rng.choice(keys)): Entry(b"b%d" % step),
                  (table, rng.choice(keys)): Entry(b"", EntryStatus.DELETED)}
            mem.prepare(block, cs)
            disk.prepare(block, cs)
            if rng.random() < 0.85:
                mem.commit(block)
                disk.commit(block)
            else:
                mem.rollback(block)
                disk.rollback(block)
            block += 1
        elif op < 0.93:
            disk.flush()
        else:
            disk.flush()
            disk.compact_once()
    assert _dump(mem) == _dump(disk)
    for table in ("t", "u"):
        assert list(mem.keys(table, b"k0")) == list(disk.keys(table, b"k0"))
    # ...and the exact same state after a clean restart
    disk.close()
    disk2 = _engine(tmp_path)
    assert _dump(mem) == _dump(disk2)
    disk2.close()


def test_prepared_but_uncommitted_vanishes_on_crash(tmp_path):
    st = _engine(tmp_path)
    st.prepare(1, {("t", b"k"): Entry(b"v")})
    st.commit(1)
    st.prepare(2, {("t", b"gone"): Entry(b"x")})
    # kill -9: no close(), reopen the directory cold
    st2 = _engine(tmp_path)
    assert st2.get("t", b"k") == b"v"
    assert st2.get("t", b"gone") is None
    st2.close()
    st.close()


# -- tombstones across flush + compaction -----------------------------------
def test_tombstones_across_flush_and_compaction(tmp_path):
    st = _engine(tmp_path)
    for i in range(20):
        st.set("t", b"d%02d" % i, b"v")
    st.flush()  # rows now live in a segment
    st.remove("t", b"d07")
    st.prepare(1, {("t", b"d08"): Entry(b"", EntryStatus.DELETED)})
    st.commit(1)
    assert st.get("t", b"d07") is None  # memtable tombstone shadows segment
    st.flush()  # tombstones now live in a NEWER segment
    assert st.get("t", b"d07") is None
    assert st.get("t", b"d08") is None
    assert st.compact_once()  # full merge drops the tombstones for real
    assert st.stats()["segment_count"] == 1
    assert st.get("t", b"d07") is None
    assert b"d07" not in list(st.keys("t"))
    # the merged segment must not carry the deleted rows at all
    seg = st._flat_locked()[0]
    assert all(not k.endswith(b"d07") and not k.endswith(b"d08")
               for k, _, _ in seg.iter_from())
    st.close()
    st2 = _engine(tmp_path)
    assert st2.get("t", b"d07") is None
    assert st2.get("t", b"d06") == b"v"
    st2.close()


def test_prefix_scan_spans_memtable_and_segments(tmp_path):
    st = _engine(tmp_path)
    for i in range(0, 30, 2):
        st.set("t", b"p%02d" % i, b"old")
    st.flush()
    for i in range(1, 30, 2):
        st.set("t", b"p%02d" % i, b"new")  # interleaved, memtable-only
    st.set("t", b"p04", b"updated")        # shadows the segment copy
    st.remove("t", b"p06")                 # tombstone over the segment copy
    got = list(st.keys("t", b"p0"))
    assert got == [b"p00", b"p01", b"p02", b"p03", b"p04", b"p05",
                   b"p07", b"p08", b"p09"]
    assert st.get("t", b"p04") == b"updated"
    assert st.get("t", b"p05") == b"new"
    assert st.get("t", b"p02") == b"old"
    st.close()


# -- WAL rotation / retirement ----------------------------------------------
def test_wal_segments_retired_after_flush(tmp_path):
    st = _engine(tmp_path)
    for i in range(50):
        st.prepare(i, {("t", b"w%02d" % i): Entry(b"x" * 100)})
        st.commit(i)
    path = st.path
    pre = SegmentedWal.list_segments(path)
    assert sum(os.path.getsize(p) for _, p in pre) > 5000
    st.flush()
    post = SegmentedWal.list_segments(path)
    # everything below the flush floor is gone; only the fresh tail remains
    assert len(post) == 1
    assert os.path.getsize(post[0][1]) == 0
    assert post[0][0] > pre[0][0]
    st.close()


def test_restart_replays_only_wal_tail(tmp_path):
    st = _engine(tmp_path)
    for i in range(100):
        st.prepare(i, {("t", b"r%03d" % i): Entry(b"y" * 50)})
        st.commit(i)
    st.flush()
    # a few post-flush commits form the tail
    for i in range(100, 104):
        st.prepare(i, {("t", b"r%03d" % i): Entry(b"z")})
        st.commit(i)
    # crash (no close); boot must read manifest + 4-record tail only
    wal_bytes = sum(os.path.getsize(p)
                    for _, p in SegmentedWal.list_segments(st.path))
    assert wal_bytes < 500  # tail, not the 100-commit history
    st2 = _engine(tmp_path)
    assert st2.get("t", b"r050") == b"y" * 50
    assert st2.get("t", b"r103") == b"z"
    assert st2.stats()["segment_count"] == 1
    st2.close()
    st.close()


def test_torn_final_wal_tail_truncated_and_recovers(tmp_path):
    st = _engine(tmp_path)
    st.prepare(1, {("t", b"good"): Entry(b"1")})
    st.commit(1)
    # kill -9 mid-append: garbage on the ACTIVE (final) segment
    segs = SegmentedWal.list_segments(st.path)
    with open(segs[-1][1], "ab") as f:
        f.write(b"\xde\xad\xbe\xef\x00\x01")
    st2 = _engine(tmp_path)
    assert st2.get("t", b"good") == b"1"
    st2.close()
    st.close()


def test_mid_stream_wal_corruption_refuses_boot(tmp_path):
    """Corruption with LATER durable records behind it must refuse boot:
    replaying over the gap would silently lose committed writes."""
    from fisco_bcos_tpu.storage.wal import WalCorruptionError

    st = _engine(tmp_path)
    st.prepare(1, {("t", b"early"): Entry(b"1")})
    st.commit(1)
    first_seg = SegmentedWal.list_segments(st.path)[-1][1]
    st._wal.rotate()
    st.prepare(2, {("t", b"late"): Entry(b"2")})
    st.commit(2)
    # rot a byte in the MIDDLE of the earlier (non-final) segment
    with open(first_seg, "rb+") as f:
        f.seek(16)
        b = f.read(1)
        f.seek(16)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(WalCorruptionError):
        _engine(tmp_path, name="db")
    st.close()


# -- injected kill -9 at every flush/compaction edge ------------------------
@pytest.mark.parametrize("failpoint", [
    "flush-before-sstable", "flush-before-manifest",
    "manifest-before-current"])
def test_kill9_mid_flush_recovers(tmp_path, failpoint):
    st = _engine(tmp_path)
    for i in range(30):
        st.set("t", b"f%02d" % i, b"v%d" % i)
    st.remove("t", b"f03")
    st._failpoints.add(failpoint)
    with pytest.raises(DiskStorage._FailPoint):
        st.flush()
    # simulate the crash: abandon the instance, reopen the directory
    st2 = _engine(tmp_path)
    assert st2.get("t", b"f00") == b"v0"
    assert st2.get("t", b"f03") is None
    assert st2.get("t", b"f29") == b"v29"
    assert sorted(st2.tables()) == ["t"]
    # and the recovered instance can flush cleanly
    assert st2.flush()
    st3_keys = list(st2.keys("t"))
    assert len(st3_keys) == 29
    st2.close()


@pytest.mark.parametrize("failpoint", [
    "compact-before-sstable", "compact-before-manifest"])
def test_kill9_mid_compaction_recovers(tmp_path, failpoint):
    st = _engine(tmp_path)
    for i in range(10):
        st.set("t", b"c%02d" % i, b"a")
    st.flush()
    st.remove("t", b"c04")
    for i in range(10, 20):
        st.set("t", b"c%02d" % i, b"b")
    st.flush()
    st._failpoints.add(failpoint)
    with pytest.raises(DiskStorage._FailPoint):
        st.compact_once()
    st2 = _engine(tmp_path)
    assert st2.get("t", b"c00") == b"a"
    assert st2.get("t", b"c04") is None
    assert st2.get("t", b"c15") == b"b"
    assert st2.compact_once()
    assert st2.get("t", b"c04") is None
    assert st2.get("t", b"c15") == b"b"
    st2.close()


@pytest.mark.parametrize("failpoint", [
    "compact-before-sstable", "compact-mid-outputs",
    "compact-before-manifest", "manifest-before-current"])
def test_kill9_mid_leveled_merge_recovers(tmp_path, failpoint):
    """PR 9's discipline extended over every leveled-merge edge — most
    importantly the NEW window between two output segments of one
    multi-output merge: recovery must land on pre-merge state (clean
    audit, every row served) and a re-run merge must complete."""
    kw = dict(seg_target_bytes=4 << 10, max_segments=2)
    st = _engine(tmp_path, **kw)
    for i in range(120):
        st.set("t", b"lm%03d" % i, b"x" * 100)
    st.flush()
    st.remove("t", b"lm007")
    for i in range(120, 240):
        st.set("t", b"lm%03d" % i, b"y" * 100)
    st.flush()
    st._failpoints.add(failpoint)
    with pytest.raises(DiskStorage._FailPoint):
        st.compact_once()
    # simulate the crash: abandon the instance, reopen the directory
    st2 = _engine(tmp_path, **kw)
    assert st2.audit() == []
    assert st2.get("t", b"lm000") == b"x" * 100
    assert st2.get("t", b"lm007") is None
    assert st2.get("t", b"lm239") == b"y" * 100
    # the recovered engine completes the interrupted merge: >1 output at
    # this segment target, non-overlapping, tombstone gone from disk
    assert st2.compact_once()
    assert st2.audit() == []
    stats = st2.stats()
    assert stats["last_merge"]["outputs"] > 1
    assert all(not k.endswith(b"lm007")
               for r in st2._flat_locked()
               for k, _, _ in r.iter_from())
    assert len(list(st2.keys("t"))) == 239
    st2.close()


def test_manifest_edge_failure_keeps_live_instance_consistent(tmp_path):
    """A TRANSIENT manifest failure mid-merge (not a crash) must leave the
    live instance on pre-merge state — the background Compactor retries
    and the retry must see coherent levels, not half-installed outputs."""
    st = _engine(tmp_path, max_segments=2)
    for i in range(30):
        st.set("t", b"tm%02d" % i, b"v")
    st.flush()
    for i in range(30, 60):
        st.set("t", b"tm%02d" % i, b"v")
    st.flush()
    st._failpoints.add("manifest-before-current")
    with pytest.raises(DiskStorage._FailPoint):
        st.compact_once()
    st._failpoints.clear()
    assert st.get("t", b"tm00") == b"v"
    assert st.get("t", b"tm59") == b"v"
    assert st.compact_once()  # retry completes on the SAME instance
    assert st.audit() == []
    assert len(list(st.keys("t"))) == 60
    st.close()


def test_leveled_merge_cost_is_level_slice_not_dataset(tmp_path):
    """THE property leveled compaction exists for: a merge reads one
    source slice + the overlapping next-level segments, so its input
    bytes stay far below total disk bytes once the store has depth."""
    st = _engine(tmp_path, max_segments=2, seg_target_bytes=8 << 10,
                 level_base_bytes=64 << 10)
    rnd = random.Random(17)
    for burst in range(12):
        for _ in range(300):
            k = b"k%06d" % rnd.randrange(20_000)
            st.set("t", k, b"z" * 100)
        st.flush()
        while st.needs_compaction():
            st.compact_once(force=False)
    stats = st.stats()
    total = sum(s["bytes"] for s in stats["segments"])
    last_in = stats["last_merge"]["input_bytes"]
    assert total > 0 and last_in > 0
    assert last_in < total, \
        f"merge read the whole dataset ({last_in}/{total} bytes)"
    assert st.audit() == []  # L1+ runs sorted + non-overlapping
    assert st.compaction_debt_bytes() == 0
    st.close()


def test_compaction_debt_tracks_backlog_and_drains(tmp_path):
    """Debt is the overload plane's saturation signal: zero at rest,
    grows while flushes outpace merging, back to zero after a drain."""
    st = _engine(tmp_path, max_segments=2)
    assert st.compaction_debt_bytes() == 0
    for burst in range(4):  # 4 L0 segments > trigger of 2
        for i in range(50):
            st.set("t", b"d%d-%02d" % (burst, i), b"w" * 64)
        st.flush()
    debt = st.compaction_debt_bytes()
    assert debt > 0
    while st.needs_compaction():
        st.compact_once(force=False)
    assert st.compaction_debt_bytes() == 0
    # reads served correctly the whole way through
    assert st.get("t", b"d0-00") == b"w" * 64
    assert st.get("t", b"d3-49") == b"w" * 64
    st.close()


def test_flush_failure_keeps_live_instance_consistent(tmp_path):
    """A failed flush folds the frozen memtable back: the SAME instance
    (not just a reopened one) must still serve every row."""
    st = _engine(tmp_path)
    for i in range(10):
        st.set("t", b"l%02d" % i, b"v")
    st._failpoints.add("flush-before-sstable")
    with pytest.raises(DiskStorage._FailPoint):
        st.flush()
    st._failpoints.clear()
    assert st.get("t", b"l05") == b"v"
    st.set("t", b"l99", b"late")
    assert st.flush()
    assert st.get("t", b"l05") == b"v"
    assert st.get("t", b"l99") == b"late"
    st.close()


# -- namespace isolation on one engine --------------------------------------
def test_namespace_isolation_on_one_engine(tmp_path):
    st = _engine(tmp_path)
    g0 = NamespacedStorage(st, "group0")
    g1 = NamespacedStorage(st, "group1")
    g0.set("t", b"k", b"zero")
    g1.set("t", b"k", b"one")
    # both groups legitimately prepare the SAME height concurrently
    g0.prepare(5, {("t", b"h5"): Entry(b"g0")})
    g1.prepare(5, {("t", b"h5"): Entry(b"g1")})
    g0.commit(5)
    g1.commit(5)
    assert g0.get("t", b"k") == b"zero"
    assert g1.get("t", b"k") == b"one"
    assert g0.get("t", b"h5") == b"g0"
    assert g1.get("t", b"h5") == b"g1"
    assert g0.tables() == ["t"]
    st.flush()
    st.compact_once()
    assert g0.get("t", b"k") == b"zero"
    assert g1.get("t", b"k") == b"one"
    st.close()
    st2 = _engine(tmp_path)
    assert NamespacedStorage(st2, "group1").get("t", b"k") == b"one"
    st2.close()


# -- background compaction bounds segments ----------------------------------
def test_auto_compaction_bounds_segments_and_rss(tmp_path):
    st = DiskStorage(str(tmp_path / "db"), memtable_bytes=8 << 10,
                     max_segments=3, auto_compact=False)
    for i in range(2000):
        st.set("t", b"big%05d" % i, b"x" * 64)  # auto-flushes many times
        if st.needs_compaction():
            st.compact_once(force=False)
    # leveled bound: the L0 flush backlog stays at/below its trigger and
    # deeper runs are non-overlapping (audit pins that), so read
    # amplification is ~L0 count + one bloom-guarded probe per level —
    # NOT one segment forever (that was the old O(dataset) full merge)
    stats = st.stats()
    l0 = next(lv for lv in stats["levels"] if lv["level"] == 0)
    assert l0["segments"] <= 4
    assert st.audit() == []
    assert st.compaction_debt_bytes() == 0
    assert st.get("t", b"big00000") == b"x" * 64
    assert st.get("t", b"big01999") == b"x" * 64
    assert len(list(st.keys("t", b"big0010"))) == 10
    # dataset exceeded the memtable cap many times over: bounded memtable
    assert st.stats()["memtable_bytes"] < 4 * (8 << 10)
    assert st.stats()["disk_bytes"] > 2000 * 64
    st.close()


def test_reads_survive_concurrent_compaction(tmp_path):
    st = DiskStorage(str(tmp_path / "db"), memtable_bytes=4 << 10,
                     max_segments=2, auto_compact=False)
    for i in range(500):
        st.set("t", b"cc%04d" % i, b"v" * 32)
    st.flush()
    errors = []

    def reader():
        try:
            for _ in range(300):
                i = random.randrange(500)
                assert st.get("t", b"cc%04d" % i) == b"v" * 32
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    while any(t.is_alive() for t in threads):
        for i in range(500, 520):
            st.set("t", b"cc%04d" % i, b"v" * 32)
        st.flush()
        st.compact_once()
    for t in threads:
        t.join()
    assert not errors, errors
    st.close()


# -- snapshot fast paths -----------------------------------------------------
def test_capture_rows_consistent_and_streams(tmp_path):
    st = _engine(tmp_path)
    for i in range(50):
        st.set("t", b"s%02d" % i, b"v%d" % i)
    st.flush()
    st.set("u", b"mem-only", b"m")
    rows = st.capture_rows()
    # writes AFTER capture must not leak into the frozen view
    first = next(rows)
    st.set("t", b"s00", b"MUTATED")
    st.set("z", b"new-table", b"n")
    got = [first] + list(rows)
    as_dict = {(t, k): v for t, k, v in got}
    assert as_dict[("t", b"s00")] == b"v0"
    assert as_dict[("u", b"mem-only")] == b"m"
    assert ("z", b"new-table") not in as_dict
    assert len(got) == 51
    st.close()


def test_install_rows_atomic_swap_preserves_private_tables(tmp_path):
    st = _engine(tmp_path)
    st.set("c_balance", b"old-acct", b"1")
    st.set("c_pbft_log", b"round", b"local-consensus-state")
    st.flush()
    st.set("c_balance", b"old-mem", b"2")
    by_table = {"c_balance": {b"alice": b"100", b"bob": b"7"},
                "s_current_state": {b"current_number": (9).to_bytes(8, "big")}}
    st.install_rows(by_table)
    # snapshot tables replaced wholesale...
    assert st.get("c_balance", b"old-acct") is None
    assert st.get("c_balance", b"old-mem") is None
    assert st.get("c_balance", b"alice") == b"100"
    # ...tables the snapshot does not carry keep their local rows
    assert st.get("c_pbft_log", b"round") == b"local-consensus-state"
    st.close()
    st2 = _engine(tmp_path)
    assert st2.get("c_balance", b"alice") == b"100"
    assert st2.get("c_pbft_log", b"round") == b"local-consensus-state"
    assert st2.stats()["segment_count"] == 1
    st2.close()


# -- engine under the real scheduler ----------------------------------------
def test_scheduler_commit_and_restart_on_disk_backend(tmp_path):
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.executor.executor import TransactionExecutor
    from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
    from fisco_bcos_tpu.protocol import Block, BlockHeader
    from fisco_bcos_tpu.scheduler.scheduler import Scheduler

    suite = make_suite(backend="host")
    st = _engine(tmp_path)
    ledger = Ledger(st, suite)
    kp = suite.generate_keypair(b"disk-node")
    ledger.build_genesis([ConsensusNode(kp.pub_bytes)])
    sched = Scheduler(st, ledger, TransactionExecutor(suite), suite, None)
    blk = Block(header=BlockHeader(number=1, sealer_list=[kp.pub_bytes]))
    result = sched.execute_block(blk)
    assert result is not None
    assert sched.commit_block(result.header)
    assert ledger.current_number() == 1
    sched.shutdown()
    st.close()

    st2 = _engine(tmp_path)
    led2 = Ledger(st2, suite)
    assert led2.current_number() == 1
    h1 = led2.header_by_number(1)
    assert h1 is not None and h1.hash(suite) == result.header.hash(suite)
    st2.close()


def test_metrics_published_with_group_label(tmp_path):
    from fisco_bcos_tpu.utils.metrics import MetricsRegistry, for_group

    reg = MetricsRegistry()
    st = DiskStorage(str(tmp_path / "db"), memtable_bytes=1 << 20,
                     auto_compact=False,
                     registry=for_group("group7", reg))
    for i in range(20):
        st.set("t", b"m%02d" % i, b"v")
    st.flush()
    st.get("t", b"m00")       # segment probe -> bloom accounting
    st.get("t", b"absent")    # negative lookup -> bloom skip
    st.set("t", b"extra", b"v")
    st.prepare(1, {("t", b"c"): Entry(b"x")})
    st.commit(1)              # commit publishes the bloom counters
    st.flush()
    st.compact_once()
    snap = reg.snapshot()
    gauges, counters = snap["gauges"], snap["counters"]
    assert gauges["bcos_storage_segments"] == 1
    assert gauges["bcos_storage_segments{'group': 'group7'}"] == 1
    assert gauges["bcos_storage_disk_bytes"] > 0
    assert "bcos_storage_memtable_bytes" in gauges
    assert "bcos_storage_compaction_debt_bytes" in gauges
    assert counters["bcos_storage_compactions_total"] == 1
    assert any(k.startswith("bcos_storage_bloom_probes_total")
               for k in counters)
    assert any(k.startswith("bcos_storage_compaction_seconds")
               for k in snap["histograms"])
    st.close()
