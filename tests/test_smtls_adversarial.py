"""Adversarial SM-TLS record/handshake tests (VERDICT r3 #9).

Active-attacker scenarios against net/smtls.py beyond the existing
tamper/replay suite: truncation, splicing, reflection, reordering,
mid-stream handshake injection, oversized records, and downgrade-shaped
mischief. The channel must fail CLOSED (SMTLSError or EOF) in every case
— never deliver attacker-influenced plaintext.

Compatibility note (documented in net/smtls.py): this is a from-scratch
GMSSL-style protocol, not GB/T 38636 TLCP on the wire; it does not
interoperate with TASSL peers.
"""

import socket
import struct
import threading

import pytest

from fisco_bcos_tpu.net.smtls import (
    CertificateAuthority,
    SMTLSContext,
    SMTLSError,
)

HANDSHAKE_FRAMES = 2  # hello + transcript signature, each direction


def handshake_through_mitm(mutator=None):
    """Client <-> MITM <-> server. The MITM forwards handshake frames
    untouched, then hands control of the raw sockets to `mutator` (or
    just keeps forwarding). Returns (client, server, mitm_c, mitm_s,
    pump_thread)."""
    ca = CertificateAuthority(seed=b"adv" * 8)
    srv_ctx = SMTLSContext(ca.pub, ca.issue("server"))
    cli_ctx = SMTLSContext(ca.pub, ca.issue("client"))
    c_inner, mitm_c = socket.socketpair()
    mitm_s, s_inner = socket.socketpair()

    def read_frame(src):
        head = src.recv(4)
        if len(head) < 4:
            raise OSError("closed")
        (ln,) = struct.unpack(">I", head)
        body = b""
        while len(body) < ln:
            chunk = src.recv(ln - len(body))
            if not chunk:
                raise OSError("closed")
            body += chunk
        return head + body

    state = {}

    def pump():
        try:
            for _ in range(HANDSHAKE_FRAMES):
                for src, dst in ((mitm_c, mitm_s), (mitm_s, mitm_c)):
                    dst.sendall(read_frame(src))
            if mutator is not None:
                mutator(mitm_c, mitm_s, read_frame)
        except OSError:
            pass

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    res = {}

    def srv():
        try:
            res["sock"] = srv_ctx.wrap_socket(s_inner, server_side=True)
        except SMTLSError as exc:
            res["err"] = exc

    st = threading.Thread(target=srv, daemon=True)
    st.start()
    client = cli_ctx.wrap_socket(c_inner, server_side=False)
    st.join(10)
    state.update(res)
    return client, state.get("sock"), mitm_c, mitm_s, t


def test_truncated_record_yields_eof_not_plaintext():
    """Cutting a record mid-body and closing must surface as EOF/error,
    never partial attacker-chosen plaintext."""
    def mutate(mitm_c, mitm_s, read_frame):
        frame = read_frame(mitm_c)
        mitm_s.sendall(frame[:len(frame) // 2])  # half a record
        mitm_s.close()

    c, s, *_ = handshake_through_mitm(mutate)
    c.sendall(b"top secret payload")
    # server sees EOF (b"") or an explicit error — never data
    try:
        got = s.recv(64)
        assert got == b""
    except SMTLSError:
        pass
    c.close()
    s.close()


def test_spliced_records_rejected():
    """Two captured records spliced into one frame: the MAC covers
    seq||ct, so any re-framing of honest bytes must fail."""
    def mutate(mitm_c, mitm_s, read_frame):
        f1 = read_frame(mitm_c)
        f2 = read_frame(mitm_c)
        body = f1[4:] + f2[4:]
        mitm_s.sendall(struct.pack(">I", len(body)) + body)

    c, s, *_ = handshake_through_mitm(mutate)
    c.sendall(b"record one")
    c.sendall(b"record two")
    with pytest.raises(SMTLSError):
        s.recv(64)
    c.close()
    s.close()


def test_reflection_rejected():
    """Echoing a peer's own record back at it must fail: send/recv keys
    are role-bound, so a reflected record's MAC cannot verify."""
    def mutate(mitm_c, mitm_s, read_frame):
        frame = read_frame(mitm_c)  # client's data record
        mitm_c.sendall(frame)       # reflect to the CLIENT

    c, s, *_ = handshake_through_mitm(mutate)
    c.sendall(b"bounce me")
    with pytest.raises(SMTLSError):
        c.recv(64)
    c.close()
    s.close()


def test_reordered_records_rejected():
    """Delivering record 2 before record 1 violates the sequence binding
    (replay/reorder protection)."""
    def mutate(mitm_c, mitm_s, read_frame):
        f1 = read_frame(mitm_c)
        f2 = read_frame(mitm_c)
        mitm_s.sendall(f2)
        mitm_s.sendall(f1)

    c, s, *_ = handshake_through_mitm(mutate)
    c.sendall(b"first")
    c.sendall(b"second")
    with pytest.raises(SMTLSError):
        s.recv(64)
    c.close()
    s.close()


def test_mid_stream_hello_injection_rejected():
    """Renegotiation-shaped garbage: a fresh handshake hello injected
    into an established channel is just an unauthenticated record."""
    def mutate(mitm_c, mitm_s, read_frame):
        ca2 = CertificateAuthority(seed=b"evil" * 8)
        ctx2 = SMTLSContext(ca2.pub, ca2.issue("mallory"))
        from fisco_bcos_tpu.crypto import refimpl
        eph_sk, eph_pub = refimpl.keygen(refimpl.SM2P256V1, b"e" * 16)
        hello = ctx2._hello(b"\x41" * 32, eph_pub)
        mitm_s.sendall(struct.pack(">I", len(hello)) + hello)

    c, s, *_ = handshake_through_mitm(mutate)
    with pytest.raises(SMTLSError):
        s.recv(64)
    c.close()
    s.close()


def test_oversized_record_header_rejected():
    """A length header beyond the record cap must be refused before any
    allocation (no memory bomb)."""
    def mutate(mitm_c, mitm_s, read_frame):
        mitm_s.sendall(struct.pack(">I", (16 * 1024 * 1024) + 1))
        mitm_s.sendall(b"\x00" * 64)

    c, s, *_ = handshake_through_mitm(mutate)
    with pytest.raises(SMTLSError):
        s.recv(64)
    c.close()
    s.close()


def test_handshake_frame_truncation_fails_closed():
    """Truncating the FIRST handshake frame (downgrade-style interference)
    aborts the handshake on at least one side; no channel half-opens."""
    ca = CertificateAuthority(seed=b"dg" * 8)
    srv_ctx = SMTLSContext(ca.pub, ca.issue("server"))
    cli_ctx = SMTLSContext(ca.pub, ca.issue("client"))
    c_inner, mitm_c = socket.socketpair()
    mitm_s, s_inner = socket.socketpair()

    def pump():
        try:
            head = mitm_c.recv(4)
            (ln,) = struct.unpack(">I", head)
            body = b""
            while len(body) < ln:
                body += mitm_c.recv(ln - len(body))
            mitm_s.sendall(head + body[:ln // 3])
            mitm_s.close()
            mitm_c.close()
        except OSError:
            pass

    threading.Thread(target=pump, daemon=True).start()
    res = {}

    def srv():
        try:
            res["sock"] = srv_ctx.wrap_socket(s_inner, server_side=True)
        except (SMTLSError, OSError) as exc:
            res["err"] = exc

    st = threading.Thread(target=srv, daemon=True)
    st.start()
    with pytest.raises((SMTLSError, OSError)):
        cli_ctx.wrap_socket(c_inner, server_side=False)
    st.join(10)
    assert "sock" not in res  # server never produced a usable channel
