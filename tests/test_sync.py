"""BlockSync unit coverage: seal verification, peer lifecycle, replay
safety, serving limits, and the two-worker stall regression.

Previously untested module. The replay-path tests drive a REAL source chain
(solo node) and hand its sealed blocks to a second node's BlockSync, so
seal verification and replay-hash checks run exactly the production path;
the serving/worker tests use a stub front so timing is deterministic.
"""

import time

from fisco_bcos_tpu.codec.wire import Reader, Writer
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import ConsensusNode
from fisco_bcos_tpu.protocol import BlockHeader, Transaction
from fisco_bcos_tpu.sync import sync as sync_mod
from fisco_bcos_tpu.sync.sync import (MAX_BLOCKS_PER_REQUEST, RESP_BLOCKS,
                                      RESP_PRUNED, BlockSync)


class StubFront:
    """Capture-everything front: broadcasts recorded, requests scripted."""

    def __init__(self):
        self.handlers = {}
        self.broadcasts = []
        self.requests = []
        self.respond_with = None  # callable(payload) -> bytes | None
        self.request_delay = 0.0

    def register_module(self, module, handler):
        self.handlers[int(module)] = handler

    def broadcast(self, module, payload):
        self.broadcasts.append((int(module), payload, time.monotonic()))

    def request(self, module, dst, payload, timeout=5.0):
        self.requests.append((int(module), dst, payload))
        if self.request_delay:
            time.sleep(self.request_delay)
        return self.respond_with(payload) if self.respond_with else None


class StubTimesync:
    def __init__(self):
        self.forgotten = []
        self.updates = []

    def forget_peer(self, p):
        self.forgotten.append(p)

    def update_peer_time(self, p, ms):
        self.updates.append((p, ms))


def make_tx(suite, kp, i, payload=b""):
    return Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "register",
                           lambda w: w.blob(b"s%d" % i + payload).u64(1)),
                       nonce=f"sync-{i}", block_limit=500).sign(suite, kp)


def build_source_chain(n_blocks, tx_payload=b""):
    """Solo node with n committed blocks -> (node, [Block with full txs])."""
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0))
    node.start()
    kp = node.suite.generate_keypair(b"sync-user")
    for i in range(n_blocks):
        res = node.send_transaction(make_tx(node.suite, kp, i, tx_payload))
        rc = node.txpool.wait_for_receipt(res.tx_hash, 15)
        assert rc is not None and rc.status == 0
    node.stop()
    blocks = [node.ledger.block_by_number(n, with_txs=True)
              for n in range(1, node.ledger.current_number() + 1)]
    return node, blocks


def build_target(source_node, front=None, timesync=None, **sync_kw):
    """A fresh node sharing the source's genesis, with a BlockSync wired to
    a stub front (production ledger/scheduler, deterministic transport)."""
    target = Node(NodeConfig(crypto_backend="host"), suite=source_node.suite)
    target.build_genesis([ConsensusNode(source_node.keypair.pub_bytes)])
    front = front or StubFront()
    bs = BlockSync(front, target.ledger, target.scheduler, target.suite,
                   timesync=timesync, **sync_kw)
    return target, bs, front


# -- seal verification ------------------------------------------------------

def quorum_fixture():
    """4-sealer ledger + a correctly-sealed header factory."""
    node = Node(NodeConfig(crypto_backend="host"))
    suite = node.suite
    kps = [suite.generate_keypair(bytes([i + 1]) * 8) for i in range(4)]
    node.build_genesis([ConsensusNode(kp.pub_bytes) for kp in kps])
    _, bs, _ = (None, BlockSync(StubFront(), node.ledger, node.scheduler,
                                suite), None)
    sealer_set = sorted(kp.pub_bytes for kp in kps)
    by_pub = {kp.pub_bytes: kp for kp in kps}

    def header_with_seals(n_seals, wrong_list=False, dup_index=False):
        h = BlockHeader(number=1, sealer_list=list(sealer_set))
        if wrong_list:
            h.sealer_list = list(reversed(sealer_set))
        hh = h.hash(suite)
        sigs = []
        for i in range(n_seals):
            sigs.append((i, suite.sign(by_pub[sealer_set[i]], hh)))
        if dup_index:
            # same sealer signing thrice must still count as ONE voice
            sigs = [(0, sigs[0][1])] * 3 + sigs
        h.signature_list = sigs
        return h

    return bs, header_with_seals


def test_seal_quorum_rejection():
    bs, make_header = quorum_fixture()
    # n=4 -> quorum 3
    assert not bs._verify_seals(make_header(2))
    assert bs._verify_seals(make_header(3))
    assert bs._verify_seals(make_header(4))


def test_duplicate_seal_indexes_are_one_voice():
    bs, make_header = quorum_fixture()
    assert not bs._verify_seals(make_header(2, dup_index=True))


def test_sealer_list_mismatch_rejected():
    bs, make_header = quorum_fixture()
    assert not bs._verify_seals(make_header(4, wrong_list=True))


def test_forged_seal_fails_quorum():
    bs, make_header = quorum_fixture()
    h = make_header(3)
    idx, seal = h.signature_list[0]
    # corrupt r (NOT the trailing recovery byte, which a pubkey-based
    # verify may ignore): 2 valid seals < quorum 3
    h.signature_list[0] = (idx, bytes([seal[0] ^ 1]) + seal[1:])
    assert not bs._verify_seals(h)


# -- replay path ------------------------------------------------------------

def test_apply_blocks_replays_and_commits():
    src, blocks = build_source_chain(2)
    target, bs, _ = build_target(src)
    bs._apply_blocks(blocks)
    assert target.ledger.current_number() == 2
    for n in (1, 2):
        assert (target.ledger.header_by_number(n).hash(src.suite)
                == src.ledger.header_by_number(n).hash(src.suite))


def test_replay_hash_mismatch_rolls_back():
    src, blocks = build_source_chain(2)
    target, bs, _ = build_target(src)
    # tamper block 1's PAYLOAD but keep its sealed header: seals verify,
    # replay produces a different txs_root -> hash mismatch -> no commit
    kp = src.suite.generate_keypair(b"attacker")
    blocks[0].transactions = [make_tx(src.suite, kp, 99)]
    bs._apply_blocks(blocks)
    assert target.ledger.current_number() == 0
    # the poisoned execution result was dropped, not cached: the honest
    # retry must succeed from a clean slate
    src2, honest = build_source_chain(2)  # fresh copy decode
    bs._apply_blocks(
        [src.ledger.block_by_number(n, with_txs=True) for n in (1, 2)])
    assert target.ledger.current_number() == 2


def test_out_of_order_and_duplicate_responses():
    src, blocks = build_source_chain(3)
    target, bs, _ = build_target(src)
    b1, b2, b3 = blocks
    # shuffled + duplicated: still commits 1..3 in order, exactly once
    bs._apply_blocks([b3, b1, b2, b1, b3])
    assert target.ledger.current_number() == 3
    # re-delivery of already-committed blocks is a no-op
    bs._apply_blocks([b1, b2])
    assert target.ledger.current_number() == 3


def test_gap_in_response_stops_cleanly():
    src, blocks = build_source_chain(3)
    target, bs, _ = build_target(src)
    bs._apply_blocks([blocks[0], blocks[2]])  # hole at 2
    assert target.ledger.current_number() == 1


# -- peer lifecycle ---------------------------------------------------------

def status_payload(number, h=b"\x00" * 32, ms=None):
    return (Writer().i64(number).blob(h)
            .i64(ms if ms is not None else int(time.time() * 1000)).bytes())


def test_peer_ttl_pruning_forgets_silent_peers():
    src, _ = build_source_chain(0)
    ts = StubTimesync()
    target, bs, front = build_target(src, timesync=ts,
                                     status_interval=0.03)
    peer = b"P" * 64
    bs._on_message(peer, status_payload(0), None)
    assert ts.updates  # clock sample ingested
    bs.start()
    try:
        deadline = time.monotonic() + 5
        while peer not in ts.forgotten and time.monotonic() < deadline:
            time.sleep(0.02)
        assert peer in ts.forgotten, "silent peer was never TTL-pruned"
        assert peer not in bs.status()["peers"]
    finally:
        bs.stop()


def test_status_gossip_not_stalled_by_slow_peer():
    """Satellite regression: a download request blocking for seconds must
    NOT delay our own status broadcasts (two-worker split) — previously
    one slow peer froze gossip long enough for peers to TTL-prune us."""
    src, _ = build_source_chain(1)
    target, bs, front = build_target(src, status_interval=0.05)
    front.request_delay = 2.0  # dead-slow peer, blocks the download worker
    front.respond_with = lambda payload: None
    bs.start()
    try:
        bs._on_message(b"P" * 64, status_payload(50), None)  # peer ahead
        deadline = time.monotonic() + 1.0
        while not front.requests and time.monotonic() < deadline:
            time.sleep(0.01)
        assert front.requests, "download never started"
        before = len(front.broadcasts)
        time.sleep(1.2)  # inside the blocked-request window
        made = len(front.broadcasts) - before
        # the old single-loop design produced ZERO broadcasts here (the
        # worker sat inside front.request); several prove the split. The
        # bound is deliberately loose — a loaded 2-core CI host can starve
        # the 0.05 s cadence, but never to zero
        assert made >= 3, (
            f"only {made} status broadcasts in 1.2s while a request was "
            "blocked — gossip is riding the download thread again")
    finally:
        bs.stop()


def test_request_timeout_stays_below_peer_ttl():
    assert (sync_mod.REQUEST_TIMEOUT
            < 1.0 * BlockSync.PEER_TTL_INTERVALS), \
        "a single blocked request must never outlive the peer TTL"


# -- serving ----------------------------------------------------------------

def serve_range(bs, lo, hi):
    out = []
    req = Writer().i64(lo).i64(hi).bytes()
    bs._on_message(b"R" * 64, req, out.append)
    assert out, "no response"
    return Reader(out[0])


def test_range_response_byte_cap(monkeypatch):
    """Satellite: full-tx responses are byte-budgeted — the server returns
    fewer blocks than asked and the client re-requests the rest."""
    src, blocks = build_source_chain(4, tx_payload=b"x" * 400)
    _, bs, _ = build_target(src)
    bs._apply_blocks(blocks)
    monkeypatch.setattr(sync_mod, "MAX_RESPONSE_BYTES", 1200)
    r = serve_range(bs, 1, 4)
    assert r.u8() == RESP_BLOCKS
    got = r.seq(lambda rr: rr.blob())
    assert 1 <= len(got) < 4  # capped
    total = sum(len(g) for g in got)
    assert total <= 1200 + max(len(g) for g in got)
    # client re-requests from where each response ends and completes
    fetched = len(got)
    for _ in range(8):
        if fetched >= 4:
            break
        r2 = serve_range(bs, 1 + fetched, 4)
        assert r2.u8() == RESP_BLOCKS
        more = r2.seq(lambda rr: rr.blob())
        assert more, "capped server stopped serving before the range ended"
        fetched += len(more)
    assert fetched == 4


def test_range_serving_clamps_and_caps_count():
    src, blocks = build_source_chain(2)
    _, bs, _ = build_target(src)
    bs._apply_blocks(blocks)
    r = serve_range(bs, 1, 1 + 10 * MAX_BLOCKS_PER_REQUEST)
    assert r.u8() == RESP_BLOCKS
    assert len(r.seq(lambda rr: rr.blob())) == 2  # clamped to our head


def test_pruned_below_marker_and_snap_failover():
    """Satellite + tentpole seam: a pruned server answers RESP_PRUNED (not
    an empty list a downloader would retry forever), and the client fails
    over to snap-sync on that answer."""
    src, blocks = build_source_chain(3)
    target, bs, front = build_target(src)
    bs._apply_blocks(blocks)
    target.ledger.prune_block_data(3)
    # server side: request below the floor -> pruned marker
    r = serve_range(bs, 1, 3)
    assert r.u8() == RESP_PRUNED
    assert r.i64() == 3
    # ranges at/above the floor still serve (tail blocks)
    r2 = serve_range(bs, 3, 3)
    assert r2.u8() == RESP_BLOCKS
    assert len(r2.seq(lambda rr: rr.blob())) == 1

    # client side: a RESP_PRUNED response triggers the snap path
    src2, _ = build_source_chain(0)
    behind, bs2, front2 = build_target(src2)
    snap_calls = []
    bs2._try_snap_sync = lambda peer: snap_calls.append(peer) or True
    front2.respond_with = \
        lambda payload: Writer().u8(RESP_PRUNED).i64(3).bytes()
    bs2._on_message(b"Q" * 64, status_payload(9), None)
    bs2._maybe_download()
    assert snap_calls == [b"Q" * 64]


def test_pruned_range_not_respammed():
    """Review fix: once a peer answered RESP_PRUNED, the download worker
    must not re-send the same doomed range request on every idle tick —
    the peer's floor is remembered and only the snap path (which carries
    its own backoff) is retried."""
    src, _ = build_source_chain(0)
    target, bs, front = build_target(src)
    peer = b"Q" * 64
    snap_calls = []
    bs._try_snap_sync = lambda p: snap_calls.append(p) or False
    front.respond_with = \
        lambda payload: Writer().u8(RESP_PRUNED).i64(50).bytes()
    bs._on_message(peer, status_payload(9), None)
    bs._maybe_download()  # ONE range request, learns the peer's floor
    assert len(front.requests) == 1
    for _ in range(20):
        bs._maybe_download()  # previously: one doomed request per tick
    assert len(front.requests) == 1, \
        "range request re-sent below a known pruned floor"
    assert snap_calls, "snap failover never attempted"
    # a forgotten peer drops its floor too (fresh state on rejoin)
    with bs._lock:
        bs._peers[peer] = (9, time.monotonic() - 1e6)
    bs._prune_peers(time.monotonic())
    assert peer not in bs._pruned_floors


def test_failed_snap_attempt_reverts_mode():
    """Review fix: sync_mode flips to "snap" BEFORE the install commit can
    publish the new height (no observer may see the new height with the
    stale "replay" mode) — so a FAILED attempt must revert it."""
    from fisco_bcos_tpu.utils.metrics import REGISTRY

    src, _ = build_source_chain(0)
    target, bs, front = build_target(src)
    assert bs._try_snap_sync(b"Q" * 64) is False  # stub front: no manifest
    assert bs.sync_mode == "replay"
    assert bs.status()["syncMode"] == "replay"
    assert REGISTRY.snapshot()["gauges"]["bcos_sync_mode"] == 0


def test_sync_status_reports_mode_and_floor():
    src, _ = build_source_chain(1)
    target, bs, _ = build_target(src)
    st = bs.status()
    assert st["syncMode"] == "replay"
    assert st["prunedBelow"] == 0


# -- coalesced range-batch seal verification -------------------------------

class _VerifyCountingSuite:
    """Delegating wrapper counting verify_batch calls + signatures — the
    instrument behind "ONE device call per range response"."""

    def __init__(self, suite):
        self._suite = suite
        self.calls = 0
        self.sigs = 0

    def __getattr__(self, name):
        return getattr(self._suite, name)

    def verify_batch(self, digests, sigs, pubs):
        self.calls += 1
        self.sigs += len(digests)
        return self._suite.verify_batch(digests, sigs, pubs)


def test_range_batch_verifies_seals_in_one_call():
    """A whole range response's commit seals go through ONE verify_batch
    (the PBFT drain-loop trick) instead of a device round trip per block."""
    src, blocks = build_source_chain(4)
    target = Node(NodeConfig(crypto_backend="host"), suite=src.suite)
    target.build_genesis([ConsensusNode(src.keypair.pub_bytes)])
    counting = _VerifyCountingSuite(src.suite)
    bs = BlockSync(StubFront(), target.ledger, target.scheduler, counting)
    bs._apply_blocks(blocks)
    assert target.ledger.current_number() == 4
    assert counting.calls == 1, (
        f"{counting.calls} verify_batch calls for a 4-block response")
    assert counting.sigs == sum(len(b.header.signature_list) for b in blocks)


def test_range_batch_forged_seal_still_rejected():
    """A forged seal mid-range fails the batched quorum check; the
    per-block fallback confirms and replay stops exactly there."""
    src, blocks = build_source_chain(3)
    target = Node(NodeConfig(crypto_backend="host"), suite=src.suite)
    target.build_genesis([ConsensusNode(src.keypair.pub_bytes)])
    counting = _VerifyCountingSuite(src.suite)
    bs = BlockSync(StubFront(), target.ledger, target.scheduler, counting)
    idx, seal = blocks[2].header.signature_list[0]
    blocks[2].header.signature_list = [(idx, b"\x00" * len(seal))]
    bs._apply_blocks(blocks)
    assert target.ledger.current_number() == 2  # stopped at the forgery
    # one range batch + one per-block fallback for the rejected header
    assert counting.calls == 2, counting.calls


def test_range_batch_falls_back_when_sealer_set_changes(monkeypatch):
    """If a replayed block changes the on-chain sealer set, the batch
    verdict (judged against the pre-replay set) is discarded and the
    remaining blocks re-verify per block against the LIVE set."""
    src, blocks = build_source_chain(3)
    target = Node(NodeConfig(crypto_backend="host"), suite=src.suite)
    target.build_genesis([ConsensusNode(src.keypair.pub_bytes)])
    counting = _VerifyCountingSuite(src.suite)
    bs = BlockSync(StubFront(), target.ledger, target.scheduler, counting)
    # simulate a mid-replay governance change: after block 1 commits, the
    # live sealer set no longer matches the batch-time snapshot
    real_set = bs._sealer_set
    state = {"mutated": False}

    def mutating_set():
        s = real_set()
        return list(reversed(s)) + [b"\xff" * 64] if state["mutated"] else s

    orig_commit = target.scheduler.commit_block

    def commit_and_mutate(header):
        ok = orig_commit(header)
        if ok and header.number == 1:
            state["mutated"] = True
        return ok

    monkeypatch.setattr(bs, "_sealer_set", mutating_set)
    monkeypatch.setattr(target.scheduler, "commit_block", commit_and_mutate)
    bs._apply_blocks(blocks)
    # block 1 rode the batch verdict; from block 2 on the live set no
    # longer matches the batch-time snapshot, so the batch verdict is NOT
    # trusted — block 2 goes through the per-block fallback, which judges
    # it against the LIVE (changed) set and rejects it: replay stops at 1
    # (both paths apply the same admission rules via _collect_seals)
    assert target.ledger.current_number() == 1
    # the rejected fallback needed no crypto (structural sealer-list
    # mismatch): the range batch stays the only verify_batch call
    assert counting.calls == 1, counting.calls


def test_range_batch_duplicate_height_cannot_ride_sibling_verdict():
    """Security regression: batch verdicts are keyed by HEADER HASH. A
    response carrying [forged block N (bogus seals), legit block N] must
    not let the forged sibling ride the legit one's True verdict — the
    forged block (first in peer-controlled order) is rejected and nothing
    from the poisoned response commits."""
    from fisco_bcos_tpu.protocol import Block

    src, blocks = build_source_chain(2)
    target = Node(NodeConfig(crypto_backend="host"), suite=src.suite)
    target.build_genesis([ConsensusNode(src.keypair.pub_bytes)])
    counting = _VerifyCountingSuite(src.suite)
    bs = BlockSync(StubFront(), target.ledger, target.scheduler, counting)
    forged = Block.decode(blocks[0].encode())
    forged.header.extra_data = b"evil"
    forged.header.invalidate()
    idx, seal = forged.header.signature_list[0]
    forged.header.signature_list = [(idx, b"\x00" * len(seal))]
    bs._apply_blocks([forged, blocks[0], blocks[1]])
    assert target.ledger.current_number() == 0, \
        "a block with forged seals was committed"
    # the legit blocks alone still replay fine afterwards
    bs._apply_blocks(blocks)
    assert target.ledger.current_number() == 2


# -- quorum-certificate blocks in range replay ------------------------------

def _certify(block, n=1):
    """Re-carry a sealed block's loose seals as a cert-mode QuorumCert —
    exactly what a seal_mode=cert source ships (signature_list is outside
    the header hash, so the header identity is untouched)."""
    from fisco_bcos_tpu.consensus import qc
    qc.attach(block.header, qc.mint_cert(
        [(i, s) for i, s in block.header.signature_list], n))
    return block


def test_mixed_legacy_and_cert_range_replays_in_one_call():
    """One range response holding legacy multi-seal blocks THEN cert-mode
    blocks (a mid-chain seal_mode rollout) replays end-to-end, and the
    whole mixed span still costs exactly ONE verify_batch call."""
    src, blocks = build_source_chain(4)
    target = Node(NodeConfig(crypto_backend="host"), suite=src.suite)
    target.build_genesis([ConsensusNode(src.keypair.pub_bytes)])
    counting = _VerifyCountingSuite(src.suite)
    bs = BlockSync(StubFront(), target.ledger, target.scheduler, counting)
    blocks = blocks[:2] + [_certify(b) for b in blocks[2:]]
    bs._apply_blocks(blocks)
    assert target.ledger.current_number() == 4
    assert counting.calls == 1, (
        f"{counting.calls} verify_batch calls for a mixed 4-block response")


def test_cert_block_with_stale_sealer_set_stops_replay(monkeypatch):
    """Mid-span governance change under a cert rollout: once the live
    sealer set diverges from the batch-time snapshot, a cert block must
    re-verify per block against the LIVE set — and fail its sealer-set
    admission (a certificate minted under a stale roster is dead)."""
    src, blocks = build_source_chain(3)
    target = Node(NodeConfig(crypto_backend="host"), suite=src.suite)
    target.build_genesis([ConsensusNode(src.keypair.pub_bytes)])
    counting = _VerifyCountingSuite(src.suite)
    bs = BlockSync(StubFront(), target.ledger, target.scheduler, counting)
    blocks = [blocks[0]] + [_certify(b) for b in blocks[1:]]
    real_set = bs._sealer_set
    state = {"mutated": False}
    monkeypatch.setattr(
        bs, "_sealer_set",
        lambda: [b"\xee" * 64] if state["mutated"] else real_set())
    orig_commit = target.scheduler.commit_block

    def commit_and_mutate(header):
        ok = orig_commit(header)
        if ok and header.number == 1:
            state["mutated"] = True
        return ok

    monkeypatch.setattr(target.scheduler, "commit_block", commit_and_mutate)
    bs._apply_blocks(blocks)
    # block 1 rode the batch; cert block 2's fallback judges against the
    # changed live set and rejects structurally (no extra lane call)
    assert target.ledger.current_number() == 1
    assert counting.calls == 1, counting.calls


def test_byzantine_legacy_flagged_cert_blob_rejected():
    """A Byzantine peer re-flags a cert blob under a legacy seal index:
    the blob must never parse as a certificate, the header fails legacy
    quorum, and nothing from the response commits."""
    src, blocks = build_source_chain(2)
    target = Node(NodeConfig(crypto_backend="host"), suite=src.suite)
    target.build_genesis([ConsensusNode(src.keypair.pub_bytes)])
    bs = BlockSync(StubFront(), target.ledger, target.scheduler, src.suite)
    evil = _certify(blocks[0])
    evil.header.signature_list = [(0, evil.header.signature_list[0][1])]
    bs._apply_blocks([evil, blocks[1]])
    assert target.ledger.current_number() == 0


def test_aggregate_block_replays_through_sync():
    """A seal_mode=aggregate block (64-byte BLS point) replays through the
    range path when the target holds the PoP registry, and is refused when
    it does not."""
    from fisco_bcos_tpu.consensus import qc
    from fisco_bcos_tpu.crypto import agg

    src, blocks = build_source_chain(1)
    seed = src.keypair.secret.to_bytes(32, "big")
    registry = agg.AggKeyRegistry.from_seeds([(src.keypair.pub_bytes, seed)])
    hh = blocks[0].header.hash(src.suite)
    qc.attach(blocks[0].header,
              qc.mint_aggregate([0], agg.sign(agg.derive_secret(seed), hh),
                                1))
    target = Node(NodeConfig(crypto_backend="host"), suite=src.suite)
    target.build_genesis([ConsensusNode(src.keypair.pub_bytes)])
    bare = BlockSync(StubFront(), target.ledger, target.scheduler, src.suite)
    bare._apply_blocks(blocks)
    assert target.ledger.current_number() == 0  # no registry -> refused
    bs = BlockSync(StubFront(), target.ledger, target.scheduler, src.suite,
                   agg_registry=registry)
    bs._apply_blocks(blocks)
    assert target.ledger.current_number() == 1
