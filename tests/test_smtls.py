"""SM-TLS dual-certificate transport (net.smtls).

Counterpart of the reference's GMSSL context tests around
bcos-boostssl/context/ContextBuilder.cpp: dual-cert issuance, mutual
authentication, record protection, and the gateway integration where
`SMTLSContext` slots into the same seam as `ssl.SSLContext`.
"""

import socket
import struct
import threading
import time

import pytest

from fisco_bcos_tpu.net.smtls import (
    Certificate,
    CertificateAuthority,
    SMTLSContext,
    SMTLSError,
    _hmac_sm3,
)


def _pair_handshake(server_ctx, client_ctx):
    a, b = socket.socketpair()
    out = {}

    def srv():
        out["server"] = server_ctx.wrap_socket(a, server_side=True)

    t = threading.Thread(target=srv)
    t.start()
    out["client"] = client_ctx.wrap_socket(b, server_side=False)
    t.join(10)
    return out["client"], out["server"]


def test_ca_issue_and_verify():
    ca = CertificateAuthority(seed=b"ca-seed" * 4)
    cred = ca.issue("node0", seed=b"node0-seed")
    assert CertificateAuthority.verify_cert(ca.pub, cred.sign_cert)
    assert CertificateAuthority.verify_cert(ca.pub, cred.enc_cert)
    assert cred.sign_cert.usage == 0 and cred.enc_cert.usage == 1
    assert cred.sign_cert.pub != cred.enc_cert.pub

    # round-trip encoding
    again = Certificate.decode(cred.sign_cert.encode())
    assert again == cred.sign_cert

    # tampered subject breaks the CA signature
    bad = Certificate("node1", again.usage, again.pub, again.serial,
                      again.sig)
    assert not CertificateAuthority.verify_cert(ca.pub, bad)


def test_handshake_and_records_both_ways():
    ca = CertificateAuthority(seed=b"ca2" * 8)
    srv_ctx = SMTLSContext(ca.pub, ca.issue("server", seed=b"s" * 8))
    cli_ctx = SMTLSContext(ca.pub, ca.issue("client", seed=b"c" * 8))
    c, s = _pair_handshake(srv_ctx, cli_ctx)

    assert c.peer_subject == "server"
    assert s.peer_subject == "client"

    c.sendall(b"ping " * 1000)
    got = b""
    while len(got) < 5000:
        got += s.recv(5000 - len(got))
    assert got == b"ping " * 1000

    s.sendall(b"pong")
    assert c.recv(4) == b"pong"
    c.close()
    s.close()


def test_untrusted_ca_rejected():
    ca1 = CertificateAuthority(seed=b"trusted!" * 4)
    ca2 = CertificateAuthority(seed=b"intruder" * 4)
    srv_ctx = SMTLSContext(ca1.pub, ca1.issue("server"))
    rogue_ctx = SMTLSContext(ca1.pub, ca2.issue("mallory"))

    a, b = socket.socketpair()
    err = {}

    def srv():
        try:
            srv_ctx.wrap_socket(a, server_side=True)
        except SMTLSError as e:
            err["server"] = e

    t = threading.Thread(target=srv)
    t.start()
    with pytest.raises(SMTLSError):
        rogue_ctx.wrap_socket(b, server_side=False)
    t.join(10)
    assert "server" in err  # server also refused the rogue cert


def test_record_tamper_and_replay_detected():
    ca = CertificateAuthority(seed=b"ca3" * 8)
    srv_ctx = SMTLSContext(ca.pub, ca.issue("server"))
    cli_ctx = SMTLSContext(ca.pub, ca.issue("client"))

    # intercept the raw byte stream with a plain socket pair in the middle
    c_inner, mitm_c = socket.socketpair()
    mitm_s, s_inner = socket.socketpair()

    done = threading.Event()

    def pump():
        # forward handshake frames untouched, then tamper with the first
        # data record's ciphertext
        try:
            for _ in range(2):  # hello + transcript signature
                for src, dst in ((mitm_c, mitm_s), (mitm_s, mitm_c)):
                    head = src.recv(4)
                    (ln,) = struct.unpack(">I", head)
                    body = b""
                    while len(body) < ln:
                        body += src.recv(ln - len(body))
                    dst.sendall(head + body)
            head = mitm_c.recv(4)
            (ln,) = struct.unpack(">I", head)
            body = b""
            while len(body) < ln:
                body += mitm_c.recv(ln - len(body))
            flipped = bytearray(body)
            flipped[10] ^= 0x01  # inside the ciphertext
            mitm_s.sendall(head + bytes(flipped))
        except OSError:
            pass
        done.set()

    threading.Thread(target=pump, daemon=True).start()

    res = {}

    def srv():
        res["sock"] = srv_ctx.wrap_socket(s_inner, server_side=True)

    t = threading.Thread(target=srv)
    t.start()
    c = cli_ctx.wrap_socket(c_inner, server_side=False)
    t.join(10)
    s = res["sock"]

    c.sendall(b"secret message")
    assert done.wait(10)
    with pytest.raises(SMTLSError):
        s.recv(32)
    for sk in (c, s):
        sk.close()


def test_hmac_sm3_keyed_and_deterministic():
    t1 = _hmac_sm3(b"k1", b"message")
    t2 = _hmac_sm3(b"k2", b"message")
    t3 = _hmac_sm3(b"k1", b"message")
    assert t1 != t2 and t1 == t3 and len(t1) == 32


def test_gateway_over_smtls():
    """Two P2P gateways linked through SM-TLS contexts deliver front
    traffic — the dual-cert plane slots into the standard ssl seam."""
    from fisco_bcos_tpu.net.p2p import P2PGateway

    ca = CertificateAuthority(seed=b"chain-ca" * 4)
    ids = [b"\x01" * 32, b"\x02" * 32]
    ctxs = [SMTLSContext(ca.pub, ca.issue(f"node{i}", seed=bytes([i]) * 8))
            for i in range(2)]

    gws = [P2PGateway(ids[i], server_ssl=ctxs[i], client_ssl=ctxs[i])
           for i in range(2)]
    gws[0].add_peer(gws[1].host, gws[1].port)
    gws[1].add_peer(gws[0].host, gws[0].port)

    got = {}

    class FakeFront:
        def __init__(self, name):
            self.name = name

        def on_network_message(self, src, payload):
            got[self.name] = (src, payload)

    try:
        gws[0].register_front(ids[0], FakeFront("a"))
        gws[1].register_front(ids[1], FakeFront("b"))
        t0 = time.monotonic()
        while time.monotonic() - t0 < 20:
            if gws[0].send(ids[0], ids[1], b"hello-sm") and "b" in got:
                break
            time.sleep(0.05)
        assert got.get("b") == (ids[0], b"hello-sm"), got
        assert gws[1].send(ids[1], ids[0], b"yo")
        t0 = time.monotonic()
        while "a" not in got and time.monotonic() - t0 < 10:
            time.sleep(0.05)
        assert got.get("a") == (ids[1], b"yo")
    finally:
        for gw in gws:
            gw.stop()


def test_transcript_signature_is_role_bound():
    """A signature produced by one role must not verify for the other —
    the anti-reflection property: a MITM mirroring the client's certs
    cannot echo the client's own signature as its server proof."""
    from fisco_bcos_tpu.crypto import refimpl

    ca = CertificateAuthority(seed=b"ca4" * 8)
    cred = ca.issue("node", seed=b"n" * 8)
    t_digest = refimpl.sm3(b"some-transcript")
    client_sig = refimpl.sm2_sign(cred.sign_key,
                                  refimpl.sm3(b"client" + t_digest))
    # verifying the reflected signature under the SERVER role fails
    assert not refimpl.sm2_verify(cred.sign_cert.pub,
                                  refimpl.sm3(b"server" + t_digest),
                                  *client_sig)
    assert refimpl.sm2_verify(cred.sign_cert.pub,
                              refimpl.sm3(b"client" + t_digest),
                              *client_sig)


def test_gateway_accept_survives_garbage_dial():
    """A port-scan / garbage inbound connection must not kill the SM-TLS
    gateway's accept loop (SMTLSError is an OSError, not an ssl.SSLError)."""
    from fisco_bcos_tpu.net.p2p import P2PGateway

    ca = CertificateAuthority(seed=b"acc-ca" * 5)
    ids = [b"\x07" * 32, b"\x08" * 32]
    ctxs = [SMTLSContext(ca.pub, ca.issue(f"n{i}", seed=bytes([9 + i]) * 8))
            for i in range(2)]
    gws = [P2PGateway(ids[i], server_ssl=ctxs[i], client_ssl=ctxs[i])
           for i in range(2)]

    class NullFront:
        def on_network_message(self, src, payload):
            pass

    try:
        gws[0].register_front(ids[0], NullFront())
        # garbage dial straight at the listener
        s = socket.create_connection((gws[0].host, gws[0].port), timeout=5)
        s.sendall(b"\x00\x00\x00\x04junk")
        s.close()
        time.sleep(0.2)
        # a legitimate SM-TLS peer must still be able to connect
        gws[1].register_front(ids[1], NullFront())
        gws[1].add_peer(gws[0].host, gws[0].port)
        gws[0].add_peer(gws[1].host, gws[1].port)  # smaller id owns the dial
        t0 = time.monotonic()
        while time.monotonic() - t0 < 15 and len(gws[0].peers()) != 1:
            time.sleep(0.05)
        assert len(gws[0].peers()) == 1, "accept loop died after garbage dial"
    finally:
        for gw in gws:
            gw.stop()
