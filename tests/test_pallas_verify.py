"""Fused end-to-end verify kernel: constant plumbing + piece parity.

The full-verify interpret run is hours on one core, so CI pins what it
can cheaply: the consts-block column layout against the Curve's host
constants (a column mixup is the likeliest silent-wrong-result bug), and
the dispatch gating. The in-kernel pieces (inv_tree, _glv_split_values)
have interpret-mode parity tests gated behind FBTPU_SLOW_TESTS; the
composition is asserted on real TPU by the device sweep before any
number is recorded.
"""

import os

import numpy as np
import pytest

from fisco_bcos_tpu.crypto import refimpl
from fisco_bcos_tpu.ops import ec, fp, pallas_verify


def test_consts_block_layout():
    cv = ec.SECP256K1
    c, gts = pallas_verify._secp_consts()
    assert (c[:, pallas_verify._C_P] == cv.fp.limbs).all()
    assert (c[:, pallas_verify._C_B] == cv.b_rep).all()
    assert (c[:, pallas_verify._C_BETA] == cv.beta_rep).all()
    assert (c[:, pallas_verify._C_N] == cv.fn.limbs).all()
    assert (c[:, pallas_verify._C_NPRIME] == cv.fn.nprime).all()
    assert (c[:, pallas_verify._C_R2] == cv.fn.r2).all()
    assert (c[:, pallas_verify._C_ONEM] == cv.fn.one_m).all()
    assert (c[:, pallas_verify._C_HALF] == cv.half_n_limbs).all()
    assert (c[:, pallas_verify._C_G1] == cv.g1_limbs).all()
    assert (c[:, pallas_verify._C_G2] == cv.g2_limbs).all()
    assert (c[:, pallas_verify._C_MB1]
            == cv.fn.encode_int(cv.mb1_int)).all()
    assert (c[:, pallas_verify._C_MB2]
            == cv.fn.encode_int(cv.mb2_int)).all()
    assert (c[:, pallas_verify._C_LAM]
            == cv.fn.encode_int(cv.glv_lambda)).all()
    assert gts.shape == (2, 16, 32)
    assert (gts[0] == cv.g_table).all()
    assert (gts[1] == cv.g_table_endo).all()


def test_fused_verify_gated_off_by_default(monkeypatch):
    monkeypatch.delenv("FBTPU_FUSED_VERIFY", raising=False)
    ec._FUSED_VERIFY_CACHE.clear()
    try:
        assert ec._use_fused_verify() is False
    finally:
        ec._FUSED_VERIFY_CACHE.clear()


def _mont_ctx(c_ref):
    V = pallas_verify
    return V._MontCtx(
        ec.SECP256K1.fn,
        c_ref[:, V._C_N:V._C_N + 1],
        c_ref[:, V._C_NPRIME:V._C_NPRIME + 1],
        c_ref[:, V._C_ONEM:V._C_ONEM + 1],
        c_ref[:, V._C_R2:V._C_R2 + 1])


@pytest.mark.skipif("FBTPU_SLOW_TESTS" not in os.environ,
                    reason="interpret-mode kernel pieces take minutes; "
                           "run with FBTPU_SLOW_TESTS=1 (device sweep "
                           "asserts the full composition on TPU)")
def test_inv_tree_parity():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    cv = ec.SECP256K1
    rng = np.random.default_rng(41)
    B = 8
    vals = ([int.from_bytes(rng.bytes(32), "big") % cv.fn.n_int
             for _ in range(B - 1)] + [0])
    arr = np.stack([fp.to_limbs(v) for v in vals], axis=1)
    consts, _ = pallas_verify._secp_consts()
    inv_digits = fp.msb_digits(cv.fn.n_int - 2, 4)

    def kernel(digs_ref, c_ref, a_ref, o_ref):
        fn = _mont_ctx(c_ref)
        o_ref[:, :] = fn.inv_tree(fn.to_rep(a_ref[:, :]), digs_ref,
                                  digs_ref.shape[0])

    got = np.asarray(pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((16, B), jnp.uint32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(), pl.BlockSpec()],
        interpret=True)(jnp.asarray(inv_digits), jnp.asarray(consts), arr))
    want = np.asarray(cv.fn.inv_batch(cv.fn.to_rep(jnp.asarray(arr))))
    assert (got == want).all()


@pytest.mark.skipif("FBTPU_SLOW_TESTS" not in os.environ,
                    reason="see test_inv_tree_parity")
def test_glv_split_parity():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    cv = ec.SECP256K1
    rng = np.random.default_rng(47)
    B = 8
    kvals = [int.from_bytes(rng.bytes(32), "big") % cv.fn.n_int
             for _ in range(B)]
    karr = np.stack([fp.to_limbs(v) for v in kvals], axis=1)
    consts, _ = pallas_verify._secp_consts()

    def kernel(c_ref, k_ref, o_ref):
        fn = _mont_ctx(c_ref)
        m1, n1, m2, n2 = pallas_verify._glv_split_values(fn, c_ref,
                                                         k_ref[:, :])
        o_ref[0] = m1
        o_ref[1] = m2
        o_ref[2] = jnp.broadcast_to(n1[None, :].astype(jnp.uint32),
                                    m1.shape)
        o_ref[3] = jnp.broadcast_to(n2[None, :].astype(jnp.uint32),
                                    m2.shape)

    got = np.asarray(pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((4, 16, B), jnp.uint32),
        interpret=True)(jnp.asarray(consts), karr))
    w1, wn1, w2, wn2 = ec._glv_split_device(cv, jnp.asarray(karr))
    assert (got[0] == np.asarray(w1)).all()
    assert (got[1] == np.asarray(w2)).all()
    assert (got[2][0].astype(bool) == np.asarray(wn1)).all()
    assert (got[3][0].astype(bool) == np.asarray(wn2)).all()
