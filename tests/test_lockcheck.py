"""Runtime lock-discipline checker (fisco_bcos_tpu/analysis/lockcheck.py).

Unit half: the detectors themselves — ABBA cycle, canonical-order
violation, blocking-while-locked on an injected fsync, self-deadlock,
condition-wait untracking, disarmed no-op shape, hold-time metrics.

Matrix half: the interleavings past PRs had to debug by hand, driven on
REAL components with the checker armed — commit-vs-sync on a live node,
compaction-vs-scan-vs-install on the disk engine, ingest-vs-shutdown,
admission-vs-release — each asserting a CLEAN report.
"""

from __future__ import annotations

import os
import threading
import time

import pytest

from fisco_bcos_tpu.analysis import lockcheck as lc
from fisco_bcos_tpu.analysis.lockorder import HOT_LOCKS, RANK


@pytest.fixture()
def armed():
    """Arm for the test, and ALWAYS reset+restore after: deliberate
    violations must not leak into the session-wide conftest gate."""
    was = lc.armed()
    lc.arm()
    lc.reset()
    yield
    lc.reset()
    if not was:
        lc.disarm()


# -- disarmed: the production state ---------------------------------------

def test_disarmed_factories_return_plain_primitives():
    was = lc.armed()
    lc.disarm()
    try:
        lock = lc.make_lock("t.plain")
        rlock = lc.make_rlock("t.plain_r")
        cv = lc.make_condition("t.plain_cv")
        assert type(lock) is type(threading.Lock())
        assert type(rlock) is type(threading.RLock())
        assert isinstance(cv, threading.Condition)
        # markers are a single flag branch — and record nothing
        with lock:
            lc.note_blocking("fsync", "disarmed")
        assert lc.report()["blocking"] == []
    finally:
        if was:
            lc.arm()


def test_disarmed_marker_is_cheap():
    was = lc.armed()
    lc.disarm()
    try:
        n = 200_000
        t0 = time.perf_counter()
        for _ in range(n):
            lc.note_blocking("fsync")
        per = (time.perf_counter() - t0) / n
        # one flag branch; generous bound for a loaded CI host
        assert per < 5e-6, f"disarmed marker costs {per*1e9:.0f}ns"
    finally:
        if was:
            lc.arm()


# -- cycle / order detection ----------------------------------------------

def test_abba_cycle_detected(armed):
    a = lc.make_lock("t.A")
    b = lc.make_lock("t.B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    rep = lc.report()
    assert len(rep["cycles"]) == 1
    cyc = rep["cycles"][0]
    assert set(cyc["path"]) == {"t.A", "t.B"}
    # the closing edge carries its acquisition stack for the report
    assert any("test_lockcheck" in fr for fr in cyc["stack"])
    with pytest.raises(AssertionError):
        lc.assert_clean()


def test_canonical_order_violation(armed):
    # engine.state ranks INSIDE scheduler.2pc; taking them inverted is a
    # violation even though no full cycle exists yet
    assert RANK["engine.state"] > RANK["scheduler.2pc"]
    inner = lc.make_rlock("engine.state")
    outer = lc.make_lock("scheduler.2pc")
    with inner:
        with outer:
            pass
    rep = lc.report()
    assert rep["cycles"] == []
    assert len(rep["order_violations"]) == 1
    v = rep["order_violations"][0]
    assert (v["outer"], v["inner"]) == ("engine.state", "scheduler.2pc")


def test_correct_order_is_clean(armed):
    outer = lc.make_lock("scheduler.2pc")
    inner = lc.make_rlock("engine.state")
    with outer:
        with inner:
            pass
    lc.assert_clean()


def test_same_name_instances_do_not_self_cycle(armed):
    # two nodes' txpool locks share the NAME; nesting them must not be
    # reported as a txpool.state -> txpool.state cycle
    l1 = lc.make_rlock("txpool.state")
    l2 = lc.make_rlock("txpool.state")
    with l1:
        with l2:
            pass
    lc.assert_clean()


# -- blocking-while-locked ------------------------------------------------

def test_blocking_under_hot_lock_via_injected_fsync(armed, tmp_path):
    """A REAL fsync (SegmentedWal.append crosses the marker) while a hot
    no-blocking lock is held must be reported with both names."""
    from fisco_bcos_tpu.storage.wal import SegmentedWal

    assert HOT_LOCKS["txpool.state"] == frozenset()
    wal = SegmentedWal(str(tmp_path), 1)
    hot = lc.make_rlock("txpool.state")
    with hot:
        wal.append(1, {})
    rep = lc.report()
    assert len(rep["blocking"]) == 1
    v = rep["blocking"][0]
    assert v["lock"] == "txpool.state" and v["kind"] == "fsync"
    assert v["detail"] == "SegmentedWal.append"


def test_allowed_blocking_kind_is_clean(armed, tmp_path):
    """The engine/WAL locks exist to ORDER durable writes: fsync under
    them is the contract (lockorder.HOT_LOCKS allow-sets), not a bug."""
    from fisco_bcos_tpu.storage.wal import SegmentedWal

    wal = SegmentedWal(str(tmp_path), 1)
    hot = lc.make_rlock("engine.state")
    with hot:
        wal.append(1, {})
    assert lc.report()["blocking"] == []
    # ...but a device crypto call under the same lock is NOT allowed
    with hot:
        lc.note_blocking("suite_batch", "verify_batch")
    rep = lc.report()
    assert [b["kind"] for b in rep["blocking"]] == ["suite_batch"]


def test_blocking_with_no_lock_held_is_clean(armed):
    lc.note_blocking("fsync", "free-standing")
    assert lc.report()["blocking"] == []


# -- self-deadlock / reentrancy / conditions -------------------------------

def test_self_deadlock_raises_instead_of_hanging(armed):
    lock = lc.make_lock("t.self")
    with lock:
        with pytest.raises(RuntimeError, match="re-acquired"):
            lock.acquire()
    assert len(lc.report()["self_deadlocks"]) == 1
    lc.reset()  # deliberate violation: do not leak into the session gate


def test_rlock_reentrancy_records_no_edge(armed):
    r = lc.make_rlock("t.re")
    inner = lc.make_lock("t.re_inner")
    with r:
        with r:  # reentrant: no t.re->t.re edge, no self-deadlock
            with inner:
                pass
    rep = lc.report()
    assert list(rep["edges"]) == ["t.re->t.re_inner"]
    lc.assert_clean()


def test_condition_wait_untracks_the_lock(armed):
    """A thread parked in cv.wait() has RELEASED the lock: blocking work
    on other threads meanwhile must not be charged against it."""
    cv = lc.make_condition("crypto.lane")  # hot, allow=∅
    parked = threading.Event()
    done = threading.Event()

    def waiter():
        with cv:
            parked.set()
            cv.wait(5)
        done.set()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    assert parked.wait(5)
    time.sleep(0.05)  # let the waiter actually park inside wait()
    lc.note_blocking("suite_batch", "from-main")  # main holds nothing
    with cv:
        cv.notify_all()
    assert done.wait(5)
    lc.assert_clean()


def test_condition_wait_for_and_reacquire(armed):
    cv = lc.make_condition("t.cv2")
    flag = []

    def setter():
        with cv:
            flag.append(1)
            cv.notify_all()

    t = threading.Timer(0.05, setter)
    t.start()
    with cv:
        assert cv.wait_for(lambda: flag, timeout=5)
    t.join()
    lc.assert_clean()


# -- metrics ---------------------------------------------------------------

def test_hold_and_wait_metrics_emitted(armed):
    from fisco_bcos_tpu.utils.metrics import REGISTRY

    name = f"t.metrics_{os.getpid()}"
    lock = lc.make_lock(name)
    with lock:
        time.sleep(0.01)
    snap = REGISTRY.snapshot()
    hold = snap["histograms"].get(
        "bcos_lock_hold_seconds{'lock': '%s'}" % name)
    assert hold is not None and hold["count"] == 1
    assert hold["sum"] >= 0.01
    acq = snap["counters"].get(
        "bcos_lock_acquisitions_total{'lock': '%s'}" % name)
    assert acq == 1.0


# -- matrix: real components under the armed checker -----------------------

@pytest.fixture()
def armed_node(armed):
    from fisco_bcos_tpu.init.node import Node, NodeConfig

    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0))
    node.start()
    yield node
    node.stop()


def _register_txs(node, tag, n, block_limit=500):
    from fisco_bcos_tpu.executor import precompiled as pc
    from fisco_bcos_tpu.protocol import Transaction

    kp = node.suite.generate_keypair(b"lockcheck-" + tag)
    return [
        Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call(
                "register",
                lambda w, i=i: w.blob(b"%s-%d" % (tag, i)).u64(1)),
            nonce=f"{tag.decode()}-{i}",
            block_limit=block_limit).sign(node.suite, kp)
        for i in range(n)
    ]


def test_matrix_commit_vs_sync_and_admission_vs_release(armed_node):
    """Concurrent submit bursts (admission) racing commits (release),
    plus sync-style pokes at the scheduler (retry probe, speculation
    abort, next_executable) from a separate thread — the PR-6/PR-11
    interleavings — leave a clean report and a converged chain."""
    node = armed_node
    txs = _register_txs(node, b"mx", 60)
    stop = threading.Event()

    def poker():
        while not stop.is_set():
            node.scheduler.retry_pending_commit()
            node.scheduler.next_executable()
            node.scheduler.pipeline_stats()
            node.txpool.pending_count()
            time.sleep(0.002)

    pk = threading.Thread(target=poker, daemon=True)
    pk.start()
    threads = [threading.Thread(
        target=lambda s=s: node.txpool.submit_batch(txs[s::4]),
        daemon=True) for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if node.ledger.total_tx_count() >= 60:
            break
        time.sleep(0.02)
    stop.set()
    pk.join(5)
    assert node.ledger.total_tx_count() >= 60
    lc.assert_clean()


def test_matrix_compaction_vs_scan_vs_install(armed, tmp_path):
    """The disk engine's three-way race (PR 9's review-wave territory):
    constant-flush writes, full-table scans, explicit compactions and a
    whole-state install, concurrently — clean report, no torn reads."""
    from fisco_bcos_tpu.storage.engine import DiskStorage

    eng = DiskStorage(str(tmp_path), memtable_bytes=256, max_segments=2,
                      auto_compact=False)
    stop = threading.Event()
    errors: list = []

    def writer():
        i = 0
        while not stop.is_set():
            eng.set("t_data", b"k%04d" % (i % 200), b"v%d" % i)
            i += 1

    def scanner():
        while not stop.is_set():
            for k in eng.keys("t_data"):
                eng.get("t_data", k)

    def compactor():
        while not stop.is_set():
            eng.compact_once()
            time.sleep(0.005)

    threads = [threading.Thread(target=f, daemon=True)
               for f in (writer, scanner, compactor)]

    def guard(t):
        def run():
            try:
                t()
            except Exception as exc:  # surface, don't vanish
                errors.append(exc)
        return run

    threads = [threading.Thread(target=guard(f), daemon=True)
               for f in (writer, scanner, compactor)]
    for t in threads:
        t.start()
    time.sleep(0.3)
    eng.install_rows({"t_fresh": {b"a": b"1"}})
    time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join(10)
    assert not errors, errors[:2]
    eng.close()
    lc.assert_clean()


def test_matrix_ingest_vs_shutdown(armed_node):
    """Submitters racing IngestLane.stop() (the PR-12 shed paths): every
    in-flight future settles (result or typed rejection), nothing hangs,
    report stays clean."""
    from fisco_bcos_tpu.txpool.ingest import LaneStopped, TxPoolIsFull

    node = armed_node
    lane = node.ingest
    assert lane is not None
    txs = _register_txs(node, b"sh", 40)
    outcomes: list = []

    def submitter(mine):
        for tx in mine:
            try:
                task = lane.submit_async(tx)
                outcomes.append(task.result(30))
            except (LaneStopped, TxPoolIsFull) as exc:
                outcomes.append(exc)
            except RuntimeError as exc:
                outcomes.append(exc)  # rejected at stop: settled, not hung

    threads = [threading.Thread(target=submitter, args=(txs[s::4],),
                                daemon=True) for s in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    lane.stop()
    for t in threads:
        t.join(30)
        assert not t.is_alive(), "submitter hung across lane shutdown"
    assert len(outcomes) == 40  # every submission SETTLED one way
    lc.assert_clean()
