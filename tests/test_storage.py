"""Storage stack tests: 2PC contract, overlay savepoints, WAL crash recovery
(reference test analogues: bcos-table/test/unittests, RocksDBStorage 2PC)."""

import os

from fisco_bcos_tpu.storage import (
    Entry,
    MemoryStorage,
    StateStorage,
    WalStorage,
)
from fisco_bcos_tpu.storage.interface import EntryStatus


def test_memory_2pc():
    st = MemoryStorage()
    st.set("t", b"k0", b"v0")
    cs = {("t", b"k1"): Entry(b"v1"), ("t", b"k0"): Entry(b"", EntryStatus.DELETED)}
    st.prepare(1, cs)
    assert st.get("t", b"k1") is None  # not visible before commit
    st.commit(1)
    assert st.get("t", b"k1") == b"v1"
    assert st.get("t", b"k0") is None

    st.prepare(2, {("t", b"k2"): Entry(b"v2")})
    st.rollback(2)
    assert st.get("t", b"k2") is None


def test_state_overlay_reads_through():
    base = MemoryStorage()
    base.set("t", b"a", b"base")
    ss = StateStorage(base)
    assert ss.get("t", b"a") == b"base"
    ss.set("t", b"a", b"over")
    assert ss.get("t", b"a") == b"over"
    assert base.get("t", b"a") == b"base"  # backend untouched
    ss.remove("t", b"a")
    assert ss.get("t", b"a") is None
    assert sorted(ss.changeset().keys()) == [("t", b"a")]


def test_state_savepoints_nested():
    ss = StateStorage(MemoryStorage())
    ss.set("t", b"x", b"1")
    sp1 = ss.savepoint()
    ss.set("t", b"x", b"2")
    ss.set("t", b"y", b"yy")
    sp2 = ss.savepoint()
    ss.remove("t", b"x")
    assert ss.get("t", b"x") is None
    ss.rollback_to(sp2)
    assert ss.get("t", b"x") == b"2"
    ss.rollback_to(sp1)
    assert ss.get("t", b"x") == b"1"
    assert ss.get("t", b"y") is None


def test_state_savepoint_release_keeps_writes():
    ss = StateStorage(MemoryStorage())
    sp = ss.savepoint()
    ss.set("t", b"k", b"v")
    ss.release(sp)
    assert ss.get("t", b"k") == b"v"
    assert not ss._journal


def test_state_keys_merge():
    base = MemoryStorage()
    base.set("t", b"a", b"1")
    base.set("t", b"b", b"2")
    ss = StateStorage(base)
    ss.set("t", b"c", b"3")
    ss.remove("t", b"a")
    assert list(ss.keys("t")) == [b"b", b"c"]


def test_wal_durability_and_recovery(tmp_path):
    p = str(tmp_path / "db")
    st = WalStorage(p)
    st.set("t", b"direct", b"d")
    st.prepare(1, {("t", b"k"): Entry(b"v")})
    st.commit(1)
    st.prepare(2, {("t", b"gone"): Entry(b"x")})
    # no commit for block 2 — simulating crash before commit
    st.close()

    st2 = WalStorage(p)
    assert st2.get("t", b"direct") == b"d"
    assert st2.get("t", b"k") == b"v"
    assert st2.get("t", b"gone") is None
    st2.close()


def test_wal_compaction(tmp_path):
    p = str(tmp_path / "db")
    st = WalStorage(p, compact_every=2)
    for i in range(5):
        st.prepare(i, {("t", f"k{i}".encode()): Entry(f"v{i}".encode())})
        st.commit(i)
    st.close()
    st2 = WalStorage(p)
    for i in range(5):
        assert st2.get("t", f"k{i}".encode()) == f"v{i}".encode()
    st2.close()


def test_wal_torn_tail_ignored(tmp_path):
    p = str(tmp_path / "db")
    st = WalStorage(p)
    st.prepare(1, {("t", b"good"): Entry(b"1")})
    st.commit(1)
    st.close()
    # append garbage (torn write)
    with open(os.path.join(p, "wal.log"), "ab") as f:
        f.write(b"\xde\xad\xbe\xef\x00\x01")
    st2 = WalStorage(p)
    assert st2.get("t", b"good") == b"1"
    st2.close()


def test_commit_block_retry_after_transient_2pc_failure():
    """A failed storage 2PC must not strand the executed result: PBFT
    retries the checkpoint commit and the scheduler must still have the
    block (regression: commit_block popped the result before the 2PC)."""
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.executor.executor import TransactionExecutor
    from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
    from fisco_bcos_tpu.protocol import Block, BlockHeader
    from fisco_bcos_tpu.scheduler.scheduler import Scheduler
    from fisco_bcos_tpu.storage.memory import MemoryStorage

    suite = make_suite(backend="host")
    storage = MemoryStorage()
    ledger = Ledger(storage, suite)
    kp = suite.generate_keypair(b"retry-node")
    ledger.build_genesis([ConsensusNode(kp.pub_bytes)])
    sched = Scheduler(storage, ledger, TransactionExecutor(suite), suite,
                      None)
    blk = Block(header=BlockHeader(number=1,
                                   sealer_list=[kp.pub_bytes]))
    result = sched.execute_block(blk)
    assert result is not None

    fails = {"n": 1}
    orig_prepare = storage.prepare

    def flaky_prepare(number, changes):
        if fails["n"]:
            fails["n"] -= 1
            raise RuntimeError("transient storage failure")
        return orig_prepare(number, changes)

    storage.prepare = flaky_prepare
    try:
        assert not sched.commit_block(result.header)  # transient failure
        assert sched.commit_block(result.header)      # retry succeeds
    finally:
        storage.prepare = orig_prepare
    assert ledger.current_number() == 1
    sched.shutdown()
