"""WASM interpreter tests: VM semantics + executor-level contract flow.

Modules are hand-assembled (no toolchain in the image); the `_Asm` helper
builds the binary sections. Covers: arithmetic/control flow/memory/tables,
deterministic traps, per-instruction gas with out-of-gas revert, and the
deploy + call + storage + revert contract path through TransactionExecutor
(reference: bcos-executor WASM path with GasInjector metering,
/root/reference/bcos-executor/src/vm/gas_meter/GasInjector.cpp).
"""

import pytest

from fisco_bcos_tpu.executor.wasm import WasmEngine, is_wasm
from fisco_bcos_tpu.executor.wasm_interp import (
    Instance,
    Module,
    WasmOutOfGas,
    WasmTrap,
)

I32, I64 = 0x7F, 0x7E


def leb(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def sleb(v: int) -> bytes:
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        done = (v == 0 and not b & 0x40) or (v == -1 and b & 0x40)
        out += bytes([b | (0 if done else 0x80)])
        if done:
            return out


class _Asm:
    """Minimal wasm module builder."""

    def __init__(self):
        self.types: list[tuple[list[int], list[int]]] = []
        self.imports: list[tuple[str, str, int]] = []
        self.funcs: list[tuple[int, list[int], bytes]] = []  # (type, locals, body)
        self.mem_pages = 0
        self.exports: list[tuple[str, int, int]] = []
        self.datas: list[tuple[int, bytes]] = []
        self.table_elems: list[int] | None = None

    def typ(self, params, results) -> int:
        key = (list(params), list(results))
        for i, t in enumerate(self.types):
            if t == key:
                return i
        self.types.append(key)
        return len(self.types) - 1

    def imp(self, name, params, results) -> int:
        self.imports.append(("env", name, self.typ(params, results)))
        return len(self.imports) - 1

    def func(self, params, results, body, locals_=()) -> int:
        self.funcs.append((self.typ(params, results), list(locals_), body))
        return len(self.imports) + len(self.funcs) - 1

    def build(self) -> bytes:
        def vec(items):
            return leb(len(items)) + b"".join(items)

        def section(sid, payload):
            return bytes([sid]) + leb(len(payload)) + payload

        out = b"\x00asm\x01\x00\x00\x00"
        out += section(1, vec([
            b"\x60" + vec([bytes([p]) for p in ps])
            + vec([bytes([r]) for r in rs]) for ps, rs in self.types]))
        if self.imports:
            out += section(2, vec([
                leb(len(m)) + m.encode() + leb(len(n)) + n.encode()
                + b"\x00" + leb(t) for m, n, t in self.imports]))
        out += section(3, vec([leb(t) for t, _, _ in self.funcs]))
        if self.table_elems is not None:
            out += section(4, vec([b"\x70\x00" + leb(len(self.table_elems))]))
        if self.mem_pages:
            out += section(5, vec([b"\x00" + leb(self.mem_pages)]))
        if self.exports:
            out += section(7, vec([
                leb(len(n)) + n.encode() + bytes([k]) + leb(i)
                for n, k, i in self.exports]))
        if self.table_elems is not None:
            out += section(9, vec([
                b"\x00\x41\x00\x0b" + vec([leb(f) for f in self.table_elems])
            ]))
        bodies = []
        for _, locals_, body in self.funcs:
            ldecl = vec([leb(1) + bytes([t]) for t in locals_])
            b = ldecl + body
            bodies.append(leb(len(b)) + b)
        out += section(10, vec(bodies))
        if self.datas:
            out += section(11, vec([
                b"\x00\x41" + sleb(off) + b"\x0b" + leb(len(blob)) + blob
                for off, blob in self.datas]))
        return out


def c32(v):
    return b"\x41" + sleb(v)


def c64(v):
    return b"\x42" + sleb(v)


# ---------------------------------------------------------------------------
# pure VM semantics
# ---------------------------------------------------------------------------

def test_arithmetic_and_calls():
    a = _Asm()
    add = a.func([I32, I32], [I32],
                 b"\x20\x00\x20\x01\x6a\x0b")  # local0 + local1
    a.func([I32], [I32],  # double(x) = add(x, x)
           b"\x20\x00\x20\x00\x10" + leb(add) + b"\x0b")
    a.exports = [("add", 0, 0), ("double", 0, 1)]
    inst = Instance(Module(a.build()), gas=10_000)
    assert inst.invoke("add", [5, 7]) == [12]
    assert inst.invoke("add", [0xFFFFFFFF, 1]) == [0]  # i32 wraps
    assert inst.invoke("double", [21]) == [42]


def test_control_flow_loop_sum():
    # sum(n) = n + (n-1) + ... + 1 via block/loop/br_if/br
    body = (b"\x02\x40"  # block
            b"\x03\x40"  # loop
            b"\x20\x00\x45\x0d\x01"  # local0 == 0 -> br_if 1 (exit block)
            b"\x20\x01\x20\x00\x6a\x21\x01"  # acc += n
            b"\x20\x00" + c32(1) + b"\x6b\x21\x00"  # n -= 1
            b"\x0c\x00"  # br 0 (continue loop)
            b"\x0b\x0b"
            b"\x20\x01\x0b")  # return acc
    a = _Asm()
    a.func([I32], [I32], body, locals_=[I32])
    a.exports = [("sum", 0, 0)]
    inst = Instance(Module(a.build()), gas=100_000)
    assert inst.invoke("sum", [10]) == [55]
    assert inst.invoke("sum", [0]) == [0]


def test_if_else_and_select():
    # max(a,b) via if/else with result type i32
    body = (b"\x20\x00\x20\x01\x4a"  # a > b (signed)
            b"\x04\x7f"  # if (result i32)
            b"\x20\x00\x05\x20\x01\x0b\x0b")
    a = _Asm()
    a.func([I32, I32], [I32], body)
    a.exports = [("max", 0, 0)]
    inst = Instance(Module(a.build()), gas=10_000)
    assert inst.invoke("max", [3, 9]) == [9]
    assert inst.invoke("max", [9, 3]) == [9]
    assert inst.invoke("max", [0xFFFFFFFF, 1]) == [1]  # -1 < 1 signed


def test_br_table_dispatch():
    # switch(i): 0->10, 1->20, default->99
    body = (b"\x02\x40\x02\x40\x02\x40"
            b"\x20\x00\x0e\x02\x00\x01\x02"  # br_table [0 1] 2
            b"\x0b" + c32(10) + b"\x0f"  # case 0: return 10
            b"\x0b" + c32(20) + b"\x0f"  # case 1: return 20
            b"\x0b" + c32(99) + b"\x0f"  # default
            + c32(0) + b"\x0b")
    a = _Asm()
    a.func([I32], [I32], body)
    a.exports = [("switch", 0, 0)]
    inst = Instance(Module(a.build()), gas=10_000)
    assert inst.invoke("switch", [0]) == [10]
    assert inst.invoke("switch", [1]) == [20]
    assert inst.invoke("switch", [7]) == [99]


def test_memory_and_i64():
    # store i64 at [8], load it back doubled
    body = (c32(8) + c64(0x1122334455667788) + b"\x37\x03\x00"
            + c32(8) + b"\x29\x03\x00" + c32(8) + b"\x29\x03\x00"
            + b"\x7c\x0b")
    a = _Asm()
    a.mem_pages = 1
    a.func([], [I64], body)
    a.exports = [("run", 0, 0)]
    inst = Instance(Module(a.build()), gas=10_000)
    assert inst.invoke("run") == [(2 * 0x1122334455667788) & ((1 << 64) - 1)]


def test_call_indirect_through_table():
    a = _Asm()
    f10 = a.func([], [I32], c32(10) + b"\x0b")
    f20 = a.func([], [I32], c32(20) + b"\x0b")
    t = a.typ([], [I32])
    a.func([I32], [I32],
           b"\x20\x00\x11" + leb(t) + b"\x00\x0b")  # call_indirect
    a.table_elems = [f10, f20]
    a.exports = [("pick", 0, 2)]
    inst = Instance(Module(a.build()), gas=10_000)
    assert inst.invoke("pick", [0]) == [10]
    assert inst.invoke("pick", [1]) == [20]
    with pytest.raises(WasmTrap):
        inst.invoke("pick", [5])


def test_deterministic_traps():
    a = _Asm()
    a.func([I32], [I32], b"\x20\x00" + c32(0) + b"\x6d\x0b")  # x / 0 signed
    a.func([], [], b"\x00\x0b")  # unreachable
    a.mem_pages = 1
    a.func([], [I32], c32(0x20000) + b"\x28\x02\x00\x0b")  # OOB load
    a.exports = [("div", 0, 0), ("boom", 0, 1), ("oob", 0, 2)]
    inst = Instance(Module(a.build()), gas=10_000)
    with pytest.raises(WasmTrap, match="divide by zero"):
        inst.invoke("div", [1])
    with pytest.raises(WasmTrap, match="unreachable"):
        inst.invoke("boom")
    with pytest.raises(WasmTrap, match="out of bounds"):
        inst.invoke("oob")


def test_out_of_gas_stops_infinite_loop():
    a = _Asm()
    a.func([], [], b"\x03\x40\x0c\x00\x0b\x0b")  # loop { br 0 }
    a.exports = [("spin", 0, 0)]
    inst = Instance(Module(a.build()), gas=5_000)
    with pytest.raises(WasmOutOfGas):
        inst.invoke("spin")
    assert inst.gas == 0


def test_gas_charges_match_metering_costs():
    # 3 default-cost ops + function-call cost structure is deterministic
    a = _Asm()
    a.func([], [I32], c32(1) + c32(2) + b"\x6a\x0b")
    a.exports = [("f", 0, 0)]
    inst = Instance(Module(a.build()), gas=1_000)
    inst.invoke("f")
    assert inst.gas == 1_000 - 4  # const, const, add, end


# ---------------------------------------------------------------------------
# executor-level contract flow
# ---------------------------------------------------------------------------

def _counter_contract() -> bytes:
    """Liquid-style counter: `add` reads an 8-byte LE amount from call args,
    adds it to storage["c"], writes back and returns the new value;
    `spin` burns gas forever; `fail` reverts with data."""
    a = _Asm()
    sread = a.imp("storage_read", [I32, I32, I32, I32], [I32])
    swrite = a.imp("storage_write", [I32, I32, I32, I32], [])
    a.imp("input_size", [], [I32])
    icopy = a.imp("input_copy", [I32], [])
    soutput = a.imp("set_output", [I32, I32], [])
    hrevert = a.imp("revert", [I32, I32], [])

    add_body = (
        c32(16) + b"\x10" + leb(icopy)  # input_copy(16)
        + c32(0) + c32(1) + c32(32) + c32(8) + b"\x10" + leb(sread)
        + c32(-1) + b"\x46"  # == -1 ?
        + b"\x04\x40" + c32(32) + c64(0) + b"\x37\x03\x00" + b"\x0b"
        + c32(32)  # store target addr
        + c32(32) + b"\x29\x03\x00"  # load current
        + c32(16) + b"\x29\x03\x00"  # load amount
        + b"\x7c" + b"\x37\x03\x00"  # add, store
        + c32(0) + c32(1) + c32(32) + c32(8) + b"\x10" + leb(swrite)
        + c32(32) + c32(8) + b"\x10" + leb(soutput)
        + b"\x0b")
    a.func([], [], add_body)
    a.func([], [], b"\x03\x40\x0c\x00\x0b\x0b")  # spin
    a.func([], [], c32(0) + c32(1) + b"\x10" + leb(hrevert) + b"\x0b")  # fail
    a.func([], [], b"\x0b")  # deploy (no-op constructor)
    base = len(a.imports)
    a.mem_pages = 1
    a.datas = [(0, b"c")]
    a.exports = [("add", 0, base), ("spin", 0, base + 1),
                 ("fail", 0, base + 2), ("deploy", 0, base + 3)]
    return a.build()


def test_wasm_contract_deploy_call_oog_revert():
    from fisco_bcos_tpu.codec import scale
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.executor.executor import TransactionExecutor
    from fisco_bcos_tpu.protocol import Transaction, TransactionStatus
    from fisco_bcos_tpu.storage.memory import MemoryStorage
    from fisco_bcos_tpu.storage.state import StateStorage

    WasmEngine.use_interpreter()
    suite = make_suite(backend="host")
    kp = suite.generate_keypair(b"wasm-user")
    ex = TransactionExecutor(suite)
    state = StateStorage(MemoryStorage())
    code = _counter_contract()
    assert is_wasm(code)

    deploy = Transaction(to=b"", input=code, nonce="w1",
                         block_limit=100).sign(suite, kp)
    rc = ex.execute_transaction(deploy, state, 1, 0)
    assert rc.status == 0, rc.message
    addr = rc.contract_address
    assert addr and len(addr) == 20

    def call(func, args=b"", nonce="w2"):
        inp = scale.Encoder().string(func).raw(args).bytes()
        tx = Transaction(to=addr, input=inp, nonce=nonce,
                         block_limit=100).sign(suite, kp)
        return ex.execute_transaction(tx, state, 1, 0)

    rc = call("add", (5).to_bytes(8, "little"), "w2")
    assert rc.status == 0, rc.message
    assert int.from_bytes(rc.output, "little") == 5
    rc = call("add", (37).to_bytes(8, "little"), "w3")
    assert rc.status == 0
    assert int.from_bytes(rc.output, "little") == 42  # persisted state

    rc = call("spin", b"", "w4")
    assert rc.status == int(TransactionStatus.OUT_OF_GAS)

    # the failed call must not have clobbered state
    rc = call("add", (0).to_bytes(8, "little"), "w5")
    assert int.from_bytes(rc.output, "little") == 42

    rc = call("fail", b"", "w6")
    assert rc.status == int(TransactionStatus.REVERT)
    assert rc.output == b"c"  # revert data = memory[0:1] (the key byte)


def test_wasm_deploy_gated_when_backend_disabled():
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.executor.executor import TransactionExecutor
    from fisco_bcos_tpu.protocol import Transaction, TransactionStatus
    from fisco_bcos_tpu.storage.memory import MemoryStorage
    from fisco_bcos_tpu.storage.state import StateStorage

    suite = make_suite(backend="host")
    kp = suite.generate_keypair(b"gate-user")
    ex = TransactionExecutor(suite)
    state = StateStorage(MemoryStorage())
    WasmEngine.set_backend(None)
    try:
        tx = Transaction(to=b"", input=_counter_contract(), nonce="g1",
                         block_limit=100).sign(suite, kp)
        rc = ex.execute_transaction(tx, state, 1, 0)
        assert rc.status == int(TransactionStatus.EXECUTION_ABORTED)
        assert not rc.contract_address
    finally:
        WasmEngine.use_interpreter()
