"""WebSocket plane: transport framing, WS JSON-RPC, event push, AMOP bridge.

Reference: bcos-boostssl websocket/ (transport), bcos-rpc jsonrpc-over-WS +
event/EventSub.cpp (push), bcos-rpc/amop (SDK topic bridge).
"""

import threading
import time

import pytest

from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.net.websocket import (
    OP_BINARY,
    OP_TEXT,
    WsServer,
    ws_connect,
)
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.sdk.ws import WsSdkClient


def wait_until(pred, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


# ---------------------------------------------------------------------------
# transport
# ---------------------------------------------------------------------------

def test_ws_echo_roundtrip_and_large_frames():
    got = []

    def on_message(conn, op, payload):
        got.append((op, payload))
        if op == OP_TEXT:
            conn.send_text(payload.decode()[::-1])
        else:
            conn.send_binary(payload)

    srv = WsServer(on_message=on_message)
    srv.start()
    try:
        conn = ws_connect("127.0.0.1", srv.port)
        conn.send_text("hello ws")
        op, data = conn.recv()
        assert (op, data) == (OP_TEXT, b"sw olleh")
        # 70 KB binary exercises the 16-bit-plus extended length path
        blob = bytes(range(256)) * 280
        conn.send_binary(blob)
        op, data = conn.recv()
        assert op == OP_BINARY and data == blob
        conn.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# WS JSON-RPC + event push + AMOP, against a live solo node
# ---------------------------------------------------------------------------

@pytest.fixture()
def ws_node(tmp_path):
    from fisco_bcos_tpu.net.gateway import FakeGateway

    gateway = FakeGateway()  # gives the solo node an AMOP plane
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           ws_port=0), gateway=gateway)
    node.start()
    yield node
    node.stop()
    gateway.stop()


def _register_tx(node, kp, nonce, name=b"wsacct", amount=5):
    return Transaction(
        to=pc.BALANCE_ADDRESS,
        input=pc.encode_call("register",
                             lambda w: w.blob(name).u64(amount)),
        nonce=nonce, block_limit=node.ledger.current_number() + 100,
    ).sign(node.suite, kp)


def test_ws_jsonrpc_surface(ws_node):
    node = ws_node
    cli = WsSdkClient("127.0.0.1", node.ws.port)
    try:
        assert cli.get_block_number() == node.ledger.current_number()
        kp = node.suite.generate_keypair(b"ws-user")
        tx = _register_tx(node, kp, "ws1")
        rc = cli.send_transaction(tx)  # waits for the receipt
        assert int(rc["status"]) == 0
        rc2 = cli.get_transaction_receipt(rc["transactionHash"])
        assert rc2 is not None and int(rc2["status"]) == 0
        assert cli.get_sync_status()["blockNumber"] >= 1
    finally:
        cli.close()


def test_ws_event_subscription_push(ws_node):
    node = ws_node
    kp = node.suite.generate_keypair(b"ws-evt")
    cli = WsSdkClient("127.0.0.1", node.ws.port)
    pushes = []
    try:
        # transfer emits a log (BalancePrecompile topics=[b"transfer"]);
        # wait on COMMITTED TX COUNT, not height — back-to-back submits may
        # legitimately batch into one block
        node.send_transaction(_register_tx(node, kp, "we1", b"a", 100))
        node.send_transaction(_register_tx(node, kp, "we2", b"b", 0))
        assert wait_until(lambda: node.ledger.total_tx_count() >= 2)
        tx = Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call("transfer", lambda w: w.blob(b"a")
                                 .blob(b"b").u64(7)),
            nonce="we3", block_limit=node.ledger.current_number() + 100,
        ).sign(node.suite, kp)
        node.send_transaction(tx)
        assert wait_until(lambda: node.ledger.total_tx_count() >= 3)

        # subscribe from block 0: the historical transfer must be replayed
        task = cli.subscribe_event({"fromBlock": 0}, pushes.append)
        assert task
        assert wait_until(lambda: len(pushes) >= 1), "no historical push"
        assert pushes[0]["log"]["topics"][0] == "0x" + b"transfer".hex()

        # a NEW transfer must be pushed live
        n0 = len(pushes)
        tx2 = Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call("transfer", lambda w: w.blob(b"b")
                                 .blob(b"a").u64(1)),
            nonce="we4", block_limit=node.ledger.current_number() + 100,
        ).sign(node.suite, kp)
        node.send_transaction(tx2)
        assert wait_until(lambda: len(pushes) > n0), "no live push"
        assert cli.unsubscribe_event(task)
    finally:
        cli.close()


def test_ws_amop_bridge_unicast_roundtrip(ws_node):
    node = ws_node
    sub = WsSdkClient("127.0.0.1", node.ws.port)
    pub = WsSdkClient("127.0.0.1", node.ws.port)
    try:
        received = []

        def on_topic(topic, data):
            received.append((topic, data))
            return b"pong:" + data

        sub.subscribe_topic("orders", on_topic)
        resp = pub.publish_topic("orders", b"ping1")
        assert resp == b"pong:ping1"
        assert received == [("orders", b"ping1")]

        # broadcast: delivered, no response expected
        sub2_received = []
        sub.broadcast_topic("orders", b"fanout")
        assert wait_until(lambda: len(received) >= 2)
        assert received[1] == ("orders", b"fanout")
        assert sub2_received == []

        sub.unsubscribe_topic("orders")
        assert pub.publish_topic("orders", b"ping2") is None
    finally:
        sub.close()
        pub.close()


def test_ws_amop_self_publish_same_connection(ws_node):
    """One connection both serves a topic and publishes to it — must not
    deadlock the session's reader thread (methods dispatch off-reader)."""
    node = ws_node
    cli = WsSdkClient("127.0.0.1", node.ws.port)
    try:
        cli.subscribe_topic("selftopic", lambda t, d: b"me:" + d)
        resp = cli.publish_topic("selftopic", b"loop")
        assert resp == b"me:loop"
    finally:
        cli.close()


def test_ws_push_outbox_overflow_policies():
    """The bounded push outbox (PR-13 blocking-while-locked fix): live
    pushes drop OLDEST on overflow (counted in the registry); a backlog
    of LOSSLESS frames (the subscribeEvent history replay) is never
    silently gapped — overflow closes the session instead."""
    from fisco_bcos_tpu.rpc.ws_server import _Session
    from fisco_bcos_tpu.utils.metrics import REGISTRY

    class FakeSock:
        def __init__(self):
            self.closed = False

        def close(self):
            self.closed = True

    class StuckConn:  # writer thread parks forever on the first send
        peer = "test"

        def __init__(self):
            import threading
            self._gate = threading.Event()
            self.sock = FakeSock()

        def send_text(self, text):
            self._gate.wait(30)

    # live pushes: drop-oldest, session survives
    sess = _Session(StuckConn())
    sess.MAX_OUTBOX = 8
    before = REGISTRY.snapshot()["counters"].get(
        "bcos_ws_push_dropped_total", 0.0)
    for i in range(20):
        assert sess.push({"type": "eventPush", "n": i}) is True
    after = REGISTRY.snapshot()["counters"].get(
        "bcos_ws_push_dropped_total", 0.0)
    assert after - before >= 10  # overflowed pushes were counted
    assert not sess.conn.sock.closed
    sess.close_push()

    # lossless backlog: overflow KILLS the session, nothing is gapped
    sess2 = _Session(StuckConn())
    sess2.MAX_OUTBOX = 8
    ok = True
    for i in range(20):
        ok = sess2.push({"type": "eventPush", "n": i}, lossless=True)
        if not ok:
            break
    assert not ok and sess2.conn.sock.closed  # RAW close: no frame sent,
    #   so the kill path can never block on the writer's _wlock
    assert sess2.push({"type": "eventPush"}) is False  # dead stays dead
