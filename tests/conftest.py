"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; all sharding tests run on a
virtual 8-device CPU platform (the driver separately dry-runs the multichip
path via __graft_entry__.dryrun_multichip).

Ordering matters on two axes:

* ``XLA_FLAGS`` must be in the environment before the first backend
  initialization (the CPU client reads it at creation).
* The container's sitecustomize registers an experimental accelerator
  plugin at interpreter startup and force-overrides ``jax_platforms`` via
  ``jax.config.update`` — so an env-var ``JAX_PLATFORMS`` set here is a
  no-op, and initializing that plugin hangs the whole process when its
  device tunnel is unhealthy. The only reliable in-process pin is another
  ``jax.config.update`` AFTER import (last write wins, and no backend is
  initialized yet when conftest runs).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests spawn
os.environ["PALLAS_AXON_POOL_IPS"] = ""  # subprocesses: skip plugin entirely

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _lockcheck_gate():
    """Concurrency-correctness gate (analysis/lockcheck.py): when the
    suite runs with BCOS_LOCKCHECK=1, every hot lock in the tree is the
    instrumented wrapper, and the whole tier-1 run must finish with ZERO
    lock-order cycles, canonical-order violations, blocking-while-locked
    hits and self-deadlocks. Disarmed runs (the default) pay nothing —
    the factories hand out plain threading primitives."""
    from fisco_bcos_tpu.analysis import lockcheck

    if not lockcheck.armed():
        yield
        return
    lockcheck.reset()
    yield
    # tests that INTENTIONALLY provoke violations (tests/test_lockcheck.py)
    # reset the plane in their teardown, so anything left here is real
    lockcheck.assert_clean()
