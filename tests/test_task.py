"""Task primitive (libtask analogue) + async tx submission."""

import threading
import time

import pytest

from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.txpool.txpool import SubmitRejected
from fisco_bcos_tpu.utils.task import Task, TaskTimeout


def test_task_resolve_then_and_gather():
    t = Task()
    got = []
    t.then(lambda tk: got.append(tk.result()))
    assert not t.done()
    t.resolve(42)
    assert t.done() and t.result() == 42 and got == [42]
    # continuation added after settlement fires immediately
    t.then(lambda tk: got.append(tk.result() + 1))
    assert got == [42, 43]
    # first settlement wins
    t.resolve(99)
    assert t.result() == 42

    e = Task()
    e.reject(ValueError("boom"))
    with pytest.raises(ValueError):
        e.result()
    assert isinstance(e.exception(), ValueError)

    with pytest.raises(TaskTimeout):
        Task().result(timeout=0.05)

    ts = [Task() for _ in range(3)]
    threading.Thread(target=lambda: [t.resolve(i)
                                     for i, t in enumerate(ts)]).start()
    assert Task.gather(ts, timeout=5) == [0, 1, 2]


def test_submit_async_settles_at_commit():
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0))
    node.start()
    try:
        kp = node.suite.generate_keypair(b"task-user")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"tk").u64(2)),
                         nonce="tk1", block_limit=100).sign(node.suite, kp)
        chained = []
        task = node.txpool.submit_async(tx)
        task.then(lambda t: chained.append(t.result().block_number))
        rc = task.result(timeout=15)
        assert rc is not None and rc.status == 0
        assert chained == [rc.block_number]

        # admission failure rejects the task
        bad = Transaction(to=pc.BALANCE_ADDRESS, input=b"", nonce="tk1",
                          block_limit=100).sign(node.suite, kp)
        t2 = node.txpool.submit_async(bad)  # duplicate nonce
        with pytest.raises(SubmitRejected):
            t2.result(timeout=5)
    finally:
        node.stop()
