"""Quorum-certificate plane: wire codec, BLS aggregation primitives, and
the adversarial matrix against `qc.verify_spans` — the ONE seal judge that
sync, snapshot and the light client all ride.

The matrix is the point: every forgery shape the certificate design claims
to kill (rogue keys without proof of possession, sub-quorum bitmaps,
bitmap/payload mismatches, tampered aggregates, stale sealer sets,
sentinel-mixing ambiguity) must be REJECTED here, and the happy paths must
cost exactly one `verify_batch` lane call per span.

BLS pairing checks cost ~0.5 s each on the pure-Python BN254 substrate, so
the aggregate fixtures are module-cached and the test count is budgeted.
"""

import numpy as np
import pytest

from fisco_bcos_tpu.consensus import qc
from fisco_bcos_tpu.crypto import agg
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.protocol import BlockHeader


class CountingSuite:
    """Delegating wrapper counting batch lane entry points (the lightnode
    test idiom) — the instrument behind the one-call-per-span contract."""

    def __init__(self, suite):
        self._suite = suite
        self.verify_calls = 0
        self.verify_sizes = []

    def __getattr__(self, name):
        return getattr(self._suite, name)

    def verify_batch(self, digests, sigs, pubs):
        self.verify_calls += 1
        self.verify_sizes.append(len(digests))
        return self._suite.verify_batch(digests, sigs, pubs)


_CTX = None


def ctx():
    """Module-cached roster: 4 ECDSA sealers + their PoP-admitted BLS keys
    (each admission is a pairing check, so build once)."""
    global _CTX
    if _CTX is None:
        suite = make_suite(backend="host")
        kps = [suite.generate_keypair(bytes([i + 1]) * 8) for i in range(4)]
        kps.sort(key=lambda kp: kp.pub_bytes)
        sealer_set = [kp.pub_bytes for kp in kps]
        secrets = [agg.derive_secret(kp.secret.to_bytes(32, "big"))
                   for kp in kps]
        registry = agg.AggKeyRegistry.from_seeds(
            [(kp.pub_bytes, kp.secret.to_bytes(32, "big")) for kp in kps])
        _CTX = (suite, kps, sealer_set, secrets, registry)
    return _CTX


def make_header(number=1, sealer_set=None):
    suite, _, roster, _, _ = ctx()
    h = BlockHeader(number=number, sealer_list=list(sealer_set or roster))
    return h, h.hash(suite)


def seal_with(idxs, hh):
    suite, kps, _, _, _ = ctx()
    return [(i, suite.sign(kps[i], hh)) for i in idxs]


def cert_header(idxs, tamper_seal=None, sealer_set=None):
    """Header carrying a cert-mode certificate signed by `idxs`."""
    h, hh = make_header(sealer_set=sealer_set)
    seals = seal_with(idxs, hh)
    if tamper_seal is not None:
        i, s = seals[tamper_seal]
        seals[tamper_seal] = (i, bytes([s[0] ^ 1]) + s[1:])
    qc.attach(h, qc.mint_cert(seals, 4))
    return h


def agg_header(idxs, tamper=False):
    """Header carrying an aggregate certificate signed by `idxs`."""
    _, _, _, secrets, _ = ctx()
    h, hh = make_header()
    sig = agg.aggregate_sigs([agg.sign(secrets[i], hh) for i in idxs])
    if tamper:
        # a DIFFERENT valid curve point (hash output), not bit-flipped junk
        sig = agg.g1_to_bytes(agg.hash_to_g1(b"tampered"))
    qc.attach(h, qc.mint_aggregate(idxs, sig, 4))
    return h


def judge(headers, suite=None, registry=None, check_sealer_list=True):
    s, _, roster, _, reg = ctx()
    return qc.verify_spans(list(headers), roster, suite or s,
                           agg_registry=registry if registry is not None
                           else reg,
                           check_sealer_list=check_sealer_list)


# -- wire codec -------------------------------------------------------------

def test_cert_wire_roundtrip():
    cert = qc.mint_cert(seal_with([0, 2, 3], make_header()[1]), 4)
    back = qc.QuorumCert.decode(cert.encode())
    assert back == cert
    assert back.signer_count() == 3


def test_unknown_wire_version_and_mode_rejected():
    raw = qc.QuorumCert(qc.MODE_CERT, b"\x07", b"x").encode()
    with pytest.raises(qc.QCFormatError):
        qc.QuorumCert.decode(bytes([qc.QC_WIRE_VERSION + 1]) + raw[1:])
    with pytest.raises(qc.QCFormatError):
        qc.QuorumCert.decode(raw[:1] + bytes([99]) + raw[2:])
    with pytest.raises(qc.QCFormatError):
        qc.QuorumCert.decode(raw + b"\x00")  # trailing bytes
    with pytest.raises(qc.QCFormatError):
        qc.QuorumCert.decode(raw[:3])  # truncated


def test_bitmap_helpers():
    bm = qc.bitmap_from_idxs([0, 3, 8], 9)
    assert qc.idxs_from_bitmap(bm, 9) == [0, 3, 8]
    assert qc.idxs_from_bitmap(bm, 4) is None          # wrong width
    assert qc.idxs_from_bitmap(b"\xff", 4) is None     # claims idx >= n
    with pytest.raises(ValueError):
        qc.bitmap_from_idxs([4], 4)


def test_extract_legacy_cert_and_mixed():
    h, hh = make_header()
    h.signature_list = seal_with([0, 1, 2], hh)
    assert qc.extract(h) is None                       # legacy
    cert = qc.mint_cert(seal_with([0, 1, 2], hh), 4)
    qc.attach(h, cert)
    assert qc.extract(h) == cert
    h.signature_list.append((0, seal_with([0], hh)[0][1]))
    with pytest.raises(qc.QCFormatError):              # sentinel + loose
        qc.extract(h)


# -- verify_spans: happy paths + one-lane-call pin --------------------------

def test_mixed_span_one_lane_call():
    """Legacy and cert headers, valid and forged, in ONE range span: the
    whole judgment is exactly one verify_batch call."""
    h_leg, hh = make_header()
    h_leg.signature_list = seal_with([0, 1, 2], hh)
    h_cert = cert_header([1, 2, 3])
    h_sub = cert_header([0, 1])                        # sub-quorum bitmap
    h_bad, hh2 = make_header()
    h_bad.signature_list = seal_with([0, 1], hh2)      # legacy sub-quorum
    h_forged = cert_header([0, 1, 2], tamper_seal=1)
    counting = CountingSuite(ctx()[0])
    ok = judge([h_leg, h_cert, h_sub, h_bad, h_forged], suite=counting)
    assert list(ok) == [True, True, False, False, False]
    assert counting.verify_calls == 1


def test_cert_requires_every_claimed_signer():
    """need = count for certs: 3 genuine seals + 1 forged under a 4-signer
    bitmap is a forgery even though 3 >= quorum."""
    assert not judge([cert_header([0, 1, 2, 3], tamper_seal=0)])[0]


def test_aggregate_happy_and_tampered():
    ok = judge([agg_header([0, 1, 2]), agg_header([1, 2, 3], tamper=True)])
    assert list(ok) == [True, False]


def test_seal_wire_bytes_ordering():
    """The whole point of the plane: aggregate < cert < legacy multi-seal
    on the wire, at the header encode() level every hop actually ships."""
    h_multi, hh = make_header()
    h_multi.signature_list = seal_with([0, 1, 2], hh)
    sizes = [qc.seal_wire_bytes(h) for h in
             (h_multi, cert_header([0, 1, 2]), agg_header([0, 1, 2]))]
    assert sizes[2] < sizes[1] < sizes[0], sizes


# -- adversarial matrix -----------------------------------------------------

def test_sub_quorum_bitmap_rejected():
    assert not judge([cert_header([0, 1])])[0]


def test_duplicated_signer_mint_cannot_inflate_quorum():
    """Duplicating a signer index at mint time collapses to one bitmap bit
    with an oversized payload — structurally rejected, never double-counted
    toward quorum."""
    h, hh = make_header()
    seals = seal_with([0, 0, 0, 1], hh)
    qc.attach(h, qc.mint_cert(seals, 4))
    assert qc.extract(h).signer_count() == 2
    assert not judge([h])[0]


def test_bitmap_claiming_foreign_signer_rejected():
    h, hh = make_header()
    cert = qc.mint_cert(seal_with([1, 2, 3], hh), 4)
    cert.bitmap = b"\xff"  # claims 8 signers in a roster of 4
    qc.attach(h, cert)
    assert not judge([h])[0]


def test_payload_size_mismatch_rejected():
    h, hh = make_header()
    cert = qc.mint_cert(seal_with([1, 2, 3], hh), 4)
    cert.payload = cert.payload[:-1]
    qc.attach(h, cert)
    assert not judge([h])[0]


def test_stale_sealer_set_cert_rejected():
    """A certificate minted under yesterday's roster must not authenticate
    against today's — admission is against the LOCAL sealer set."""
    _, _, roster, _, _ = ctx()
    h = cert_header([0, 1, 2], sealer_set=list(reversed(roster)))
    assert not judge([h])[0]
    # the light client configures its own roster and skips the header's
    # sealer_list claim, but signatures still bind to local roster keys
    assert judge([h], check_sealer_list=False)[0]


def test_cert_blob_under_legacy_index_is_not_a_cert():
    """A Byzantine peer re-flagging a cert blob as a legacy seal (index 0)
    gets a header judged by legacy rules — one bad seal, no quorum, and
    the blob is never parsed as a certificate."""
    h, hh = make_header()
    cert = qc.mint_cert(seal_with([0, 1, 2], hh), 4)
    h.signature_list = [(0, cert.encode())]
    counting = CountingSuite(ctx()[0])
    assert not judge([h], suite=counting)[0]


def test_sentinel_mixed_with_loose_seals_rejected():
    """Quorum of genuine loose seals + a sentinel entry: the ambiguity is
    refused outright, NOT salvaged by the legacy path."""
    h, hh = make_header()
    qc.attach(h, qc.mint_cert(seal_with([0, 1, 2], hh), 4))
    h.signature_list = seal_with([0, 1, 2], hh) + h.signature_list
    assert not judge([h])[0]


def test_aggregate_without_registry_rejected():
    s, _, roster, _, _ = ctx()
    ok = qc.verify_spans([agg_header([0, 1, 2])], roster, s,
                         agg_registry=None)
    assert not ok[0]


def test_unregistered_key_never_aggregates():
    """Registry admission is the rogue-key gate: a signer the registry has
    never PoP-admitted poisons the whole certificate."""
    s, _, roster, _, _ = ctx()
    partial = agg.AggKeyRegistry.from_seeds(
        [(pk, sk.secret.to_bytes(32, "big"))
         for pk, sk in zip(roster[:2], ctx()[1][:2])])
    assert not qc.verify_spans([agg_header([0, 1, 2])], roster, s,
                               agg_registry=partial)[0]


# -- BLS primitives + rogue-key attack --------------------------------------

def test_agg_sign_verify_roundtrip():
    sk = agg.derive_secret(b"roundtrip")
    pub = agg.pub_from_secret(sk)
    sig = agg.sign(sk, b"\xab" * 32)
    assert agg.verify(pub, b"\xab" * 32, sig)
    assert not agg.verify(pub, b"\xcd" * 32, sig)


def test_g2_from_bytes_rejects_junk():
    with pytest.raises(ValueError):
        agg.g2_from_bytes(b"\x01" * agg.G2_BYTES)      # not on curve
    with pytest.raises(ValueError):
        agg.g2_from_bytes(b"\x01" * 16)                # wrong length
    with pytest.raises(ValueError):
        agg.g1_from_bytes(b"\x02" * agg.G1_BYTES)


def test_rogue_key_without_pop_cannot_register():
    """The classic same-message rogue-key shape: X_evil = Y - X_target lets
    an attacker forge an 'aggregate' for {target, evil} — but evil has no
    known discrete log, so the attacker cannot produce a proof of
    possession and the registry refuses the key."""
    target_sk = agg.derive_secret(b"victim")
    target_pub = agg.pub_from_secret(target_sk)
    y_sk = agg.derive_secret(b"attacker")
    x_evil = agg.g2_add(agg.pub_from_secret(y_sk),
                        agg.g2_neg(target_pub))
    reg = agg.AggKeyRegistry()
    # attacker's best effort: a PoP signed with a secret it DOES know
    forged_pop = agg.g1_to_bytes(
        agg.g1_mul(agg.hash_to_g1(agg.g2_to_bytes(x_evil), agg.DST_POP),
                   y_sk))
    assert not reg.register(b"evil", agg.g2_to_bytes(x_evil), forged_pop)
    assert len(reg) == 0
    # while a genuine key with a genuine PoP is admitted
    assert reg.register(b"honest", agg.g2_to_bytes(target_pub),
                        agg.pop_prove(target_sk))


def test_aggregate_sigs_rejects_malformed_point():
    with pytest.raises(ValueError):
        agg.aggregate_sigs([b"\x03" * agg.G1_BYTES])
