"""Native EC engine (native/ncrypto) vs the pure-Python oracle.

Equivalence across valid, invalid, and malformed inputs: the host-path
suite swaps the oracle for the native engine when the library loads, so
classification AND recovered keys must match refimpl bit for bit.
"""

import numpy as np
import pytest

from fisco_bcos_tpu.crypto import nativeec, refimpl
from fisco_bcos_tpu.crypto.suite import make_suite

pytestmark = pytest.mark.skipif(
    not nativeec.available(), reason="libncrypto.so not built")


def _sigs(params, count, sm=False):
    rows = []
    for i in range(count):
        sk, pub = refimpl.keygen(params, bytes([i + 9]) * 24)
        digest = (refimpl.sm3 if sm else refimpl.keccak256)(
            b"native-ec-%d" % i)
        if sm:
            r, s = refimpl.sm2_sign(sk, digest)
            v = 0
        else:
            r, s, v = refimpl.ecdsa_sign(params, sk, digest)
        rows.append((int.from_bytes(digest, "big"), r, s, v, pub, digest))
    return rows


def test_ecdsa_verify_matches_oracle():
    params = refimpl.SECP256K1
    rows = _sigs(params, 6)
    es = [r[0] for r in rows]
    rs = [r[1] for r in rows]
    ss = [r[2] for r in rows]
    qx = [r[4][0] for r in rows]
    qy = [r[4][1] for r in rows]
    # edge rows: r=0, s=n, tampered e, swapped pub, x>=p style huge coords
    es += [es[0], es[1], es[2] ^ 1, es[3], es[4]]
    rs += [0, rs[1], rs[2], rs[3], rs[4]]
    ss += [ss[0], params.n, ss[2], ss[3], ss[4]]
    qx += [qx[0], qx[1], qx[2], qx[4], params.p + 1]  # x >= p: implicit
    qy += [qy[0], qy[1], qy[2], qy[4], qy[4]]         # mod-p reduction
    got = nativeec.ecdsa_verify_batch(es, rs, ss, qx, qy)
    want = [refimpl.ecdsa_verify(params, (x, y),
                                 int(e).to_bytes(32, "big"), r, s)
            for e, r, s, x, y in zip(es, rs, ss, qx, qy)]
    assert got == want
    assert got[:6] == [True] * 6 and got[6:9] == [False] * 3


def test_sm2_verify_matches_oracle():
    params = refimpl.SM2P256V1
    rows = _sigs(params, 5, sm=True)
    es = [r[0] for r in rows] + [rows[0][0] ^ 1]
    rs = [r[1] for r in rows] + [rows[0][1]]
    ss = [r[2] for r in rows] + [rows[0][2]]
    qx = [r[4][0] for r in rows] + [rows[0][4][0]]
    qy = [r[4][1] for r in rows] + [rows[0][4][1]]
    got = nativeec.sm2_verify_batch(es, rs, ss, qx, qy)
    want = [refimpl.sm2_verify((x, y), int(e).to_bytes(32, "big"), r, s)
            for e, r, s, x, y in zip(es, rs, ss, qx, qy)]
    assert got == want
    assert got == [True] * 5 + [False]


def test_ecdsa_recover_matches_oracle():
    params = refimpl.SECP256K1
    rows = _sigs(params, 6)
    es = [r[0] for r in rows]
    rs = [r[1] for r in rows]
    ss = [r[2] for r in rows]
    vs = [r[3] for r in rows]
    # edge rows: flipped v (wrong key, still valid), v>=4, r=0, huge v
    es += [es[0], es[1], es[2], es[3]]
    rs += [rs[0], rs[1], 0, rs[3]]
    ss += [ss[0], ss[1], ss[2], ss[3]]
    vs += [vs[0] ^ 1, 4, vs[2], 255]
    pubs, ok = nativeec.ecdsa_recover_batch(es, rs, ss, vs)
    for i, (e, r, s, v) in enumerate(zip(es, rs, ss, vs)):
        Q = refimpl.ecdsa_recover(params, int(e).to_bytes(32, "big"),
                                  r, s, v)
        assert ok[i] == (Q is not None), i
        if Q is not None:
            want = Q[0].to_bytes(32, "big") + Q[1].to_bytes(32, "big")
            assert pubs[i] == want, i
    # the 6 untampered rows recover the signing keys
    for i in range(6):
        assert ok[i] and pubs[i] == (
            rows[i][4][0].to_bytes(32, "big")
            + rows[i][4][1].to_bytes(32, "big"))


def test_host_suite_routes_through_native():
    """The host-path CryptoSuite classification equals the oracle's for a
    mixed good/bad workload (suite-level integration)."""
    for sm in (False, True):
        suite = make_suite(sm, backend="host")
        kps = [suite.generate_keypair(bytes([i + 3]) * 20)
               for i in range(4)]
        digests = [suite.hash(b"route-%d" % i) for i in range(4)]
        sigs = [suite.sign(kp, d) for kp, d in zip(kps, digests)]
        pubs = [kp.pub_bytes for kp in kps]
        sigs[-1] = sigs[-1][:10] + b"\x77" + sigs[-1][11:]
        ok = suite.verify_batch(digests, sigs, pubs)
        assert ok.tolist() == [True, True, True, False]
        if not sm:
            addrs, okr = suite.recover_addresses(digests, sigs)
            assert okr.tolist()[:3] == [True] * 3
            assert addrs[:3] == [kp.address for kp in kps[:3]]


def test_native_ec_throughput_sane():
    """Native recover must be orders faster than the Python oracle —
    a cheap regression guard against silently falling back."""
    import time

    params = refimpl.SECP256K1
    rows = _sigs(params, 2)
    es = [rows[0][0]] * 64
    rs = [rows[0][1]] * 64
    ss = [rows[0][2]] * 64
    vs = [rows[0][3]] * 64
    nativeec.ecdsa_recover_batch(es[:2], rs[:2], ss[:2], vs[:2])  # warm
    t0 = time.perf_counter()
    _, ok = nativeec.ecdsa_recover_batch(es, rs, ss, vs)
    dt = time.perf_counter() - t0
    assert all(ok)
    assert 64 / dt > 500, f"native recover too slow: {64 / dt:.0f}/s"


def test_oversized_digest_matches_oracle():
    """Digests longer than 32 bytes classify exactly like refimpl
    (e reduced mod n), instead of crashing the batch."""
    params = refimpl.SECP256K1
    sk, pub = refimpl.keygen(params, b"\x21" * 24)
    digest = b"\x9f" * 40  # 320-bit digest
    r, s, v = refimpl.ecdsa_sign(params, sk, digest)
    e = int.from_bytes(digest, "big")
    got = nativeec.ecdsa_verify_batch([e], [r], [s], [pub[0]], [pub[1]])
    assert got == [refimpl.ecdsa_verify(params, pub, digest, r, s)] == [True]
    pubs, ok = nativeec.ecdsa_recover_batch([e], [r], [s], [v])
    assert ok == [True]
    assert pubs[0] == pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")


def test_mismatched_batch_lengths_rejected():
    """Short argument lists must fail loudly, never read past a buffer."""
    with pytest.raises(ValueError):
        nativeec.ecdsa_verify_batch([1, 2], [1], [1, 2], [1, 2], [1, 2])
    with pytest.raises(ValueError):
        nativeec.ecdsa_recover_batch([1, 2], [1, 2], [1, 2], [0])


def test_ecdsa_recover_rows_door_matches_int_door():
    """The zero-marshalling rows entry (pre-packed 32-byte rows, no int
    round trip) returns bit-identical pubs/ok to the int-marshalling
    door for the same batch, including rejected rows."""
    params = refimpl.SECP256K1
    rows = _sigs(params, 5)
    es = [r[0] for r in rows]
    rs = [r[1] for r in rows]
    ss = [r[2] for r in rows]
    vs = [r[3] for r in rows]
    # edge rows the C side must classify, not crash on
    es += [es[0], es[1]]
    rs += [0, rs[1]]
    ss += [ss[0], ss[1]]
    vs += [vs[0], 255]
    want_pubs, want_ok = nativeec.ecdsa_recover_batch(es, rs, ss, vs)
    got_pubs, got_ok = nativeec.ecdsa_recover_batch_rows(
        b"".join(int(e).to_bytes(32, "big") for e in es),
        b"".join(int(r).to_bytes(32, "big") for r in rs),
        b"".join(int(s).to_bytes(32, "big") for s in ss),
        bytes(vs))
    assert got_ok == want_ok
    assert got_pubs == want_pubs
    with pytest.raises(ValueError):
        nativeec.ecdsa_recover_batch_rows(b"\x00" * 32, b"\x00" * 32,
                                          b"\x00" * 32, bytes([0, 0]))


def test_suite_recover_rows_fast_path_parity(monkeypatch):
    """suite.recover_batch answers identically with the rows fast path
    live vs forced off (int door), across valid / tampered / malformed-
    short signatures; oversized digests take the int door (which
    pre-reduces mod n) without error."""
    suite = make_suite(False, backend="host")
    kps = [suite.generate_keypair(bytes([i + 41]) * 20) for i in range(4)]
    digests = [suite.hash(b"rows-%d" % i) for i in range(4)]
    sigs = [suite.sign(kp, d) for kp, d in zip(kps, digests)]
    sigs[1] = b"\x00" * 32 + sigs[1][32:]  # r=0: unrecoverable
    sigs[2] = sigs[2][:17]                 # malformed: short
    live = suite.recover_batch(digests, sigs)
    monkeypatch.setattr(nativeec, "ecdsa_recover_batch_rows",
                        lambda *a: None)
    forced = suite.recover_batch(digests, sigs)
    assert live[0] == forced[0]
    assert live[1].tolist() == forced[1].tolist() == [
        True, False, False, True]
    monkeypatch.undo()
    # oversized digest: the rows door declines (not 32 bytes), the int
    # door classifies it like the oracle
    params = refimpl.SECP256K1
    sk, pub = refimpl.keygen(params, b"\x23" * 24)
    digest = b"\x8c" * 40
    r, s, v = refimpl.ecdsa_sign(params, sk, digest)
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([v])
    pubs, ok = suite.recover_batch([digest], [sig])
    assert ok.tolist() == [True]
    assert pubs[0] == pub[0].to_bytes(32, "big") + pub[1].to_bytes(32, "big")
