"""Max-mode composition: shared shard cluster + hot-standby failover.

Reference counterpart: Max deployments — TiKV distributed commit + etcd
master election + scheduler term switching. The test races two node
replicas over ONE 3-shard cluster through a master crash: exactly one is
ever active, and the survivor continues the chain where the dead master
stopped (the chain itself is the checkpoint).
"""

import time

from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.services.max_node import (
    MaxNode,
    start_lease_registry,
    start_storage_shard,
)

TTL = 1.0
HB = 0.2


def wait_until(pred, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_max_failover_continues_chain(tmp_path):
    shards = [start_storage_shard(str(tmp_path / f"s{i}")) for i in range(3)]
    regs = [start_lease_registry(str(tmp_path / f"r{i}.json"))
            for i in range(3)]
    shard_addrs = [("127.0.0.1", s.port) for s in shards]
    reg_addrs = [("127.0.0.1", r.port) for r in regs]

    cfg = NodeConfig(crypto_backend="host", min_seal_time=0.0)
    a = MaxNode(cfg, shard_addrs, reg_addrs, "replica-a",
                lease_ttl=TTL, heartbeat=HB)
    b = MaxNode(cfg, shard_addrs, reg_addrs, "replica-b",
                lease_ttl=TTL, heartbeat=HB)
    a.start()
    try:
        assert wait_until(a.is_active)
        assert not b.is_active()
        b.start()
        time.sleep(3 * HB)
        assert not b.is_active()  # standby stays cold while a leads

        # commit real blocks through the cluster on the active master
        suite = a.node.suite
        kp = suite.generate_keypair(b"max-user")
        for i in range(2):
            tx = Transaction(
                to=pc.BALANCE_ADDRESS,
                input=pc.encode_call(
                    "register", lambda w: w.blob(b"m%d" % i).u64(50)),
                nonce=f"m{i}",
                block_limit=a.node.ledger.current_number() + 100,
            ).sign(suite, kp)
            r = a.node.send_transaction(tx)
            assert r.status == 0
            rec = a.node.txpool.wait_for_receipt(r.tx_hash, 15)
            assert rec is not None and rec.status == 0
        height_before = a.node.ledger.current_number()
        assert height_before >= 1

        # CRASH the master: leases expire, standby must take over
        a.stop(release=False)
        assert wait_until(b.is_active, timeout=TTL * 12)
        assert b.election.fence_token() > 0

        # the survivor sees the whole chain and keeps extending it
        assert b.node.ledger.current_number() >= height_before
        kp2 = b.node.suite.generate_keypair(b"max-user")
        tx = Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call("register",
                                 lambda w: w.blob(b"after").u64(9)),
            nonce="after1",
            block_limit=b.node.ledger.current_number() + 100,
        ).sign(b.node.suite, kp2)
        r = b.node.send_transaction(tx)
        assert r.status == 0
        rec = b.node.txpool.wait_for_receipt(r.tx_hash, 15)
        assert rec is not None and rec.status == 0
        assert b.node.ledger.current_number() > height_before
        # pre-crash state readable through the new master
        h1 = b.node.ledger.header_by_number(1)
        assert h1 is not None
    finally:
        for m in (a, b):
            try:
                m.stop()
            except Exception:
                pass
        for s in shards:
            s.stop()
            s.backend.close()
        for r in regs:
            r.stop()


def test_max_failover_over_smtls(tmp_path):
    """The full Max composition (shards, registries, replicas) on the
    SM-TLS service plane, through an election + one block commit."""
    from fisco_bcos_tpu.net.smtls import CertificateAuthority, SMTLSContext
    from fisco_bcos_tpu.executor import precompiled as pc
    from fisco_bcos_tpu.protocol import Transaction

    ca = CertificateAuthority(seed=b"mx-tls" * 6)

    def ctx(name):
        return SMTLSContext(ca.pub, ca.issue(name))

    shards = [start_storage_shard(str(tmp_path / f"s{i}"),
                                  tls_ctx=ctx(f"shard{i}"))
              for i in range(3)]
    regs = [start_lease_registry(str(tmp_path / f"r{i}.json"),
                                 tls_ctx=ctx(f"reg{i}"))
            for i in range(3)]
    m = MaxNode(NodeConfig(crypto_backend="host", min_seal_time=0.0),
                [("127.0.0.1", s.port) for s in shards],
                [("127.0.0.1", r.port) for r in regs],
                "tls-replica", lease_ttl=TTL, heartbeat=HB,
                tls_ctx=ctx("tls-replica"))
    m.start()
    try:
        assert wait_until(m.is_active)
        suite = m.node.suite
        kp = suite.generate_keypair(b"mx-tls-user")
        tx = Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call("register",
                                 lambda w: w.blob(b"sec").u64(7)),
            nonce="s1", block_limit=100).sign(suite, kp)
        rec = m.node.txpool.wait_for_receipt(
            m.node.send_transaction(tx).tx_hash, 15)
        assert rec is not None and rec.status == 0
        assert m.node.ledger.current_number() >= 1
    finally:
        m.stop()
        for s in shards:
            s.stop()
            s.backend.close()
        for r in regs:
            r.stop()


def test_load_max_node_from_generated_layout(tmp_path):
    """build_chain --mode max layout boots end to end via load_max_node:
    two replicas from node dirs + max_cluster.json, failover included."""
    import importlib.util as _ilu
    import os as _os

    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    spec = _ilu.spec_from_file_location(
        "fbtpu_build_chain", _os.path.join(repo, "tools", "build_chain.py"))
    bc = _ilu.module_from_spec(spec)
    spec.loader.exec_module(bc)
    info = bc.build_chain(str(tmp_path), 1, consensus="solo")
    bc.build_max_cluster(str(tmp_path), n_shards=3, n_registries=3)

    import json as _json

    from fisco_bcos_tpu.tool import load_max_node

    cluster = _json.loads((tmp_path / "max_cluster.json").read_text())
    shards = [start_storage_shard(s["dir"]) for s in cluster["shards"]]
    regs = [start_lease_registry(r["state"]) for r in cluster["registries"]]
    # rewrite endpoints with the actually-bound ephemeral ports
    cluster["shards"] = [{"host": "127.0.0.1", "port": s.port}
                         for s in shards]
    cluster["registries"] = [{"host": "127.0.0.1", "port": r.port}
                             for r in regs]
    (tmp_path / "max_cluster.json").write_text(_json.dumps(cluster))

    node_dir = str(tmp_path / "node0")
    a = load_max_node(node_dir, str(tmp_path / "max_cluster.json"), "ra",
                      lease_ttl=TTL, heartbeat=HB)
    b = load_max_node(node_dir, str(tmp_path / "max_cluster.json"), "rb",
                      lease_ttl=TTL, heartbeat=HB)
    a.start()
    b.start()
    try:
        assert wait_until(lambda: a.is_active() or b.is_active())
        active, standby = (a, b) if a.is_active() else (b, a)
        from fisco_bcos_tpu.executor import precompiled as pc
        from fisco_bcos_tpu.protocol import Transaction

        suite = active.node.suite
        kp = suite.generate_keypair(b"cfg-user")
        tx = Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call("register",
                                 lambda w: w.blob(b"cfg").u64(4)),
            nonce="c1", block_limit=100).sign(suite, kp)
        rec = active.node.txpool.wait_for_receipt(
            active.node.send_transaction(tx).tx_hash, 15)
        assert rec is not None and rec.status == 0
        h = active.node.ledger.current_number()
        active.stop(release=False)  # crash
        assert wait_until(standby.is_active, timeout=TTL * 12)
        assert standby.node.ledger.current_number() >= h
    finally:
        for m in (a, b):
            try:
                m.stop()
            except Exception:
                pass
        for s in shards:
            s.stop()
            s.backend.close()
        for r in regs:
            r.stop()


def test_mispointed_cluster_refused(tmp_path):
    """A replica whose genesis config disagrees with the chain already in
    the cluster must refuse to serve (and abdicate), not extend it."""
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.ledger.ledger import ConsensusNode

    shards = [start_storage_shard(str(tmp_path / f"s{i}"))
              for i in range(3)]
    regs = [start_lease_registry(str(tmp_path / f"r{i}.json"))
            for i in range(3)]
    shard_addrs = [("127.0.0.1", s.port) for s in shards]
    reg_addrs = [("127.0.0.1", r.port) for r in regs]
    suite = make_suite(backend="host")
    chain_a = suite.generate_keypair(b"chain-a-sealer")
    chain_b = suite.generate_keypair(b"chain-b-sealer")
    cfg = NodeConfig(crypto_backend="host", min_seal_time=0.0)

    # replica 1 builds chain A's genesis in the cluster
    m1 = MaxNode(cfg, shard_addrs, reg_addrs, "m1", keypair=chain_a,
                 lease_ttl=TTL, heartbeat=HB,
                 genesis_sealers=[chain_a.pub_bytes])
    m1.start()
    try:
        assert wait_until(m1.is_active)
    finally:
        m1.stop()

    # replica 2 arrives configured for a DIFFERENT chain: must refuse
    m2 = MaxNode(cfg, shard_addrs, reg_addrs, "m2", keypair=chain_b,
                 lease_ttl=TTL, heartbeat=HB,
                 genesis_sealers=[chain_b.pub_bytes])
    m2.start()
    try:
        time.sleep(TTL * 4)  # several election+activation attempts
        assert not m2.is_active()
    finally:
        m2.stop()
        for s in shards:
            s.stop()
            s.backend.close()
        for r in regs:
            r.stop()
