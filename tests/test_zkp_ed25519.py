"""Ed25519, discrete-log ZKPs, and ring signatures + their precompiles.

Reference: bcos-crypto signature/ed25519/, zkp/discretezkp/, and
bcos-executor extension/{RingSig,GroupSig}Precompiled.cpp.
"""

import pytest

from fisco_bcos_tpu.codec.wire import Reader
from fisco_bcos_tpu.crypto import ed25519, refimpl, zkp
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.executor.executor import TransactionExecutor
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage


# ---------------------------------------------------------------------------
# ed25519
# ---------------------------------------------------------------------------

def test_ed25519_sign_verify_and_batch():
    priv, pub = ed25519.keygen(b"ed-seed-1")
    msg = b"consortium message"
    sig = ed25519.sign(priv, msg)
    assert ed25519.verify(pub, msg, sig)
    assert not ed25519.verify(pub, msg + b"!", sig)
    assert not ed25519.verify(pub, msg, b"\x00" * 64)

    priv2, pub2 = ed25519.keygen(b"ed-seed-2")
    oks = ed25519.verify_batch(
        [pub, pub2, pub], [msg, msg, msg],
        [sig, ed25519.sign(priv2, msg), ed25519.sign(priv2, msg)])
    assert list(oks) == [True, True, False]


def test_ed25519_keypair_through_suite_sign():
    suite = make_suite(backend="host")
    kp = ed25519.Ed25519KeyPair(suite, b"ed-kp-seed")
    digest = suite.hash(b"payload")
    sig = suite.sign(kp, digest)  # dispatches to sign_digest
    assert ed25519.verify(kp.pub_raw, digest, sig[:64])
    assert sig[64:] == kp.pub_raw  # carries the pubkey like SM2


# ---------------------------------------------------------------------------
# ZKPs
# ---------------------------------------------------------------------------

def test_knowledge_proof_roundtrip():
    x = 0x1234567890ABCDEF
    P = refimpl.ec_mul(zkp.C, x, zkp.G)
    proof = zkp.prove_knowledge(x, b"ctx")
    assert zkp.verify_knowledge(P, proof, b"ctx")
    assert not zkp.verify_knowledge(P, proof, b"other-ctx")
    Q = refimpl.ec_mul(zkp.C, x + 1, zkp.G)
    assert not zkp.verify_knowledge(Q, proof, b"ctx")
    # encode/decode stability
    again = zkp.KnowledgeProof.decode(proof.encode())
    assert zkp.verify_knowledge(P, again, b"ctx")


def test_equality_proof_roundtrip():
    x = 987654321
    H = zkp.hash_to_point(b"second-base")
    P = refimpl.ec_mul(zkp.C, x, zkp.G)
    Q = refimpl.ec_mul(zkp.C, x, H)
    proof = zkp.prove_equality(x, H)
    assert zkp.verify_equality(P, Q, H, proof)
    # different exponents must fail
    Q2 = refimpl.ec_mul(zkp.C, x + 5, H)
    assert not zkp.verify_equality(P, Q2, H, proof)
    again = zkp.EqualityProof.decode(proof.encode())
    assert zkp.verify_equality(P, Q, H, again)


def test_ring_signature_hides_signer_and_links():
    secrets = [1000 + i for i in range(4)]
    ring = [refimpl.ec_mul(zkp.C, s, zkp.G) for s in secrets]
    sig = zkp.ring_sign(b"vote-A", ring, secrets[2], 2)
    assert zkp.ring_verify(b"vote-A", ring, sig)
    assert not zkp.ring_verify(b"vote-B", ring, sig)
    # tamper: different ring order invalidates
    assert not zkp.ring_verify(b"vote-A", ring[::-1], sig)
    # linkability: same signer twice -> same key image
    sig2 = zkp.ring_sign(b"vote-B", ring, secrets[2], 2)
    assert zkp.ring_verify(b"vote-B", ring, sig2)
    assert zkp.linked(sig, sig2)
    sig3 = zkp.ring_sign(b"vote-C", ring, secrets[0], 0)
    assert not zkp.linked(sig, sig3)
    again = zkp.RingSignature.decode(sig.encode())
    assert zkp.ring_verify(b"vote-A", ring, again)


# ---------------------------------------------------------------------------
# precompiles
# ---------------------------------------------------------------------------

@pytest.fixture()
def env():
    suite = make_suite(backend="host")
    return (suite, TransactionExecutor(suite),
            StateStorage(MemoryStorage()),
            suite.generate_keypair(b"zkp-user"))


_N = iter(range(10000))


def run(env, to, method, build, status=0):
    suite, ex, state, kp = env
    tx = Transaction(to=to, input=pc.encode_call(method, build),
                     nonce=f"zk{next(_N)}", block_limit=100).sign(suite, kp)
    rc = ex.execute_transaction(tx, state, 1, 0)
    assert rc.status == int(status), (method, rc.status, rc.message)
    return rc


def test_zkp_precompile_verifies(env):
    x = 777
    P = refimpl.ec_mul(zkp.C, x, zkp.G)
    proof = zkp.prove_knowledge(x, b"pc")
    rc = run(env, pc.DISCRETE_ZKP_ADDRESS, "verifyKnowledgeProof",
             lambda w: w.blob(zkp._enc(P)).blob(proof.encode()).blob(b"pc"))
    assert Reader(rc.output).u8() == 1
    rc = run(env, pc.DISCRETE_ZKP_ADDRESS, "verifyKnowledgeProof",
             lambda w: w.blob(zkp._enc(P)).blob(proof.encode()).blob(b"no"))
    assert Reader(rc.output).u8() == 0


def test_ring_sig_precompile(env):
    secrets = [5000 + i for i in range(3)]
    ring = [refimpl.ec_mul(zkp.C, s, zkp.G) for s in secrets]
    sig = zkp.ring_sign(b"anon", ring, secrets[1], 1)
    rc = run(env, pc.RING_SIG_ADDRESS, "ringSigVerify",
             lambda w: w.blob(b"anon")
             .seq([zkp._enc(P) for P in ring], lambda ww, b: ww.blob(b))
             .blob(sig.encode()))
    assert Reader(rc.output).u8() == 1
    rc = run(env, pc.RING_SIG_ADDRESS, "ringSigVerify",
             lambda w: w.blob(b"forged")
             .seq([zkp._enc(P) for P in ring], lambda ww, b: ww.blob(b))
             .blob(sig.encode()))
    assert Reader(rc.output).u8() == 0


def test_group_sig_gated(env):
    run(env, pc.GROUP_SIG_ADDRESS, "groupSigVerify", lambda w: w.blob(b"x"),
        status=TransactionStatus.PRECOMPILED_ERROR)
