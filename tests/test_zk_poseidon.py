"""ZK proof plane: Poseidon correctness (zk/poseidon*.py, zk/merkle.py).

The host reference is pinned against the PUBLISHED poseidonperm_x5_254_3
test vector (the Poseidon paper's reference repository), which transitively
pins the whole Grain-generated constant schedule; the JAX batch path must
be bit-identical to the host at every padding bucket, including inputs
above the field modulus (canonicalized via one mod-r reduction on both
paths)."""

import numpy as np
import pytest

from fisco_bcos_tpu.zk import merkle as zm
from fisco_bcos_tpu.zk import poseidon as ref
from fisco_bcos_tpu.zk import poseidon_jax as pj

# reference vector: permutation of (0, 1, 2) from the Poseidon reference
# implementation's poseidonperm_x5_254_3 script (Grassi et al.)
PINNED_PERM_012 = [
    0x115CC0F5E7D690413DF64C6B9662E9CF2A3617F2743245519E19607A4417189A,
    0x0FCA49B798923AB0239DE1C9E7A4A9A2210312B6A2F616D18B5A87F9B628AE29,
    0x0E7AE82E40091E63CBD4F16A6D16310B3729D4B6E138FCF54110E2867045A30C,
]
# first Grain round constant of the same instance — pins the generator
# independently of the permutation structure
PINNED_RC0 = 0x0EE9A592BA9A9518D05986D656F40C2114C4993C11BB29938D21D47304CD8E6E


def test_pinned_reference_vector():
    assert ref.permute([0, 1, 2]) == PINNED_PERM_012


def test_grain_schedule_pins():
    rc, mds = ref.params()
    assert len(rc) == (ref.R_F + ref.R_P) * ref.T
    assert rc[0] == PINNED_RC0
    assert all(0 <= v < ref.P for v in rc)
    assert len(set(rc)) == len(rc)  # schedule has no repeats
    # MDS is invertible (Cauchy over distinct points): det != 0
    a, b, c = mds[0]
    d, e, f = mds[1]
    g, h, i = mds[2]
    det = (a * (e * i - f * h) - b * (d * i - f * g)
           + c * (d * h - e * g)) % ref.P
    assert det != 0


def test_hash2_field_mapping():
    # inputs at/above the modulus canonicalize via mod-r — the documented
    # mapping for arbitrary 32-byte ledger digests
    top = b"\xff" * 32
    assert ref.hash2_bytes(top, top) == ref.hash2_bytes(
        ref.to_bytes(ref.to_field(top)), ref.to_bytes(ref.to_field(top)))
    assert ref.to_field(ref.to_bytes(ref.P - 1)) == ref.P - 1
    assert ref.hash2(0, 0) == ref.permute([0, 0, 0])[0]


def test_limb_roundtrip():
    rng = np.random.default_rng(7)
    vals = [rng.bytes(32) for _ in range(130)] + [b"\x00" * 32,
                                                  b"\xff" * 32]
    assert pj.limbs_to_bytes(pj.bytes_to_limbs(vals)) == vals


@pytest.mark.parametrize("n", [1, 3, 126, 129])
def test_host_jax_bit_identity_across_buckets(n):
    """Bit identity host vs JAX at every padding bucket the sizes cover
    (1/3/126 pad into the 128 bucket, 129 crosses into 512), over random
    inputs that mostly exceed the modulus (256-bit draws vs r ~ 2^254)."""
    rng = np.random.default_rng(n)
    lefts = [rng.bytes(32) for _ in range(n)]
    rights = [rng.bytes(32) for _ in range(n)]
    lefts[0] = b"\x00" * 32  # zero / all-ones edges ride along
    rights[0] = b"\xff" * 32
    assert pj.hash2_batch(lefts, rights) == ref.hash2_batch_host(
        lefts, rights)


def test_poseidon_merkle_roundtrip_property():
    rng = np.random.default_rng(11)
    for size in (1, 2, 3, 8, 13):
        leaves = [rng.bytes(32) for _ in range(size)]
        levels = zm.build_levels(leaves)
        root = levels[-1][0]
        for idx in range(size):
            proof = zm.proof_from_levels(levels, idx)
            assert zm.verify(leaves[idx], proof, root)
            # tampered leaf / root / sibling all reject
            bad = bytes([leaves[idx][0] ^ 1]) + leaves[idx][1:]
            assert not zm.verify(bad, proof, root)
            assert not zm.verify(leaves[idx], proof, b"\x01" * 32)
            if proof:
                left, right, pos = proof[0]
                forged = [(left, b"\x03" * 32, pos)] + proof[1:]
                if pos == 0:  # keep the path slot intact, break the sibling
                    assert not zm.verify(leaves[idx], forged, root)


def test_poseidon_merkle_batched_verify_jax_hasher():
    """N proofs verify as ONE batched hash call, through the same JAX
    path production uses (reuses the 128 bucket's executable)."""
    rng = np.random.default_rng(13)
    leaves = [rng.bytes(32) for _ in range(13)]
    levels = zm.build_levels(leaves, hasher=pj.hash2_batch)
    # host- and device-built trees agree
    assert levels[-1][0] == zm.root(leaves)
    items = [(leaves[i], zm.proof_from_levels(levels, i), levels[-1][0])
             for i in range(13)]
    ok = zm.verify_batch(items, hasher=pj.hash2_batch)
    assert ok.all()
    items[4] = (items[4][0], items[4][1], b"\x02" * 32)
    ok = zm.verify_batch(items, hasher=pj.hash2_batch)
    assert not ok[4] and ok.sum() == 12


def test_proof_json_roundtrip():
    rng = np.random.default_rng(17)
    leaves = [rng.bytes(32) for _ in range(5)]
    proof = zm.merkle_proof(leaves, 3)
    assert zm.proof_from_json(zm.proof_json(proof)) == proof
