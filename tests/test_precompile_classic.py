"""Classic precompiles 6/7/9 (bn128 add/mul, blake2f) + pairing policy.

Validation strategy: blake2f against hashlib.blake2b (an independent
implementation of the same function); bn128 against algebraic identities
(2G via add == 2G via mul, P + (-P) = O, order*G = O, commutativity)
rather than memorized vectors.
"""

import hashlib

import pytest

from fisco_bcos_tpu.executor import precompile_classic as pcc
from fisco_bcos_tpu.executor.evm import EVM
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage
from tests.test_nevm import ENV

SUITE = make_suite(backend="host")
G1 = (1, 2)  # bn128 generator


def w32(*vals: int) -> bytes:
    return b"".join(v.to_bytes(32, "big") for v in vals)


def point(out: bytes) -> tuple[int, int]:
    return (int.from_bytes(out[:32], "big"),
            int.from_bytes(out[32:], "big"))


def test_bn128_add_mul_identities():
    # 2G via ECADD(G, G) == 2G via ECMUL(G, 2)
    two_g_add = point(pcc.bn128_add(w32(*G1, *G1)))
    two_g_mul = point(pcc.bn128_mul(w32(*G1, 2)))
    assert two_g_add == two_g_mul != (0, 0)
    # commutativity: G + 2G == 2G + G == 3G
    three_a = point(pcc.bn128_add(w32(*G1, *two_g_add)))
    three_b = point(pcc.bn128_add(w32(*two_g_add, *G1)))
    assert three_a == three_b == point(pcc.bn128_mul(w32(*G1, 3)))
    # inverse: P + (-P) = O  (-P = (x, p - y))
    neg_g = (G1[0], pcc.BN_P - G1[1])
    assert point(pcc.bn128_add(w32(*G1, *neg_g))) == (0, 0)
    # order annihilates: n*G = O; (n+1)*G = G
    assert point(pcc.bn128_mul(w32(*G1, pcc.BN_N))) == (0, 0)
    assert point(pcc.bn128_mul(w32(*G1, pcc.BN_N + 1))) == G1
    # infinity handling
    assert point(pcc.bn128_add(w32(0, 0, *G1))) == G1
    assert point(pcc.bn128_mul(w32(0, 0, 55))) == (0, 0)
    # short input is zero-padded per spec (ECADD of G and O)
    assert point(pcc.bn128_add(w32(*G1))) == G1


def test_bn128_invalid_points_rejected():
    with pytest.raises(pcc.PrecompileInputError):
        pcc.bn128_add(w32(1, 3, *G1))  # (1,3) not on curve
    with pytest.raises(pcc.PrecompileInputError):
        pcc.bn128_mul(w32(pcc.BN_P, 2, 1))  # x >= p


def _blake2f_input(rounds: int, h: list[int], m: bytes, t0: int, t1: int,
                   final: bool) -> bytes:
    return (rounds.to_bytes(4, "big")
            + b"".join(x.to_bytes(8, "little") for x in h)
            + m.ljust(128, b"\x00")
            + t0.to_bytes(8, "little") + t1.to_bytes(8, "little")
            + (b"\x01" if final else b"\x00"))


def test_blake2f_matches_hashlib_blake2b():
    """One compression of 'abc' with the standard parameter block must
    reproduce hashlib.blake2b(b'abc') — an independent implementation."""
    h = list(pcc._IV)
    h[0] ^= 0x01010040  # digest_length=64, fanout=1, depth=1
    out, cost = pcc.blake2f(_blake2f_input(12, h, b"abc", 3, 0, True))
    assert cost == 12
    assert out == hashlib.blake2b(b"abc").digest()


def test_blake2f_multi_block_matches_hashlib():
    msg = bytes(range(256))  # two 128-byte blocks
    h = list(pcc._IV)
    h[0] ^= 0x01010040
    out1, _ = pcc.blake2f(_blake2f_input(12, h, msg[:128], 128, 0, False))
    h2 = [int.from_bytes(out1[8 * i:8 * (i + 1)], "little")
          for i in range(8)]
    out2, _ = pcc.blake2f(_blake2f_input(12, h2, msg[128:], 256, 0, True))
    assert out2 == hashlib.blake2b(msg).digest()


def test_blake2f_input_validation():
    with pytest.raises(pcc.PrecompileInputError):
        pcc.blake2f(b"\x00" * 212)  # short
    bad = bytearray(_blake2f_input(1, list(pcc._IV), b"", 0, 0, True))
    bad[212] = 2
    with pytest.raises(pcc.PrecompileInputError):
        pcc.blake2f(bytes(bad))


def addr(n: int) -> bytes:
    return n.to_bytes(20, "big")


def call_pre(which: int, data: bytes, gas: int = 100_000):
    evm = EVM(SUITE, native=False)
    st = StateStorage(MemoryStorage())
    return evm.execute_message(st, ENV, b"\x22" * 20, addr(which), 0,
                               data, gas)


def test_evm_dispatch_and_gas():
    res = call_pre(6, w32(*G1, *G1))
    assert res.success and point(res.output) == point(
        pcc.bn128_mul(w32(*G1, 2)))
    assert res.gas_left == 100_000 - pcc.G_BNADD
    res = call_pre(7, w32(*G1, 5))
    assert res.success and res.gas_left == 100_000 - pcc.G_BNMUL
    h = list(pcc._IV)
    h[0] ^= 0x01010040
    res = call_pre(9, _blake2f_input(12, h, b"abc", 3, 0, True))
    assert res.success and res.output == hashlib.blake2b(b"abc").digest()
    assert res.gas_left == 100_000 - 12
    # invalid input consumes all gas (EIP-196 semantics)
    res = call_pre(6, w32(1, 3, *G1))
    assert not res.success and res.gas_left == 0


def test_pairing_policy():
    res = call_pre(8, b"")
    assert res.success
    assert int.from_bytes(res.output, "big") == 1
    res = call_pre(8, b"\x00" * 192)
    assert not res.success and "pairing" in res.error


def test_blake2f_huge_rounds_gas_gated_fast():
    """rounds = 2^32-1 with insufficient gas must fail in O(1) — the gas
    gate runs BEFORE any compression work (DoS guard)."""
    import time as _time

    data = (0xFFFFFFFF).to_bytes(4, "big") + b"\x00" * 208 + b"\x01"
    t0 = _time.monotonic()
    res = call_pre(9, data, gas=50_000)
    assert _time.monotonic() - t0 < 1.0
    assert not res.success and res.error == "oog"
