"""Classic precompiles 6/7/9 (bn128 add/mul, blake2f) + pairing policy.

Validation strategy: blake2f against hashlib.blake2b (an independent
implementation of the same function); bn128 against algebraic identities
(2G via add == 2G via mul, P + (-P) = O, order*G = O, commutativity)
rather than memorized vectors.
"""

import hashlib

import pytest

from fisco_bcos_tpu.executor import precompile_classic as pcc
from fisco_bcos_tpu.executor.evm import EVM
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage
from tests.test_nevm import ENV

SUITE = make_suite(backend="host")
G1 = (1, 2)  # bn128 generator


def w32(*vals: int) -> bytes:
    return b"".join(v.to_bytes(32, "big") for v in vals)


def point(out: bytes) -> tuple[int, int]:
    return (int.from_bytes(out[:32], "big"),
            int.from_bytes(out[32:], "big"))


def test_bn128_add_mul_identities():
    # 2G via ECADD(G, G) == 2G via ECMUL(G, 2)
    two_g_add = point(pcc.bn128_add(w32(*G1, *G1)))
    two_g_mul = point(pcc.bn128_mul(w32(*G1, 2)))
    assert two_g_add == two_g_mul != (0, 0)
    # commutativity: G + 2G == 2G + G == 3G
    three_a = point(pcc.bn128_add(w32(*G1, *two_g_add)))
    three_b = point(pcc.bn128_add(w32(*two_g_add, *G1)))
    assert three_a == three_b == point(pcc.bn128_mul(w32(*G1, 3)))
    # inverse: P + (-P) = O  (-P = (x, p - y))
    neg_g = (G1[0], pcc.BN_P - G1[1])
    assert point(pcc.bn128_add(w32(*G1, *neg_g))) == (0, 0)
    # order annihilates: n*G = O; (n+1)*G = G
    assert point(pcc.bn128_mul(w32(*G1, pcc.BN_N))) == (0, 0)
    assert point(pcc.bn128_mul(w32(*G1, pcc.BN_N + 1))) == G1
    # infinity handling
    assert point(pcc.bn128_add(w32(0, 0, *G1))) == G1
    assert point(pcc.bn128_mul(w32(0, 0, 55))) == (0, 0)
    # short input is zero-padded per spec (ECADD of G and O)
    assert point(pcc.bn128_add(w32(*G1))) == G1


def test_bn128_invalid_points_rejected():
    with pytest.raises(pcc.PrecompileInputError):
        pcc.bn128_add(w32(1, 3, *G1))  # (1,3) not on curve
    with pytest.raises(pcc.PrecompileInputError):
        pcc.bn128_mul(w32(pcc.BN_P, 2, 1))  # x >= p


def _blake2f_input(rounds: int, h: list[int], m: bytes, t0: int, t1: int,
                   final: bool) -> bytes:
    return (rounds.to_bytes(4, "big")
            + b"".join(x.to_bytes(8, "little") for x in h)
            + m.ljust(128, b"\x00")
            + t0.to_bytes(8, "little") + t1.to_bytes(8, "little")
            + (b"\x01" if final else b"\x00"))


def test_blake2f_matches_hashlib_blake2b():
    """One compression of 'abc' with the standard parameter block must
    reproduce hashlib.blake2b(b'abc') — an independent implementation."""
    h = list(pcc._IV)
    h[0] ^= 0x01010040  # digest_length=64, fanout=1, depth=1
    out, cost = pcc.blake2f(_blake2f_input(12, h, b"abc", 3, 0, True))
    assert cost == 12
    assert out == hashlib.blake2b(b"abc").digest()


def test_blake2f_multi_block_matches_hashlib():
    msg = bytes(range(256))  # two 128-byte blocks
    h = list(pcc._IV)
    h[0] ^= 0x01010040
    out1, _ = pcc.blake2f(_blake2f_input(12, h, msg[:128], 128, 0, False))
    h2 = [int.from_bytes(out1[8 * i:8 * (i + 1)], "little")
          for i in range(8)]
    out2, _ = pcc.blake2f(_blake2f_input(12, h2, msg[128:], 256, 0, True))
    assert out2 == hashlib.blake2b(msg).digest()


def test_blake2f_input_validation():
    with pytest.raises(pcc.PrecompileInputError):
        pcc.blake2f(b"\x00" * 212)  # short
    bad = bytearray(_blake2f_input(1, list(pcc._IV), b"", 0, 0, True))
    bad[212] = 2
    with pytest.raises(pcc.PrecompileInputError):
        pcc.blake2f(bytes(bad))


def addr(n: int) -> bytes:
    return n.to_bytes(20, "big")


def call_pre(which: int, data: bytes, gas: int = 100_000,
             native: bool = False, version: str | None = None):
    evm = EVM(SUITE, native=native)
    st = StateStorage(MemoryStorage())
    if version is not None:
        from fisco_bcos_tpu.codec.wire import Writer
        from fisco_bcos_tpu.ledger import ledger as ledger_mod
        w = Writer()
        w.text(version).i64(0)
        st.set(ledger_mod.SYS_CONFIG,
               ledger_mod.SYSTEM_KEY_COMPATIBILITY_VERSION.encode(),
               w.bytes())
    return evm.execute_message(st, ENV, b"\x22" * 20, addr(which), 0,
                               data, gas)


def test_evm_dispatch_and_gas():
    res = call_pre(6, w32(*G1, *G1))
    assert res.success and point(res.output) == point(
        pcc.bn128_mul(w32(*G1, 2)))
    assert res.gas_left == 100_000 - pcc.G_BNADD
    res = call_pre(7, w32(*G1, 5))
    assert res.success and res.gas_left == 100_000 - pcc.G_BNMUL
    h = list(pcc._IV)
    h[0] ^= 0x01010040
    res = call_pre(9, _blake2f_input(12, h, b"abc", 3, 0, True))
    assert res.success and res.output == hashlib.blake2b(b"abc").digest()
    assert res.gas_left == 100_000 - 12
    # invalid input consumes all gas (EIP-196 semantics)
    res = call_pre(6, w32(1, 3, *G1))
    assert not res.success and res.gas_left == 0


def test_pairing_gated_below_1_1_0():
    """Pre-1.1 chains keep round-4 semantics: vacuous empty-input true,
    real input refused loudly (the compatibility_version gate)."""
    for version in (None, "1.0.0"):
        res = call_pre(8, b"", version=version)
        assert res.success
        assert int.from_bytes(res.output, "big") == 1
        res = call_pre(8, bytes(192), version=version)
        assert not res.success and "compatibility_version" in res.error


def _pairing_gas(n_pairs: int) -> int:
    return pcc.G_PAIRING_BASE + pcc.G_PAIRING_PER_PAIR * n_pairs


@pytest.mark.parametrize("native", [False, True])
def test_pairing_canonical_vectors(native):
    """The public go-ethereum bn256 pairing corpus (as carried by the
    reference, EVMPrecompiledTest.cpp:1241) through BOTH interpreters at
    compatibility_version 1.1.0 — positive, negative and 10-pair cases."""
    from tests.data_bn256_pairing import PAIRING_VECTORS

    gas = 20_000_000  # 10-pair corpus rows cost ~13.5M at the repriced gas
    for name, inp, exp in PAIRING_VECTORS[:4] + PAIRING_VECTORS[-3:]:
        data = bytes.fromhex(inp)
        res = call_pre(8, data, gas=gas, native=native, version="1.1.0")
        assert res.success, (name, res.error)
        assert res.output.hex() == exp, name
        assert res.gas_left == gas - _pairing_gas(len(data) // 192), name


def test_pairing_empty_and_malformed_at_1_1_0():
    res = call_pre(8, b"", version="1.1.0")
    assert res.success and int.from_bytes(res.output, "big") == 1
    # not a multiple of 192 -> failure consuming all gas
    res = call_pre(8, bytes(191), version="1.1.0")
    assert not res.success and res.gas_left == 0
    # on-curve but out-of-subgroup G2 point must be rejected (EIP-197)
    from fisco_bcos_tpu.crypto import bn254

    def f2_sqrt(a):
        """Complex-method sqrt in Fp2 (p = 3 mod 4); None if non-residue."""
        c0, c1 = a
        p = bn254.P
        if c1 == 0:
            y = pow(c0, (p + 1) // 4, p)
            return (y, 0) if y * y % p == c0 else None
        norm = (c0 * c0 + c1 * c1) % p
        lam = pow(norm, (p + 1) // 4, p)
        if lam * lam % p != norm:
            return None
        for l in (lam, (-lam) % p):
            delta = (c0 + l) * pow(2, p - 2, p) % p
            x0 = pow(delta, (p + 1) // 4, p)
            if x0 * x0 % p == delta and x0:
                x1 = c1 * pow(2 * x0, p - 2, p) % p
                cand = (x0, x1)
                if bn254.f2_sqr(cand) == a:
                    return cand
        return None

    q = None
    for xi in range(1, 200):
        x = (xi, xi + 1)
        rhs = bn254.f2_add(bn254.f2_mul(bn254.f2_sqr(x), x), bn254.TWIST_B)
        y = f2_sqrt(rhs)
        if y is None:
            continue
        cand = (x, y)
        assert bn254.g2_on_curve(cand)
        if not bn254.g2_in_subgroup(cand):
            q = cand
            break
    assert q is not None, "no out-of-subgroup twist point found in range"
    g1 = (1, 2)
    data = w32(*g1, q[0][1], q[0][0], q[1][1], q[1][0])
    res = call_pre(8, data, version="1.1.0", gas=2_000_000)
    assert not res.success and res.gas_left == 0


def test_pairing_bilinearity():
    """e(aP, bQ) == e(abP, Q): product e(2P,3Q) * e(-6P,Q) == 1, pure
    algebra independent of the vector corpus."""
    from fisco_bcos_tpu.crypto import bn254

    P1 = (1, 2)
    # the canonical G2 generator (EIP-197 / go-ethereum twist generator)
    G2 = ((10857046999023057135944570762232829481370756359578518086990519993285655852781,
           11559732032986387107991004021392285783925812861821192530917403151452391805634),
          (8495653923123431417604973247489272438418190587263600148770280649306958101930,
           4082367875863433681332203403145435568316851327593401208105741076214120093531))
    assert bn254.g2_in_subgroup(G2)
    p2 = pcc._bn_mul(P1, 2)
    q3 = bn254.g2_mul(G2, 3)
    p6neg = pcc._bn_mul(P1, pcc.BN_N - 6)
    assert bn254.pairing_check([(p2, q3), (p6neg, G2)])
    # and the unbalanced variant must NOT check out
    assert not bn254.pairing_check([(p2, q3), (p6neg, bn254.g2_mul(G2, 2))])


@pytest.mark.parametrize("native", [False, True])
def test_bn128_add_mul_canonical_vectors(native):
    """go-ethereum bn256 add/mul corpora through both interpreters."""
    from tests.data_bn256_pairing import ADD_VECTORS, MUL_VECTORS

    for name, inp, exp in ADD_VECTORS[:8]:
        res = call_pre(6, bytes.fromhex(inp), native=native)
        assert res.success, name
        assert res.output.hex() == exp, name
    for name, inp, exp in MUL_VECTORS[:8]:
        res = call_pre(7, bytes.fromhex(inp), native=native)
        assert res.success, name
        assert res.output.hex() == exp, name


def test_blake2f_huge_rounds_gas_gated_fast():
    """rounds = 2^32-1 with insufficient gas must fail in O(1) — the gas
    gate runs BEFORE any compression work (DoS guard)."""
    import time as _time

    data = (0xFFFFFFFF).to_bytes(4, "big") + b"\x00" * 208 + b"\x01"
    t0 = _time.monotonic()
    res = call_pre(9, data, gas=50_000)
    assert _time.monotonic() - t0 < 1.0
    assert not res.success and res.error == "oog"


def test_pairing_over_limit_fails_fast():
    """An over-cap pairing call (the ~0.45 s/pair DoS vector) must be
    refused in O(1) with a cap error — even with ample gas — instead of
    pinning the execution lane for seconds."""
    import time as _time

    data = bytes(192) * (pcc.MAX_PAIRING_PAIRS + 1)  # all-infinity pairs
    t0 = _time.monotonic()
    res = call_pre(8, data, gas=1_000_000_000, version="1.1.0")
    assert _time.monotonic() - t0 < 1.0
    assert not res.success and res.gas_left == 0
    assert "per-call cap" in res.error
    # the raw implementation enforces the same cap for direct callers
    with pytest.raises(pcc.PrecompileInputError):
        pcc.bn128_pairing(data)
    # under-gassed at-cap input also fails fast, by price
    res = call_pre(8, bytes(192) * pcc.MAX_PAIRING_PAIRS, gas=100_000,
                   version="1.1.0")
    assert not res.success and res.error == "oog"


def test_pairing_per_tx_budget():
    """Nested frames of ONE transaction share a deterministic pairing-pair
    budget (the contract-loops-CALLs DoS shape); a fresh transaction starts
    with a full budget. The budget is per-tx, not a shared per-block
    counter, so parallel DAG execution stays order-independent."""
    from fisco_bcos_tpu.codec.wire import Writer
    from fisco_bcos_tpu.ledger import ledger as ledger_mod
    from tests.test_nevm import ENV as _ENV

    evm = EVM(SUITE)
    st = StateStorage(MemoryStorage())
    w = Writer()
    w.text("1.1.0").i64(0)
    st.set(ledger_mod.SYS_CONFIG,
           ledger_mod.SYSTEM_KEY_COMPATIBILITY_VERSION.encode(), w.bytes())
    budget = evm.MAX_PAIRING_PAIRS_PER_TX
    per_call = min(pcc.MAX_PAIRING_PAIRS, budget)
    gas = pcc.G_PAIRING_BASE + pcc.G_PAIRING_PER_PAIR * per_call
    data = bytes(192) * per_call  # infinity pairs: valid, cheap to parse
    caller = b"\x22" * 20
    # one tx: depth>0 frames do NOT reset the per-tx access context
    evm.begin_tx_access(caller, addr(8))
    calls, spent = 0, 0
    while spent + per_call <= budget:
        res = evm.execute_message(st, _ENV, caller, addr(8), 0, data, gas,
                                  depth=1)
        assert res.success, res.error
        calls, spent = calls + 1, spent + per_call
    assert calls >= 1
    res = evm.execute_message(st, _ENV, caller, addr(8), 0, data, gas,
                              depth=1)
    assert not res.success and "per-transaction pair budget" in res.error
    # a NEW transaction (depth-0 entry resets the tx context): full budget
    res = evm.execute_message(st, _ENV, caller, addr(8), 0, data, gas)
    assert res.success, res.error
