"""JSON-RPC server + SDK client round trip against a solo node.

Covers the reference's access-layer surface (bcos-rpc JsonRpcInterface.cpp
method table; bcos-sdk Sdk/TransactionBuilder) end to end over real HTTP.
"""

import pytest

from fisco_bcos_tpu.codec.wire import Reader
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ops import merkle as merkle_mod
from fisco_bcos_tpu.sdk.client import RpcCallError, SdkClient, TransactionBuilder


@pytest.fixture()
def rpc_node():
    n = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                        rpc_port=0))
    n.start()
    client = SdkClient(f"http://{n.rpc.host}:{n.rpc.port}")
    yield n, client
    n.stop()


def test_rpc_tx_lifecycle(rpc_node):
    node, client = rpc_node
    suite = node.suite
    kp = suite.generate_keypair(b"rpcuser")
    builder = TransactionBuilder(suite, client)

    rc = builder.send(kp, pc.BALANCE_ADDRESS,
                      pc.encode_call("register",
                                     lambda w: w.blob(b"rpc").u64(77)))
    assert rc["status"] == 0
    tx_hash = rc["transactionHash"]

    # queries
    assert client.get_block_number() >= 1
    got = client.get_transaction(tx_hash)
    assert got["hash"] == tx_hash and got["from"]
    # single-tx block: the proof is empty (leaf == root), and must verify
    rcpt = client.get_transaction_receipt(tx_hash, require_proof=True)
    assert rcpt["status"] == 0 and "receiptProof" in rcpt

    blk = client.get_block_by_number(rc["blockNumber"])
    assert blk["number"] == rc["blockNumber"]
    assert blk["transactions"][0]["hash"] == tx_hash
    assert client.get_block_by_hash(blk["hash"], only_header=True)[
        "number"] == blk["number"]
    assert client.request("getBlockHashByNumber",
                          ["group0", "", blk["number"]]) == blk["hash"]

    out = client.call(pc.BALANCE_ADDRESS,
                      pc.encode_call("balanceOf", lambda w: w.blob(b"rpc")))
    assert out["status"] == 0
    assert Reader(bytes.fromhex(out["output"][2:])).u64() == 77

    # tx merkle proof verifies against the block's txsRoot
    got_proof = client.get_transaction(tx_hash, require_proof=True)
    proof = [(list(map(lambda s: bytes.fromhex(s[2:]), lvl["siblings"])),
              lvl["index"]) for lvl in got_proof["txProof"]]
    assert merkle_mod.verify_merkle_proof(
        bytes.fromhex(tx_hash[2:]), proof,
        bytes.fromhex(got_proof["txsRoot"][2:]), suite.hash_name)


def test_rpc_status_and_errors(rpc_node):
    node, client = rpc_node
    status = client.get_sync_status()
    assert status["blockNumber"] == node.ledger.current_number()
    counts = client.get_total_transaction_count()
    assert counts["blockNumber"] == node.ledger.current_number()
    sealers = client.get_sealer_list()
    assert sealers and sealers[0]["nodeID"].startswith("0x")
    cfg = client.get_system_config("tx_count_limit")
    assert cfg["value"] == "1000"
    info = client.get_group_info()
    assert info["groupID"] == "group0" and info["genesisHash"].startswith("0x")
    assert client.request("getGroupList", [])["groupList"] == ["group0"]
    assert client.get_pending_tx_size() == 0

    with pytest.raises(RpcCallError):
        client.request("noSuchMethod", [])
    with pytest.raises(RpcCallError):
        client.request("getBlockNumber", ["wrong-group", ""])
    # malformed tx hex -> internal error, not a crash
    with pytest.raises(RpcCallError):
        client.request("sendTransaction", ["group0", "", "0xdeadbeef", False])
