"""Golden tests for Keccak256/SM3 TPU kernels vs known vectors + Python oracle."""

import random

import pytest

import jax.numpy as jnp
import numpy as np

from fisco_bcos_tpu.crypto import refimpl
from fisco_bcos_tpu.ops import keccak, merkle, sm3

rng = random.Random(7)


def test_keccak_vectors_ref():
    assert refimpl.keccak256(b"").hex() == (
        "c5d2460186f7233c927e7db2dcc703c0e500b653ca82273b7bfad8045d85a470"
    )
    assert refimpl.keccak256(b"abc").hex() == (
        "4e03657aea45a94fc7d47ba826c8d667c0d1e6e33a64a036ec44f58fa12d6c45"
    )


def test_sm3_vectors_ref():
    assert refimpl.sm3(b"abc").hex() == (
        "66c7f0f462eeedd9d1f2d46bdc10e4e24167c4875cf2f7a2297da02b8f4ba8e0"
    )
    assert refimpl.sm3(b"abcd" * 16).hex() == (
        "debe9ff92275b8a138604889c18e5a4d6fdb70e5387e5765293dcba39c0c5732"
    )


def test_keccak_device_matches_ref():
    msgs = [b"", b"abc", bytes(range(136)), rng.randbytes(300), rng.randbytes(135),
            rng.randbytes(136), rng.randbytes(137), rng.randbytes(500)]
    got = keccak.keccak256_batch_np(msgs)
    for i, m in enumerate(msgs):
        assert bytes(got[i]) == refimpl.keccak256(m), f"msg {i} len {len(m)}"


def test_sm3_device_matches_ref():
    msgs = [b"", b"abc", rng.randbytes(55), rng.randbytes(56), rng.randbytes(64),
            rng.randbytes(200)]
    got = sm3.sm3_batch_np(msgs)
    for i, m in enumerate(msgs):
        assert bytes(got[i]) == refimpl.sm3(m), f"msg {i} len {len(m)}"


def _host_root(leaves, alg):
    return merkle.merkle_levels_host(leaves, alg)[-1][0]


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_merkle_root_device_vs_host():
    for alg in ("keccak256", "sm3"):
        for n in (1, 2, 16, 17, 40, 256, 300):
            leaves = [rng.randbytes(32) for _ in range(n)]
            dev = bytes(np.asarray(merkle.merkle_root(
                np.frombuffer(b"".join(leaves), dtype=np.uint8).reshape(n, 32), alg)))
            host = _host_root(leaves, alg)
            assert dev == host, (alg, n)


def test_merkle_bucket_invariance():
    # same logical n must give same root regardless of bucket padding
    leaves = [rng.randbytes(32) for _ in range(20)]
    arr = np.frombuffer(b"".join(leaves), dtype=np.uint8).reshape(20, 32)
    r1 = bytes(np.asarray(merkle.merkle_root(arr)))
    big = np.concatenate([arr, np.zeros((1004, 32), np.uint8)])
    r2 = bytes(np.asarray(merkle._merkle_root_bucketed(jnp.asarray(big), jnp.int32(20), "keccak256")))
    assert r1 == r2


def test_merkle_proof():
    leaves = [rng.randbytes(32) for _ in range(40)]
    root = _host_root(leaves, "keccak256")
    for idx in (0, 15, 16, 39):
        proof = merkle.merkle_proof(leaves, idx)
        assert merkle.verify_merkle_proof(leaves[idx], proof, root)
    bad = merkle.merkle_proof(leaves, 3)
    assert not merkle.verify_merkle_proof(leaves[4], bad, root)


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_suite_chunked_device_batches(monkeypatch):
    """Batches above CHUNK pipeline multiple kernel calls (double-buffered
    staging analogue) and must be bit-identical to the host oracle."""
    from fisco_bcos_tpu.crypto import suite as suite_mod
    from fisco_bcos_tpu.crypto.suite import make_suite

    monkeypatch.setattr(suite_mod, "CHUNK", 8)
    s = make_suite(backend="device", device_min_batch=1)
    host = make_suite(backend="host")
    kps = [host.generate_keypair(bytes([i + 1]) * 8) for i in range(4)]
    digests, sigs, pubs = [], [], []
    for i in range(20):  # > 2 chunks of 8
        kp = kps[i % 4]
        d = host.hash(b"chunk-%d" % i)
        digests.append(d)
        sigs.append(host.sign(kp, d))
        pubs.append(kp.pub_bytes)
    # corrupt one signature: chunking must preserve per-index results
    sigs[13] = sigs[12]

    ok_dev = s.verify_batch(digests, sigs, pubs)
    ok_host = host.verify_batch(digests, sigs, pubs)
    assert list(ok_dev) == list(ok_host)
    assert not ok_dev[13] and ok_dev[12]

    pubs_dev, okr_dev = s.recover_batch(digests, sigs)
    pubs_host, okr_host = host.recover_batch(digests, sigs)
    assert list(okr_dev) == list(okr_host)
    assert pubs_dev == pubs_host


def test_native_host_hash_matches_refimpl():
    """native/nevm's C++ Keccak-256 and SM3 (the host-path suite hashers)
    must match the pure-Python oracle across padding boundaries (empty,
    sub-rate, rate-1/rate/rate+1, multi-block)."""
    import pytest

    from fisco_bcos_tpu.crypto import nativehash, refimpl

    nk, ns = nativehash.keccak256(), nativehash.sm3()
    if nk is None:
        pytest.skip("libnevm.so not built")
    rng = np.random.default_rng(9)
    sizes = [0, 1, 31, 32, 55, 56, 63, 64, 65, 135, 136, 137, 200, 500,
             1000]
    for n in sizes:
        data = rng.bytes(n)
        assert nk(data) == refimpl.keccak256(data), n
        assert ns(data) == refimpl.sm3(data), n


def test_suite_host_hash_uses_native_when_available():
    from fisco_bcos_tpu.crypto import nativehash, refimpl
    from fisco_bcos_tpu.crypto.suite import make_suite

    s = make_suite(backend="host")
    if nativehash.keccak256() is not None:
        assert s._host_hash is not refimpl.keccak256
    assert s.hash(b"abc") == refimpl.keccak256(b"abc")
    sm = make_suite(True, backend="host")
    assert sm.hash(b"abc") == refimpl.sm3(b"abc")


def test_native_host_hash_accepts_buffer_types():
    from fisco_bcos_tpu.crypto import nativehash, refimpl

    nk = nativehash.keccak256()
    if nk is None:
        import pytest
        pytest.skip("libnevm.so not built")
    want = refimpl.keccak256(b"buffer-shapes")
    assert nk(bytearray(b"buffer-shapes")) == want
    assert nk(memoryview(b"buffer-shapes")) == want
