"""Robustness plane: failpoints, health state machine, invariant auditor.

Tier-1 coverage for ISSUE 11's three pieces:

  * utils/failpoints.py — action semantics, the registered-site inventory,
    and an in-process 4-node PBFT matrix firing raise/loss/stall actions
    at the registered pipeline/network sites, asserting all nodes converge
    to the identical head hash and byte-identical `c_balance` rows with a
    clean invariant audit after every fault;
  * utils/health.py — commit-thread exception and injected ENOSPC each
    flip the node to degraded (writes shed with the typed status, reads
    keep serving) and self-heal back to ok without a restart;
  * ops/audit.py — detects forged cross-group credits and WAL corruption;
    `getAuditReport`, `/healthz`, `/failpoints` and the `bcos_node_health`
    gauge round-trip over a real RPC edge.

The in-process xshard saga sweep below is the tier-1 guard for the saga
legs; the real-SIGKILL two-phase test in test_xshard.py stays as the slow
e2e gate. The ChaosHarness crash/Byzantine runs live behind `-m slow` and
`tools/sanitize_ci.sh --faults`.
"""

import errno
import json
import random
import threading
import time
import urllib.error
import urllib.request

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.group import GroupManager
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import ConsensusNode
from fisco_bcos_tpu.net.gateway import FakeGateway
from fisco_bcos_tpu.ops.audit import (audit_cross_group, audit_node,
                                      audit_report)
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.utils import failpoints as fp
from fisco_bcos_tpu.utils.health import Health


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.disarm_all()
    yield
    fp.disarm_all()


def wait_until(pred, timeout=30.0, tick=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(tick)
    return False


# -- failpoint plane unit behavior ------------------------------------------

def test_failpoint_actions_budgets_and_parsing():
    fp.arm("t.raise", "raise*2")
    fired = 0
    for _ in range(5):
        try:
            fp.fire("t.raise")
        except fp.FailpointError as exc:
            assert exc.site == "t.raise"
            fired += 1
    assert fired == 2 and "t.raise" not in fp.list_armed()
    assert fp.hits("t.raise") == 2

    fp.arm("t.onein", "one_in(3)")
    fired = sum(1 for _ in range(9)
                if _raises(lambda: fp.fire("t.onein")))
    assert fired == 3  # deterministic modulo, not probabilistic

    fp.arm("t.err", "return_err*1")
    assert fp.fire("t.err") is True
    assert fp.fire("t.err") is False  # budget exhausted -> disarmed

    fp.arm("t.enospc", "enospc*1")
    with pytest.raises(OSError) as ei:
        fp.fire("t.enospc")
    assert ei.value.errno == errno.ENOSPC

    fp.arm("t.sleep", "sleep(30)*1")
    t0 = time.monotonic()
    assert fp.fire("t.sleep") is False
    assert time.monotonic() - t0 >= 0.025

    with fp.armed("t.ctx", "raise"):
        assert "t.ctx" in fp.list_armed()
    assert "t.ctx" not in fp.list_armed()

    assert fp.arm_spec("a.b=raise; c.d=sleep(5)*2") == 2
    assert fp.list_armed()["c.d"] == "sleep(5)*2"
    for bad in ("nope", "x=unknown", "x=sleep", "x=raise*0", "x=one_in(0)"):
        with pytest.raises(ValueError):
            fp.arm_spec(bad)


def _raises(fn) -> bool:
    try:
        fn()
        return False
    except fp.FailpointError:
        return True


def test_registered_site_inventory_is_complete():
    """Every edge the issue names must be an enumerable site — a new edge
    that forgets to register never makes it into the matrix sweep."""
    import fisco_bcos_tpu.crypto.lane  # noqa: F401
    import fisco_bcos_tpu.init.xshard  # noqa: F401
    import fisco_bcos_tpu.net.p2p  # noqa: F401
    import fisco_bcos_tpu.scheduler.scheduler  # noqa: F401
    import fisco_bcos_tpu.snapshot.export  # noqa: F401
    import fisco_bcos_tpu.storage.engine  # noqa: F401

    expected = {
        "storage.wal.append_before_fsync", "storage.wal.rotate",
        "storage.memtable.flush",
        "storage.engine.flush_before_sstable",
        "storage.engine.flush_before_manifest",
        "storage.engine.manifest_before_current",
        "storage.engine.compact_before_sstable",
        "storage.engine.compact_before_manifest",
        "scheduler.commit.handoff", "scheduler.commit.entry",
        "scheduler.2pc.prepare", "scheduler.2pc.commit",
        "scheduler.2pc.rollback",
        "snapshot.export", "snapshot.install",
        "xshard.sweep", "xshard.credit.before_submit",
        "xshard.finish.before_submit",
        "p2p.send", "p2p.recv",
        "crypto.lane.dispatch", "crypto.lane.dispatcher",
    }
    missing = expected - set(fp.list_sites())
    assert not missing, f"unregistered failpoint sites: {sorted(missing)}"


# -- p2p reconnect jitter (satellite) ---------------------------------------

def test_reconnect_backoff_has_jitter_and_cap():
    from fisco_bcos_tpu.net.p2p import reconnect_delay

    base, cap = 1.0, 30.0
    rng_a, rng_b = random.Random(1), random.Random(2)
    sched_a = [reconnect_delay(base, f, cap, rng_a) for f in range(20)]
    sched_b = [reconnect_delay(base, f, cap, rng_b) for f in range(20)]
    for f, d in enumerate(sched_a):
        step = min(base * 2.0 ** min(f, 16), cap)
        assert 0.5 * step <= d <= step  # jitter window, cap respected
    # two peers never compute the same schedule -> no reconnect lockstep
    assert sched_a != sched_b
    # overflow guard: absurd failure counts still return the capped delay
    assert reconnect_delay(base, 100_000, cap, random.Random(3)) <= cap


# -- health state machine ----------------------------------------------------

def test_health_aggregation_probe_and_gauge():
    from fisco_bcos_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = Health(registry=reg, probe_interval=0.05)
    transitions = []
    h.on_change.append(lambda old, new: transitions.append((old, new)))
    assert h.state() == "ok" and not h.writes_shed()

    h.degraded("a", "first")
    h.failed("b", "worse")
    assert h.state() == "failed" and h.writes_shed()
    assert not h.sealing_allowed()
    h.clear("b")
    assert h.state() == "degraded"
    h.clear("a")
    assert h.state() == "ok"
    assert transitions == [("ok", "degraded"), ("degraded", "failed"),
                           ("failed", "degraded"), ("degraded", "ok")]
    # self-healing probe: clears the fault once the probe succeeds
    healed = {"ok": False}
    h.degraded("probed", "x", probe=lambda: healed["ok"])
    assert h.state() == "degraded"
    healed["ok"] = True
    assert wait_until(lambda: h.state() == "ok", timeout=5)
    # gauge follows transitions (0 ok / 1 degraded / 2 failed)
    assert reg.snapshot()["gauges"]["bcos_node_health"] == 0
    h.failed("z")
    assert reg.snapshot()["gauges"]["bcos_node_health"] == 2
    h.stop()


def _mktx(node, kp, nonce, name, amount=5):
    return Transaction(
        to=pc.BALANCE_ADDRESS,
        input=pc.encode_call("register",
                             lambda w: w.blob(name).u64(amount)),
        nonce=nonce, group_id=node.config.group_id,
        block_limit=node.ledger.current_number() + 100
    ).sign(node.suite, kp)


@pytest.fixture()
def solo_node():
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0))
    node.start()
    yield node
    node.stop()


def test_commit_thread_exception_trips_health_and_self_heals(solo_node):
    """Satellite regression: an uncaught exception on the commit path used
    to leave the pipeline silently wedged with the sealer still granting.
    It must now flip health to degraded, shed writes, and the retry probe
    must land the stalled height and return the node to ok — no restart."""
    node = solo_node
    kp = node.suite.generate_keypair(b"fault-user-1")
    res = node.send_transaction(_mktx(node, kp, "h1", b"a"))
    assert node.txpool.wait_for_receipt(res.tx_hash, 30).status == 0

    fp.arm("scheduler.commit.entry", "raise*1")
    res2 = node.send_transaction(_mktx(node, kp, "h2", b"b"))
    saw_degraded = {"v": False}

    def committed_and_ok():
        if node.health.state() != "ok":
            saw_degraded["v"] = True
        return (node.txpool.wait_for_receipt(res2.tx_hash, 0.05) is not None
                and node.health.state() == "ok")

    assert wait_until(committed_and_ok, timeout=60), node.health.snapshot()
    assert saw_degraded["v"], "health plane never tripped"
    # chain still fully alive afterwards
    res3 = node.send_transaction(_mktx(node, kp, "h3", b"c"))
    assert node.txpool.wait_for_receipt(res3.tx_hash, 30).status == 0
    assert audit_report(node)["ok"]


def test_enospc_degrades_sheds_writes_and_recovers(tmp_path):
    """Satellite regression: WAL append hitting ENOSPC used to crash
    mid-commit with no operator signal. It must fail the 2PC cleanly,
    flip health to degraded (visible as a storage.space fault), shed
    writes with the TYPED status, and return to ok once space returns —
    simulated deterministically with the `enospc` failpoint action on the
    exact fsync path a full tmpfs would break."""
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           storage_path=str(tmp_path / "d"),
                           storage_backend="wal"))
    node.start()
    try:
        kp = node.suite.generate_keypair(b"fault-user-2")
        res = node.send_transaction(_mktx(node, kp, "e1", b"a"))
        assert node.txpool.wait_for_receipt(res.tx_hash, 30).status == 0

        # shed behavior is deterministic to observe with a held fault:
        node.health.degraded("storage.space", "held for assertion")
        shed = node.send_transaction(_mktx(node, kp, "e-shed", b"x"))
        assert shed.status == TransactionStatus.NODE_DEGRADED
        # reads keep serving while degraded
        assert node.ledger.current_number() >= 1
        assert node.ledger.header_by_number(1) is not None
        node.health.clear("storage.space")

        # now the real thing: the disk "fills" for the next few fsyncs
        fp.arm("storage.wal.append_before_fsync", "enospc*3")
        res2 = node.send_transaction(_mktx(node, kp, "e2", b"b"))
        saw_space_fault = {"v": False}

        def healed():
            if "storage.space" in node.health.snapshot()["faults"]:
                saw_space_fault["v"] = True
            return (node.txpool.wait_for_receipt(res2.tx_hash, 0.05)
                    is not None and node.health.state() == "ok")

        assert wait_until(healed, timeout=60), node.health.snapshot()
        assert saw_space_fault["v"], "ENOSPC never reached the health plane"
        res3 = node.send_transaction(_mktx(node, kp, "e3", b"c"))
        assert node.txpool.wait_for_receipt(res3.tx_hash, 30).status == 0
        rep = audit_report(node)
        assert rep["ok"], rep
    finally:
        node.stop()


def test_crypto_lane_dispatcher_death_self_heals():
    from fisco_bcos_tpu.crypto.lane import CryptoLane, LaneSuite

    base = make_suite(False, backend="host")
    lane = CryptoLane(base)
    events = []
    lane.on_fault.append(lambda e, m: events.append(e))
    suite = LaneSuite(lane, tag="t", timeout=20.0)
    kp = base.generate_keypair(b"lane-user")
    digest = bytes(range(32))
    sig = base.sign(kp, digest)

    fp.arm("crypto.lane.dispatcher", "raise*1")
    with pytest.raises(Exception):
        suite.verify_batch([digest] * 4, [sig] * 4, [kp.pub_bytes] * 4)
    assert wait_until(lambda: "died" in events, timeout=10)
    # next submission revives the dispatcher and serves correctly
    ok = suite.verify_batch([digest] * 4, [sig] * 4, [kp.pub_bytes] * 4)
    assert all(bool(v) for v in ok)
    assert events == ["died", "recovered"]
    lane.stop()


# -- in-process 4-node PBFT failpoint matrix --------------------------------

def _build_cluster(n=4, view_timeout=2.0):
    suite = make_suite(backend="host")
    gateway = FakeGateway()
    keypairs = [suite.generate_keypair(bytes([i + 1]) * 16)
                for i in range(n)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    nodes = []
    for kp in keypairs:
        node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0,
                               view_timeout=view_timeout),
                    keypair=kp, gateway=gateway)
        node.build_genesis(sealers)
        nodes.append(node)
    for node in nodes:
        node.start()
    return suite, gateway, nodes


def _balances(node):
    return sorted((k, node.storage.get("c_balance", k))
                  for k in node.storage.keys("c_balance"))


def _assert_converged(nodes, min_height, timeout=90.0):
    """Identical head hash at the max common height >= min_height AND
    byte-identical c_balance rows AND a clean audit on every node."""
    def same_head():
        hs = [n.ledger.current_number() for n in nodes]
        h = min(hs)
        if h < min_height:
            return False
        hashes = {n.ledger.header_by_number(h).hash(n.suite)
                  if n.ledger.header_by_number(h) else None for n in nodes}
        return None not in hashes and len(hashes) == 1

    assert wait_until(same_head, timeout=timeout), \
        [n.ledger.current_number() for n in nodes]
    assert wait_until(
        lambda: len({tuple(_balances(n)) for n in nodes}) == 1,
        timeout=30), "c_balance rows diverged"
    for n in nodes:
        rep = audit_node(n)
        assert rep["ok"], rep


# one matrix entry per registered site reachable in an in-process PBFT
# cluster (memory storage: the storage.* sites get their own sweep below)
_MATRIX = [
    ("scheduler.commit.entry", "raise*1"),
    ("scheduler.2pc.prepare", "raise*1"),
    ("scheduler.2pc.commit", "raise*1"),
    ("scheduler.commit.handoff", "sleep(40)*3"),
    ("p2p.send", "one_in(5)*5"),
    ("p2p.recv", "one_in(5)*5"),
]


def test_pbft_failpoint_matrix_converges_with_clean_audit():
    """The matrix sweep: fire every reachable registered site in ONE live
    4-node chain and require convergence to identical head hash, byte-
    identical balances and a clean audit after every fault."""
    suite, gateway, nodes = _build_cluster()
    try:
        kp = suite.generate_keypair(b"matrix-user")
        height = 0
        for i, (site, action) in enumerate(_MATRIX):
            fp.arm(site, action)
            tx = Transaction(
                to=pc.BALANCE_ADDRESS,
                input=pc.encode_call(
                    "register",
                    lambda w, i=i: w.blob(b"m%d" % i).u64(10 + i)),
                nonce=f"mx-{i}", block_limit=500).sign(suite, kp)
            res = nodes[i % len(nodes)].send_transaction(tx)
            assert int(res.status) in (
                int(TransactionStatus.OK),
                int(TransactionStatus.ALREADY_IN_TXPOOL)), (site, res)
            height += 1
            _assert_converged(nodes, height)
            fp.disarm(site)
            assert fp.hits(site) > 0, f"{site} never fired"
            # every node must be back to ok before the next fault
            assert wait_until(
                lambda: all(n.health.state() == "ok" for n in nodes),
                timeout=30), [n.health.snapshot() for n in nodes]
    finally:
        for n in nodes:
            n.stop()
        gateway.stop()


def test_asymmetric_partition_heals_and_converges():
    """A->B dropped while B->A flows (the FakeGateway filter is the
    in-process seam; LinkProxy.blackhole is the socket-level analogue):
    the quorum keeps committing, and after the heal the starved node
    catches up to the identical head with a clean audit."""
    suite, gateway, nodes = _build_cluster()
    try:
        id0 = nodes[0].keypair.pub_bytes
        id3 = nodes[3].keypair.pub_bytes
        gateway.set_filter(lambda s, d, _data: not (s == id0 and d == id3))
        kp = suite.generate_keypair(b"part-user")
        for i in range(3):
            tx = Transaction(
                to=pc.BALANCE_ADDRESS,
                input=pc.encode_call(
                    "register",
                    lambda w, i=i: w.blob(b"p%d" % i).u64(1 + i)),
                nonce=f"pt-{i}", block_limit=500).sign(suite, kp)
            nodes[i % 3].send_transaction(tx)
        # survivors commit during the partition
        assert wait_until(
            lambda: min(n.ledger.current_number() for n in nodes[:3]) >= 3,
            timeout=90)
        gateway.set_filter(None)  # heal
        _assert_converged(nodes, 3)
    finally:
        for n in nodes:
            n.stop()
        gateway.stop()


# -- disk engine failpoint sweep (storage.* sites, reopen = crash) ----------

@pytest.mark.parametrize("site", [
    "storage.wal.append_before_fsync",
    "storage.memtable.flush",
    "storage.engine.flush_before_sstable",
    "storage.engine.flush_before_manifest",
    "storage.engine.manifest_before_current",
])
def test_disk_engine_global_failpoints_recover(tmp_path, site):
    """The global plane drives the same crash-edge coverage the legacy
    per-instance set did: raise at the site, abandon the instance (the
    in-process crash), reopen, and require identical state + clean audit."""
    from fisco_bcos_tpu.storage.engine import DiskStorage

    st = DiskStorage(str(tmp_path / "db"), auto_compact=False)
    for i in range(20):
        st.set("t", b"k%02d" % i, b"v%d" % i)
    fp.arm(site, "raise*1")
    try:
        st.set("t", b"late", b"x")
        st.flush()
    except (fp.FailpointError, Exception):
        pass
    fp.disarm(site)
    st2 = DiskStorage(str(tmp_path / "db"), auto_compact=False)
    assert st2.get("t", b"k00") == b"v0"
    assert st2.get("t", b"k19") == b"v19"
    assert st2.audit() == []
    st2.close()


# -- xshard saga failpoint sweep (the tier-1 guard; SIGKILL test is slow) ---

@pytest.fixture()
def two_groups():
    mgr = GroupManager(storage=MemoryStorage())
    a = mgr.add_group(NodeConfig(group_id="group0", crypto_backend="host",
                                 min_seal_time=0.0))
    b = mgr.add_group(NodeConfig(group_id="group1", crypto_backend="host",
                                 min_seal_time=0.0))
    mgr.start()
    kp = a.suite.generate_keypair(b"xs-fault-user")
    for node, name, amt, nonce in ((a, b"alice", 100, "rg-a"),
                                   (b, b"bob", 5, "rg-b")):
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register",
                             lambda w, n=name, m=amt: w.blob(n).u64(m)),
                         nonce=nonce, group_id=node.config.group_id,
                         block_limit=100).sign(node.suite, kp)
        res = node.send_transaction(tx)
        assert node.txpool.wait_for_receipt(res.tx_hash, 30).status == 0
    yield mgr, a, b, kp
    mgr.stop()


def _bal(node, account):
    raw = node.storage.get("c_balance", account)
    return None if raw is None else int.from_bytes(raw, "big")


def _transfer(a, kp, xid, amount, nonce):
    tx = Transaction(to=pc.XSHARD_ADDRESS,
                     input=pc.encode_call(
                         "transferOut",
                         lambda w: w.blob(xid).text("group1").blob(b"alice")
                         .blob(b"bob").u64(amount)),
                     nonce=nonce, group_id="group0",
                     block_limit=a.ledger.current_number() + 100
                     ).sign(a.suite, kp)
    res = a.send_transaction(tx)
    rc = a.txpool.wait_for_receipt(res.tx_hash, 30)
    assert rc is not None and rc.status == 0


@pytest.mark.parametrize("site", ["xshard.credit.before_submit",
                                  "xshard.finish.before_submit"])
def test_xshard_saga_leg_crash_settles_exactly_once(two_groups, site):
    """Crash between the escrow commit and the credit (or between the
    credit and the settle): the sweep retries off the durable pending
    marker and the transfer lands EXACTLY once — the in-process tier-1
    replacement for the real-SIGKILL two-phase test (now `slow`)."""
    mgr, a, b, kp = two_groups
    bob0 = _bal(b, b"bob")
    fp.arm(site, "raise*1")
    _transfer(a, kp, b"fx-" + site.encode()[:8], 30, f"fx-{site}")
    assert wait_until(
        lambda: not list(a.storage.keys(pc.T_XSHARD_PEND)), timeout=60)
    assert fp.hits(site) >= 1, "leg failpoint never fired"
    assert _bal(b, b"bob") == bob0 + 30  # exactly once, never double
    assert _bal(a, b"alice") == 70
    xg = audit_cross_group(mgr)
    assert xg["ok"], xg


def test_xshard_duplicate_sweep_wakeup_never_double_drives(two_groups):
    """Concurrent sweeps (worker + two direct wakeups, the duplicate-
    wakeup race) must not double-submit legs: the in-flight claim set
    serializes them and the credit stays idempotent regardless."""
    mgr, a, b, kp = two_groups
    bob0 = _bal(b, b"bob")
    fp.arm("xshard.sweep", "sleep(25)")  # widen the race window
    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                mgr.coordinator.sweep()
            except Exception:
                pass
    threads = [threading.Thread(target=hammer, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    try:
        _transfer(a, kp, b"dup-1", 12, "dup-1")
        assert wait_until(
            lambda: not list(a.storage.keys(pc.T_XSHARD_PEND)), timeout=60)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    fp.disarm("xshard.sweep")
    assert _bal(b, b"bob") == bob0 + 12
    assert _bal(a, b"alice") == 88
    xg = audit_cross_group(mgr)
    assert xg["ok"], xg


# -- auditor detects real violations ----------------------------------------

def test_audit_detects_forged_inbox_credit(two_groups):
    mgr, a, b, kp = two_groups
    clean = audit_cross_group(mgr)
    assert clean["ok"], clean
    # forge a credit on group1 that group0 never escrowed: minted value
    from fisco_bcos_tpu.codec.wire import Writer
    record = Writer().text("group0").blob(b"bob").u64(999).bytes()
    b.storage.set(pc.T_XSHARD_IN, b"forged", record)
    bad = audit_cross_group(mgr)
    assert not bad["ok"]
    assert any("minted" in p for p in bad["problems"])


def test_nonce_filter_survives_restart(tmp_path):
    """Found by the auditor during the crash e2e: after a WAL-replay
    restart the rolling nonce filter came up empty, so a different-hash
    tx reusing a just-committed nonce was re-admittable inside the
    replay-protection window. Boot must reseed the filter."""
    path = str(tmp_path / "d")
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           storage_path=path, storage_backend="wal"))
    node.start()
    kp = node.suite.generate_keypair(b"nonce-user")
    res = node.send_transaction(_mktx(node, kp, "replay-me", b"a"))
    assert node.txpool.wait_for_receipt(res.tx_hash, 30).status == 0
    node.stop()

    node2 = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                            storage_path=path, storage_backend="wal"))
    node2.start()
    try:
        assert "replay-me" in node2.txpool.known_nonces()
        # a DIFFERENT tx (different payload -> different hash) reusing
        # the committed nonce must be refused
        replay = _mktx(node2, kp, "replay-me", b"other", amount=99)
        res2 = node2.send_transaction(replay)
        assert res2.status == TransactionStatus.NONCE_CHECK_FAIL, res2
        rep = audit_report(node2)
        assert rep["ok"], rep
    finally:
        node2.stop()


def test_wal_partial_write_failure_rewinds_torn_record(tmp_path,
                                                       monkeypatch):
    """A real ENOSPC can fail AFTER part of the record reached the file.
    A surviving node (health plane keeps it up) must rewind the torn
    bytes — otherwise later appends land behind them and the next
    restart's replay silently drops every acked commit after the tear."""
    import os as _os

    from fisco_bcos_tpu.storage.interface import Entry
    from fisco_bcos_tpu.storage.wal import WalStorage

    st = WalStorage(str(tmp_path / "w"))
    st.set("t", b"k0", b"v0")
    logp = str(tmp_path / "w" / "wal.log")
    good = _os.path.getsize(logp)

    real_fsync = _os.fsync

    def fail_once(fd):
        monkeypatch.setattr(_os, "fsync", real_fsync)
        raise OSError(errno.ENOSPC, "disk full after partial write")

    monkeypatch.setattr(_os, "fsync", fail_once)
    with pytest.raises(OSError):
        st.set("t", b"k1", b"v1")  # bytes written+flushed, fsync fails
    assert _os.path.getsize(logp) == good  # torn record rewound
    st.prepare(1, {("t", b"k2"): Entry(b"v2")})
    st.commit(1)  # append after the rewind lands at a record boundary
    assert st.audit() == []
    st.close()

    st2 = WalStorage(str(tmp_path / "w"))
    assert st2.get("t", b"k0") == b"v0"
    assert st2.get("t", b"k1") is None  # the failed write never happened
    assert st2.get("t", b"k2") == b"v2"  # the post-rewind commit survived
    st2.close()


def test_wal_audit_detects_corruption(tmp_path):
    from fisco_bcos_tpu.storage.wal import WalStorage

    st = WalStorage(str(tmp_path / "w"))
    st.set("t", b"k", b"v")
    assert st.audit() == []
    with open(str(tmp_path / "w" / "wal.log"), "ab") as f:
        f.write(b"\xde\xad\xbe\xef-torn-garbage")
    problems = st.audit()
    assert problems and "unparseable" in problems[0]
    st.close()


# -- ops surface round-trip (healthz / failpoints / audit RPC / gauge) ------

def _http_get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read()


def _rpc(port, method, params):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                         "params": params}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


def test_ops_surface_healthz_failpoints_audit_gauge(monkeypatch):
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           rpc_port=0))
    node.start()
    try:
        port = node.rpc.port
        code, body = _http_get(port, "/healthz")
        assert code == 200 and json.loads(body)["state"] == "ok"

        # arming over ops is OFF unless the test-build env gate is set
        monkeypatch.delenv("BCOS_FAILPOINTS_OPS", raising=False)
        code, _ = _http_get(port, "/failpoints?arm=t.ops=raise")
        assert code == 403
        code, body = _http_get(port, "/failpoints")  # listing always on
        assert code == 200 and "scheduler.2pc.commit" in \
            json.loads(body)["sites"]

        monkeypatch.setenv("BCOS_FAILPOINTS_OPS", "1")
        code, body = _http_get(port, "/failpoints?arm=t.ops=sleep(1)")
        assert code == 200 and json.loads(body)["armed"] == {
            "t.ops": "sleep(1)"}
        code, body = _http_get(port, "/failpoints?disarm=all")
        assert code == 200 and json.loads(body)["armed"] == {}

        # degraded flips /healthz to 503 and the gauge to 1; writes shed
        # over RPC with the typed code while reads keep serving
        node.health.degraded("test.ops", "held")
        code, body = _http_get(port, "/healthz")
        assert code == 503 and "test.ops" in json.loads(body)["faults"]
        _, metrics = _http_get(port, "/metrics")
        gauge_lines = [line for line in metrics.decode().splitlines()
                       if line.startswith("bcos_node_health")]
        assert gauge_lines and any(
            float(line.split()[-1]) == 1.0 for line in gauge_lines)
        resp = _rpc(port, "sendTransaction", ["group0", "", "00", False])
        assert resp["error"]["code"] == int(TransactionStatus.NODE_DEGRADED)
        assert _rpc(port, "getBlockNumber", ["group0", ""])["result"] == 0
        node.health.clear("test.ops")
        code, _ = _http_get(port, "/healthz")
        assert code == 200

        rep = _rpc(port, "getAuditReport", ["group0", ""])["result"]
        assert rep["ok"] and {c["name"] for c in rep["checks"]} == {
            "chain", "storage", "nonce_filter"}
    finally:
        node.stop()


# -- slow e2e: real processes, crash actions, Byzantine peer ----------------

@pytest.mark.slow
def test_chaos_crash_failpoint_matrix_e2e(tmp_path):
    """Real OS processes: arm a `crash` (os._exit inside the storage WAL
    append) on one node over the ops endpoint, keep traffic flowing, let
    the node die mid-commit, restart it, and require convergence to the
    survivors' head hash, a clean getAuditReport everywhere, and the
    /healthz + bcos_node_health round-trip."""
    from fisco_bcos_tpu.executor import precompiled as pcm
    from fisco_bcos_tpu.sdk.client import TransactionBuilder
    from fisco_bcos_tpu.testing.chaos import ChaosHarness

    with ChaosHarness(str(tmp_path / "chain"), tls=False) as h:
        h.start_all()
        for i in range(h.n):
            h.wait_rpc_up(i)
        suite = h.suite()
        kp = suite.generate_keypair(b"faults-e2e")
        builder = TransactionBuilder(suite, None,
                                     chain_id=h.info["chain_id"],
                                     group_id=h.info["group_id"])
        sent = 0

        def burst(n, via):
            nonlocal sent
            for k in range(n):
                tx = builder.build(
                    kp, pcm.BALANCE_ADDRESS,
                    pcm.encode_call("register",
                                    lambda w: w.blob(b"fa%d" % sent)
                                    .u64(1)),
                    nonce=f"fa-{sent}", block_limit=500)
                h.client(via[k % len(via)]).send_transaction(tx, wait=False)
                sent += 1

        burst(6, via=[0, 1, 2])
        h.wait_until(lambda: min(h.total_txs(i) for i in range(h.n)) >= 3,
                     timeout=180, what="pre-fault commits everywhere")
        code, doc = h.healthz(0)
        assert code == 200 and doc["state"] == "ok"
        assert "bcos_node_health 0" in h.metrics_text(0).replace(".0", "")

        # node3 dies INSIDE its next WAL append — kill -9 from within
        h.arm_failpoint(3, "storage.wal.append_before_fsync", "crash*1")
        burst(8, via=[0, 1, 2])
        h.wait_until(lambda: h.procs[3].poll() is not None, timeout=180,
                     what="node3 crashed at the armed failpoint")
        assert h.procs[3].wait() == 137  # the crash action's exit code
        h.procs[3] = None
        burst(4, via=[0, 1, 2])
        h.start(3)
        h.wait_rpc_up(3)
        height = h.wait_converged(range(h.n), min_height=1, timeout=240)
        assert {h.block_hash(i, height) for i in range(h.n)} and height >= 1
        for i in range(h.n):
            rep = h.audit_report(i)
            assert rep["ok"], (i, rep)
            assert h.healthz(i)[0] == 200


@pytest.mark.slow
def test_chaos_byzantine_peer_and_asymmetric_partition_e2e(tmp_path):
    """Byzantine frames at the gateway seam of a real chain (garbage,
    corrupt compression, spoofed identities, junk consensus/sync module
    payloads) plus a scheduled asymmetric partition: the chain keeps
    committing, converges, and every node's audit stays clean."""
    from fisco_bcos_tpu.executor import precompiled as pcm
    from fisco_bcos_tpu.net.moduleid import ModuleID
    from fisco_bcos_tpu.sdk.client import TransactionBuilder
    from fisco_bcos_tpu.testing.chaos import ChaosHarness

    with ChaosHarness(str(tmp_path / "chain"), tls=False) as h:
        proxy = h.inject_link(0, 3)
        h.start_all()
        for i in range(h.n):
            h.wait_rpc_up(i)
        suite = h.suite()
        kp = suite.generate_keypair(b"byz-e2e")
        builder = TransactionBuilder(suite, None,
                                     chain_id=h.info["chain_id"],
                                     group_id=h.info["group_id"])
        byz = h.byzantine_peer(1)
        victim = h.node_id(1)
        byz.send_garbage()
        byz.send_corrupt_frames(victim)
        byz.send_spoofed(h.node_id(2), victim, b"\x00\x01junk")
        for module in (ModuleID.PBFT, ModuleID.BlockSync,
                       ModuleID.TxsSync):
            byz.send_module_junk(victim, int(module))
        # asymmetric partition on the 0<->3 link, healed after 6 s
        h.partition_link(proxy, src=0)
        proxy.heal_after(6.0)
        for k in range(8):
            tx = builder.build(
                kp, pcm.BALANCE_ADDRESS,
                pcm.encode_call("register",
                                lambda w: w.blob(b"bz%d" % k).u64(1)),
                nonce=f"bz-{k}", block_limit=500)
            h.client(k % 3).send_transaction(tx, wait=False)
        byz.close()
        h.wait_until(lambda: min(h.total_txs(i) for i in [0, 1, 2]) >= 4,
                     timeout=240, what="commits despite byzantine traffic")
        height = h.wait_converged(range(h.n), min_height=1, timeout=240)
        assert height >= 1
        for i in range(h.n):
            rep = h.audit_report(i)
            assert rep["ok"], (i, rep)
