"""Columnar transaction substrate (protocol/columnar.py).

The layout contract that makes the hot path safe: wire frames round-trip
through columns BYTE-IDENTICALLY (encode/encode_unsigned are arena
slices), identity (hash/sender) matches the object path exactly, and
failure is isolated PER ROW — a malformed frame, a bad signature or a
padded non-canonical variant rejects its own slot without poisoning
batchmates. Plus the admission integration: `TxPool.submit_columns`
admits a mixed batch with per-row statuses and ONE batched hash + ONE
batched recover, and a solo node commits txs submitted as raw wire bytes
through the ingest lane's wire door.
"""

import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus
from fisco_bcos_tpu.protocol.columnar import (TxView, columns_from_transactions,
                                              decode_columns)
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.txpool import TxPool

from tests.test_ingest import CountingSuite, _make_pool, _tx


@pytest.fixture(scope="module")
def suite():
    return make_suite(False, backend="host")


@pytest.fixture(scope="module")
def kp(suite):
    return suite.generate_keypair(b"columnar-user")


def _wire(suite, kp, i, group="group0", attribute=0):
    tx = Transaction(group_id=group, to=pc.BALANCE_ADDRESS,
                     input=b"payload-%d" % i, nonce=f"col-{i}",
                     block_limit=100, attribute=attribute,
                     import_time=1700000000000 + i).sign(suite, kp)
    return tx, tx.encode()


# -- round-trip identity ----------------------------------------------------

def test_roundtrip_byte_identical(suite, kp):
    txs, wires = zip(*(_wire(suite, kp, i, attribute=(i % 3) << 24)
                       for i in range(16)))
    cols = decode_columns(list(wires))
    assert len(cols) == 16 and cols.decode_ok.all() and not cols.fallback
    for i, (tx, w) in enumerate(zip(txs, wires)):
        v = cols.view(i)
        assert isinstance(v, TxView)
        assert v.encode() == w                      # arena slice == wire
        assert v.encode_unsigned() == tx.encode_unsigned()
        assert v.signature == tx.signature
        # payload fields decode straight from the arena
        assert (v.chain_id, v.group_id, v.nonce) == \
            (tx.chain_id, tx.group_id, tx.nonce)
        assert (v.to, v.input, v.abi) == (tx.to, tx.input, tx.abi)
        assert (v.version, v.block_limit) == (tx.version, tx.block_limit)
        assert (v.import_time, v.attribute) == \
            (tx.import_time, tx.attribute)
        assert cols.band(i) == (tx.attribute >> 24) & 0xFF


def test_identity_matches_object_path(suite, kp):
    txs, wires = zip(*(_wire(suite, kp, i) for i in range(8)))
    cols = decode_columns(list(wires))
    cols.ensure_hashes(suite)
    ok = cols.ensure_senders(suite)
    assert ok.all()
    for i, tx in enumerate(txs):
        assert cols.hashes[i] == tx.hash(suite)
        assert cols.senders[i] == tx.sender(suite)
        # the view shares the column cache both ways
        v = cols.view(i)
        assert v.hash(suite) == tx.hash(suite)
        assert v.sender(suite) == tx.sender(suite)
        t = v.to_transaction()
        assert t._hash == tx.hash(suite) and t.encode() == tx.encode()


def test_view_publishes_identity_back_to_column(suite, kp):
    _tx0, w = _wire(suite, kp, 0)
    cols = decode_columns([w])
    v = cols.view(0)  # created BEFORE any batch fill
    h = v.hash(suite)
    assert cols.hashes[0] == h  # solo compute published to the column
    assert v.sender(suite) is not None
    assert cols.senders[0] == v._sender
    # and the reverse: a later batch fill is visible through the view
    cols2 = decode_columns([w])
    v2 = cols2.view(0)
    cols2.ensure_senders(suite)
    assert v2.sender(suite) == cols2.senders[0]


def test_chain_group_interned_per_batch(suite, kp):
    _, wires = zip(*(_wire(suite, kp, i) for i in range(4)))
    cols = decode_columns(list(wires))
    assert cols.chain_id[0] is cols.chain_id[3]  # one str per batch
    assert cols.group_id[0] is cols.group_id[2]


def test_mixed_group_batch(suite, kp):
    pairs = [_wire(suite, kp, i, group=f"group{i % 2}") for i in range(6)]
    cols = decode_columns([w for _t, w in pairs])
    for i, (tx, _w) in enumerate(pairs):
        assert cols.view(i).group_id == tx.group_id == f"group{i % 2}"


# -- per-slice failure isolation --------------------------------------------

def test_malformed_rows_isolated(suite, kp):
    txs, wires = zip(*(_wire(suite, kp, i) for i in range(4)))
    batch = [wires[0], b"\xff\xff", wires[1], b"", wires[2],
             wires[3][:9], wires[3]]
    cols = decode_columns(batch)
    assert list(cols.decode_ok) == [True, False, True, False, True,
                                    False, True]
    cols.ensure_hashes(suite)
    good = [0, 2, 4, 6]
    for j, i in enumerate(good):
        assert cols.hashes[i] == txs[j].hash(suite)
        assert cols.wire(i) == wires[j]
    with pytest.raises(ValueError):
        cols.view(1)


def test_non_canonical_frame_falls_back_with_object_identity(suite, kp):
    tx, w = _wire(suite, kp, 0)
    padded = w + b"\x00\x00"  # trailing garbage: parses, NOT canonical
    cols = decode_columns([w, padded])
    assert cols.decode_ok.all()
    assert 1 in cols.fallback and 0 not in cols.fallback
    cols.ensure_hashes(suite)
    # identity is canonical (re-serialise-from-fields), NOT over the
    # padded bytes — exactly what Transaction.decode does
    assert cols.hashes[1] == Transaction.decode(padded).hash(suite) \
        == cols.hashes[0]
    # the fallback row's view is the materialised Transaction and its
    # re-encode is the CANONICAL form, not the padded input
    v = cols.view(1)
    assert isinstance(v, Transaction)
    assert v.encode() == w != padded
    assert cols.wire(1) == w


def test_bad_signature_isolated_in_recover(suite, kp):
    good = [_tx(suite, kp, i) for i in range(3)]
    bad = _tx(suite, kp, 99, valid=False)
    order = [good[0], bad, good[1], good[2]]
    cols = decode_columns([t.encode() for t in order])
    ok = cols.ensure_senders(suite)
    assert list(ok) == [True, False, True, True]
    assert cols.senders[1] is None
    assert all(cols.senders[i] is not None for i in (0, 2, 3))


def test_columns_from_transactions_carries_caches(suite, kp):
    txs = [_tx(suite, kp, i) for i in range(3)]
    for t in txs:
        t.hash(suite), t.sender(suite)
    cols = columns_from_transactions(txs)
    for i, t in enumerate(txs):
        assert cols.hashes[i] == t._hash and cols.senders[i] == t._sender
        assert cols.wire(i) == t.encode()


# -- admission integration ---------------------------------------------------

def test_submit_columns_statuses_and_batched_crypto():
    counting = CountingSuite(make_suite(False, backend="host"))
    pool = _make_pool(counting)
    kp = counting.generate_keypair(b"columnar-admit")
    good = [_tx(counting, kp, i) for i in range(5)]
    bad = _tx(counting, kp, 98, valid=False)
    wires = [t.encode() for t in good[:2]] + [bad.encode(), b"junk"] + \
        [t.encode() for t in good[2:]]
    counting.recover_calls = counting.hash_batch_calls = 0
    res = pool.submit_columns(decode_columns(wires))
    assert [r.status for r in res] == [
        TransactionStatus.OK, TransactionStatus.OK,
        TransactionStatus.INVALID_SIGNATURE,
        TransactionStatus.REQUEST_NOT_BELIEVABLE,
        TransactionStatus.OK, TransactionStatus.OK, TransactionStatus.OK]
    assert res[3].tx_hash == b""  # no trustworthy identity to report
    assert counting.hash_batch_calls == 1 and counting.recover_calls == 1
    assert pool.pending_count() == 5
    # duplicate wire batch dedupes without a second recover
    counting.recover_calls = 0
    res2 = pool.submit_columns(decode_columns([t.encode() for t in good]))
    assert all(r.status == TransactionStatus.ALREADY_IN_TXPOOL
               for r in res2)
    assert counting.recover_calls == 0
    # sealed set returns views whose re-encode is byte-identical
    txs, hashes = pool.seal(10)
    assert sorted(t.encode() for t in txs) == \
        sorted(t.encode() for t in good)


def test_wire_ingest_solo_commit():
    """E2E: raw wire bytes -> ingest lane wire door -> columnar admission
    -> seal -> execute -> commit on a solo node."""
    from fisco_bcos_tpu.init.node import Node, NodeConfig

    node = Node(NodeConfig(consensus="solo", p2p_port=0, rpc_port=0,
                           min_seal_time=0.01))
    node.start()
    try:
        suite = node.suite
        kp = suite.generate_keypair(b"wire-e2e")
        wires = [Transaction(to=pc.BALANCE_ADDRESS,
                             input=b"register w%d 50" % i,
                             nonce=f"wire-{i}", block_limit=600)
                 .sign(suite, kp).encode() for i in range(4)]
        results = [node.ingest.submit_wire(w, timeout=30.0) for w in wires]
        assert all(r.status == TransactionStatus.OK for r in results)
        deadline = time.time() + 20
        while time.time() < deadline:
            # txs may split across blocks — wait for every receipt
            if all(node.ledger.receipt(r.tx_hash) is not None
                   for r in results):
                break
            time.sleep(0.05)
        assert node.ledger.current_number() >= 1
        for r in results:
            assert node.ledger.receipt(r.tx_hash) is not None
    finally:
        node.stop()
