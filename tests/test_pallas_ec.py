"""Fused-ladder building blocks: bit-parity with the XLA point ops.

The value-level Jacobian ops used inside the fused ladder kernel must
match ops.ec's complete-by-selection ops exactly — same field, same
selection semantics. Full-ladder parity is covered by a slower
offline harness (interpret mode) and by the device sweep's verify
assertions on real TPU; here CI pins the per-op contracts cheaply.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental import pallas as pl

from fisco_bcos_tpu.crypto import refimpl
from fisco_bcos_tpu.ops import ec, fp, pallas_ec, pallas_fp

B = 128
CV = ec.SECP256K1
F = CV.fp


@pytest.fixture(scope="module")
def points():
    rng = np.random.default_rng(21)
    pts = [refimpl.ec_mul(refimpl.SECP256K1,
                          int.from_bytes(rng.bytes(32), "big")
                          % refimpl.SECP256K1.n,
                          (refimpl.SECP256K1.gx, refimpl.SECP256K1.gy))
           for _ in range(8)]
    xs = np.stack([fp.to_limbs(pts[i % 8][0]) for i in range(B)], axis=1)
    ys = np.stack([fp.to_limbs(pts[i % 8][1]) for i in range(B)], axis=1)
    xr, yr = np.asarray(F.to_rep(xs)), np.asarray(F.to_rep(ys))
    one = np.asarray(F.one_rep(xr.shape))
    return np.stack([xr, yr, one])


def _run(body, *arrays):
    consts = pallas_fp.field_consts(F)

    def kernel(c_ref, *refs):
        fc = pallas_ec.FieldCtx(F, c_ref[:, 0:1])
        out_ref = refs[-1]
        ins = [r[:, :, :] for r in refs[:-1]]
        out_ref[:, :, :] = body(fc, *ins)

    return np.asarray(pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((3, 16, B), jnp.uint32),
        interpret=True)(consts, *arrays))


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_vjac_double_matches(points):
    got = _run(lambda fc, p: pallas_ec.vjac_double(fc, p, True, False),
               points)
    want = np.asarray(ec.jac_double(CV, jnp.asarray(points)))
    assert (got == want).all()


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_vjac_add_doubling_case(points):
    got = _run(lambda fc, p, q: pallas_ec.vjac_add(fc, p, q, True, False),
               points, points.copy())
    want = np.asarray(ec.jac_add(CV, jnp.asarray(points),
                                 jnp.asarray(points)))
    assert (got == want).all()


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_vjac_add_generic_and_infinity(points):
    q2 = np.asarray(ec.jac_double(CV, jnp.asarray(points)))
    got = _run(lambda fc, p, q: pallas_ec.vjac_add(fc, p, q, True, False),
               points, q2)
    want = np.asarray(ec.jac_add(CV, jnp.asarray(points), jnp.asarray(q2)))
    assert (got == want).all()

    inf = np.zeros_like(points)
    got = _run(lambda fc, p, q: pallas_ec.vjac_add(fc, p, q, True, False),
               points, inf)
    assert (got == points).all()  # P + inf = P


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_sm2_point_ops_match():
    """The a = -3 branch of vjac_double/vjac_add (SM2, Montgomery base
    field) against the XLA ops — the secp tests only cover a = 0."""
    cv = ec.SM2P256V1
    f = cv.fp
    rng = np.random.default_rng(29)
    pts = [refimpl.ec_mul(refimpl.SM2P256V1,
                          int.from_bytes(rng.bytes(32), "big")
                          % refimpl.SM2P256V1.n,
                          (refimpl.SM2P256V1.gx, refimpl.SM2P256V1.gy))
           for _ in range(4)]
    xs = np.stack([fp.to_limbs(pts[i % 4][0]) for i in range(B)], axis=1)
    ys = np.stack([fp.to_limbs(pts[i % 4][1]) for i in range(B)], axis=1)
    xr, yr = np.asarray(f.to_rep(xs)), np.asarray(f.to_rep(ys))
    P = np.stack([xr, yr, np.asarray(f.one_rep(xr.shape))])
    consts = pallas_fp.field_consts(f)
    one_m = np.zeros((16, 1), np.uint32)
    one_m[:, 0] = f.one_m

    def kernel(c_ref, one_ref, p_ref, q_ref, o_ref):
        fc = pallas_ec.FieldCtx(f, c_ref[:, 0:1], c_ref[:, 1:2],
                                one_ref[:, 0:1])
        o_ref[:, :, :] = pallas_ec.vjac_add(
            fc, p_ref[:, :, :], q_ref[:, :, :], False, True)

    q2 = np.asarray(ec.jac_double(cv, jnp.asarray(P)))
    got = np.asarray(pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((3, 16, B), jnp.uint32),
        interpret=True)(consts, one_m, P, q2))
    want = np.asarray(ec.jac_add(cv, jnp.asarray(P), jnp.asarray(q2)))
    assert (got == want).all()


def test_take_tables_match(points):
    rng = np.random.default_rng(3)
    dig = rng.integers(0, 16, (B,), dtype=np.uint32)
    gx, gy = pallas_ec._take_const_table(jnp.asarray(CV.g_table),
                                         jnp.asarray(dig))
    wx, wy = ec._take_const(CV.g_table, jnp.asarray(dig))
    assert (np.asarray(gx) == np.asarray(wx)).all()
    assert (np.asarray(gy) == np.asarray(wy)).all()
