"""Fused varlen batch hashing: parity with the host oracle.

CI keeps interpret-mode work tiny (single-block Keccak batch); multi-block
masking and SM3 are covered by the offline harness and by the device
sweep / suite assertions on real TPU.
"""

import numpy as np
import pytest

from fisco_bcos_tpu.crypto import refimpl
from fisco_bcos_tpu.ops import keccak, pallas_hash, sm3


def _pack(msgs, pad_fn, rate):
    padded = [pad_fn(m) for m in msgs]
    maxb = max(p.shape[0] for p in padded)
    B = ((len(msgs) + 127) // 128) * 128
    blocks = np.zeros((B, maxb, rate), np.uint8)
    nvalid = np.zeros((B,), np.int32)
    for i, p in enumerate(padded):
        blocks[i, : p.shape[0]] = p
        nvalid[i] = p.shape[0]
    return blocks, nvalid


def test_keccak_varlen_fused_single_block():
    rng = np.random.default_rng(31)
    msgs = [rng.bytes(int(n)) for n in rng.integers(0, 100, 30)] + [b""]
    blocks, nvalid = _pack(msgs, keccak.pad_message_np, keccak.RATE_BYTES)
    got = np.asarray(pallas_hash.keccak256_varlen_fused(
        blocks, nvalid, interpret=True))
    for i, m in enumerate(msgs):
        assert bytes(got[i]) == refimpl.keccak256(m), (i, len(m))


@pytest.mark.skipif("FBTPU_SLOW_TESTS" not in __import__("os").environ,
                    reason="multi-block + SM3 interpret runs are covered "
                           "by the offline harness / device sweep")
def test_sm3_varlen_fused():
    rng = np.random.default_rng(33)
    msgs = [rng.bytes(int(n)) for n in rng.integers(0, 80, 16)]
    blocks, nvalid = _pack(msgs, sm3.pad_message_np, sm3.BLOCK_BYTES)
    got = np.asarray(pallas_hash.sm3_varlen_fused(
        blocks, nvalid, interpret=True))
    for i, m in enumerate(msgs):
        assert bytes(got[i]) == refimpl.sm3(m), (i, len(m))
