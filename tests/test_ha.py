"""Cross-machine leader election: quorum leases over service RPC.

Reference counterpart: bcos-leader-election/src/LeaderElection.h:30-92
(etcd campaign/KeepAlive/onSeized). VERDICT r3 done-criterion: majority
grant across 3 registry processes, fencing tokens monotone across
failover, process-kill takeover — no shared filesystem anywhere.
"""

import time

from fisco_bcos_tpu.ha.quorum import LeaseRegistryServer, QuorumLeaseElection

TTL = 1.0
HB = 0.2


def wait_until(pred, timeout=15.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def spawn_registries(tmp_path, n=3):
    regs = []
    for i in range(n):
        r = LeaseRegistryServer(state_path=str(tmp_path / f"reg{i}.json"))
        r.start()
        regs.append(r)
    return regs, [("127.0.0.1", r.port) for r in regs]


def make_candidate(addrs, member):
    return QuorumLeaseElection(addrs, member, lease_ttl=TTL, heartbeat=HB,
                               rpc_timeout=0.5)


def test_single_candidate_elected(tmp_path):
    regs, addrs = spawn_registries(tmp_path)
    a = make_candidate(addrs, "node-a")
    a.start()
    try:
        assert wait_until(a.is_leader)
        assert a.fence_token() >= 1
        assert a.leader() == "node-a"
    finally:
        a.stop()
        for r in regs:
            r.stop()


def test_crash_failover_with_fence_increase(tmp_path):
    regs, addrs = spawn_registries(tmp_path)
    a = make_candidate(addrs, "node-a")
    b = make_candidate(addrs, "node-b")
    a.start()
    try:
        assert wait_until(a.is_leader)
        fence_a = a.fence_token()
        b.start()
        time.sleep(3 * HB)
        assert not b.is_leader()  # can't steal a live lease
        a.stop(release=False)  # CRASH: no release, leases must expire
        assert wait_until(b.is_leader, timeout=TTL * 10)
        assert b.fence_token() > fence_a  # fencing monotone across crash
        assert b.leader() == "node-b"
    finally:
        b.stop()
        for r in regs:
            r.stop()


def test_clean_stop_fast_takeover(tmp_path):
    regs, addrs = spawn_registries(tmp_path)
    a = make_candidate(addrs, "node-a")
    b = make_candidate(addrs, "node-b")
    a.start()
    try:
        assert wait_until(a.is_leader)
        b.start()
        t0 = time.time()
        a.stop()  # clean release
        assert wait_until(b.is_leader, timeout=TTL * 10)
        # released leases mean takeover well before a full TTL wait-out
        assert time.time() - t0 < TTL * 6
    finally:
        b.stop()
        for r in regs:
            r.stop()


def test_minority_registry_down_leader_survives(tmp_path):
    regs, addrs = spawn_registries(tmp_path)
    a = make_candidate(addrs, "node-a")
    a.start()
    try:
        assert wait_until(a.is_leader)
        regs[2].stop()  # minority outage
        time.sleep(TTL * 2)
        assert a.is_leader()  # 2/3 renewals keep the lease
    finally:
        a.stop()
        for r in regs[:2]:
            r.stop()


def test_majority_down_demotes_leader(tmp_path):
    regs, addrs = spawn_registries(tmp_path)
    a = make_candidate(addrs, "node-a")
    a.start()
    try:
        assert wait_until(a.is_leader)
        regs[1].stop()
        regs[2].stop()
        assert wait_until(lambda: not a.is_leader(), timeout=TTL * 10)
    finally:
        a.stop()
        regs[0].stop()


def test_no_dual_leadership_under_contention(tmp_path):
    regs, addrs = spawn_registries(tmp_path)
    cands = [make_candidate(addrs, f"node-{i}") for i in range(3)]
    for c in cands:
        c.start()
    try:
        assert wait_until(lambda: any(c.is_leader() for c in cands),
                          timeout=TTL * 20)
        # sample for a while: never more than one concurrent leader
        deadline = time.time() + TTL * 3
        while time.time() < deadline:
            assert sum(1 for c in cands if c.is_leader()) <= 1
            time.sleep(0.02)
    finally:
        for c in cands:
            c.stop()
        for r in regs:
            r.stop()


def test_registry_restart_preserves_fence_monotonicity(tmp_path):
    regs, addrs = spawn_registries(tmp_path)
    a = make_candidate(addrs, "node-a")
    a.start()
    assert wait_until(a.is_leader)
    fence_a = a.fence_token()
    a.stop(release=False)
    for r in regs:
        r.stop()
    # full registry-cluster restart from persisted state, same ports
    regs2 = []
    for i, (_, port) in enumerate(addrs):
        r = LeaseRegistryServer(state_path=str(tmp_path / f"reg{i}.json"),
                                port=port)
        r.start()
        regs2.append(r)
    b = make_candidate(addrs, "node-b")
    b.start()
    try:
        assert wait_until(b.is_leader, timeout=TTL * 10)
        assert b.fence_token() > fence_a
    finally:
        b.stop()
        for r in regs2:
            r.stop()
