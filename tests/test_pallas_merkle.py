"""Fused whole-tree Merkle kernel: parity with the host oracle.

Interpreter-mode execution of the pallas kernel is slow, so CI keeps the
buckets small (single level + the n<=1 edge); the 2-level case and the
device-path dispatch are covered by the device sweep on real TPU
(benchmark/device_sweep.py asserts device == host root every run).
"""

import numpy as np
import pytest

from fisco_bcos_tpu.ops import merkle, pallas_merkle


def _host_root(data, alg):
    return merkle.merkle_levels_host([bytes(x) for x in data], alg)[-1][0]


@pytest.mark.parametrize("n", [1, 5, 16])
def test_keccak_single_level(n):
    rng = np.random.default_rng(5 + n)
    leaves = np.zeros((16, 32), np.uint8)
    data = rng.integers(0, 256, (n, 32), dtype=np.uint8)
    leaves[:n] = data
    got = bytes(np.asarray(pallas_merkle.merkle_root_fused(
        leaves, n, "keccak256", interpret=True)))
    assert got == _host_root(data, "keccak256")


@pytest.mark.skipif("FBTPU_SLOW_TESTS" not in __import__("os").environ,
                    reason="SM3 interpret-mode eval takes ~1h on one core; "
                           "device sweep asserts SM3 tree parity on TPU")
def test_sm3_single_level():
    rng = np.random.default_rng(7)
    leaves = np.zeros((16, 32), np.uint8)
    data = rng.integers(0, 256, (13, 32), dtype=np.uint8)
    leaves[:13] = data
    got = bytes(np.asarray(pallas_merkle.merkle_root_fused(
        leaves, 13, "sm3", interpret=True)))
    assert got == _host_root(data, "sm3")


def test_levels_for():
    assert pallas_merkle._levels_for(16) == [1]
    assert pallas_merkle._levels_for(256) == [16, 1]
    assert pallas_merkle._levels_for(10240) == [640, 40, 3, 1]
    assert pallas_merkle._levels_for(65536) == [4096, 256, 16, 1]
