"""ZK proof plane: verifiable getProof serving + batched verification.

Covers the commit-time render path (zero tree walks on a hit), the
tamper-detect negative cases (proof / value / root), state-changeset
proofs anchored at header.state_root, the verifyProofs batched RPC, and
the crypto lane's poseidon op (two concurrent callers merge into ONE
base-suite call)."""

import threading
import time

import numpy as np

from fisco_bcos_tpu.crypto.lane import CryptoLane, LaneSuite
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.executor.executor import state_leaf_payload
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.zk import poseidon as zp
from fisco_bcos_tpu.zk import proof as zkproof


def _unhex(s):
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


def _commit_tx(node, kp, nonce, who=b"zkp", amount=9):
    tx = Transaction(to=pc.BALANCE_ADDRESS,
                     input=pc.encode_call(
                         "register", lambda w: w.blob(who).u64(amount)),
                     nonce=nonce,
                     block_limit=node.ledger.current_number() + 100
                     ).sign(node.suite, kp)
    res = node.send_transaction(tx)
    rc = node.txpool.wait_for_receipt(res.tx_hash, 20)
    assert rc is not None and rc.status == 0
    return res.tx_hash


def _commit_cohort(node, kp, tag, n=4):
    """Commit n txs submitted as one batch (one or few blocks) and return
    a tx hash whose block carries >= 2 txs — so its inclusion proof has
    at least one real level (a single-leaf tree's proof is empty)."""
    txs = [Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "register",
                           lambda w, i=i: w.blob(b"%s%d" % (tag, i)).u64(i + 1)),
                       nonce=f"{tag.decode()}-{i}",
                       block_limit=node.ledger.current_number() + 100
                       ).sign(node.suite, kp) for i in range(n)]
    for res in node.txpool.submit_batch(txs):
        assert int(res.status) == 0, res
    hashes = [tx.hash(node.suite) for tx in txs]
    for h in hashes:
        assert node.txpool.wait_for_receipt(h, 20) is not None
    for h in hashes:
        num = node.ledger.receipt(h).block_number
        if len(node.ledger.tx_hashes_by_number(num)) >= 2:
            return h
    raise AssertionError("every block came out single-tx")


def _wait_primed(impl, h, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if impl.cache is not None and impl.cache.get(("proof", h)):
            return True
        time.sleep(0.02)
    return False


def _node():
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0))
    impl = node.make_rpc_impl()
    node.start()
    return node, impl


def test_get_proof_roundtrip_and_tamper():
    node, impl = _node()
    try:
        kp = node.suite.generate_keypair(b"zk-proof-1")
        h = _commit_cohort(node, kp, b"zp1")
        doc = impl.get_proof("group0", tx_hash="0x" + h.hex())
        assert doc["found"]
        suite = node.suite
        tx_items = [(h, zkproof.w16_proof_from_json(doc["txProof"]),
                     _unhex(doc["txsRoot"]))]
        assert zkproof.verify_inclusion_batch(suite, tx_items).all()
        rc = node.ledger.receipt(h)
        rc_items = [(rc.hash(suite),
                     zkproof.w16_proof_from_json(doc["receiptProof"]),
                     _unhex(doc["receiptsRoot"]))]
        assert zkproof.verify_inclusion_batch(suite, rc_items).all()
        # the roots anchor to the committed header
        header = node.ledger.header_by_number(doc["blockNumber"])
        assert header.txs_root == _unhex(doc["txsRoot"])
        assert header.receipts_root == _unhex(doc["receiptsRoot"])
        # tampered value (leaf), root, and proof all reject
        leaf, proof, root = tx_items[0]
        bad_leaf = bytes([leaf[0] ^ 1]) + leaf[1:]
        assert not zkproof.verify_inclusion_batch(
            suite, [(bad_leaf, proof, root)]).any()
        assert not zkproof.verify_inclusion_batch(
            suite, [(leaf, proof, b"\x05" * 32)]).any()
        sibs, pos = proof[0]
        forged = [([b"\x06" * 32] * len(sibs), pos)] + proof[1:]
        assert not zkproof.verify_inclusion_batch(
            suite, [(leaf, forged, root)]).any()
        # unknown hash: typed not-found (unpruned chain -> floor 0)
        missing = impl.get_proof("group0", tx_hash="0x" + b"\x07".hex() * 32)
        assert missing == {"found": False, "prunedBelow": 0}
    finally:
        node.stop()


def test_get_proof_served_from_commit_prime():
    """After the commit-time prime lands, getProof hits cost ZERO tree
    walks — the ledger proof builders are never touched."""
    node, impl = _node()
    try:
        kp = node.suite.generate_keypair(b"zk-proof-2")
        h = _commit_cohort(node, kp, b"zp2")
        assert _wait_primed(impl, h), "commit prime never rendered"

        def boom(*_a, **_k):
            raise AssertionError("tree walk on a primed hit")

        node.ledger.tx_proof = boom
        node.ledger.receipt_proof = boom
        doc = impl.get_proof("group0", tx_hash="0x" + h.hex())
        assert doc["found"] and doc["txProof"]
        assert node.zk.stats()["proofHits"] >= 1
    finally:
        node.stop()


def test_state_proof_roundtrip_and_tamper():
    """getProof state entries prove 'block N wrote key := value' against
    header.state_root: leaf digest recomputed from the claimed value via
    the canonical payload, inclusion checked batched, tamper rejected."""
    node, impl = _node()
    try:
        kp = node.suite.generate_keypair(b"zk-proof-3")
        h = _commit_tx(node, kp, "zp3", who=b"zks", amount=44)
        n = node.ledger.receipt(h).block_number
        table, key = "c_balance", None
        for t, k, _d in node.ledger.state_leaf_index(n):
            if t == table:
                key = k
                break
        assert key is not None, "balance write missing from state index"
        doc = impl.get_proof("group0", number=n,
                             state_keys=[[table, "0x" + key.hex()]])
        entry = doc["stateEntries"][0]
        assert entry["present"]
        value = node.storage.get(table, key)
        suite = node.suite
        leaf = suite.hash(state_leaf_payload(table, key, value))
        assert leaf == _unhex(entry["leafDigest"])
        root = _unhex(entry["stateRoot"])
        assert node.ledger.header_by_number(n).state_root == root
        proof = zkproof.w16_proof_from_json(entry["stateProof"])
        assert zkproof.verify_inclusion_batch(
            suite, [(leaf, proof, root)]).all()
        # a lying value produces a different leaf -> rejected
        bad = suite.hash(state_leaf_payload(table, key, value + b"\x01"))
        assert not zkproof.verify_inclusion_batch(
            suite, [(bad, proof, root)]).any()
        # a key the block never wrote: typed absence
        doc2 = impl.get_proof("group0", number=n,
                              state_keys=[[table, "0x" + b"\xaa".hex() * 4]])
        assert doc2["stateEntries"][0]["present"] is False
    finally:
        node.stop()


def test_verify_proofs_rpc_batched():
    node, impl = _node()
    try:
        kp = node.suite.generate_keypair(b"zk-proof-4")
        hashes = [_commit_tx(node, kp, f"zp4-{i}", who=b"z4%d" % i)
                  for i in range(3)]
        docs = [impl.get_proof("group0", tx_hash="0x" + h.hex())
                for h in hashes]
        proofs = [{"leaf": "0x" + h.hex(), "proof": d["txProof"],
                   "root": d["txsRoot"]} for h, d in zip(hashes, docs)]
        proofs.append({"leaf": "0x" + b"\x09".hex() * 32,
                       "proof": docs[0]["txProof"],
                       "root": docs[0]["txsRoot"]})
        out = impl.verify_proofs("group0", proofs=proofs)
        assert out["results"] == [True, True, True, False]
        assert out["verified"] == 3
        assert node.zk.stats()["proofsVerified"] >= 4
        assert node.system_status()["zk"]["verifyCalls"] >= 1
    finally:
        node.stop()


def test_lane_merges_poseidon_batches():
    """Two groups' concurrent poseidon_batch calls land in ONE base-suite
    call (the gated-dispatch idiom from test_crypto_lane)."""
    base = make_suite(backend="host")
    calls = []
    gate = threading.Event()
    entered = threading.Event()
    orig = base.poseidon_batch

    def counting(lefts, rights):
        calls.append(len(lefts))
        if not entered.is_set():
            entered.set()
            assert gate.wait(30)
        return orig(lefts, rights)

    base.poseidon_batch = counting
    lane = CryptoLane(base)
    g0, g1 = LaneSuite(lane, "g0"), LaneSuite(lane, "g1")
    rng = np.random.default_rng(3)
    a = [rng.bytes(32) for _ in range(8)]
    b = [rng.bytes(32) for _ in range(8)]
    try:
        # park the dispatcher on a first call so the two real submissions
        # below provably queue together
        warm = lane.submit("poseidon", ([a[0], a[1]], [b[0], b[1]]), 2, "w")
        assert entered.wait(30)
        results = {}
        threads = [
            threading.Thread(target=lambda: results.__setitem__(
                "g0", g0.poseidon_batch(a[:5], b[:5]))),
            threading.Thread(target=lambda: results.__setitem__(
                "g1", g1.poseidon_batch(a[5:], b[5:]))),
        ]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while sum(len(q) for q in lane._q.values()) < 2:
            assert time.monotonic() < deadline, "submissions never queued"
            time.sleep(0.01)
        gate.set()
        for t in threads:
            t.join(30)
        warm.result(30)
        # call 1 = the gated warm-up; call 2 = BOTH groups merged
        assert calls == [2, 8], calls
        want = zp.hash2_batch_host(a, b)
        assert results["g0"] == want[:5]
        assert results["g1"] == want[5:]
        stats = lane.stats()
        assert stats["per_op"]["poseidon"]["calls"] == 2
        assert stats["merged_calls"] >= 1
    finally:
        base.poseidon_batch = orig
        lane.stop()
