"""Key locks + DMC sharded execution tests.

Mirrors the reference's testKeyLocks.cpp / testDmcExecutor.cpp semantics:
lock grant/queue, deadlock cycle detection with requester revert, and
shard-parallel block execution whose results equal the serial schedule.
"""

import threading

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor.executor import TransactionExecutor
from fisco_bcos_tpu.executor.precompiled import BALANCE_ADDRESS, KV_TABLE_ADDRESS
from fisco_bcos_tpu.codec.wire import Writer
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.scheduler.dmc import DmcExecutor
from fisco_bcos_tpu.scheduler.keylocks import DeadlockError, GraphKeyLocks
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage

SUITE = make_suite(backend="host")


# ---------------------------------------------------------------------------
# GraphKeyLocks
# ---------------------------------------------------------------------------

def test_keylock_grant_and_reentrancy():
    kl = GraphKeyLocks()
    kl.acquire("t1", b"A", b"k")
    kl.acquire("t1", b"A", b"k")  # re-entrant
    assert kl.holder_of(b"A", b"k") == "t1"
    assert not kl.try_acquire("t2", b"A", b"k")
    kl.release_all("t1")
    assert kl.try_acquire("t2", b"A", b"k")


def test_keylock_deadlock_detection():
    kl = GraphKeyLocks()
    kl.acquire("t1", b"A", b"k")
    kl.acquire("t2", b"B", b"k")
    # t1 waits for B (held by t2) in a thread; then t2 requesting A closes
    # the cycle and must be chosen as victim.
    started = threading.Event()
    got = []

    def t1_wait():
        started.set()
        kl.acquire("t1", b"B", b"k", timeout=5)
        got.append("t1-acquired")
        kl.release_all("t1")

    th = threading.Thread(target=t1_wait)
    th.start()
    started.wait()
    import time
    time.sleep(0.05)  # let t1 enter the wait
    with pytest.raises(DeadlockError):
        kl.acquire("t2", b"A", b"k", timeout=5)
    kl.release_all("t2")  # victim reverts, releasing B
    th.join(timeout=5)
    assert got == ["t1-acquired"]


def test_keylock_timeout():
    kl = GraphKeyLocks()
    kl.acquire("t1", b"A", b"k")
    with pytest.raises(TimeoutError):
        kl.acquire("t2", b"A", b"k", timeout=0.05)


# ---------------------------------------------------------------------------
# DMC block execution
# ---------------------------------------------------------------------------

def _transfer_tx(frm: bytes, to_acct: bytes, amount: int) -> Transaction:
    w = Writer()
    w.text("transfer").blob(frm).blob(to_acct).u64(amount)
    tx = Transaction(to=BALANCE_ADDRESS, input=w.bytes())
    tx._sender = b"\xaa" * 20
    return tx


def _register_tx(acct: bytes, amount: int) -> Transaction:
    w = Writer()
    w.text("register").blob(acct).u64(amount)
    tx = Transaction(to=BALANCE_ADDRESS, input=w.bytes())
    tx._sender = b"\xaa" * 20
    return tx


def _kv_create_tx(table: str) -> Transaction:
    w = Writer()
    w.text("createTable").text(table)
    tx = Transaction(to=KV_TABLE_ADDRESS, input=w.bytes())
    tx._sender = b"\xaa" * 20
    return tx


def _kv_set_tx(table: str, k: bytes, v: bytes) -> Transaction:
    w = Writer()
    w.text("set").text(table).blob(k).blob(v)
    tx = Transaction(to=KV_TABLE_ADDRESS, input=w.bytes())
    tx._sender = b"\xaa" * 20
    return tx


def test_dmc_matches_serial():
    accounts = [b"acct%d" % i for i in range(4)]
    txs = [_register_tx(a, 1000) for a in accounts]
    txs.append(_kv_create_tx("kv"))
    for i in range(12):
        txs.append(_transfer_tx(accounts[i % 4], accounts[(i + 1) % 4],
                                10 + i))
    for i in range(6):
        txs.append(_kv_set_tx("kv", b"key%d" % i, b"val%d" % i))

    # serial reference
    st_serial = StateStorage(MemoryStorage())
    ex = TransactionExecutor(SUITE)
    serial = [ex.execute_transaction(t, st_serial, 1, 1000) for t in txs]

    st_dmc = StateStorage(MemoryStorage())
    dmc = DmcExecutor(TransactionExecutor(SUITE), SUITE)
    parallel = dmc.execute_block(txs, st_dmc, 1, 1000)

    assert len(parallel) == len(serial)
    for a, b in zip(parallel, serial):
        assert (a.status, a.output) == (b.status, b.output)
    # same final state
    assert st_serial.changeset() == st_dmc.changeset()


def test_dmc_single_shard_order():
    txs = [_kv_create_tx("t")]
    txs += [_kv_set_tx("t", b"k", b"v%d" % i) for i in range(5)]
    st = StateStorage(MemoryStorage())
    dmc = DmcExecutor(TransactionExecutor(SUITE), SUITE)
    rcs = dmc.execute_block(txs, st, 1, 1000)
    assert all(r.status == 0 for r in rcs)
    # last write in block order wins: read back through the precompile
    from fisco_bcos_tpu.codec.wire import Reader
    ex = TransactionExecutor(SUITE)
    w = Writer()
    w.text("get").text("t").blob(b"k")
    q = Transaction(to=KV_TABLE_ADDRESS, input=w.bytes())
    q._sender = b"\xaa" * 20
    rc = ex.execute_transaction(q, st, 1, 1000)
    r = Reader(rc.output)
    assert r.u8() == 1 and r.blob() == b"v4"


def test_dmc_wave_plan_properties():
    """Planner invariants: shard order kept, cross-shard key conflicts split
    across waves, opaque txs are global barriers."""
    accounts = [b"a", b"b", b"c"]
    txs = [_register_tx(a, 100) for a in accounts]       # disjoint keys
    txs.append(_transfer_tx(b"a", b"b", 1))              # conflicts with 0,1
    evm_tx = Transaction(to=b"\x77" * 20, input=b"")      # opaque -> barrier
    evm_tx._sender = b"\xaa" * 20
    txs.append(evm_tx)
    txs.append(_transfer_tx(b"b", b"c", 1))
    dmc = DmcExecutor(TransactionExecutor(SUITE), SUITE)
    waves = dmc.plan(txs)
    pos = {i: w for w, wv in enumerate(waves) for i in wv}
    # registers share a wave (same shard, serial) or honour order
    assert pos[0] <= pos[1] <= pos[2]
    assert pos[3] >= pos[2]          # transfer after the registers it reads
    assert waves[pos[4]] == [4]      # barrier is alone
    assert pos[5] > pos[4]           # post-barrier work comes later


def test_dmc_deterministic_across_runs():
    accounts = [b"x%d" % i for i in range(6)]
    txs = [_register_tx(a, 500) for a in accounts]
    for i in range(20):
        txs.append(_transfer_tx(accounts[i % 6], accounts[(i + 2) % 6], i))
    outs = []
    for _ in range(3):
        st = StateStorage(MemoryStorage())
        dmc = DmcExecutor(TransactionExecutor(SUITE), SUITE, max_workers=4)
        rcs = dmc.execute_block(txs, st, 1, 1000)
        outs.append((tuple((r.status, r.output) for r in rcs),
                     tuple(sorted((k, e.value) for (t, k), e in
                                  st.changeset().items()))))
    assert outs[0] == outs[1] == outs[2]
