"""Cancun opcodes: TLOAD/TSTORE (EIP-1153) + MCOPY (EIP-5656), enforced
on BOTH interpreters via the parity harness."""

import pytest

from fisco_bcos_tpu.executor import nevm
from fisco_bcos_tpu.executor.evm import EVM, G_SLOAD, T_CODE
from tests.test_nevm import (
    ADDR,
    ENV,
    SUITE,
    _fresh_state,
    asm,
    push,
    ret_top,
    run_both,
)

pytestmark = pytest.mark.skipif(
    not nevm.available(), reason="libnevm.so not built")


def test_tstore_tload_roundtrip():
    code = asm(push(0x1234, 2), push(7, 1), 0x5D,   # TSTORE slot7
               push(7, 1), 0x5C) + ret_top()         # TLOAD slot7
    n, p = run_both(code)
    assert n.success and int.from_bytes(n.output, "big") == 0x1234


def test_tload_unset_is_zero_and_cheap():
    n1, _ = run_both(asm(push(9, 1), 0x5C) + ret_top(), gas=10_000)
    assert int.from_bytes(n1.output, "big") == 0
    # flat 100 gas, never cold (EIP-1153): a second TLOAD costs exactly
    # push(3) + 100 + pop(2) more — no cold surcharge anywhere
    n2, _ = run_both(asm(push(9, 1), 0x5C, 0x50, push(9, 1), 0x5C)
                     + ret_top(), gas=10_000)
    assert n1.gas_left - n2.gas_left == 3 + G_SLOAD + 2


def test_tstore_static_context_fails():
    code = asm(push(1, 1), push(7, 1), 0x5D)
    n, p = run_both(code, static=True)
    assert not n.success and not p.success


def test_transient_not_persisted_and_not_shared():
    """TSTORE leaves no trace in persistent storage; a fresh tx sees 0."""
    code = asm(push(0xAA, 1), push(1, 1), 0x5D, 0x00)
    probe = asm(push(1, 1), 0x5C) + ret_top()
    for native in (True, False):
        st = _fresh_state(code)
        evm = EVM(SUITE, native=native)
        res = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0, b"",
                                  1_000_000)
        assert res.success
        persisted = [k for k in st.changeset() if k[0] != "s_code"]
        assert not persisted  # nothing persisted beyond the fixture code
        # next tx: transient state must be gone
        st.set(T_CODE, ADDR, probe)
        res2 = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0, b"",
                                   1_000_000)
        assert int.from_bytes(res2.output, "big") == 0


def test_revert_rolls_back_transient():
    """EIP-1153: a reverted frame's transient writes roll back. CALLCODE
    runs inner code against our context; its revert must restore our
    transient slot."""
    inner_addr = b"\x66" * 20
    inner = asm(push(0xBB, 1), push(3, 1), 0x5D,      # TSTORE slot3 = BB
                push(0, 1), push(0, 1), 0xFD)          # REVERT
    outer = asm(push(0x11, 1), push(3, 1), 0x5D,       # TSTORE slot3 = 11
                push(0, 1), push(0, 1), push(0, 1), push(0, 1), push(0, 1),
                push(int.from_bytes(inner_addr, "big")), push(50_000, 4),
                0xF2, 0x50,                            # CALLCODE (reverts)
                push(3, 1), 0x5C) + ret_top()          # TLOAD slot3
    n, p = run_both(outer, extra=[("s_code", inner_addr, inner)])
    assert n.success
    assert int.from_bytes(n.output, "big") == 0x11  # 0xBB rolled back


def test_mcopy_semantics_and_overlap():
    # write pattern at 0..32, MCOPY to 16 (overlapping, memmove), return
    code = asm(push(0x1122334455667788, 8), push(0, 1), 0x52,  # MSTORE@0
               push(32, 1), push(0, 1), push(16, 1), 0x5E,     # MCOPY 16<-0
               push(32, 1), push(16, 1), 0xF3)                 # ret mem[16:48]
    n, p = run_both(code)
    assert n.success
    # mem[16:48] must equal the ORIGINAL mem[0:32] (memmove semantics)
    expect = (b"\x00" * 24 + (0x1122334455667788).to_bytes(8, "big")
              ).ljust(32, b"\x00")[:32]
    assert n.output == expect


def test_mcopy_gas_and_expansion():
    # MCOPY expanding destination memory charges expansion on both sides
    code = asm(push(32, 1), push(0, 1), push(256, 2), 0x5E, 0x00)
    n, p = run_both(code, gas=10_000)
    assert n.success and n.gas_left == p.gas_left


def test_mcopy_huge_size_oog():
    code = asm(push(1 << 40, 6), push(0, 1), push(0, 1), 0x5E)
    n, p = run_both(code, gas=100_000)
    assert not n.success and not p.success
    assert n.gas_left == 0 and p.gas_left == 0


SD_RUNTIME = bytes([0x73]) + b"\x99" * 20 + bytes([0xFF])  # SELFDESTRUCT(0x99..)
# writes storage slot5=1, then SELFDESTRUCT(0x99..)
SD_STORE_RUNTIME = (bytes([0x60, 0x01, 0x60, 0x05, 0x55])
                    + bytes([0x73]) + b"\x99" * 20 + bytes([0xFF]))
SD_INIT = (bytes([0x60, len(SD_RUNTIME), 0x60, 0x0c, 0x60, 0x00, 0x39,
                  0x60, len(SD_RUNTIME), 0x60, 0x00, 0xF3]) + SD_RUNTIME)

# parent: CREATE(calldata initcode), CALL the child, return its address
PARENT = bytes([
    0x36, 0x60, 0x00, 0x60, 0x00, 0x37,      # CALLDATACOPY(0,0,size)
    0x36, 0x60, 0x00, 0x60, 0x00, 0xF0,      # CREATE -> [addr]
    0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x60, 0x00,
    0x85,                                     # DUP6 -> addr
    0x61, 0xFF, 0xFF, 0xF1, 0x50,             # CALL, POP status
    0x60, 0x00, 0x52, 0x60, 0x20, 0x60, 0x00, 0xF3])


def test_eip6780_same_tx_create_selfdestruct_destroys():
    """A contract created and self-destructed in ONE transaction is fully
    destroyed (code + storage gone), on both interpreters."""
    for native in (True, False):
        st = _fresh_state(PARENT)
        evm = EVM(SUITE, native=native)
        res = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0, SD_INIT,
                                  1_000_000)
        assert res.success, res
        child = res.output[12:32]
        assert len(child) == 20 and child != b"\x00" * 20
        assert evm.get_code(st, child) == b""  # destroyed
        evm.take_refund(0)


def _initcode_for(runtime: bytes) -> bytes:
    return (bytes([0x60, len(runtime), 0x60, 0x0c, 0x60, 0x00, 0x39,
                   0x60, len(runtime), 0x60, 0x00, 0xF3]) + runtime)


def test_eip6780_destroys_storage_and_burns_residual():
    """Deferred deletion wipes the destroyed contract's STORAGE too, and
    any residual balance is burned at end of tx (heir == self)."""
    from fisco_bcos_tpu.executor.evm import T_STORE

    self_heir_runtime = (bytes([0x60, 0x01, 0x60, 0x05, 0x55])  # SSTORE
                         + bytes([0x30, 0xFF]))  # SELFDESTRUCT(ADDRESS)
    for native in (True, False):
        st = _fresh_state(PARENT)
        evm = EVM(SUITE, native=native)
        res = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0,
                                  _initcode_for(self_heir_runtime),
                                  1_000_000)
        assert res.success, res
        child = res.output[12:32]
        assert evm.get_code(st, child) == b""
        # storage of the destroyed contract is gone
        assert list(st.keys(T_STORE, child)) == []
        # the self-heired balance was burned, not resurrected
        assert evm.balance_of(st, child) == 0
        evm.take_refund(0)


def test_eip6780_deletion_removes_nonce_and_balance_records():
    """Full account deletion: after a same-tx create+selfdestruct, the
    account's NONCE and BALANCE records are REMOVED (not zero-valued
    entries), so a CREATE2 redeploy at that address restarts at nonce 0
    and no dead-account rows leak into the changeset."""
    from fisco_bcos_tpu.executor.evm import T_BAL, T_NONCE

    # child: CREATE(0,0,0) (bumps own nonce record), SELFDESTRUCT(self)
    child_runtime = bytes([0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0xF0, 0x50,
                           0x30, 0xFF])
    for native in (True, False):
        st = _fresh_state(PARENT)
        evm = EVM(SUITE, native=native)
        res = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0,
                                  _initcode_for(child_runtime), 1_000_000)
        assert res.success, res
        child = res.output[12:32]
        assert evm.get_code(st, child) == b""
        assert list(st.keys(T_NONCE, child)) == []
        assert list(st.keys(T_BAL, child)) == []
        evm.take_refund(0)


def test_eip6780_late_frames_still_see_code():
    """Destruction is deferred to END of tx: a later frame in the same
    tx still observes the child's code (EXTCODESIZE != 0)."""
    # parent: CREATE(child), CALL child (selfdestructs), then
    # EXTCODESIZE(child) -> return it
    parent = bytes([
        0x36, 0x60, 0x00, 0x60, 0x00, 0x37,
        0x36, 0x60, 0x00, 0x60, 0x00, 0xF0,       # CREATE -> [addr]
        0x80,                                      # DUP1 [addr, addr]
        0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x60, 0x00, 0x60, 0x00,
        0x86,                                      # DUP7 -> addr
        0x61, 0xFF, 0xFF, 0xF1, 0x50,              # CALL, POP
        0x3B,                                      # EXTCODESIZE(addr)
        0x60, 0x00, 0x52, 0x60, 0x20, 0x60, 0x00, 0xF3])
    for native in (True, False):
        st = _fresh_state(parent)
        evm = EVM(SUITE, native=native)
        res = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0,
                                  _initcode_for(SD_RUNTIME), 1_000_000)
        assert res.success, res
        # mid-tx view: code still present (size == len(SD_RUNTIME))
        assert int.from_bytes(res.output, "big") == len(SD_RUNTIME)
        evm.take_refund(0)


def test_eip6780_preexisting_contract_survives():
    """A PRE-EXISTING contract that self-destructs keeps its code (only
    the balance moves) — Cancun semantics, both interpreters."""
    target = b"\x44" * 20
    for native in (True, False):
        st = _fresh_state()
        st.set(T_CODE, target, SD_RUNTIME)
        evm = EVM(SUITE, native=native)
        evm.set_balance(st, target, 777)
        res = evm.execute_message(st, ENV, b"\x22" * 20, target, 0, b"",
                                  200_000)
        assert res.success, res
        assert evm.get_code(st, target) == SD_RUNTIME  # code survives
        assert evm.balance_of(st, target) == 0
        assert evm.balance_of(st, b"\x99" * 20) == 777  # heir credited
        evm.take_refund(0)
