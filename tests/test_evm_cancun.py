"""Cancun opcodes: TLOAD/TSTORE (EIP-1153) + MCOPY (EIP-5656), enforced
on BOTH interpreters via the parity harness."""

import pytest

from fisco_bcos_tpu.executor import nevm
from fisco_bcos_tpu.executor.evm import EVM, G_SLOAD, T_CODE
from tests.test_nevm import (
    ADDR,
    ENV,
    SUITE,
    _fresh_state,
    asm,
    push,
    ret_top,
    run_both,
)

pytestmark = pytest.mark.skipif(
    not nevm.available(), reason="libnevm.so not built")


def test_tstore_tload_roundtrip():
    code = asm(push(0x1234, 2), push(7, 1), 0x5D,   # TSTORE slot7
               push(7, 1), 0x5C) + ret_top()         # TLOAD slot7
    n, p = run_both(code)
    assert n.success and int.from_bytes(n.output, "big") == 0x1234


def test_tload_unset_is_zero_and_cheap():
    n1, _ = run_both(asm(push(9, 1), 0x5C) + ret_top(), gas=10_000)
    assert int.from_bytes(n1.output, "big") == 0
    # flat 100 gas, never cold (EIP-1153): a second TLOAD costs exactly
    # push(3) + 100 + pop(2) more — no cold surcharge anywhere
    n2, _ = run_both(asm(push(9, 1), 0x5C, 0x50, push(9, 1), 0x5C)
                     + ret_top(), gas=10_000)
    assert n1.gas_left - n2.gas_left == 3 + G_SLOAD + 2


def test_tstore_static_context_fails():
    code = asm(push(1, 1), push(7, 1), 0x5D)
    n, p = run_both(code, static=True)
    assert not n.success and not p.success


def test_transient_not_persisted_and_not_shared():
    """TSTORE leaves no trace in persistent storage; a fresh tx sees 0."""
    code = asm(push(0xAA, 1), push(1, 1), 0x5D, 0x00)
    probe = asm(push(1, 1), 0x5C) + ret_top()
    for native in (True, False):
        st = _fresh_state(code)
        evm = EVM(SUITE, native=native)
        res = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0, b"",
                                  1_000_000)
        assert res.success
        persisted = [k for k in st.changeset() if k[0] != "s_code"]
        assert not persisted  # nothing persisted beyond the fixture code
        # next tx: transient state must be gone
        st.set(T_CODE, ADDR, probe)
        res2 = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0, b"",
                                   1_000_000)
        assert int.from_bytes(res2.output, "big") == 0


def test_revert_rolls_back_transient():
    """EIP-1153: a reverted frame's transient writes roll back. CALLCODE
    runs inner code against our context; its revert must restore our
    transient slot."""
    inner_addr = b"\x66" * 20
    inner = asm(push(0xBB, 1), push(3, 1), 0x5D,      # TSTORE slot3 = BB
                push(0, 1), push(0, 1), 0xFD)          # REVERT
    outer = asm(push(0x11, 1), push(3, 1), 0x5D,       # TSTORE slot3 = 11
                push(0, 1), push(0, 1), push(0, 1), push(0, 1), push(0, 1),
                push(int.from_bytes(inner_addr, "big")), push(50_000, 4),
                0xF2, 0x50,                            # CALLCODE (reverts)
                push(3, 1), 0x5C) + ret_top()          # TLOAD slot3
    n, p = run_both(outer, extra=[("s_code", inner_addr, inner)])
    assert n.success
    assert int.from_bytes(n.output, "big") == 0x11  # 0xBB rolled back


def test_mcopy_semantics_and_overlap():
    # write pattern at 0..32, MCOPY to 16 (overlapping, memmove), return
    code = asm(push(0x1122334455667788, 8), push(0, 1), 0x52,  # MSTORE@0
               push(32, 1), push(0, 1), push(16, 1), 0x5E,     # MCOPY 16<-0
               push(32, 1), push(16, 1), 0xF3)                 # ret mem[16:48]
    n, p = run_both(code)
    assert n.success
    # mem[16:48] must equal the ORIGINAL mem[0:32] (memmove semantics)
    expect = (b"\x00" * 24 + (0x1122334455667788).to_bytes(8, "big")
              ).ljust(32, b"\x00")[:32]
    assert n.output == expect


def test_mcopy_gas_and_expansion():
    # MCOPY expanding destination memory charges expansion on both sides
    code = asm(push(32, 1), push(0, 1), push(256, 2), 0x5E, 0x00)
    n, p = run_both(code, gas=10_000)
    assert n.success and n.gas_left == p.gas_left


def test_mcopy_huge_size_oog():
    code = asm(push(1 << 40, 6), push(0, 1), push(0, 1), 0x5E)
    n, p = run_both(code, gas=100_000)
    assert not n.success and not p.success
    assert n.gas_left == 0 and p.gas_left == 0
