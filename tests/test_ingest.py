"""Continuous-batching ingest lane (txpool/ingest.py).

Asserts the lane's contract: N concurrent submitters cost FAR fewer
device/native recover calls than N (one `submit_batch` per drained set),
every submitter gets its OWN admission result (including invalid-signature
mixes), a full queue rejects with `TxPoolIsFull` instead of blocking
forever, an idle lane adds no coalescing latency, and the tx-hash cache
survives submit -> seal -> verify_proposal without a rehash.
"""

import threading
import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
from fisco_bcos_tpu.protocol import Block, Transaction, TransactionStatus
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.txpool import IngestLane, TxPool, TxPoolIsFull
from fisco_bcos_tpu.txpool.txpool import TxSubmitResult
from fisco_bcos_tpu.utils.metrics import REGISTRY


class CountingSuite:
    """Delegating suite wrapper that counts batch crypto entry points —
    the instrument behind every "calls << N" assertion here."""

    def __init__(self, suite):
        self._suite = suite
        self.recover_calls = 0
        self.recover_sigs = 0
        self.hash_batch_calls = 0

    def __getattr__(self, name):
        return getattr(self._suite, name)

    def recover_addresses(self, hashes, sigs):
        self.recover_calls += 1
        self.recover_sigs += len(hashes)
        return self._suite.recover_addresses(hashes, sigs)

    def hash_batch(self, msgs):
        self.hash_batch_calls += 1
        return self._suite.hash_batch(msgs)


class _GatedPool:
    """Pool stub whose submit_batch parks on `gate` — backpressure tests
    use it to hold the dispatcher mid-dispatch while the queue fills."""

    def __init__(self):
        self.gate = threading.Event()
        self.entered = threading.Event()

    def submit_batch(self, txs, broadcast=True):
        self.entered.set()
        assert self.gate.wait(30)
        return [TxSubmitResult(b"\x00" * 32, TransactionStatus.OK)
                for _ in txs]


def _make_pool(suite):
    ledger = Ledger(MemoryStorage(), suite)
    ledger.build_genesis([ConsensusNode(b"\x01" * 64)])
    return TxPool(suite, ledger)


def _tx(suite, kp, i, valid=True):
    tx = Transaction(to=pc.BALANCE_ADDRESS, input=b"payload-%d" % i,
                     nonce=f"ing-{i}", block_limit=100).sign(suite, kp)
    if not valid:
        # r = 2^256-1 > curve order: deterministically unrecoverable (a
        # random byte flip can still recover SOME key — ecrecover is
        # total over on-curve r values)
        sig = bytearray(tx.signature)
        sig[:32] = b"\xff" * 32
        tx.signature = bytes(sig)
    return tx


@pytest.fixture()
def counting_lane():
    counting = CountingSuite(make_suite(False, backend="host"))
    pool = _make_pool(counting)
    lane = IngestLane(pool, max_batch=512, max_wait_ms=20.0, queue_cap=1024)
    lane.start()
    yield counting, pool, lane
    lane.stop()


def test_concurrent_submits_coalesce(counting_lane):
    """N threads x M txs -> recover calls << N*M, every result per-tx OK."""
    counting, pool, lane = counting_lane
    kp = counting.generate_keypair(b"ingest-user")
    n_threads, per_thread = 16, 8
    txs = [[_tx(counting, kp, t * per_thread + i)
            for i in range(per_thread)] for t in range(n_threads)]
    counting.recover_calls = 0
    results: dict[int, list] = {}
    barrier = threading.Barrier(n_threads)

    def worker(t):
        barrier.wait()
        results[t] = [lane.submit(tx, timeout=30.0) for tx in txs[t]]

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(60)
    total = n_threads * per_thread
    flat = [r for rs in results.values() for r in rs]
    assert len(flat) == total
    assert all(r.status == TransactionStatus.OK for r in flat)
    assert pool.pending_count() == total
    # the whole point: coalescing must amortize the verify engine. 16
    # concurrent submitters keep the queue non-empty while a dispatch is
    # in flight, so batches grow well past 1 even before the adaptive
    # window engages.
    assert counting.recover_calls <= total // 4, (
        f"{counting.recover_calls} recover calls for {total} txs — "
        f"lane is not coalescing")
    stats = lane.stats()
    assert stats["txs_total"] == total
    assert stats["mean_batch"] > 2.0


def test_per_tx_results_with_invalid_mix(counting_lane):
    """Concurrent valid/invalid submitters each get their own verdict."""
    counting, pool, lane = counting_lane
    kp = counting.generate_keypair(b"ingest-mixed")
    n = 24
    outcomes: dict[int, object] = {}
    barrier = threading.Barrier(n)

    def worker(i):
        tx = _tx(counting, kp, i, valid=(i % 3 != 0))
        barrier.wait()
        outcomes[i] = lane.submit(tx, timeout=30.0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(60)
    assert len(outcomes) == n
    for i, res in outcomes.items():
        want = TransactionStatus.OK if i % 3 != 0 \
            else TransactionStatus.INVALID_SIGNATURE
        assert res.status == want, f"tx {i}: {res.status} != {want}"


def test_full_queue_rejects_not_blocks():
    """Backpressure: at capacity the lane rejects IMMEDIATELY with
    TxPoolIsFull — no unbounded memory, no blocked submitter."""
    pool = _GatedPool()
    gate = pool.gate
    suite = make_suite(False, backend="host")
    kp = suite.generate_keypair(b"ingest-full")
    lane = IngestLane(pool, max_batch=64, max_wait_ms=0.0, queue_cap=4)
    lane.start()
    try:
        # first tx occupies the dispatcher inside the gated submit_batch
        first = lane.submit_async(_tx(suite, kp, 0))
        assert pool.entered.wait(10)
        # fill the queue to its cap behind the blocked dispatch
        queued = [lane.submit_async(_tx(suite, kp, 1 + i)) for i in range(4)]
        t0 = time.monotonic()
        with pytest.raises(TxPoolIsFull):
            lane.submit_async(_tx(suite, kp, 99))
        assert time.monotonic() - t0 < 1.0, "rejection must not block"
        gate.set()
        for task in [first] + queued:
            assert task.result(30).status == TransactionStatus.OK
        assert lane.stats()["rejected_total"] == 1
    finally:
        gate.set()
        lane.stop()


def test_idle_submit_has_no_coalescing_tax(counting_lane):
    """A lone tx on an idle lane dispatches immediately (window ~0)."""
    counting, pool, lane = counting_lane
    kp = counting.generate_keypair(b"ingest-idle")
    t0 = time.monotonic()
    res = lane.submit(_tx(counting, kp, 0), timeout=10.0)
    elapsed = time.monotonic() - t0
    assert res.status == TransactionStatus.OK
    # generous bound for a loaded CI host; the claim is "no deliberate
    # max_wait park", not a latency SLO
    assert elapsed < 2.0


def test_gossip_bulk_enqueue_drops_over_cap():
    """submit_many_nowait accepts what fits and drops the rest (gossip is
    fire-and-forget; anti-entropy re-delivers)."""
    pool = _GatedPool()
    gate = pool.gate
    suite = make_suite(False, backend="host")
    kp = suite.generate_keypair(b"ingest-gossip")
    lane = IngestLane(pool, max_batch=64, max_wait_ms=0.0, queue_cap=8)
    lane.start()
    try:
        lane.submit_async(_tx(suite, kp, 0))
        assert pool.entered.wait(10)
        txs = [_tx(suite, kp, 1 + i) for i in range(12)]
        accepted = lane.submit_many_nowait(txs)
        assert accepted == 8
        assert lane.stats()["dropped_total"] == 4
    finally:
        gate.set()
        lane.stop()


def test_lane_metrics_emitted(counting_lane):
    counting, pool, lane = counting_lane
    kp = counting.generate_keypair(b"ingest-metrics")
    lane.submit(_tx(counting, kp, 0), timeout=10.0)
    snap = REGISTRY.snapshot()
    assert snap["counters"].get("bcos_ingest_txs_total", 0) >= 1
    assert snap["counters"].get("bcos_ingest_batches_total", 0) >= 1
    assert any(k.startswith("bcos_ingest_batch_size")
               for k in snap["histograms"])
    text = REGISTRY.prometheus_text()
    assert "bcos_ingest_queue_depth" in text
    assert 'bcos_ingest_batch_size_bucket{le="64"}' in text


def test_hash_cache_survives_submit_seal_verify():
    """Satellite: batch_hash fills each tx's cache ONCE at submit; seal and
    verify_proposal reuse it — zero additional hash_batch calls."""
    counting = CountingSuite(make_suite(False, backend="host"))
    pool = _make_pool(counting)
    kp = counting.generate_keypair(b"hash-cache")
    txs = [_tx(counting, kp, i) for i in range(32)]
    for tx in txs:
        assert tx._hash is not None  # sign() hashed it already
    counting.hash_batch_calls = 0
    pool.submit_batch(txs)
    assert counting.hash_batch_calls == 0, "submit rehashed cached txs"
    sealed, hashes = pool.seal(32)
    assert len(sealed) == 32
    block = Block(transactions=sealed)
    assert pool.verify_proposal(block)
    assert counting.hash_batch_calls == 0, (
        "seal/verify_proposal rehashed txs whose hash was cached at submit")
    # a decoded copy (gossip/proposal arrival) hashes ONCE, in one batch
    fresh = [Transaction.decode(tx.encode()) for tx in txs]
    from fisco_bcos_tpu.protocol import batch_hash
    assert batch_hash(fresh, counting) == hashes
    assert counting.hash_batch_calls == 1
    assert batch_hash(fresh, counting) == hashes  # now cached
    assert counting.hash_batch_calls == 1


def test_rpc_concurrent_clients_share_batches():
    """End to end over real HTTP: 8 concurrent sendTransaction clients on
    a live solo node coalesce into shared verify batches, and every
    client gets its own committed receipt (event-driven wait).

    De-flaked for the 2-core CI host: the first dispatch is HELD until the
    whole first cohort is enqueued (deterministic coalescing instead of
    hoping 8 client threads race in before the dispatcher drains), client
    failures propagate as the test failure instead of a confusing
    missing-receipts count, and the join asserts the threads actually
    finished."""
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.sdk.client import SdkClient

    counting = CountingSuite(make_suite(False, backend="host"))
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           rpc_port=0), suite=counting)
    node.start()
    try:
        kp = counting.generate_keypair(b"rpc-ingest")
        n_clients, per_client = 8, 4
        wire: dict[int, list[str]] = {}
        for c in range(n_clients):
            wire[c] = []
            for i in range(per_client):
                tx = Transaction(
                    to=pc.BALANCE_ADDRESS,
                    input=pc.encode_call(
                        "register",
                        lambda w, c=c, i=i: w.blob(b"rc%d-%d" % (c, i))
                        .u64(1)),
                    nonce=f"rpc-{c}-{i}", block_limit=100,
                ).sign(counting, kp)
                wire[c].append("0x" + tx.encode().hex())
        counting.recover_calls = 0
        # deterministic readiness: the dispatcher's first submit_batch
        # parks until every client's first tx is in the lane queue (or a
        # generous deadline), so the cohort coalesces regardless of how
        # the scheduler interleaves 8 client threads on 2 cores
        orig_sb = node.txpool.submit_batch
        state = {"first": True}

        def gated_submit(txs, broadcast=True):
            if state["first"]:
                state["first"] = False
                deadline = time.monotonic() + 10
                while (time.monotonic() < deadline
                       and len(txs) + len(node.ingest._q) < n_clients):
                    time.sleep(0.002)
            return orig_sb(txs, broadcast)

        node.txpool.submit_batch = gated_submit
        receipts: dict[int, list] = {}
        errors: list[str] = []
        barrier = threading.Barrier(n_clients)

        def client(c):
            try:
                sdk = SdkClient(f"http://{node.rpc.host}:{node.rpc.port}")
                barrier.wait()
                receipts[c] = [
                    sdk.request("sendTransaction",
                                ["group0", "", tx_hex, False, True, 30.0])
                    for tx_hex in wire[c]]
            except Exception as exc:  # noqa: BLE001 — surface, don't hang
                errors.append(f"client {c}: {type(exc).__name__}: {exc}")

        threads = [threading.Thread(target=client, args=(c,), daemon=True)
                   for c in range(n_clients)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(120)
        assert not any(th.is_alive() for th in threads), \
            "client wedged past join deadline"
        assert not errors, errors
        node.txpool.submit_batch = orig_sb
        flat = [r for rs in receipts.values() for r in rs]
        assert len(flat) == n_clients * per_client
        assert all(r["status"] == 0 for r in flat)
        # coalescing across independent HTTP connections: far fewer
        # recover calls than txs (solo node: submit is the only recover
        # site). With the gated first dispatch this is deterministic:
        # at least the first cohort shares one batch.
        assert counting.recover_calls < n_clients * per_client
        assert node.ingest.stats()["mean_batch"] > 1.0
    finally:
        node.stop()


def test_node_send_transaction_contract_survives_lane_conditions():
    """Node.send_transaction must ALWAYS return a TxSubmitResult (the
    lightnode wire path encodes res.status): a full lane maps to a
    TXPOOL_FULL status, a stopped lane falls back to the direct pool."""
    from fisco_bcos_tpu.init.node import Node, NodeConfig

    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           ingest_queue_cap=1))
    node.start()
    try:
        kp = node.suite.generate_keypair(b"contract")
        res = node.send_transaction(_tx(node.suite, kp, 0))
        assert res.status == TransactionStatus.OK
        # wedge the dispatcher, fill the 1-slot queue, then submit: the
        # lane's TxPoolIsFull must surface as a status, not an exception.
        # Deterministic readiness: `entered` proves the dispatcher is
        # parked INSIDE submit_batch (no sleep guessing on a loaded host).
        gate = threading.Event()
        entered = threading.Event()
        orig = node.txpool.submit_batch

        def gated(txs, broadcast=True):
            entered.set()
            gate.wait(20)
            return orig(txs, broadcast)

        node.txpool.submit_batch = gated
        node.ingest.submit_async(_tx(node.suite, kp, 1))
        assert entered.wait(10), "dispatcher never picked up the tx"
        node.ingest.submit_async(_tx(node.suite, kp, 2))  # fills cap=1
        res = node.send_transaction(_tx(node.suite, kp, 3))
        assert res.status == TransactionStatus.TXPOOL_FULL
        gate.set()
        node.txpool.submit_batch = orig
        # stopped lane: falls back to the pool, still a result
        node.ingest.stop()
        res = node.send_transaction(_tx(node.suite, kp, 4))
        assert res.status == TransactionStatus.OK
    finally:
        node.stop()


def test_wait_for_receipt_concurrent_waiters_survive_timeout():
    """Regression: with the old per-hash Event dict, the FIRST waiter to
    time out popped the registration and stranded every other waiter on
    the same hash. The shared condition variable must deliver to all."""

    class _FakeLedger:
        def __init__(self):
            self.receipts = {}

        def current_number(self):
            return 0

        def receipt(self, h):
            return self.receipts.get(h)

    suite = make_suite(False, backend="host")
    ledger = _FakeLedger()
    pool = TxPool(suite, ledger)
    h = b"\xab" * 32
    got: dict[str, object] = {}

    def short_waiter():
        got["short"] = pool.wait_for_receipt(h, timeout=0.15)

    def long_waiter():
        got["long"] = pool.wait_for_receipt(h, timeout=10.0)

    ts = threading.Thread(target=short_waiter)
    tl = threading.Thread(target=long_waiter)
    ts.start()
    tl.start()
    ts.join(5)
    assert got["short"] is None  # timed out before commit
    marker = object()
    ledger.receipts[h] = marker
    pool.on_block_committed(1, [h], [])
    tl.join(5)
    assert not tl.is_alive(), "long waiter stranded after peer timeout"
    assert got["long"] is marker
