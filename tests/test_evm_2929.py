"""EIP-2929/2200/3529 gas semantics, enforced on BOTH interpreters.

Every scenario runs through tests/test_nevm.py's run_both harness so the
native and Python interpreters must agree bit-for-bit on the new
cold/warm accounting, net SSTORE metering and refund behavior.
Reference counterpart: evmone's Berlin/London gas rules behind
bcos-executor/src/vm/VMFactory.h:46-64.
"""

import pytest

from fisco_bcos_tpu.executor import nevm
from fisco_bcos_tpu.executor.evm import (
    EVM,
    G_COLD_ACCOUNT,
    G_COLD_SLOAD,
    G_SLOAD,
    G_SSTORE_RESET,
    G_SSTORE_SET,
    R_SSTORE_CLEARS,
    T_STORE,
    TxEnv,
)
from tests.test_nevm import ADDR, ENV, SUITE, asm, push, run_both, _fresh_state

pytestmark = pytest.mark.skipif(
    not nevm.available(), reason="libnevm.so not built")


def gas_used(code, gas=1_000_000, **kw):
    n, p = run_both(code, gas=gas, **kw)
    assert n.success and p.success, (n, p)
    return gas - n.gas_left


def test_sload_cold_then_warm():
    # SLOAD slot0 twice: first cold (2100), second warm (100)
    one = asm(push(0, 1), 0x54, 0x50)  # SLOAD + POP
    base = gas_used(one)
    twice = gas_used(one + one)
    # second iteration costs PUSH(3)+warm(100)+POP(2)
    assert twice - base == 3 + G_SLOAD + 2
    assert base == 3 + G_COLD_SLOAD + 2


def test_distinct_slots_each_cold():
    two = asm(push(0, 1), 0x54, 0x50, push(1, 1), 0x54, 0x50)
    assert gas_used(two) == 2 * (3 + G_COLD_SLOAD + 2)


def test_balance_cold_then_warm():
    one = asm(push(0xAB, 1), 0x31, 0x50)
    base = gas_used(one)
    twice = gas_used(one + one)
    assert base == 3 + G_COLD_ACCOUNT + 2
    assert twice - base == 3 + G_SLOAD + 2


def test_extcode_family_shares_warmth():
    # EXTCODESIZE then EXTCODEHASH on the same address: cold then warm
    code = asm(push(0xCD, 1), 0x3B, 0x50, push(0xCD, 1), 0x3F, 0x50)
    assert gas_used(code) == (3 + G_COLD_ACCOUNT + 2) + (3 + G_SLOAD + 2)


def test_sstore_fresh_set_then_update_then_noop():
    store = lambda v: asm(push(v, 1), push(7, 1), 0x55)  # noqa: E731
    # fresh slot, 0 -> 1: cold surcharge + SET
    assert gas_used(store(1)) == 2 * 3 + G_COLD_SLOAD + G_SSTORE_SET
    # same tx: 0->1 (SET), then 1->2 (dirty, warm: 100)
    assert gas_used(store(1) + store(2)) == \
        (2 * 3 + G_COLD_SLOAD + G_SSTORE_SET) + (2 * 3 + G_SLOAD)
    # no-op write (1->1 after 0->1): warm 100
    assert gas_used(store(1) + store(1)) == \
        (2 * 3 + G_COLD_SLOAD + G_SSTORE_SET) + (2 * 3 + G_SLOAD)


def test_sstore_preexisting_reset():
    # slot pre-populated outside the tx: 5 -> 6 is RESET (2900) + cold
    extra = [(T_STORE, ADDR + (7).to_bytes(32, "big"),
              (5).to_bytes(32, "big"))]
    code = asm(push(6, 1), push(7, 1), 0x55)
    assert gas_used(code, extra=extra) == \
        2 * 3 + G_COLD_SLOAD + G_SSTORE_RESET


def test_sstore_sentry():
    code = asm(push(1, 1), push(7, 1), 0x55)
    # gas after the two pushes lands exactly at the 2300 sentry -> OOG
    n, p = run_both(code, gas=2306)
    assert not n.success and not p.success
    assert n.gas_left == 0 and p.gas_left == 0


def test_refund_on_clear_via_executor():
    """Clearing a pre-existing slot refunds 4800 (capped by gas/5) —
    observable through the executor's receipt gas, both interpreters."""
    from fisco_bcos_tpu.storage.memory import MemoryStorage
    from fisco_bcos_tpu.storage.state import StateStorage

    # contract: SSTORE(slot7, 0)
    code = asm(push(0, 1), push(7, 1), 0x55, 0x00)
    used = {}
    for native in (True, False):
        st = _fresh_state(code)
        st.set(T_STORE, ADDR + (7).to_bytes(32, "big"),
               (5).to_bytes(32, "big"))
        evm = EVM(SUITE, native=native)
        res = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0, b"",
                                  100_000)
        assert res.success
        raw_used = 100_000 - res.gas_left
        refund = evm.take_refund(raw_used)
        # clearing refund is 4800 but capped at gas_used/5
        assert refund == min(R_SSTORE_CLEARS, raw_used // 5)
        used[native] = raw_used - refund
    assert used[True] == used[False]


def test_dirty_restore_refund_is_2800():
    """Berlin/London: restoring a dirty nonzero slot to its original value
    credits RESET - warm = 2800 (a ReentrancyGuard round-trip), not 4900."""
    # slot7 original=5; tx: 5 -> 9 (RESET 2900), then 9 -> 5 (dirty warm
    # 100, refund 2800)
    code = asm(push(9, 1), push(7, 1), 0x55,
               push(5, 1), push(7, 1), 0x55, 0x00)
    for native in (True, False):
        st = _fresh_state(code)
        st.set(T_STORE, ADDR + (7).to_bytes(32, "big"),
               (5).to_bytes(32, "big"))
        evm = EVM(SUITE, native=native)
        res = evm.execute_message(st, ENV, b"\x22" * 20, ADDR, 0, b"",
                                  100_000)
        assert res.success
        acc = evm.access()
        assert acc.refund == G_SSTORE_RESET - G_SLOAD  # 2800
        evm.take_refund(100_000 - res.gas_left)


def test_create_failure_rolls_back_access_and_refund():
    """Initcode that earns a refund then fails the code-size check must
    not leave refunds/warmth behind (failed deploys pay full gas)."""
    # initcode: clear pre-warmed... pre-existing slot (refund), then
    # return > MAX_CODE_SIZE bytes -> "code too large"
    initcode = asm(push(0, 1), push(7, 1), 0x55,           # SSTORE(7, 0)
                   push(0x7000, 2), push(0, 1), 0xF3)      # RETURN 28k
    for native in (True, False):
        st = _fresh_state()
        evm = EVM(SUITE, native=native)
        # deploy from CALLER; the created address owns slot7 — seed the
        # slot under the deterministic create address
        from fisco_bcos_tpu.executor.evm import T_NONCE
        nonce = 0
        seed = (b"\x22" * 20) + nonce.to_bytes(8, "big")
        new_addr = SUITE.hash(b"\xd6\x94" + seed)[12:]
        st.set(T_STORE, new_addr + (7).to_bytes(32, "big"),
               (5).to_bytes(32, "big"))
        res = evm.create(st, ENV, b"\x22" * 20, 0, initcode, 200_000)
        assert not res.success and res.error == "code too large"
        assert evm.access().refund == 0  # rolled back with the frame
        assert evm.take_refund(200_000) == 0


def test_revert_restores_cold_state():
    """A reverted subcall's warming must not persist: SLOAD after a
    reverted frame that touched the slot is still cold."""
    # inner contract at 0x..33: SLOAD slot7 then REVERT
    inner_addr = b"\x33" * 20
    inner = asm(push(7, 1), 0x54, 0x50, push(0, 1), push(0, 1), 0xFD)
    # CALLCODE runs inner's code against OUR storage and reverts, so the
    # outer frame's later SLOAD of slot7 must still be cold (the callee's
    # warming rolled back with the revert).
    outer = asm(
        push(0, 1), push(0, 1), push(0, 1), push(0, 1),  # ret/arg windows
        push(0, 1),                                       # value
        push(int.from_bytes(inner_addr, "big")), push(50_000, 4),
        0xF2,                                             # CALLCODE
        0x50,                                             # pop status
        push(7, 1), 0x54, 0x50)                           # SLOAD slot7
    extra = [("s_code", inner_addr, inner)]
    gas = 1_000_000
    n, p = run_both(outer, gas=gas, extra=extra)
    assert n.success and p.success
    assert n.gas_left == p.gas_left
    # the final SLOAD must be COLD (2100): compute by differencing against
    # the same program whose final SLOAD is the only difference
    probe_warm = asm(
        push(0, 1), push(0, 1), push(0, 1), push(0, 1),
        push(0, 1),
        push(int.from_bytes(inner_addr, "big")), push(50_000, 4),
        0xF2, 0x50,
        push(7, 1), 0x54, 0x50, push(7, 1), 0x54, 0x50)
    n2, _ = run_both(probe_warm, gas=gas, extra=extra)
    # second SLOAD warm -> delta between programs = 3 + 100 + 2
    assert (gas - n2.gas_left) - (gas - n.gas_left) == 3 + G_SLOAD + 2


def test_call_target_cold_vs_warm():
    target = b"\x44" * 20
    callseq = asm(
        push(0, 1), push(0, 1), push(0, 1), push(0, 1), push(0, 1),
        push(int.from_bytes(target, "big")), push(1000, 2), 0xF1, 0x50)
    one = gas_used(callseq)
    two = gas_used(callseq + callseq)
    # second CALL to the same (empty-code) target: warm 100 vs cold 2600
    assert one - (two - one) == G_COLD_ACCOUNT - G_SLOAD


def test_origin_and_self_prewarmed():
    # BALANCE(self) and BALANCE(origin) are warm from tx start
    code = asm(0x30, 0x31, 0x50, 0x32, 0x31, 0x50)  # ADDRESS/ORIGIN+BALANCE
    assert gas_used(code) == 2 * (2 + G_SLOAD + 2)
