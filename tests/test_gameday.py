"""Scenario workload shapes + game-day schedule plane (fast tier).

The real-cluster game day itself is exercised by tests/test_gameday_e2e.py
(slow tier) and tools/sanitize_ci.sh --gameday; this file pins the parts
that must hold BEFORE a cluster ever boots: deterministic workload
generation, open-loop admission accounting, and schedule validation."""

import copy
import threading

import pytest

from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.testing import scenario as sc
from fisco_bcos_tpu.testing.gameday import (BUILTIN_SCHEDULES,
                                            GameDayFailure,
                                            validate_schedule)


# -- scenario shapes ---------------------------------------------------------

def test_scenario_spec_rejects_unknown_name():
    with pytest.raises(ValueError, match="unknown scenario"):
        sc.ScenarioSpec(name="tsunami")


def test_prefund_rows_cover_each_scenarios_sources():
    hot = sc.prefund_rows(sc.ScenarioSpec("hot-key", accounts=100))
    assert len(hot[pc.T_BALANCE]) == 100
    bal = sc.ACCOUNT_BALANCE.to_bytes(16, "big")
    assert all(v == bal for _, v in hot[pc.T_BALANCE])

    air = sc.prefund_rows(sc.ScenarioSpec("airdrop-sweep", funders=5))
    assert [k for k, _ in air[pc.T_BALANCE]] == [
        b"funder-%d" % i for i in range(5)]

    wide = sc.prefund_rows(sc.ScenarioSpec("wide-table"))
    assert wide[pc.T_USER_PREFIX + "gd"] == [(b"\x00__meta__", b"kv")]

    # mint-storm needs nothing pre-funded: registers are self-contained
    assert sc.prefund_rows(sc.ScenarioSpec("mint-storm")) == {}


def test_prefund_storage_injects_rows():
    st = MemoryStorage()
    spec = sc.ScenarioSpec("hot-key", accounts=64)
    n = sc.prefund_storage(st, spec)
    assert n == 64
    assert st.get(pc.T_BALANCE, b"acct-0000063") == \
        sc.ACCOUNT_BALANCE.to_bytes(16, "big")


def test_prefund_fields_fund_through_the_chain():
    fields = sc.prefund_fields(sc.ScenarioSpec("hot-key", accounts=7))
    assert len(fields) == 7
    assert all(to == pc.BALANCE_ADDRESS for to, _, _ in fields)
    nonces = [nonce for _, _, nonce in fields]
    assert len(set(nonces)) == 7 and nonces[0] == "gda-0"
    # wide-table prefund is the table DDL
    (to, _, nonce), = sc.prefund_fields(sc.ScenarioSpec("wide-table"))
    assert to == pc.KV_TABLE_ADDRESS and nonce == "gdt-0"


def test_tx_fields_deterministic_and_shaped():
    spec = sc.ScenarioSpec("hot-key", accounts=1000, hot_keys=4,
                           hot_share=1.0)
    assert sc.tx_fields(spec, 42) == sc.tx_fields(spec, 42)
    assert sc.tx_fields(spec, 42) != sc.tx_fields(spec, 43)
    # hot_share=1.0: every arrival lands in the hot set
    for i in range(50):
        to, data, nonce = sc.tx_fields(spec, i)
        assert to == pc.BALANCE_ADDRESS and nonce == f"gdh-{i}"
        assert b"hot-" in data

    wide = sc.ScenarioSpec("wide-table", value_bytes=32, wide_rows=10)
    _, data, _ = sc.tx_fields(wide, 3)
    assert b"row-" in data

    # different seed -> different stream (chunk determinism is per-seed)
    other = sc.ScenarioSpec("hot-key", accounts=1000, seed=99)
    assert sc.tx_fields(spec, 7) != sc.tx_fields(other, 7)


def test_sign_workload_produces_decodable_wire_txs():
    from fisco_bcos_tpu.protocol import Transaction

    spec = sc.ScenarioSpec("mint-storm")
    raws = sc.sign_workload(spec, sm=False, n=5, block_limit=77,
                            start=3)
    assert len(raws) == 5
    txs = [Transaction.decode(r) for r in raws]
    assert [t.nonce for t in txs] == [f"gdm-{i}" for i in range(3, 8)]
    assert all(t.block_limit == 77 and t.group_id == "group0"
               for t in txs)


# -- open-loop driver --------------------------------------------------------

def test_open_loop_poisson_counts_admission_shed_and_errors():
    calls = []

    def submit(batch):
        calls.append(len(batch))
        if len(calls) == 1:
            raise ConnectionError("node died mid-window")
        return max(0, len(batch) - 1)  # shed one per batch

    counts = sc.open_loop_poisson(submit, list(range(400)), rate=5000.0,
                                  window_s=2.0)
    assert counts["offered"] == 400
    assert counts["submit_errors"] >= 1
    assert counts["shed"] >= counts["submit_errors"]
    assert counts["admitted"] + counts["shed"] == counts["offered"]
    assert 0 < counts["shed_rate"] <= 1


def test_open_loop_poisson_samples_admitted_indexes():
    seen = []
    counts = sc.open_loop_poisson(
        lambda b: len(b), list(range(300)), rate=5000.0, window_s=2.0,
        on_sample=lambda k, t: seen.append(k), sample_every=8)
    assert counts["admitted"] == 300 and counts["shed"] == 0
    assert seen and seen == sorted(seen) and len(set(seen)) == len(seen)
    assert all(0 <= k < 300 for k in seen)


def test_open_loop_poisson_stop_predicate_halts_early():
    stop = threading.Event()

    def submit(batch):
        stop.set()
        return len(batch)

    counts = sc.open_loop_poisson(submit, list(range(10_000)),
                                  rate=100_000.0, window_s=5.0,
                                  stop=stop.is_set)
    assert counts["offered"] < 10_000
    assert counts["wall_seconds"] < 5.0


# -- schedule validation -----------------------------------------------------

def test_builtin_schedules_validate_and_fill_defaults():
    for name, schedule in BUILTIN_SCHEDULES.items():
        v = validate_schedule(schedule)
        assert v["name"] == name and v["phases"]
        for p in v["phases"]:
            assert p["load"]["scenario"] in sc.SCENARIOS
            for ev in p["events"]:
                assert 0 <= ev["at_s"] <= p["duration_s"]


def test_validate_schedule_does_not_mutate_input():
    raw = {"name": "d", "tls": False,
           "phases": [{"name": "p", "duration_s": 5}]}
    snapshot = copy.deepcopy(raw)
    v = validate_schedule(raw)
    assert raw == snapshot
    assert v["phases"][0]["load"]["scenario"] == "mint-storm"
    assert v["nodes"] == 4 and v["recovery_slo_s"] > 0


@pytest.mark.parametrize("mutate,msg", [
    (lambda s: s.pop("phases"), "no phases"),
    (lambda s: s.__setitem__("nodes", 3), ">= 4 nodes"),
    (lambda s: s["phases"][0].__setitem__("duration_s", 0), "duration_s"),
    (lambda s: s["phases"][0]["load"].__setitem__(
        "scenario", "xshard-heavy"), "multi-group"),
    (lambda s: s["phases"][0]["events"].append(
        {"action": "meteor"}), "unknown action"),
    (lambda s: s["phases"][0]["events"].append(
        {"action": "sigkill", "node": 11}), "valid 'node'"),
    (lambda s: s["phases"][0]["events"].append(
        {"action": "sigkill", "node": 0, "at_s": 99.0}),
     "outside the phase"),
    (lambda s: s["phases"][0]["events"].append(
        {"action": "partition", "a": 1, "b": 1}), "distinct nodes"),
    (lambda s: s["phases"][0]["events"].append(
        {"action": "failpoint", "node": 0}), "needs a 'site'"),
])
def test_validate_schedule_rejects_bad_shapes(mutate, msg):
    s = {"name": "d", "tls": False,
         "phases": [{"name": "p", "duration_s": 10.0,
                     "load": {"scenario": "hot-key", "intensity": 0.5},
                     "events": []}]}
    mutate(s)
    with pytest.raises(ValueError, match=msg):
        validate_schedule(s)


def test_byzantine_requires_plaintext_p2p():
    s = {"name": "d", "tls": True,
         "phases": [{"name": "p", "duration_s": 10.0,
                     "events": [{"action": "byzantine", "node": 1}]}]}
    with pytest.raises(ValueError, match="tls=false"):
        validate_schedule(s)


def test_gameday_failure_names_phase_and_invariant():
    exc = GameDayFailure("kill9-under-mint", "heads-converge", "stuck")
    assert exc.phase == "kill9-under-mint"
    assert exc.invariant == "heads-converge"
    assert "kill9-under-mint" in str(exc) and "heads-converge" in str(exc)
