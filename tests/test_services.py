"""Pro/Max service split: storage + executor services over real sockets."""

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.codec.wire import Writer
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.services import (ExecutorServer, RemoteExecutor,
                                     RemoteStorage, StorageServer)
from fisco_bcos_tpu.services.rpc import (ServiceClient, ServiceRemoteError,
                                         ServiceServer)
from fisco_bcos_tpu.storage.interface import Entry
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.wal import WalStorage

SUITE = make_suite(backend="host")


def test_service_rpc_roundtrip_and_errors():
    srv = ServiceServer("echo")
    srv.register("echo", lambda r, w: w.blob(r.blob()))

    def boom(r, w):
        raise ValueError("kaput")

    srv.register("boom", boom)
    srv.start()
    try:
        cli = ServiceClient("127.0.0.1", srv.port)
        assert cli.call("echo", lambda w: w.blob(b"hi")).blob() == b"hi"
        with pytest.raises(ServiceRemoteError, match="kaput"):
            cli.call("boom")
        with pytest.raises(ServiceRemoteError, match="unknown method"):
            cli.call("nope")
        # the connection survives handler errors
        assert cli.call("echo", lambda w: w.blob(b"x")).blob() == b"x"
        cli.close()
    finally:
        srv.stop()


def test_remote_storage_contract(tmp_path):
    srv = StorageServer(WalStorage(str(tmp_path / "db")))
    srv.start()
    try:
        st = RemoteStorage("127.0.0.1", srv.port)
        st.set("t", b"k", b"v")
        assert st.get("t", b"k") == b"v"
        assert st.get("t", b"missing") is None
        st.set("t", b"k2", b"v2")
        assert list(st.keys("t")) == [b"k", b"k2"]
        assert st.get_batch("t", [b"k", b"zz", b"k2"]) == [b"v", None, b"v2"]
        st.prepare(3, {("t", b"k3"): Entry(b"v3")})
        assert st.get("t", b"k3") is None
        st.commit(3)
        assert st.get("t", b"k3") == b"v3"
        st.prepare(4, {("t", b"k4"): Entry(b"v4")})
        st.rollback(4)
        assert st.get("t", b"k4") is None
        st.close()
    finally:
        srv.stop()
        srv.backend.close()


def test_remote_executor_block_execution(tmp_path):
    # Max shape: executor process reads state through the storage service
    storage_srv = StorageServer(WalStorage(str(tmp_path / "db")))
    storage_srv.start()
    exec_storage = RemoteStorage("127.0.0.1", storage_srv.port)
    exec_srv = ExecutorServer(SUITE, exec_storage)
    exec_srv.start()
    try:
        ex = RemoteExecutor("127.0.0.1", exec_srv.port)
        assert ex.status() >= 0

        def tx(method, build, nonce):
            w = Writer()
            w.text(method)
            build(w)
            t = Transaction(to=pc.BALANCE_ADDRESS, input=w.bytes(),
                            nonce=nonce)
            t._sender = b"\xaa" * 20
            return t

        txs = [tx("register", lambda w: w.blob(b"a").u64(100), "n1"),
               tx("register", lambda w: w.blob(b"b").u64(0), "n2"),
               tx("transfer",
                  lambda w: w.blob(b"a").blob(b"b").u64(30), "n3")]
        receipts, changes = ex.execute_block(txs, 1, 1000)
        assert [rc.status for rc in receipts] == [0, 0, 0]
        assert changes  # the scheduler-side changeset came back

        # scheduler-side 2PC against the same storage service
        sched_storage = RemoteStorage("127.0.0.1", storage_srv.port)
        sched_storage.prepare(1, changes)
        sched_storage.commit(1)
        from fisco_bcos_tpu.executor.precompiled import T_BALANCE
        assert int.from_bytes(
            sched_storage.get(T_BALANCE, b"b"), "big") == 30

        ex.bump_term()
        receipts2, _ = ex.execute_block(
            [tx("balanceOf", lambda w: w.blob(b"b"), "n4")], 2, 2000)
        assert receipts2[0].status == 0
        from fisco_bcos_tpu.codec.wire import Reader
        assert Reader(receipts2[0].output).u64() == 30
        ex.close()
        sched_storage.close()
    finally:
        exec_srv.stop()
        storage_srv.stop()
        exec_storage.close()
        storage_srv.backend.close()


def test_service_plane_over_smtls(tmp_path):
    """Max cross-machine planes (shards, lease registries) secured with
    the SM-TLS dual-cert channel: trusted clients work end to end,
    untrusted CAs are refused at the handshake."""
    from fisco_bcos_tpu.net.smtls import CertificateAuthority, SMTLSContext
    from fisco_bcos_tpu.storage.interface import Entry
    from fisco_bcos_tpu.storage.sharded import (
        DurablePrepareStorage, ShardServer, ShardedStorage,
        make_shard_client)
    from fisco_bcos_tpu.storage.wal import WalStorage

    ca = CertificateAuthority(seed=b"svc" * 8)
    servers = []
    for i in range(3):
        backend = DurablePrepareStorage(
            WalStorage(str(tmp_path / f"s{i}" / "wal")),
            str(tmp_path / f"s{i}" / "prep"))
        srv = ShardServer(backend, tls_ctx=SMTLSContext(
            ca.pub, ca.issue(f"shard{i}")))
        srv.start()
        servers.append(srv)
    st = ShardedStorage([
        make_shard_client("127.0.0.1", s.port,
                          tls_ctx=SMTLSContext(ca.pub, ca.issue("coord")))
        for s in servers])
    st.prepare(1, {("t", b"secret"): Entry(b"payload")})
    st.commit(1)
    assert st.get("t", b"secret") == b"payload"

    # untrusted CA: the handshake fails, no RPC goes through
    evil = CertificateAuthority(seed=b"evil" * 8)
    bad = make_shard_client("127.0.0.1", servers[0].port,
                            tls_ctx=SMTLSContext(evil.pub,
                                                 evil.issue("mallory")))
    with pytest.raises(Exception):
        bad.get("t", b"secret")
    bad.close()

    # elections over the same secured plane
    from fisco_bcos_tpu.ha.quorum import (LeaseRegistryServer,
                                          QuorumLeaseElection)
    regs = [LeaseRegistryServer(
        state_path=str(tmp_path / f"r{i}.json"),
        tls_ctx=SMTLSContext(ca.pub, ca.issue(f"reg{i}")))
        for i in range(3)]
    for r in regs:
        r.start()
    el = QuorumLeaseElection(
        [("127.0.0.1", r.port) for r in regs], "tls-node",
        lease_ttl=1.0, heartbeat=0.2, rpc_timeout=1.0,
        tls_ctx=SMTLSContext(ca.pub, ca.issue("tls-node")))
    el.start()
    try:
        import time

        deadline = time.time() + 15
        while not el.is_leader() and time.time() < deadline:
            time.sleep(0.05)
        assert el.is_leader()
    finally:
        el.stop()
        for r in regs:
            r.stop()
        st.close()
        for s in servers:
            s.stop()
            s.backend.close()
