"""Shared crypto lane (crypto/lane.py).

Asserts the lane's contract: concurrent batch submissions from >= 2
callers (groups) merge into ONE base-suite device call (counted with an
instrumented suite + the gated-dispatch idiom from tests/test_ingest.py,
so coalescing is deterministic on the 2-core host), results demux
positionally (a failed verify in one group's slice never poisons another
group's verdicts), a dispatch error rejects exactly the merged cohort and
the lane survives it, and `LaneSuite` preserves the full CryptoSuite
surface (delegation + tiny-batch bypass).
"""

import threading

import numpy as np
import pytest

from fisco_bcos_tpu.crypto.lane import CryptoLane, LaneSuite
from fisco_bcos_tpu.crypto.suite import make_suite


class CountingSuite:
    """Delegating wrapper counting (and optionally gating) batch entry
    points — the instrument behind every "calls == 1" assertion here."""

    def __init__(self, suite):
        self._suite = suite
        self.recover_calls = 0
        self.verify_calls = 0
        self.hash_calls = 0
        self.recover_sizes = []
        self.verify_sizes = []
        self.gate = None      # threading.Event: first call parks on it
        self.entered = threading.Event()
        self.fail_next = None  # exception to raise on the next batch call

    def __getattr__(self, name):
        return getattr(self._suite, name)

    def _maybe_gate(self):
        if self.fail_next is not None:
            exc, self.fail_next = self.fail_next, None
            raise exc
        if self.gate is not None:
            self.entered.set()
            gate, self.gate = self.gate, None  # first call only
            assert gate.wait(30)

    def recover_batch(self, digests, sigs):
        self.recover_calls += 1
        self.recover_sizes.append(len(digests))
        self._maybe_gate()
        return self._suite.recover_batch(digests, sigs)

    def verify_batch(self, digests, sigs, pubs):
        self.verify_calls += 1
        self.verify_sizes.append(len(digests))
        self._maybe_gate()
        return self._suite.verify_batch(digests, sigs, pubs)

    def hash_batch(self, msgs):
        self.hash_calls += 1
        self._maybe_gate()
        return self._suite.hash_batch(msgs)


def _sigs(suite, kp, n, valid=True):
    """n (digest, sig) pairs; invalid ones are deterministically
    unrecoverable (r > curve order)."""
    digests, sigs = [], []
    for i in range(n):
        d = suite.hash(b"lane-msg-%d" % i)
        g = suite.sign(kp, d)
        if not valid:
            g = b"\xff" * 32 + g[32:]
        digests.append(d)
        sigs.append(g)
    return digests, sigs


@pytest.fixture()
def lane_pair():
    counting = CountingSuite(make_suite(False, backend="host"))
    # host_workers=1: the "exactly ONE base call" assertions below count
    # LANE dispatches — the host path's intra-call core fan-out (covered
    # by test_host_fan_out_preserves_results) would split the counter
    lane = CryptoLane(counting, host_workers=1)
    a = LaneSuite(lane, tag="group0")
    b = LaneSuite(lane, tag="group1")
    yield counting, lane, a, b
    lane.stop()


def _gated_concurrent(counting, lane, calls, probe_op="hash"):
    """Run `calls` (thunks) concurrently with the FIRST base-suite call
    gated until every thunk's request is enqueued: the dispatcher parks
    inside call #1 while the rest queue, so the second device call
    deterministically merges ALL remaining requests (test_ingest's
    gated-dispatch idiom, lifted to the crypto plane)."""
    counting.gate = threading.Event()
    gate = counting.gate
    # `entered` is sticky from any earlier gated stage on this fixture;
    # without the clear, wait(10) below is a no-op and the callers race
    # the dispatcher's coalesce window (their requests get swept into the
    # PROBE's round and _q never refills -> "requests never queued")
    counting.entered.clear()
    # occupy the dispatcher: a tiny probe that parks inside the base call
    # (pick an op DIFFERENT from the one under count so the probe never
    # pollutes the assertion's counter)
    if probe_op == "hash":
        probe = lane.submit("hash", ([b"p1", b"p2"],), 2, "probe")
    else:
        probe = lane.submit("verify", ([b"\x00" * 32] * 2, [b"\x00"] * 2,
                                       [b"\x00" * 64] * 2), 2, "probe")
    assert counting.entered.wait(10), "dispatcher never reached the base"
    results = [None] * len(calls)
    threads = []
    started = threading.Barrier(len(calls) + 1)

    def run(i, fn):
        started.wait()
        results[i] = fn()

    for i, fn in enumerate(calls):
        th = threading.Thread(target=run, args=(i, fn), daemon=True)
        th.start()
        threads.append(th)
    started.wait()
    # every caller parks on its Task BEFORE we release the gate; their
    # requests are already in the lane queue (submit enqueues first)
    deadline = 10.0
    import time
    t0 = time.monotonic()
    while sum(len(lane._q[op]) for op in ("verify", "recover", "hash")) \
            < len(calls):
        assert time.monotonic() - t0 < deadline, "requests never queued"
        time.sleep(0.002)
    gate.set()
    for th in threads:
        th.join(30)
    assert not any(th.is_alive() for th in threads)
    probe.result(10)
    return results


def test_two_groups_one_recover_device_call(lane_pair):
    counting, lane, a, b = lane_pair
    kp = counting.generate_keypair(b"lane-user")
    da, sa = _sigs(counting, kp, 8)
    db, sb = _sigs(counting, kp, 8)
    counting.recover_calls = 0
    counting.recover_sizes = []
    ra, rb = _gated_concurrent(counting, lane, [
        lambda: a.recover_batch(da, sa),
        lambda: b.recover_batch(db, sb),
    ])
    # the claim: BOTH groups' batches crossed the device in ONE call
    assert counting.recover_calls == 1, counting.recover_sizes
    assert counting.recover_sizes == [16]
    for (pubs, ok), n in ((ra, 8), (rb, 8)):
        assert len(pubs) == n and bool(np.all(np.asarray(ok)))
    stats = lane.stats()
    assert stats["merged_calls"] >= 1
    assert stats["per_tag_mean_batch"]["group0"] == 8.0


def test_failed_verify_slice_does_not_poison_other_group(lane_pair):
    counting, lane, a, b = lane_pair
    kp = counting.generate_keypair(b"lane-mixed")
    da, sa = _sigs(counting, kp, 6, valid=False)  # group0: all bad
    db, sb = _sigs(counting, kp, 6, valid=True)   # group1: all good
    counting.recover_calls = 0
    (pa, oka), (pb, okb) = _gated_concurrent(counting, lane, [
        lambda: a.recover_batch(da, sa),
        lambda: b.recover_batch(db, sb),
    ])
    assert counting.recover_calls == 1  # merged, yet verdicts stay per-slice
    assert not np.any(np.asarray(oka))
    assert all(p is None for p in pa)
    assert np.all(np.asarray(okb))
    assert all(p is not None for p in pb)


def test_verify_and_hash_merge_too(lane_pair):
    counting, lane, a, b = lane_pair
    kp = counting.generate_keypair(b"lane-v")
    d1, s1 = _sigs(counting, kp, 4)
    d2, s2 = _sigs(counting, kp, 4)
    pub = kp.pub_bytes
    counting.verify_calls = 0
    va, vb = _gated_concurrent(counting, lane, [
        lambda: a.verify_batch(d1, s1, [pub] * 4),
        lambda: b.verify_batch(d2, s2, [pub] * 4),
    ])
    assert counting.verify_calls == 1
    assert np.all(np.asarray(va)) and np.all(np.asarray(vb))
    counting.hash_calls = 0
    ha, hb = _gated_concurrent(counting, lane, [
        lambda: a.hash_batch([b"x%d" % i for i in range(5)]),
        lambda: b.hash_batch([b"y%d" % i for i in range(5)]),
    ], probe_op="verify")
    assert counting.hash_calls == 1
    base = counting._suite
    assert ha == base.hash_batch([b"x%d" % i for i in range(5)])
    assert hb == base.hash_batch([b"y%d" % i for i in range(5)])


def test_dispatch_error_rejects_cohort_and_lane_survives(lane_pair):
    counting, lane, a, b = lane_pair
    kp = counting.generate_keypair(b"lane-err")
    d, s = _sigs(counting, kp, 4)
    counting.fail_next = RuntimeError("device fell over")
    with pytest.raises(RuntimeError, match="device fell over"):
        a.recover_batch(d, s)
    # the lane thread survived the failed dispatch: next call succeeds
    pubs, ok = b.recover_batch(d, s)
    assert bool(np.all(np.asarray(ok)))


def test_lane_suite_delegates_and_bypasses_tiny_batches(lane_pair):
    counting, lane, a, _b = lane_pair
    kp = a.generate_keypair(b"lane-del")  # delegated keygen
    d = a.hash(b"single")                 # delegated scalar hash
    sig = a.sign(kp, d)                   # delegated signing
    before = lane.stats()["requests_total"]
    # single-item verify takes the base path (no thread hop for size-1)
    assert a.verify(kp.pub_bytes, d, sig)
    assert a.recover(d, sig) is not None
    assert lane.stats()["requests_total"] == before
    # recover_addresses rides the lane's recover and hashes host-side
    ds, ss = _sigs(counting, kp, 4)
    addrs, ok = a.recover_addresses(ds, ss)
    assert bool(np.all(np.asarray(ok)))
    assert all(addr == kp.address for addr in addrs)


def test_host_fan_out_preserves_results():
    """Large merged HOST batches split across the lane's worker pool (the
    tbb verify_worker_num analogue): results must be order-preserving and
    bit-identical to the unsplit call, bad slices staying positional."""
    counting = CountingSuite(make_suite(False, backend="host"))
    lane = CryptoLane(counting, host_workers=2)
    suite = LaneSuite(lane, tag="g")
    try:
        kp = counting.generate_keypair(b"fan-out")
        d, s = _sigs(counting, kp, 20)
        db, sb = _sigs(counting, kp, 4, valid=False)
        digests = d[:10] + db + d[10:]
        sigs = s[:10] + sb + s[10:]
        counting.recover_calls = 0
        pubs, ok = suite.recover_batch(digests, sigs)
        assert counting.recover_calls == 2  # fanned across the pool
        want = [True] * 10 + [False] * 4 + [True] * 10
        assert list(np.asarray(ok)) == want
        ref_pubs, _ = counting._suite.recover_batch(digests, sigs)
        assert pubs == ref_pubs
        hashes = suite.hash_batch([b"m%d" % i for i in range(24)])
        assert hashes == counting._suite.hash_batch(
            [b"m%d" % i for i in range(24)])
    finally:
        lane.stop()


def test_stop_rejects_queued_and_refuses_new():
    counting = CountingSuite(make_suite(False, backend="host"))
    lane = CryptoLane(counting)
    counting.gate = threading.Event()
    gate = counting.gate
    parked = lane.submit("hash", ([b"a", b"b"],), 2, "t")
    assert counting.entered.wait(10)
    queued = lane.submit("hash", ([b"c", b"d"],), 2, "t")
    stopper = threading.Thread(target=lane.stop, daemon=True)
    stopper.start()
    gate.set()
    stopper.join(15)
    assert not stopper.is_alive()
    parked.result(5)  # the in-flight call completed
    # the queued one either completed (drained before stop) or was
    # rejected — it must NOT hang
    try:
        queued.result(5)
    except RuntimeError:
        pass
    with pytest.raises(RuntimeError):
        lane.submit("hash", ([b"e", b"f"],), 2, "t")
