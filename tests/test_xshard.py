"""Cross-group atomic transfers (XShardPrecompile + CrossShardCoordinator).

The satellite contract (per the c_* table gotcha, assertions spot-check
`c_balance` ROWS, never state_root):

  * happy path moves value between two groups' balance tables exactly once;
  * the abort path (unknown destination group) refunds the escrow and
    leaves BOTH groups' balances byte-identical to before;
  * credit is idempotent (a coordinator retry after a crash cannot
    double-credit) and a reused id with different terms is rejected;
  * kill -9 between the escrow commit (phase-1 "prepare") and the credit
    commit recovers through WAL replay on both groups to the same
    all-or-nothing outcome.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.group import GroupManager
from fisco_bcos_tpu.init.node import NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.namespace import NamespacedStorage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bal(node, account: bytes):
    raw = node.storage.get("c_balance", account)
    return None if raw is None else int.from_bytes(raw, "big")


def _submit(node, kp, to, data, nonce):
    tx = Transaction(to=to, input=data, nonce=nonce,
                     group_id=node.config.group_id,
                     block_limit=node.ledger.current_number() + 100
                     ).sign(node.suite, kp)
    res = node.send_transaction(tx)
    rc = node.txpool.wait_for_receipt(res.tx_hash, 30)
    assert rc is not None, f"{nonce}: no receipt"
    return rc


def _transfer_out(node, kp, xid, dst_group, src, dst, amount, nonce):
    return _submit(node, kp, pc.XSHARD_ADDRESS, pc.encode_call(
        "transferOut",
        lambda w: w.blob(xid).text(dst_group).blob(src).blob(dst)
        .u64(amount)), nonce)


@pytest.fixture()
def two_groups():
    mgr = GroupManager(storage=MemoryStorage())
    a = mgr.add_group(NodeConfig(group_id="group0", crypto_backend="host",
                                 min_seal_time=0.0))
    b = mgr.add_group(NodeConfig(group_id="group1", crypto_backend="host",
                                 min_seal_time=0.0))
    mgr.start()
    kp = a.suite.generate_keypair(b"xshard-user")
    rc = _submit(a, kp, pc.BALANCE_ADDRESS, pc.encode_call(
        "register", lambda w: w.blob(b"alice").u64(100)), "reg-a")
    assert rc.status == 0
    rc = _submit(b, kp, pc.BALANCE_ADDRESS, pc.encode_call(
        "register", lambda w: w.blob(b"bob").u64(5)), "reg-b")
    assert rc.status == 0
    yield mgr, a, b, kp
    mgr.stop()


def _wait(cond, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_happy_path_moves_balance_exactly_once(two_groups):
    mgr, a, b, kp = two_groups
    rc = _transfer_out(a, kp, b"x1", "group1", b"alice", b"bob", 30, "x1")
    assert rc.status == 0
    assert _wait(lambda: _bal(b, b"bob") == 35)
    assert _bal(a, b"alice") == 70
    # escrow settles AFTER the credit (finish is the third leg): wait for
    # the pending marker to drain, then assert the terminal state
    assert _wait(lambda: not list(a.storage.keys(pc.T_XSHARD_PEND)))
    intent = pc.decode_intent(a.storage.get(pc.T_XSHARD_OUT, b"x1"))
    assert intent["status"] == pc.XS_DONE
    assert b.storage.get(pc.T_XSHARD_IN, b"x1") is not None
    assert _wait(lambda: mgr.coordinator.stats()["completed_total"] == 1)


def test_abort_unknown_group_leaves_both_balances_untouched(two_groups):
    mgr, a, b, kp = two_groups
    before_a = sorted((k, a.storage.get("c_balance", k))
                      for k in a.storage.keys("c_balance"))
    before_b = sorted((k, b.storage.get("c_balance", k))
                      for k in b.storage.keys("c_balance"))
    rc = _transfer_out(a, kp, b"x2", "groupZ", b"alice", b"bob", 40, "x2")
    assert rc.status == 0
    assert _wait(lambda: mgr.coordinator.stats()["aborted_total"] >= 1)
    assert _wait(lambda: not list(a.storage.keys(pc.T_XSHARD_PEND)))
    # both groups' balance ROWS byte-identical to before (state_root
    # can't prove this — it is per-changeset)
    after_a = sorted((k, a.storage.get("c_balance", k))
                     for k in a.storage.keys("c_balance"))
    after_b = sorted((k, b.storage.get("c_balance", k))
                     for k in b.storage.keys("c_balance"))
    assert after_a == before_a
    assert after_b == before_b
    intent = pc.decode_intent(a.storage.get(pc.T_XSHARD_OUT, b"x2"))
    assert intent["status"] == pc.XS_ABORTED


def test_insufficient_balance_reverts_escrow(two_groups):
    mgr, a, b, kp = two_groups
    rc = _transfer_out(a, kp, b"x3", "group1", b"alice", b"bob", 10_000,
                       "x3")
    assert rc.status != 0  # REVERT at execution: nothing escrowed
    assert _bal(a, b"alice") == 100
    assert a.storage.get(pc.T_XSHARD_OUT, b"x3") is None
    assert list(a.storage.keys(pc.T_XSHARD_PEND)) == []


def test_duplicate_transfer_id_and_idempotent_credit(two_groups):
    mgr, a, b, kp = two_groups
    rc = _transfer_out(a, kp, b"x4", "group1", b"alice", b"bob", 10, "x4")
    assert rc.status == 0
    assert _wait(lambda: _bal(b, b"bob") == 15)
    # same id again on the source: rejected, no second escrow
    rc = _transfer_out(a, kp, b"x4", "group1", b"alice", b"bob", 10, "x4b")
    assert rc.status != 0
    assert _bal(a, b"alice") == 90
    # a replayed credit with IDENTICAL terms is an ok no-op (coordinator
    # crash-retry); different terms revert — never a double credit
    rc = _submit(b, kp, pc.XSHARD_ADDRESS, pc.encode_call(
        "credit", lambda w: w.blob(b"x4").text("group0").blob(b"bob")
        .u64(10)), "x4-replay")
    assert rc.status == 0
    assert _bal(b, b"bob") == 15  # unchanged
    rc = _submit(b, kp, pc.XSHARD_ADDRESS, pc.encode_call(
        "credit", lambda w: w.blob(b"x4").text("group0").blob(b"bob")
        .u64(999)), "x4-evil")
    assert rc.status != 0
    assert _bal(b, b"bob") == 15


def test_namespaced_storage_isolates_groups_and_2pc():
    from fisco_bcos_tpu.storage.interface import Entry

    base = MemoryStorage()
    g0 = NamespacedStorage(base, "group0")
    g1 = NamespacedStorage(base, "group1")
    g0.set("t", b"k", b"v0")
    g1.set("t", b"k", b"v1")
    assert g0.get("t", b"k") == b"v0"
    assert g1.get("t", b"k") == b"v1"
    assert g0.tables() == ["t"] and g1.tables() == ["t"]
    # SAME height prepared by both groups: ids must not collide
    g0.prepare(5, {("t", b"a"): Entry(b"A0")})
    g1.prepare(5, {("t", b"a"): Entry(b"A1")})
    g0.commit(5)
    assert g0.get("t", b"a") == b"A0"
    assert g1.get("t", b"a") is None  # still only prepared
    g1.rollback(5)
    assert g1.get("t", b"a") is None


_PHASE_SCRIPT = r"""
import json, os, signal, sys, time
sys.path.insert(0, %(repo)r)
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.group import GroupManager
from fisco_bcos_tpu.init.node import NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.storage.wal import WalStorage

phase = sys.argv[1]
path = sys.argv[2]
store = WalStorage(path)
# phase A runs WITHOUT the coordinator: the transfer stops exactly between
# the escrow commit ("prepare") and the credit ("commit")
mgr = GroupManager(storage=store, xshard=(phase == "recover"))
a = mgr.add_group(NodeConfig(group_id="group0", crypto_backend="host",
                             min_seal_time=0.0))
b = mgr.add_group(NodeConfig(group_id="group1", crypto_backend="host",
                             min_seal_time=0.0))
mgr.start()
kp = a.suite.keypair_from_secret(7777)

def submit(node, to, data, nonce):
    tx = Transaction(to=to, input=data, nonce=nonce,
                     group_id=node.config.group_id,
                     block_limit=node.ledger.current_number() + 100
                     ).sign(node.suite, kp)
    res = node.send_transaction(tx)
    rc = node.txpool.wait_for_receipt(res.tx_hash, 30)
    assert rc is not None and rc.status == 0, (nonce, rc)

if phase == "escrow":
    submit(a, pc.BALANCE_ADDRESS, pc.encode_call(
        "register", lambda w: w.blob(b"alice").u64(100)), "reg-a")
    submit(b, pc.BALANCE_ADDRESS, pc.encode_call(
        "register", lambda w: w.blob(b"bob").u64(5)), "reg-b")
    submit(a, pc.XSHARD_ADDRESS, pc.encode_call(
        "transferOut", lambda w: w.blob(b"k9").text("group1")
        .blob(b"alice").blob(b"bob").u64(30)), "x-k9")
    # escrow IS committed (WAL record fsynced); the credit has NOT run.
    # Die exactly here — no graceful stop, no WAL close.
    os.kill(os.getpid(), signal.SIGKILL)

# phase "recover": WAL replay restored both groups; the coordinator's
# boot sweep must re-drive the pending transfer to completion
deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    if not list(a.storage.keys(pc.T_XSHARD_PEND)):
        break
    time.sleep(0.05)
out = {
    "alice": int.from_bytes(a.storage.get("c_balance", b"alice"), "big"),
    "bob": int.from_bytes(b.storage.get("c_balance", b"bob"), "big"),
    "pending": len(list(a.storage.keys(pc.T_XSHARD_PEND))),
    "outbox_status": pc.decode_intent(
        a.storage.get(pc.T_XSHARD_OUT, b"k9"))["status"],
    "inbox": (b.storage.get(pc.T_XSHARD_IN, b"k9") or b"").hex(),
}
mgr.stop()
store.close()
print("RESULT " + json.dumps(out))
"""


@pytest.mark.slow
def test_kill9_between_prepare_and_commit_recovers_all_or_nothing(tmp_path):
    """Phase A escrows the debit on group0 (committed, WAL-durable) and is
    SIGKILLed before the credit ever reaches group1 — the exact
    prepare->commit window. Phase B reopens the same WAL: replay restores
    the escrow + pending marker on group0 and the untouched balance on
    group1, and the coordinator's recovery sweep lands the credit and
    settles the escrow. Outcome must be ALL (never half, never double).

    Slow e2e gate: the fast tier-1 guard for these saga legs is the
    in-process failpoint sweep in test_faults.py (same crash windows,
    no subprocess boot)."""
    script = _PHASE_SCRIPT % {"repo": REPO}
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    wal_dir = str(tmp_path / "shared-wal")

    r = subprocess.run([sys.executable, "-c", script, "escrow", wal_dir],
                       env=env, capture_output=True, text=True, timeout=180)
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr[-2000:])

    r = subprocess.run([sys.executable, "-c", script, "recover", wal_dir],
                       env=env, capture_output=True, text=True, timeout=180)
    assert r.returncode == 0, r.stderr[-2000:]
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith("RESULT "))
    out = json.loads(line[len("RESULT "):])
    # all-or-nothing: the transfer completed exactly once after replay
    assert out == {"alice": 70, "bob": 35, "pending": 0,
                   "outbox_status": pc.XS_DONE, "inbox": out["inbox"]}
    assert out["inbox"]  # dedup record present on the destination
