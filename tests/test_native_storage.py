"""Native bcoskv engine vs pure-Python WalStorage: same 2PC contract.

Mirrors the reference's storage tests (bcos-storage backends both implement
StorageInterface.h:126-141; tests/perf/benchmark.cpp compares them). Both
backends here run the same scenario suite, including a crash-recovery check
(close without compaction -> reopen -> WAL replay).
"""

import pytest

from fisco_bcos_tpu.storage.interface import Entry, EntryStatus
from fisco_bcos_tpu.storage.wal import WalStorage
from fisco_bcos_tpu.storage import native


def _backends(tmp_path):
    out = [("wal", lambda p: WalStorage(str(tmp_path / ("w" + p))))]
    if native.available():
        out.append(("native",
                    lambda p: native.NativeStorage(str(tmp_path / ("n" + p)))))
    return out


@pytest.fixture(params=["wal", "native"])
def storage_factory(request, tmp_path):
    if request.param == "native" and not native.available():
        pytest.skip("native toolchain unavailable")
    if request.param == "wal":
        return lambda p="x": WalStorage(str(tmp_path / ("w" + p)))
    return lambda p="x": native.NativeStorage(str(tmp_path / ("n" + p)))


def test_basic_kv(storage_factory):
    st = storage_factory()
    assert st.get("t", b"k") is None
    st.set("t", b"k", b"v1")
    st.set("t", b"k2", b"v2")
    st.set("u", b"k", b"other-table")
    assert st.get("t", b"k") == b"v1"
    assert st.get("u", b"k") == b"other-table"
    st.remove("t", b"k")
    assert st.get("t", b"k") is None
    assert st.get("t", b"k2") == b"v2"
    st.close()


def test_prefix_scan(storage_factory):
    st = storage_factory()
    for i in range(5):
        st.set("t", b"a%d" % i, b"x")
    st.set("t", b"b0", b"y")
    st.remove("t", b"a3")
    keys = list(st.keys("t", b"a"))
    assert keys == [b"a0", b"a1", b"a2", b"a4"]
    assert list(st.keys("t")) == [b"a0", b"a1", b"a2", b"a4", b"b0"]
    st.close()


def test_2pc_commit_rollback(storage_factory):
    st = storage_factory()
    st.set("t", b"base", b"0")
    cs = {("t", b"k1"): Entry(b"v1"),
          ("t", b"base"): Entry(b"", EntryStatus.DELETED)}
    st.prepare(7, cs)
    # nothing visible before commit
    assert st.get("t", b"k1") is None
    st.commit(7)
    assert st.get("t", b"k1") == b"v1"
    assert st.get("t", b"base") is None
    st.prepare(8, {("t", b"k2"): Entry(b"v2")})
    st.rollback(8)
    with pytest.raises(Exception):
        st.commit(8)
    assert st.get("t", b"k2") is None
    st.close()


def test_crash_recovery(tmp_path, storage_factory):
    st = storage_factory("crash")
    st.set("t", b"a", b"1")
    st.prepare(1, {("t", b"b"): Entry(b"2")})
    st.commit(1)
    st.prepare(2, {("t", b"c"): Entry(b"3")})  # prepared, never committed
    st.close()  # crash: prepared block must vanish, committed must survive

    st2 = storage_factory("crash")
    assert st2.get("t", b"a") == b"1"
    assert st2.get("t", b"b") == b"2"
    assert st2.get("t", b"c") is None
    st2.close()


@pytest.mark.skipif(not native.available(), reason="no native toolchain")
def test_native_flush_and_sst_reads(tmp_path):
    st = native.NativeStorage(str(tmp_path / "flush"), flush_bytes=1 << 10)
    for i in range(200):  # > 1KiB total -> forces SST flushes
        st.set("t", b"key%03d" % i, b"val%03d" % i)
    st.remove("t", b"key100")
    st.flush()
    assert st.get("t", b"key007") == b"val007"
    assert st.get("t", b"key100") is None
    st.close()
    # reopen reads from SSTs (WAL truncated by flush)
    st2 = native.NativeStorage(str(tmp_path / "flush"))
    assert st2.get("t", b"key199") == b"val199"
    assert st2.get("t", b"key100") is None
    assert len(list(st2.keys("t", b"key19"))) == 10
    st2.close()
