"""KeyPageStorage: row semantics over a paged backend + split/2PC checks."""

import pytest

from fisco_bcos_tpu.storage.interface import Entry, EntryStatus
from fisco_bcos_tpu.storage.keypage import (
    KeyPageStorage, META_KEY, PAGE_PREFIX)
from fisco_bcos_tpu.storage.wal import WalStorage


@pytest.fixture
def kp(tmp_path):
    return KeyPageStorage(WalStorage(str(tmp_path / "kv")), page_size=256)


def test_row_semantics(kp):
    assert kp.get("t", b"a") is None
    kp.set("t", b"m", b"1")
    kp.set("t", b"a", b"2")  # extends page range downward
    kp.set("t", b"z", b"3")
    assert kp.get("t", b"a") == b"2"
    assert kp.get("t", b"m") == b"1"
    kp.remove("t", b"m")
    assert kp.get("t", b"m") is None
    assert list(kp.keys("t")) == [b"a", b"z"]


def test_page_split_and_backend_shape(kp):
    # small page_size forces splits; rows must stay addressable
    for i in range(40):
        kp.set("t", b"k%02d" % i, b"v" * 20)
    for i in range(40):
        assert kp.get("t", b"k%02d" % i) == b"v" * 20
    # the backend sees pages + meta, not 40 rows
    backend_keys = list(kp.backend.keys("t"))
    assert META_KEY in backend_keys
    pages = [k for k in backend_keys if k.startswith(PAGE_PREFIX)]
    assert 1 < len(pages) < 40
    assert list(kp.keys("t", b"k1")) == [b"k%02d" % i for i in range(10, 20)]


def test_2pc_translate(kp):
    kp.set("t", b"a", b"0")
    cs = {("t", b"b"): Entry(b"1"),
          ("t", b"a"): Entry(b"", EntryStatus.DELETED)}
    kp.prepare(5, cs)
    assert kp.get("t", b"b") is None  # not visible pre-commit
    kp.commit(5)
    assert kp.get("t", b"b") == b"1"
    assert kp.get("t", b"a") is None
    kp.prepare(6, {("t", b"c"): Entry(b"2")})
    kp.rollback(6)
    assert kp.get("t", b"c") is None


def test_persistence_across_reopen(tmp_path):
    st = WalStorage(str(tmp_path / "kv"))
    kp = KeyPageStorage(st, page_size=128)
    for i in range(30):
        kp.set("t", b"p%02d" % i, b"x%d" % i)
    kp.prepare(1, {("t", b"zz"): Entry(b"last")})
    kp.commit(1)
    kp.close()

    kp2 = KeyPageStorage(WalStorage(str(tmp_path / "kv")), page_size=128)
    assert kp2.get("t", b"p07") == b"x7"
    assert kp2.get("t", b"zz") == b"last"
    assert len(list(kp2.keys("t", b"p"))) == 30
    kp2.close()
