"""KeyPageStorage: row semantics over a paged backend + split/2PC checks."""

import pytest

from fisco_bcos_tpu.storage.interface import Entry, EntryStatus
from fisco_bcos_tpu.storage.keypage import (
    KeyPageStorage, META_KEY, PAGE_PREFIX)
from fisco_bcos_tpu.storage.wal import WalStorage


@pytest.fixture
def kp(tmp_path):
    return KeyPageStorage(WalStorage(str(tmp_path / "kv")), page_size=256)


def test_row_semantics(kp):
    assert kp.get("t", b"a") is None
    kp.set("t", b"m", b"1")
    kp.set("t", b"a", b"2")  # extends page range downward
    kp.set("t", b"z", b"3")
    assert kp.get("t", b"a") == b"2"
    assert kp.get("t", b"m") == b"1"
    kp.remove("t", b"m")
    assert kp.get("t", b"m") is None
    assert list(kp.keys("t")) == [b"a", b"z"]


def test_page_split_and_backend_shape(kp):
    # small page_size forces splits; rows must stay addressable
    for i in range(40):
        kp.set("t", b"k%02d" % i, b"v" * 20)
    for i in range(40):
        assert kp.get("t", b"k%02d" % i) == b"v" * 20
    # the backend sees pages + meta, not 40 rows
    backend_keys = list(kp.backend.keys("t"))
    assert META_KEY in backend_keys
    pages = [k for k in backend_keys if k.startswith(PAGE_PREFIX)]
    assert 1 < len(pages) < 40
    assert list(kp.keys("t", b"k1")) == [b"k%02d" % i for i in range(10, 20)]


def test_2pc_translate(kp):
    kp.set("t", b"a", b"0")
    cs = {("t", b"b"): Entry(b"1"),
          ("t", b"a"): Entry(b"", EntryStatus.DELETED)}
    kp.prepare(5, cs)
    assert kp.get("t", b"b") is None  # not visible pre-commit
    kp.commit(5)
    assert kp.get("t", b"b") == b"1"
    assert kp.get("t", b"a") is None
    kp.prepare(6, {("t", b"c"): Entry(b"2")})
    kp.rollback(6)
    assert kp.get("t", b"c") is None


def test_range_scan_reads_one_page(tmp_path):
    """The property the page layout exists for: a `keys(prefix)` range
    scan over rows co-resident in one page costs ONE backend page read,
    not a per-row walk — and pages past the prefix range are never read."""
    kp = KeyPageStorage(WalStorage(str(tmp_path / "kv")), page_size=4096)
    for i in range(64):
        kp.set("t", b"acct%04d" % i, b"balance-%d" % i)
    for i in range(64):
        kp.set("other", b"x%04d" % i, b"y")
    kp.flush_caches()
    base = kp.stats()["backend_reads"]
    got = list(kp.keys("t", b"acct001"))
    assert got == [b"acct%04d" % i for i in range(10, 20)]
    reads = kp.stats()["backend_reads"] - base
    # meta row + the page(s) covering the prefix range; with a 4KB page
    # the 10 matching rows share one page -> 2 backend reads total
    assert reads <= 2, f"range scan cost {reads} backend reads"
    # the cached page serves the next scan with ZERO backend reads
    base = kp.stats()["backend_reads"]
    assert list(kp.keys("t", b"acct001")) == got
    assert kp.stats()["backend_reads"] == base
    kp.close()


def test_point_get_reads_one_page(tmp_path):
    kp = KeyPageStorage(WalStorage(str(tmp_path / "kv")), page_size=2048)
    for i in range(100):
        kp.set("t", b"row%04d" % i, b"v" * 40)
    kp.flush_caches()
    base = kp.stats()["backend_reads"]
    assert kp.get("t", b"row0042") == b"v" * 40
    assert kp.stats()["backend_reads"] - base <= 2  # meta + one page
    kp.close()


def test_tables_passthrough(kp):
    kp.set("t", b"a", b"1")
    kp.set("u", b"b", b"2")
    assert kp.tables() == ["t", "u"]


def test_keypage_over_disk_engine(tmp_path):
    """The engine's value layout for wide tables ([storage] key_page_size):
    row semantics over DiskStorage, surviving flush+compaction+reopen."""
    from fisco_bcos_tpu.storage.engine import DiskStorage

    st = DiskStorage(str(tmp_path / "db"), memtable_bytes=1 << 20,
                     auto_compact=False)
    kp = KeyPageStorage(st, page_size=1024)
    for i in range(80):
        kp.set("wide", b"w%04d" % i, b"v%d" % i)
    kp.prepare(1, {("wide", b"tx-row"): Entry(b"committed"),
                   ("wide", b"w0005"): Entry(b"", EntryStatus.DELETED)})
    kp.commit(1)
    st.flush()
    st.compact_once()
    kp.flush_caches()
    assert kp.get("wide", b"w0004") == b"v4"
    assert kp.get("wide", b"w0005") is None
    assert kp.get("wide", b"tx-row") == b"committed"
    assert len(list(kp.keys("wide", b"w00"))) == 79  # 80 rows - 1 deleted
    kp.close()

    st2 = DiskStorage(str(tmp_path / "db"), auto_compact=False)
    kp2 = KeyPageStorage(st2, page_size=1024)
    assert kp2.get("wide", b"w0042") == b"v42"
    assert kp2.get("wide", b"w0005") is None
    # the engine sees pages, not rows: far fewer backend keys than rows
    backend_keys = list(st2.keys("wide"))
    assert META_KEY in backend_keys
    assert len(backend_keys) < 40
    kp2.close()


def test_make_storage_wires_keypage(tmp_path):
    from fisco_bcos_tpu.storage import make_storage
    from fisco_bcos_tpu.storage.engine import DiskStorage

    st = make_storage("disk", str(tmp_path / "db"), key_page_size=2048)
    assert isinstance(st, KeyPageStorage)
    assert isinstance(st.backend, DiskStorage)
    st.set("t", b"k", b"v")
    assert st.get("t", b"k") == b"v"
    st.close()


def test_persistence_across_reopen(tmp_path):
    st = WalStorage(str(tmp_path / "kv"))
    kp = KeyPageStorage(st, page_size=128)
    for i in range(30):
        kp.set("t", b"p%02d" % i, b"x%d" % i)
    kp.prepare(1, {("t", b"zz"): Entry(b"last")})
    kp.commit(1)
    kp.close()

    kp2 = KeyPageStorage(WalStorage(str(tmp_path / "kv")), page_size=128)
    assert kp2.get("t", b"p07") == b"x7"
    assert kp2.get("t", b"zz") == b"last"
    assert len(list(kp2.keys("t", b"p"))) == 30
    kp2.close()
