"""Generic DAG critical-field analysis (VERDICT r3 #7).

Reference counterpart: bcos-executor/src/dag/CriticalFields.h:45-60 —
conflict keys derived generically from parallel-contract annotations.
Here: every precompile self-describes via Precompile.conflict_keys, and
EVM contracts opt in with a `"parallel": N` ABI annotation. Mixed blocks
must plan into parallel waves, and the DAG schedule must equal the
serial schedule bit-for-bit.
"""

import json

from fisco_bcos_tpu.codec import abi as abi_mod
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.executor.executor import TransactionExecutor
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage

SUITE = make_suite(backend="host")


def make_tx(suite, kp, to, input_, nonce):
    return Transaction(to=to, input=input_, nonce=nonce,
                       block_limit=100).sign(suite, kp)


def fresh():
    ex = TransactionExecutor(SUITE)
    st = StateStorage(MemoryStorage())
    kp = SUITE.generate_keypair(b"dag-criticals")
    return ex, st, kp


def balance_tx(kp, nonce, method, *args):
    def build(w):
        for a in args:
            w.blob(a) if isinstance(a, bytes) else w.u64(a)
    return make_tx(SUITE, kp, pc.BALANCE_ADDRESS,
                   pc.encode_call(method, build), nonce)


def kv_tx(kp, nonce, table, key, value):
    return make_tx(SUITE, kp, pc.KV_TABLE_ADDRESS,
                   pc.encode_call("set", lambda w: (w.text(table),
                                                    w.blob(key),
                                                    w.blob(value))), nonce)


def test_disjoint_precompile_txs_one_wave():
    ex, st, kp = fresh()
    txs = [balance_tx(kp, f"r{i}", "register", b"acct%d" % i, 100)
           for i in range(4)]
    txs += [kv_tx(kp, f"k{i}", "t1", b"key%d" % i, b"v") for i in range(3)]
    waves = ex.plan_dag(txs, st)
    assert len(waves) == 1 and sorted(waves[0]) == list(range(7))


def test_conflicting_transfers_chain_waves():
    ex, st, kp = fresh()
    # A->B, B->C (conflict on B), D->E (independent)
    txs = [balance_tx(kp, "t1", "transfer", b"A", b"B", 1),
           balance_tx(kp, "t2", "transfer", b"B", b"C", 1),
           balance_tx(kp, "t3", "transfer", b"D", b"E", 1)]
    waves = ex.plan_dag(txs, st)
    assert len(waves) == 2
    assert sorted(waves[0]) == [0, 2] and waves[1] == [1]


def test_opaque_tx_is_a_barrier():
    ex, st, kp = fresh()
    opaque = make_tx(SUITE, kp, b"\x77" * 20, b"\x01\x02", "op")
    txs = [balance_tx(kp, "b1", "register", b"X", 1),
           opaque,
           balance_tx(kp, "b2", "register", b"Y", 1)]
    waves = ex.plan_dag(txs, st)
    assert waves == [[0], [1], [2]]


PARALLEL_ABI = json.dumps([{
    "type": "function", "name": "setAcct",
    "inputs": [{"type": "uint256"}, {"type": "uint256"}],
    "parallel": 1,
}])

# setAcct(uint256 slot, uint256 value): SSTORE(slot, value)
SET_ACCT_CODE = bytes([0x60, 36, 0x35,   # PUSH1 36 CALLDATALOAD (value)
                       0x60, 4, 0x35,    # PUSH1 4  CALLDATALOAD (slot)
                       0x55, 0x00])      # SSTORE STOP


def evm_tx(kp, nonce, contract, slot, value):
    data = abi_mod.encode_call("setAcct(uint256,uint256)", [slot, value],
                               SUITE.hash)
    return make_tx(SUITE, kp, contract, data, nonce)


def test_evm_parallel_annotation_waves_and_determinism():
    ex, st, kp = fresh()
    contract = b"\x55" * 20
    st.set("s_code", contract, SET_ACCT_CODE)
    st.set(ex.T_ABI, contract, PARALLEL_ABI.encode())
    # slots 1,2,3 disjoint; second write to slot 1 conflicts
    txs = [evm_tx(kp, "e1", contract, 1, 10),
           evm_tx(kp, "e2", contract, 2, 20),
           evm_tx(kp, "e3", contract, 3, 30),
           evm_tx(kp, "e4", contract, 1, 40)]
    waves = ex.plan_dag(txs, st)
    assert len(waves) == 2
    assert sorted(waves[0]) == [0, 1, 2] and waves[1] == [3]

    # same calldata WITHOUT the annotation: opaque singleton waves
    st2 = StateStorage(MemoryStorage())
    st2.set("s_code", contract, SET_ACCT_CODE)
    assert ex.plan_dag(txs, st2) == [[0], [1], [2], [3]]


def test_mixed_block_dag_equals_serial():
    """Determinism: the wave schedule must produce identical receipts and
    state as strict serial execution, on a block mixing annotated EVM,
    precompiles and an opaque barrier."""
    contract = b"\x55" * 20

    def build_block(ex, st, kp):
        st.set("s_code", contract, SET_ACCT_CODE)
        st.set(ex.T_ABI, contract, PARALLEL_ABI.encode())
        txs = [balance_tx(kp, "r1", "register", b"A", 100),
               balance_tx(kp, "r2", "register", b"B", 50),
               evm_tx(kp, "e1", contract, 7, 70),
               balance_tx(kp, "t1", "transfer", b"A", b"B", 10),
               evm_tx(kp, "e2", contract, 8, 80),
               kv_tx(kp, "k1", "t2", b"k", b"v1"),
               evm_tx(kp, "e3", contract, 7, 71),
               balance_tx(kp, "t2", "transfer", b"B", b"A", 5)]
        return txs

    ex1, st1, kp = fresh()
    txs = build_block(ex1, st1, kp)
    dag_receipts = ex1.execute_block_dag(txs, st1, 1, 0)

    ex2, st2, _ = fresh()
    build_block(ex2, st2, kp)
    serial_receipts = [ex2.execute_transaction(t, st2, 1, 0) for t in txs]

    assert [(r.status, r.gas_used, r.output) for r in dag_receipts] == \
        [(r.status, r.gas_used, r.output) for r in serial_receipts]
    assert sorted(st1.changeset().items()) == sorted(st2.changeset().items())


def test_parallel_wave_execution_equals_serial():
    """Thread-pooled wave execution (per-tx overlays merged back) must be
    bit-identical to workers=1 serial execution — receipts AND state."""
    contract = b"\x55" * 20

    def build(ex, st, kp):
        st.set("s_code", contract, SET_ACCT_CODE)
        st.set(ex.T_ABI, contract, PARALLEL_ABI.encode())
        txs = [balance_tx(kp, f"pr{i}", "register", b"P%d" % i, 100)
               for i in range(6)]
        txs += [evm_tx(kp, f"pe{i}", contract, i + 1, i * 10)
                for i in range(6)]
        txs += [balance_tx(kp, "pt", "transfer", b"P0", b"P1", 5)]
        return txs

    results = []
    for workers in (1, 4):
        ex, st, kp = fresh()
        txs = build(ex, st, kp)
        rcs = ex.execute_block_dag(txs, st, 1, 0, workers=workers)
        results.append((
            [(r.status, r.gas_used, r.output) for r in rcs],
            sorted(st.changeset().items()),
        ))
    assert results[0] == results[1]


def test_create_table_then_set_same_block():
    """createTable must act as a barrier: a set to the just-created table
    later in the same block sees it, parallel or serial."""
    for workers in (1, 4):
        ex, st, kp = fresh()
        txs = [make_tx(SUITE, kp, pc.KV_TABLE_ADDRESS,
                       pc.encode_call("createTable",
                                      lambda w: w.text("tnew")), "ct"),
               kv_tx(kp, "cs1", "tnew", b"k1", b"v1"),
               kv_tx(kp, "cs2", "tnew", b"k2", b"v2")]
        rcs = ex.execute_block_dag(txs, st, 1, 0, workers=workers)
        assert [r.status for r in rcs] == [0, 0, 0], \
            [(r.status, r.message) for r in rcs]
        assert st.get("u_tnew", b"k1") == b"v1"
        waves = ex.plan_dag(txs, st)
        assert waves[0] == [0]  # createTable is a barrier wave
