"""AMOP pub/sub + event subscription tests (multi-node over FakeGateway)."""

import threading
import time

from fisco_bcos_tpu.net.amop import AMOPService
from fisco_bcos_tpu.net.front import FrontService
from fisco_bcos_tpu.net.gateway import FakeGateway
from fisco_bcos_tpu.rpc.eventsub import EventFilter, EventSub
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.executor import precompiled as pc


def _amop_net(n):
    gw = FakeGateway()
    fronts = [FrontService(bytes([i + 1]) * 32, gw) for i in range(n)]
    services = [AMOPService(f) for f in fronts]
    time.sleep(0.1)  # let announcements drain
    return gw, fronts, services


def test_amop_announce_and_publish():
    gw, fronts, svcs = _amop_net(3)
    got = []

    def handler(topic, data, src):
        got.append((topic, data))
        return b"reply:" + data

    svcs[1].subscribe("weather", handler)
    deadline = time.time() + 5
    while not svcs[0].peer_subscribers("weather") and time.time() < deadline:
        time.sleep(0.02)
    assert svcs[0].peer_subscribers("weather") == [fronts[1].node_id]

    resp = svcs[0].publish("weather", b"sunny?")
    assert resp == b"reply:sunny?"
    assert got == [("weather", b"sunny?")]

    svcs[1].unsubscribe("weather")
    deadline = time.time() + 5
    while svcs[0].peer_subscribers("weather") and time.time() < deadline:
        time.sleep(0.02)
    assert svcs[0].publish("weather", b"again", timeout=0.5) is None
    gw.stop()


def test_amop_broadcast():
    gw, fronts, svcs = _amop_net(3)
    hits = []
    ev = threading.Event()

    def mk(i):
        def h(topic, data, src):
            hits.append((i, data))
            if len(hits) >= 2:
                ev.set()
            return None
        return h

    svcs[1].subscribe("news", mk(1))
    svcs[2].subscribe("news", mk(2))
    deadline = time.time() + 5
    while len(svcs[0].peer_subscribers("news")) < 2 and time.time() < deadline:
        time.sleep(0.02)
    n = svcs[0].broadcast("news", b"flash")
    assert n == 2
    assert ev.wait(5)
    assert sorted(hits) == [(1, b"flash"), (2, b"flash")]
    gw.stop()


def test_eventsub_live_and_historical(tmp_path):
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0))
    node.start()
    kp = node.suite.generate_keypair(b"evt-user")

    def send(nonce, inp):
        tx = Transaction(to=pc.BALANCE_ADDRESS, input=inp, nonce=nonce,
                         block_limit=node.ledger.current_number() + 100
                         ).sign(node.suite, kp)
        r = node.send_transaction(tx)
        rc = node.txpool.wait_for_receipt(r.tx_hash, 15)
        assert rc is not None and rc.status == 0, (rc and rc.message)
        return r.tx_hash

    send("n1", pc.encode_call("register", lambda w: w.blob(b"a").u64(100)))
    send("n2", pc.encode_call("register", lambda w: w.blob(b"b").u64(0)))
    # transfer emits a LogEntry with topic b"transfer"
    send("n3", pc.encode_call("transfer",
                              lambda w: w.blob(b"a").blob(b"b").u64(7)))

    # historical subscription sees the past transfer
    seen = []
    flt = EventFilter(from_block=0, addresses={pc.BALANCE_ADDRESS},
                      topics=[{b"transfer"}])
    node.eventsub.subscribe(flt, lambda n, h, i, log: seen.append(log.data))
    assert len(seen) == 1

    # live: a new transfer is pushed on commit
    send("n4", pc.encode_call("transfer",
                              lambda w: w.blob(b"a").blob(b"b").u64(5)))
    deadline = time.time() + 10
    while len(seen) < 2 and time.time() < deadline:
        time.sleep(0.02)
    assert len(seen) == 2

    # bounded range auto-completes and unsubscribes
    done = []
    fid = node.eventsub.subscribe(
        EventFilter(from_block=0, to_block=node.ledger.current_number(),
                    topics=[{b"transfer"}]),
        lambda n, h, i, log: done.append(n))
    assert len(done) == 2
    assert fid not in node.eventsub.active()

    node.stop()
