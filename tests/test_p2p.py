"""PBFT consensus over the real TCP socket gateway (net.p2p).

The socket-path analogue of tests/test_pbft.py — the reference's
bcos-gateway/test/integtests pattern (real sockets, localhost).
"""

import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import ConsensusNode
from fisco_bcos_tpu.net.p2p import P2PGateway
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus


def wait_until(pred, timeout=20.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_four_node_pbft_over_tcp():
    suite = make_suite(backend="host")
    keypairs = [suite.generate_keypair(bytes([i + 40]) * 16)
                for i in range(4)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]

    gateways = [P2PGateway(kp.pub_bytes) for kp in keypairs]
    # full mesh: everyone dials everyone (dedupe keeps one session per pair)
    for i, gw in enumerate(gateways):
        for j, other in enumerate(gateways):
            if i != j:
                gw.add_peer(other.host, other.port)

    nodes = []
    try:
        for kp, gw in zip(keypairs, gateways):
            node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                                   min_seal_time=0.0, view_timeout=5.0),
                        keypair=kp, gateway=gw)
            node.build_genesis(sealers)
            nodes.append(node)
        for node in nodes:
            node.start()

        # sessions come up via the reconnect loops
        assert wait_until(
            lambda: all(len(gw.peers()) == 3 for gw in gateways)), \
            [len(gw.peers()) for gw in gateways]

        kp = suite.generate_keypair(b"tcp-user")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"tcp").u64(9)),
                         nonce="t1", block_limit=100).sign(suite, kp)
        res = nodes[0].send_transaction(tx)
        assert res.status == TransactionStatus.OK

        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes),
            timeout=30.0), [n.ledger.current_number() for n in nodes]
        hashes = {n.ledger.header_by_number(1).hash(suite) for n in nodes}
        assert len(hashes) == 1
        for n in nodes:
            rc = n.ledger.receipt(tx.hash(suite))
            assert rc is not None and rc.status == 0
    finally:
        for node in nodes:
            node.stop()
        for gw in gateways:
            gw.stop()
