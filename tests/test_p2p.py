"""PBFT consensus over the real TCP socket gateway (net.p2p).

The socket-path analogue of tests/test_pbft.py — the reference's
bcos-gateway/test/integtests pattern (real sockets, localhost).
"""

import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import ConsensusNode
from fisco_bcos_tpu.net.p2p import P2PGateway
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus


def wait_until(pred, timeout=20.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_four_node_pbft_over_tcp():
    suite = make_suite(backend="host")
    keypairs = [suite.generate_keypair(bytes([i + 40]) * 16)
                for i in range(4)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]

    gateways = [P2PGateway(kp.pub_bytes) for kp in keypairs]
    # full mesh: everyone dials everyone (dedupe keeps one session per pair)
    for i, gw in enumerate(gateways):
        for j, other in enumerate(gateways):
            if i != j:
                gw.add_peer(other.host, other.port)

    nodes = []
    try:
        for kp, gw in zip(keypairs, gateways):
            node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                                   min_seal_time=0.0, view_timeout=5.0),
                        keypair=kp, gateway=gw)
            node.build_genesis(sealers)
            nodes.append(node)
        for node in nodes:
            node.start()

        # sessions come up via the reconnect loops
        assert wait_until(
            lambda: all(len(gw.peers()) == 3 for gw in gateways)), \
            [len(gw.peers()) for gw in gateways]

        kp = suite.generate_keypair(b"tcp-user")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"tcp").u64(9)),
                         nonce="t1", block_limit=100).sign(suite, kp)
        res = nodes[0].send_transaction(tx)
        assert res.status == TransactionStatus.OK

        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes),
            timeout=30.0), [n.ledger.current_number() for n in nodes]
        hashes = {n.ledger.header_by_number(1).hash(suite) for n in nodes}
        assert len(hashes) == 1
        for n in nodes:
            rc = n.ledger.receipt(tx.hash(suite))
            assert rc is not None and rc.status == 0
    finally:
        for node in nodes:
            node.stop()
        for gw in gateways:
            gw.stop()


def test_multi_hop_routing_compression_line_topology():
    """3-hop line A-B-C-D: the distance-vector router must deliver PBFT
    traffic end to end (RouterTableImpl.cpp semantics) with large frames
    compressed (P2PMessageV2)."""
    suite = make_suite(backend="host")
    keypairs = [suite.generate_keypair(bytes([i + 60]) * 16)
                for i in range(4)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    gateways = [P2PGateway(kp.pub_bytes, compress_threshold=256)
                for kp in keypairs]
    # line topology: only adjacent nodes know each other's addresses
    for i in range(3):
        gateways[i].add_peer(gateways[i + 1].host, gateways[i + 1].port)
        gateways[i + 1].add_peer(gateways[i].host, gateways[i].port)

    nodes = []
    try:
        for kp, gw in zip(keypairs, gateways):
            node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                                   min_seal_time=0.0, view_timeout=8.0),
                        keypair=kp, gateway=gw)
            node.build_genesis(sealers)
            nodes.append(node)
        for node in nodes:
            node.start()

        # every node must see all 3 others as reachable (1 direct + routed)
        assert wait_until(
            lambda: all(len(gw.peers()) == 3 for gw in gateways), 30), \
            [len(gw.peers()) for gw in gateways]
        # ends of the line have ONE session but THREE reachable peers
        assert len(gateways[0]._sessions) == 1
        assert len(gateways[3]._sessions) == 1

        kp = suite.generate_keypair(b"hop-user")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"hop").u64(3)),
                         nonce="hop1", block_limit=100).sign(suite, kp)
        res = nodes[0].send_transaction(tx)
        assert res.status == TransactionStatus.OK
        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes), 30), \
            [n.ledger.current_number() for n in nodes]
        headers = [n.ledger.header_by_number(1) for n in nodes]
        assert len({h.hash(suite) for h in headers}) == 1
    finally:
        for n in nodes:
            n.stop()
        for gw in gateways:
            gw.stop()


def test_peer_acl_allow_and_deny():
    suite = make_suite(backend="host")
    kps = [suite.generate_keypair(bytes([i + 80]) * 16) for i in range(3)]

    class StubFront:
        def __init__(self):
            self.got = []

        def on_network_message(self, src, data):
            self.got.append((src, data))

    # gw0 denies kp1 and allows only kp2
    gw0 = P2PGateway(kps[0].pub_bytes,
                     allow_list={kps[2].pub_bytes},
                     deny_list={kps[1].pub_bytes})
    gw1 = P2PGateway(kps[1].pub_bytes)
    gw2 = P2PGateway(kps[2].pub_bytes)
    fronts = [StubFront() for _ in range(3)]
    try:
        for gw, kp, fr in zip((gw0, gw1, gw2), kps, fronts):
            gw.register_front(kp.pub_bytes, fr)
        gw0.add_peer(gw1.host, gw1.port)
        gw1.add_peer(gw0.host, gw0.port)
        gw0.add_peer(gw2.host, gw2.port)
        gw2.add_peer(gw0.host, gw0.port)

        assert wait_until(lambda: kps[2].pub_bytes in gw0.peers(), 10)
        time.sleep(1.5)  # give the denied link time to (not) form
        assert kps[1].pub_bytes not in gw0.peers()

        # compressed large payload round trip over the allowed link
        blob = b"Z" * 50_000
        assert gw0.send(kps[0].pub_bytes, kps[2].pub_bytes, blob)
        assert wait_until(lambda: len(fronts[2].got) >= 1, 10)
        assert fronts[2].got[0] == (kps[0].pub_bytes, blob)
    finally:
        for gw in (gw0, gw1, gw2):
            gw.stop()


def test_zstd_codec_negotiation():
    """zstd frames are used only when EVERY session negotiated CAP_ZSTD;
    a single legacy peer downgrades the mesh to zlib (no frame loss)."""
    import fisco_bcos_tpu.net.p2p as p2p_mod
    from fisco_bcos_tpu.net.p2p import FLAG_COMPRESSED, FLAG_ZSTD, P2PGateway

    suite = make_suite(backend="host")
    kps = [suite.generate_keypair(bytes([i + 90]) * 16) for i in range(2)]
    gws = [P2PGateway(kp.pub_bytes, compress_threshold=64) for kp in kps]

    class _F:
        def on_network_message(self, src, data):
            pass

    for gw, kp in zip(gws, kps):
        gw.register_front(kp.pub_bytes, _F())
    gws[0].add_peer(gws[1].host, gws[1].port)
    gws[1].add_peer(gws[0].host, gws[0].port)
    try:
        assert wait_until(lambda: all(len(g._sessions) == 1 for g in gws))
        # both sides advertised CAP_ZSTD (zstandard importable here)
        flag, payload = gws[0]._encode_payload(b"z" * 512)
        assert flag == FLAG_ZSTD
        assert p2p_mod._zstd.ZstdDecompressor().decompress(
            payload, max_output_size=1 << 16) == b"z" * 512
        # simulate one legacy peer: clear its negotiated capability
        with gws[0]._lock:
            for s in gws[0]._sessions.values():
                s.caps = 0
            gws[0]._recompute_codec_locked()
        flag, payload = gws[0]._encode_payload(b"z" * 512)
        assert flag == FLAG_COMPRESSED  # zlib fallback, still compressed
    finally:
        for g in gws:
            g.stop()
