"""Metrics registry + Prometheus endpoint tests."""

import urllib.request

from fisco_bcos_tpu.utils.metrics import MetricsRegistry, MetricsServer
from fisco_bcos_tpu.utils.log import metric


def test_registry_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.inc("reqs_total")
    reg.inc("reqs_total", 2)
    reg.set_gauge("height", 42, {"group": "g0"})
    reg.observe("latency_seconds", 0.004)
    reg.observe("latency_seconds", 0.2)
    with reg.timer("timed_seconds"):
        pass
    snap = reg.snapshot()
    assert snap["counters"]["reqs_total"] == 3
    assert snap["gauges"]["height{'group': 'g0'}"] == 42
    assert snap["histograms"]["latency_seconds"]["count"] == 2
    text = reg.prometheus_text()
    assert "# TYPE reqs_total counter" in text
    assert 'height{group="g0"} 42' in text
    assert "latency_seconds_count 2" in text
    assert 'le="+Inf"' in text


def test_metric_feeds_default_registry():
    from fisco_bcos_tpu.utils.metrics import REGISTRY
    before = REGISTRY.snapshot()["counters"].get("bcos_test_evt_total", 0)
    metric("test.evt", ms=12, n=5)
    snap = REGISTRY.snapshot()
    assert snap["counters"]["bcos_test_evt_total"] == before + 1
    assert snap["gauges"]["bcos_test_evt_n"] == 5
    assert snap["histograms"]["bcos_test_evt_seconds"]["count"] >= 1


def test_metrics_http_endpoint():
    reg = MetricsRegistry()
    reg.inc("up")
    srv = MetricsServer(reg, port=0)
    srv.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as f:
            body = f.read().decode()
        assert "up 1.0" in body
    finally:
        srv.stop()


def test_prometheus_single_type_line_per_name():
    reg = MetricsRegistry()
    reg.inc("rpc_total", labels={"method": "a"})
    reg.inc("rpc_total", labels={"method": "b"})
    text = reg.prometheus_text()
    assert text.count("# TYPE rpc_total counter") == 1
    assert 'rpc_total{method="a"}' in text and 'rpc_total{method="b"}' in text


def test_election_and_shard_gauges(tmp_path):
    """The Max components publish operator gauges to the shared registry
    (same plane the /metrics endpoint scrapes)."""
    from fisco_bcos_tpu.ha.quorum import (LeaseRegistryServer,
                                          QuorumLeaseElection)
    from fisco_bcos_tpu.utils.metrics import REGISTRY

    regs = [LeaseRegistryServer() for _ in range(3)]
    for r in regs:
        r.start()
    el = QuorumLeaseElection([("127.0.0.1", r.port) for r in regs],
                             "metrics-node", lease_ttl=1.0, heartbeat=0.2,
                             rpc_timeout=0.5)
    el.start()
    try:
        import time as _t
        deadline = _t.time() + 15
        while not el.is_leader() and _t.time() < deadline:
            _t.sleep(0.05)
        assert el.is_leader()
        text = REGISTRY.prometheus_text()
        assert 'bcos_election_is_leader{member="metrics-node"} 1' in text
        assert 'bcos_election_fence{member="metrics-node"}' in text
    finally:
        el.stop()
        for r in regs:
            r.stop()
    text = REGISTRY.prometheus_text()
    assert 'bcos_election_is_leader{member="metrics-node"} 0' in text
    # shard-plane series: drive one commit through a local cluster
    from fisco_bcos_tpu.storage.interface import Entry
    from fisco_bcos_tpu.storage.sharded import (DurablePrepareStorage,
                                                ShardedStorage)
    from fisco_bcos_tpu.storage.wal import WalStorage

    shards = [DurablePrepareStorage(WalStorage(str(tmp_path / f"g{i}/w")),
                                    str(tmp_path / f"g{i}/p"))
              for i in range(2)]
    st = ShardedStorage(shards)
    st.prepare(1, {("t", b"k"): Entry(b"v")})
    st.commit(1)
    st.close()
    text = REGISTRY.prometheus_text()
    assert "bcos_shard_commits" in text
    assert "bcos_shard_unresolved_blocks 0" in text
