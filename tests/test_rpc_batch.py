"""JSON-RPC 2.0 batch semantics + the event-loop edge's HTTP behaviors.

Covers the spec shapes (mixed valid/invalid entries with per-id error
objects, empty batch, parse error, notifications, order preservation)
over BOTH transports (HTTP and WS share JsonRpcImpl.handle_payload), and
the rpc/edge.py serving properties: keep-alive connection reuse and
request pipelining with in-order responses.
"""

import http.client
import json
import socket

import pytest

from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.net.websocket import ws_connect
from fisco_bcos_tpu.sdk.client import SdkClient


@pytest.fixture(scope="module")
def batch_node():
    n = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                        rpc_port=0, ws_port=0))
    n.start()
    yield n
    n.stop()


def _post_raw(node, body: bytes, extra_headers: str = "") -> bytes:
    """One raw POST, returns the response body bytes."""
    conn = http.client.HTTPConnection(node.rpc.host, node.rpc.port,
                                      timeout=30)
    try:
        conn.request("POST", "/", body=body,
                     headers={"Content-Type": "application/json"})
        return conn.getresponse().read()
    finally:
        conn.close()


def test_batch_mixed_entries_per_id_errors(batch_node):
    """Valid + unknown-method + non-dict + notification + bad params in
    ONE batch: per-entry verdicts, response order matches request order,
    the notification is absent from the response."""
    payload = [
        {"jsonrpc": "2.0", "id": 1, "method": "getBlockNumber",
         "params": ["group0", ""]},
        {"jsonrpc": "2.0", "id": 2, "method": "noSuchMethod", "params": []},
        42,  # not a request object at all
        {"jsonrpc": "2.0", "method": "getBlockNumber",
         "params": ["group0", ""]},  # notification: no id -> no response
        {"jsonrpc": "2.0", "id": 3, "method": "getBlockNumber",
         "params": ["wrong-group", ""]},
    ]
    out = json.loads(_post_raw(batch_node, json.dumps(payload).encode()))
    assert isinstance(out, list) and len(out) == 4
    assert out[0]["id"] == 1 and out[0]["result"] >= 0
    assert out[1]["id"] == 2 and out[1]["error"]["code"] == -32601
    assert out[2]["id"] is None and out[2]["error"]["code"] == -32600
    assert out[3]["id"] == 3 and "error" in out[3]
    assert [r.get("id") for r in out] == [1, 2, None, 3]


def test_empty_batch_is_single_error(batch_node):
    out = json.loads(_post_raw(batch_node, b"[]"))
    assert isinstance(out, dict)
    assert out["error"]["code"] == -32600 and out["id"] is None


def test_oversized_batch_rejected(batch_node):
    cap = batch_node.config.rpc_max_batch
    payload = [{"jsonrpc": "2.0", "id": i, "method": "getBlockNumber",
                "params": ["group0", ""]} for i in range(cap + 1)]
    out = json.loads(_post_raw(batch_node, json.dumps(payload).encode()))
    assert isinstance(out, dict) and out["error"]["code"] == -32600


def test_parse_error(batch_node):
    out = json.loads(_post_raw(batch_node, b"{not json"))
    assert out["error"]["code"] == -32700 and out["id"] is None


def test_all_notifications_empty_body(batch_node):
    payload = [
        {"jsonrpc": "2.0", "method": "getBlockNumber",
         "params": ["group0", ""]},
        {"jsonrpc": "2.0", "method": "getPendingTxSize",
         "params": ["group0", ""]},
    ]
    assert _post_raw(batch_node, json.dumps(payload).encode()) == b""
    # single notification too
    assert _post_raw(batch_node, json.dumps(payload[0]).encode()) == b""


def test_sdk_request_batch_roundtrip(batch_node):
    sdk = SdkClient(f"http://{batch_node.rpc.host}:{batch_node.rpc.port}")
    resps = sdk.request_batch([
        ("getBlockNumber", ["group0", ""]),
        ("getGroupList", []),
        ("noSuchMethod", []),
    ])
    assert len(resps) == 3
    assert resps[0]["result"] >= 0
    assert resps[1]["result"]["groupList"] == ["group0"]
    assert resps[2]["error"]["code"] == -32601


def test_keepalive_connection_reuse(batch_node):
    """Many sequential requests on ONE persistent connection."""
    conn = http.client.HTTPConnection(batch_node.rpc.host,
                                      batch_node.rpc.port, timeout=30)
    try:
        for i in range(16):
            body = json.dumps({"jsonrpc": "2.0", "id": i,
                               "method": "getBlockNumber",
                               "params": ["group0", ""]}).encode()
            conn.request("POST", "/", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            out = json.loads(resp.read())
            assert out["id"] == i and not resp.will_close
    finally:
        conn.close()


def test_pipelined_requests_answered_in_order(batch_node):
    """Two POSTs written back-to-back before reading either response:
    the edge must answer both, in request order, on one connection."""
    reqs = b""
    for i in (101, 102):
        body = json.dumps({"jsonrpc": "2.0", "id": i,
                           "method": "getBlockNumber",
                           "params": ["group0", ""]}).encode()
        reqs += (b"POST / HTTP/1.1\r\nHost: x\r\n"
                 b"Content-Type: application/json\r\n"
                 b"Content-Length: " + str(len(body)).encode() +
                 b"\r\n\r\n" + body)
    sock = socket.create_connection(
        (batch_node.rpc.host, batch_node.rpc.port), timeout=30)
    try:
        sock.sendall(reqs)
        buf = b""
        bodies = []
        while len(bodies) < 2:
            chunk = sock.recv(65536)
            assert chunk, "edge closed mid-pipeline"
            buf += chunk
            while b"\r\n\r\n" in buf:
                head, rest = buf.split(b"\r\n\r\n", 1)
                length = int([ln.split(b":")[1] for ln in head.split(b"\r\n")
                              if ln.lower().startswith(b"content-length")][0])
                if len(rest) < length:
                    break
                bodies.append(rest[:length])
                buf = rest[length:]
        assert [json.loads(b)["id"] for b in bodies] == [101, 102]
    finally:
        sock.close()


def test_connection_close_honored(batch_node):
    """Connection: close -> the edge answers, then closes the socket."""
    body = json.dumps({"jsonrpc": "2.0", "id": 7,
                       "method": "getBlockNumber",
                       "params": ["group0", ""]}).encode()
    sock = socket.create_connection(
        (batch_node.rpc.host, batch_node.rpc.port), timeout=30)
    try:
        sock.sendall(b"POST / HTTP/1.1\r\nHost: x\r\nConnection: close\r\n"
                     b"Content-Type: application/json\r\n"
                     b"Content-Length: " + str(len(body)).encode() +
                     b"\r\n\r\n" + body)
        data = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            data += chunk
        head, payload = data.split(b"\r\n\r\n", 1)
        assert b"Connection: close" in head
        assert json.loads(payload)["id"] == 7
    finally:
        sock.close()


def test_batch_budget_bounds_worker_time(monkeypatch):
    """A batch whose entries block must stop executing once the payload
    budget is spent: remaining entries get per-id -32000 errors (order
    preserved, notifications silent) so the shared-pool worker returns."""
    import time as _time

    from fisco_bcos_tpu.rpc import server as srv

    monkeypatch.setattr(srv, "BATCH_BUDGET_SECONDS", 0.2)

    class SlowImpl:
        def handle(self, req):
            _time.sleep(0.15)
            return {"jsonrpc": "2.0", "id": req.get("id"), "result": "ok"}

    payload = [{"jsonrpc": "2.0", "id": i, "method": "m", "params": []}
               for i in range(5)]
    t0 = _time.monotonic()
    out = srv.handle_payload_with(SlowImpl(), payload)
    assert _time.monotonic() - t0 < 1.0  # nowhere near 5 * 0.15 + slack
    assert [r["id"] for r in out] == list(range(5))
    exhausted = [r for r in out if "error" in r]
    assert exhausted and all(
        r["error"]["message"] == "batch budget exhausted" for r in exhausted)
    assert any("result" in r for r in out)  # early entries did execute


def test_negative_content_length_rejected(batch_node):
    """A negative Content-Length must be answered 400 and the connection
    closed — not re-parsed forever (it would un-consume rbuf and spin the
    event loop)."""
    sock = socket.create_connection(
        (batch_node.rpc.host, batch_node.rpc.port), timeout=10)
    try:
        sock.sendall(b"POST / HTTP/1.1\r\nHost: x\r\n"
                     b"Content-Length: -999999\r\n\r\n")
        data = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            data += chunk
        assert data.startswith(b"HTTP/1.1 400"), data[:80]
    finally:
        sock.close()
    # the edge survived: a normal request still works
    out = json.loads(_post_raw(batch_node, json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": "getBlockNumber",
         "params": ["group0", ""]}).encode()))
    assert out["result"] >= 0


def test_ws_request_without_method_gets_error(batch_node):
    """An id-carrying WS frame with no \"method\" is answered with a
    -32600 error (not silently dropped, which would hang the client)."""
    conn = ws_connect(batch_node.config.rpc_host, batch_node.ws.port)
    try:
        conn.send_text(json.dumps({"jsonrpc": "2.0", "id": 5,
                                   "params": []}))
        _op, data = conn.recv()
        out = json.loads(data)
        assert out["id"] == 5 and out["error"]["code"] == -32600
    finally:
        conn.close()


def test_nondraining_connection_reaped():
    """A peer that sends requests but never reads responses must be
    reaped after keepalive_s of zero write progress — not pin an fd and
    its outbuf forever."""
    import time as _time

    from fisco_bcos_tpu.rpc.edge import EventLoopHttpServer

    # responses far larger than the kernel socket buffer, so the server's
    # sends stall and outbuf stays nonempty (exercising the stalled-WRITE
    # reap, not the idle reap)
    srv = EventLoopHttpServer(lambda body: b'{"ok": 1}' * (256 * 1024),
                              keepalive_s=0.6)
    srv.start()
    try:
        sock = socket.create_connection((srv.host, srv.port), timeout=10)
        body = b'{"jsonrpc": "2.0", "id": 1}'
        for _ in range(4):
            sock.sendall(b"POST / HTTP/1.1\r\nHost: x\r\n"
                         b"Content-Length: " + str(len(body)).encode() +
                         b"\r\n\r\n" + body)
        # never recv(): responses pile in outbuf server-side (tiny socket
        # buffers aside, last_active stops advancing once sends stall)
        deadline = _time.monotonic() + 8
        while _time.monotonic() < deadline:
            if not srv._conns:
                break
            _time.sleep(0.1)
        assert not srv._conns, "non-draining connection never reaped"
        sock.close()
    finally:
        srv.stop()


def test_ws_batch_parity(batch_node):
    """The SAME batch semantics over the WS transport (one list frame in,
    one list frame out; notifications omitted)."""
    conn = ws_connect(batch_node.config.rpc_host, batch_node.ws.port)
    try:
        payload = [
            {"jsonrpc": "2.0", "id": "a", "method": "getBlockNumber",
             "params": ["group0", ""]},
            {"jsonrpc": "2.0", "id": "b", "method": "noSuchMethod",
             "params": []},
            {"jsonrpc": "2.0", "method": "getBlockNumber",
             "params": ["group0", ""]},  # notification
        ]
        conn.send_text(json.dumps(payload))
        _op, data = conn.recv()
        out = json.loads(data)
        assert isinstance(out, list) and len(out) == 2
        assert out[0]["id"] == "a" and out[0]["result"] >= 0
        assert out[1]["id"] == "b" and out[1]["error"]["code"] == -32601
    finally:
        conn.close()


def test_ws_single_notification_no_response(batch_node):
    """A lone notification over WS gets no reply; a follow-up request on
    the same session is answered normally (the session survives)."""
    conn = ws_connect(batch_node.config.rpc_host, batch_node.ws.port)
    try:
        conn.send_text(json.dumps(
            {"jsonrpc": "2.0", "method": "getBlockNumber",
             "params": ["group0", ""]}))
        conn.send_text(json.dumps(
            {"jsonrpc": "2.0", "id": 9, "method": "getBlockNumber",
             "params": ["group0", ""]}))
        _op, data = conn.recv()
        out = json.loads(data)
        assert out["id"] == 9 and out["result"] >= 0
    finally:
        conn.close()


def test_parse_burst_respects_pipeline_cap(monkeypatch):
    """One recv burst of tiny pipelined requests must not dispatch past
    MAX_PIPELINE: the cap gates the PARSE loop (excess stays in rbuf),
    and parsing resumes as completions free slots — every request is
    still answered, in order."""
    import threading as _threading
    import time as _time

    from fisco_bcos_tpu.rpc import edge as edge_mod
    from fisco_bcos_tpu.rpc.edge import EventLoopHttpServer, WorkerPool

    monkeypatch.setattr(edge_mod, "MAX_PIPELINE", 4)
    gate = _threading.Event()

    class CountingPool(WorkerPool):
        def __init__(self):
            super().__init__(workers=2)
            self.submitted = 0

        def try_submit(self, fn):
            ok = super().try_submit(fn)
            if ok:
                self.submitted += 1
            return ok

    pool = CountingPool()
    pool.start()

    def handler(body: bytes) -> bytes:
        gate.wait(10)
        return body  # echo: response carries the request id

    srv = EventLoopHttpServer(handler, pool=pool)
    srv.start()
    try:
        n = 50
        burst = b"".join(
            b"POST / HTTP/1.1\r\nHost: x\r\nContent-Length: " +
            str(len(b)).encode() + b"\r\n\r\n" + b
            for b in (json.dumps({"id": i}).encode() for i in range(n)))
        sock = socket.create_connection((srv.host, srv.port), timeout=10)
        sock.sendall(burst)  # one buffer: arrives in very few recvs
        _time.sleep(0.5)
        # with the gate held nothing completes, so dispatch depth IS the
        # number of pool submissions — must be capped, not ~n
        assert pool.submitted <= 4, pool.submitted
        gate.set()
        sock.settimeout(15)
        buf = b""
        ids = []
        while len(ids) < n:
            chunk = sock.recv(65536)
            if not chunk:
                break
            buf += chunk
            while True:
                head, sep, rest = buf.partition(b"\r\n\r\n")
                if not sep:
                    break
                clen = int([ln for ln in head.split(b"\r\n")
                            if ln.lower().startswith(b"content-length")
                            ][0].split(b":")[1])
                if len(rest) < clen:
                    break
                ids.append(json.loads(rest[:clen])["id"])
                buf = rest[clen:]
        assert ids == list(range(n))  # all answered, request order
        sock.close()
    finally:
        srv.stop()
        pool.stop()


def test_stop_without_start_releases_listener():
    """stop() on a never-started edge must close the bound listener and
    the selector/wake fds (Node binds the port in __init__; Node.start()
    can raise before rpc.start() — cleanup used to rely on the loop
    thread's exit path). Double-stop stays idempotent."""
    from fisco_bcos_tpu.rpc.edge import EventLoopHttpServer

    srv = EventLoopHttpServer(lambda body: b"{}")
    port = srv.port
    srv.stop()
    assert srv._listener.fileno() == -1
    assert srv._wake_r.fileno() == -1 and srv._wake_w.fileno() == -1
    srv.stop()  # second stop: no-op, no raise
    # the port is actually free again
    relisten = socket.create_server(("127.0.0.1", port))
    relisten.close()


def test_ws_fallback_threads_bounded(batch_node, monkeypatch):
    """When the shared pool can't take a WS dispatch, the one-off-thread
    fallback is BOUNDED: past the cap the frame is shed with the same
    -32000 busy error HTTP answers, not given yet another OS thread."""
    import threading as _threading

    ws = batch_node.ws
    monkeypatch.setattr(ws, "pool", None)  # every _offload hits fallback
    taken = 0
    while ws._fallback.acquire(blocking=False):
        taken += 1
    replies = []

    class FakeSess:
        def push(self, obj):
            replies.append(obj)
            return True

        send_now = push  # shed errors are lossless sends (same capture)

    try:
        ws._offload(lambda s, m: None, FakeSess(),
                    {"id": 7, "method": "x"})
        assert replies and replies[0]["id"] == 7
        assert replies[0]["error"]["code"] == -32000
    finally:
        for _ in range(taken):
            ws._fallback.release()
    # with permits back, the fallback dispatches (and returns its permit)
    ran = _threading.Event()
    ws._offload(lambda s, m: ran.set(), FakeSess(), {"id": 8})
    assert ran.wait(5)


def test_chunked_transfer_encoding_rejected(batch_node):
    """A Transfer-Encoding: chunked POST is answered 411 and the
    connection closed — not treated as a zero-length body with the chunk
    framing misparsed as a pipelined request."""
    sock = socket.create_connection(
        (batch_node.rpc.host, batch_node.rpc.port), timeout=10)
    try:
        sock.sendall(b"POST / HTTP/1.1\r\nHost: x\r\n"
                     b"Transfer-Encoding: chunked\r\n\r\n"
                     b"24\r\n" + b"x" * 0x24 + b"\r\n0\r\n\r\n")
        data = b""
        while True:
            chunk = sock.recv(65536)
            if not chunk:
                break
            data += chunk
        assert data.startswith(b"HTTP/1.1 411"), data[:80]
        assert data.count(b"HTTP/1.1") == 1  # chunk framing NOT re-parsed
    finally:
        sock.close()
    # the edge survived
    out = json.loads(_post_raw(batch_node, json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": "getBlockNumber",
         "params": ["group0", ""]}).encode()))
    assert out["result"] >= 0


def test_ws_shed_keeps_notifications_silent(batch_node, monkeypatch):
    """A notification frame shed at full fallback capacity gets NO reply
    (the id:null busy error would be uncorrelatable to an SDK); an
    id-carrying frame shed in the same state still gets its error."""
    ws = batch_node.ws
    monkeypatch.setattr(ws, "pool", None)
    taken = 0
    while ws._fallback.acquire(blocking=False):
        taken += 1
    replies = []

    class FakeSess:
        def push(self, obj):
            replies.append(obj)
            return True

        send_now = push  # shed errors are lossless sends (same capture)

    try:
        ws._offload(lambda s, m: None, FakeSess(),
                    {"jsonrpc": "2.0", "method": "getBlockNumber",
                     "params": ["group0", ""]})  # notification: no id
        assert replies == []
        ws._offload(lambda s, m: None, FakeSess(),
                    {"jsonrpc": "2.0", "id": 4, "method": "x"})
        assert len(replies) == 1 and replies[0]["id"] == 4
    finally:
        for _ in range(taken):
            ws._fallback.release()


def test_ws_shed_batch_gets_per_id_errors(batch_node, monkeypatch):
    """A batch frame shed at full fallback capacity is answered with
    PER-ID busy errors (notifications and non-dict entries silent) — a
    single id:null error would strand every per-id response waiter."""
    ws = batch_node.ws
    monkeypatch.setattr(ws, "pool", None)
    taken = 0
    while ws._fallback.acquire(blocking=False):
        taken += 1
    replies = []

    class FakeSess:
        def push(self, obj):
            replies.append(obj)
            return True

        send_now = push  # shed errors are lossless sends (same capture)

    try:
        ws._offload(lambda s, m: None, FakeSess(), [
            {"jsonrpc": "2.0", "id": 1, "method": "getBlockNumber",
             "params": ["group0", ""]},
            {"jsonrpc": "2.0", "method": "getBlockNumber",
             "params": ["group0", ""]},  # notification
            "garbage",
            {"jsonrpc": "2.0", "id": 2, "method": "getBlockNumber",
             "params": ["group0", ""]},
        ])
        assert len(replies) == 1 and isinstance(replies[0], list)
        assert [e["id"] for e in replies[0]] == [1, 2]
        assert all(e["error"]["code"] == -32000 for e in replies[0])
    finally:
        for _ in range(taken):
            ws._fallback.release()
