"""EVM interpreter tests — hand-assembled bytecode, no external toolchain.

Covers the executor's VM slot (the reference embeds evmone,
bcos-executor/src/vm/VMFactory.h:46): deploy/call/storage/revert/CALL
family/CREATE2/logs/precompiles/gas accounting.
"""

import numpy as np

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor.evm import EVM, TxEnv, T_STORE
from fisco_bcos_tpu.executor.executor import TransactionExecutor
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage


SUITE = make_suite(backend="host")
SENDER = b"\xaa" * 20


def push(v: int) -> bytes:
    if v == 0:
        return b"\x5f"  # PUSH0
    n = (v.bit_length() + 7) // 8
    return bytes([0x5F + n]) + v.to_bytes(n, "big")


def initcode_for(runtime: bytes) -> bytes:
    """Standard deploy wrapper: CODECOPY the runtime, RETURN it."""
    rt_len = len(runtime)

    def body(off: int) -> bytes:
        # PUSH len, PUSH off, PUSH 0, CODECOPY, PUSH len, PUSH 0, RETURN
        return (push(rt_len) + push(off) + push(0) + b"\x39"
                + push(rt_len) + push(0) + b"\xf3")

    off = len(body(0))
    while len(body(off)) != off:  # fixed point: offset encodes its own length
        off = len(body(off))
    return body(off) + runtime


# runtime: SSTORE(0, CALLDATALOAD(0)); RETURN(SLOAD(0))
STORE_RT = (push(0) + b"\x35"          # CALLDATALOAD(0) -> value
            + push(0) + b"\x55"        # SSTORE(key=0, value)
            + push(0) + b"\x54"        # SLOAD(0)
            + push(0) + b"\x52"        # MSTORE(0, v)
            + push(32) + push(0) + b"\xf3")


def _fresh():
    state = StateStorage(MemoryStorage())
    evm = EVM(SUITE)
    env = TxEnv(origin=SENDER, gas_price=0, block_number=1,
                timestamp=1000_000, gas_limit=10_000_000)
    return state, evm, env


def test_deploy_and_call_storage():
    state, evm, env = _fresh()
    res = evm.create(state, env, SENDER, 0, initcode_for(STORE_RT), 5_000_000)
    assert res.success, res.error
    addr = res.create_address
    assert evm.get_code(state, addr) == STORE_RT

    res2 = evm.execute_message(state, env, SENDER, addr, 0,
                               (0xBEEF).to_bytes(32, "big"), 1_000_000)
    assert res2.success
    assert int.from_bytes(res2.output, "big") == 0xBEEF
    # storage actually written
    assert state.get(T_STORE, addr + (0).to_bytes(32, "big")) == \
        (0xBEEF).to_bytes(32, "big")
    assert res2.gas_left < 1_000_000  # gas was metered


def test_arithmetic_and_comparison():
    state, evm, env = _fresh()
    # RETURN ( (7 + 3) * 5 ) == 50
    rt = (push(3) + push(7) + b"\x01"   # ADD -> 10
          + push(5) + b"\x02"           # MUL -> 50
          + push(0) + b"\x52" + push(32) + push(0) + b"\xf3")
    res = evm._run(state, env, rt, SENDER, b"\x01" * 20, 0, b"", 100000, 0,
                   False)
    assert res.success and int.from_bytes(res.output, "big") == 50


def test_revert_rolls_back_state():
    state, evm, env = _fresh()
    # SSTORE(0, 1) then REVERT("")
    rt = (push(1) + push(0) + b"\x55" + push(0) + push(0) + b"\xfd")
    res = evm.create(state, env, SENDER, 0, initcode_for(rt), 5_000_000)
    addr = res.create_address
    res2 = evm.execute_message(state, env, SENDER, addr, 0, b"", 1_000_000)
    assert not res2.success and res2.error == "revert"
    assert state.get(T_STORE, addr + (0).to_bytes(32, "big")) is None


def test_out_of_gas_and_bad_jump():
    state, evm, env = _fresh()
    # infinite loop: JUMPDEST; PUSH 0; JUMP
    rt = b"\x5b" + push(0) + b"\x56"
    res = evm._run(state, env, rt, SENDER, b"\x01" * 20, 0, b"", 10_000, 0,
                   False)
    assert not res.success and res.error == "out of gas"
    # jump to non-JUMPDEST
    rt2 = push(1) + b"\x56"
    res2 = evm._run(state, env, rt2, SENDER, b"\x01" * 20, 0, b"", 10_000, 0,
                    False)
    assert not res2.success and "jump" in res2.error


def test_inter_contract_call():
    state, evm, env = _fresh()
    res = evm.create(state, env, SENDER, 0, initcode_for(STORE_RT), 5_000_000)
    callee = res.create_address
    # caller runtime: CALL(gas=100000, callee, v=0, in=mem[0:32], out=mem[32:64])
    # then return out word. calldata word is forwarded via MSTORE.
    rt = (push(0) + b"\x35" + push(0) + b"\x52"      # mem[0:32] = calldata
          + push(32) + push(32) + push(32) + push(0) + push(0)
          + push(int.from_bytes(callee, "big")) + push(100_000)
          + b"\xf1"                                   # CALL
          + b"\x50"                                   # POP success flag
          + push(32) + push(32) + b"\xf3")            # RETURN mem[32:64]
    res2 = evm.create(state, env, SENDER, 0, initcode_for(rt), 5_000_000)
    caller = res2.create_address
    out = evm.execute_message(state, env, SENDER, caller, 0,
                              (0x1234).to_bytes(32, "big"), 2_000_000)
    assert out.success
    assert int.from_bytes(out.output, "big") == 0x1234
    # callee's storage written under callee's address
    assert state.get(T_STORE, callee + (0).to_bytes(32, "big")) == \
        (0x1234).to_bytes(32, "big")


def test_create2_address():
    state, evm, env = _fresh()
    init = initcode_for(STORE_RT)
    salt = 42
    res = evm.create(state, env, SENDER, 0, init, 5_000_000, salt=salt)
    assert res.success
    want = SUITE.hash(b"\xff" + SENDER + salt.to_bytes(32, "big")
                      + SUITE.hash(init))[12:]
    assert res.create_address == want


def test_logs():
    state, evm, env = _fresh()
    # LOG1 over mem[0:4] with topic 0x77
    rt = (push(0xDEADBEEF) + push(0) + b"\x52"
          + push(0x77) + push(4) + push(28) + b"\xa1"
          + push(0) + push(0) + b"\xf3")
    res = evm._run(state, env, rt, SENDER, b"\x05" * 20, 0, b"", 100_000, 0,
                   False)
    assert res.success and len(res.logs) == 1
    log = res.logs[0]
    assert log.address == b"\x05" * 20
    assert log.topics == [(0x77).to_bytes(32, "big")]
    assert log.data == b"\xde\xad\xbe\xef"


def test_ecrecover_precompile():
    state, evm, env = _fresh()
    kp = SUITE.generate_keypair(b"ecr" * 11)
    digest = SUITE.hash(b"hello evm")
    sig = SUITE.sign(kp, digest)  # r|s|v
    data = (digest + (27 + sig[64]).to_bytes(32, "big") + sig[:32]
            + sig[32:64])
    res = evm.execute_message(state, env, SENDER, b"\x00" * 19 + b"\x01", 0,
                              data, 100_000)
    assert res.success
    assert res.output[12:] == kp.address


def test_executor_dispatches_evm():
    """TransactionExecutor routes create/evm-call txs through the VM."""
    state = StateStorage(MemoryStorage())
    ex = TransactionExecutor(SUITE)
    deploy = Transaction(to=b"", input=initcode_for(STORE_RT))
    deploy._sender = SENDER
    rc = ex.execute_transaction(deploy, state, 1, 1000)
    assert rc.status == int(TransactionStatus.OK)
    assert len(rc.contract_address) == 20

    call = Transaction(to=rc.contract_address,
                       input=(0x55).to_bytes(32, "big"))
    call._sender = SENDER
    rc2 = ex.execute_transaction(call, state, 1, 1000)
    assert rc2.status == int(TransactionStatus.OK)
    assert int.from_bytes(rc2.output, "big") == 0x55
    assert rc2.gas_used > 0


def test_evm_calls_framework_precompile():
    """In-EVM CALL to a framework system contract must really execute it
    (review finding: used to fall through as a successful no-op)."""
    from fisco_bcos_tpu.executor.precompiled import (
        PRECOMPILED_REGISTRY, BALANCE_ADDRESS)
    from fisco_bcos_tpu.codec.wire import Writer
    state = StateStorage(MemoryStorage())
    evm = EVM(SUITE, registry=dict(PRECOMPILED_REGISTRY))
    env = TxEnv(origin=SENDER, gas_price=0, block_number=1,
                timestamp=1000_000, gas_limit=10_000_000)
    w = Writer()
    w.text("register").blob(b"evm-acct").u64(777)
    res = evm.execute_message(state, env, SENDER, BALANCE_ADDRESS, 0,
                              w.bytes(), 1_000_000)
    assert res.success
    from fisco_bcos_tpu.executor.precompiled import T_BALANCE
    assert int.from_bytes(state.get(T_BALANCE, b"evm-acct"), "big") == 777

    # PrecompileError surfaces as a revert, not a silent success
    res2 = evm.execute_message(state, env, SENDER, BALANCE_ADDRESS, 0,
                               w.bytes(), 1_000_000)  # duplicate register
    assert not res2.success and res2.error == "revert"


def test_ecrecover_malformed_is_empty_success():
    state, evm, env = _fresh()
    data = b"\xff" * 128  # garbage v word
    res = evm.execute_message(state, env, SENDER, b"\x00" * 19 + b"\x01", 0,
                              data, 100_000)
    assert res.success and res.output == b""
    assert res.gas_left > 0  # gas not burned to zero
