"""Native EVM frame interpreter (native/nevm) vs the Python interpreter.

Equivalence suite: every scenario runs twice — once with the native
interpreter, once pure-Python — and the results must match bit for bit
(success, output, gas_left, logs, state). This is the determinism contract
that lets a chain mix native and Python executors (the reference's evmone
vs its reference interpreters behave the same way behind EVMC).
"""

import os

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import nevm
from fisco_bcos_tpu.executor.evm import EVM, TxEnv, T_CODE, T_STORE
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage

pytestmark = pytest.mark.skipif(
    not nevm.available(), reason="libnevm.so not built")

SUITE = make_suite(backend="host")
ENV = TxEnv(origin=b"\x0a" * 20, gas_price=1, block_number=7,
            timestamp=1700000000000, gas_limit=10_000_000, chain_id=20200,
            coinbase=b"\x0c" * 20)
ADDR = b"\x11" * 20
CALLER = b"\x22" * 20


def _fresh_state(code=b"", extra=None):
    st = StateStorage(MemoryStorage())
    if code:
        st.set(T_CODE, ADDR, code)
    for (tbl, k, v) in (extra or []):
        st.set(tbl, k, v)
    return st


def run_both(code: bytes, calldata: bytes = b"", gas: int = 1_000_000,
             value: int = 0, static: bool = False, extra=None):
    """-> (native EVMResult, python EVMResult) plus state-dump equality."""
    results = []
    dumps = []
    for native in (True, False):
        st = _fresh_state(code, extra)
        evm = EVM(SUITE, native=native)
        res = evm._run(st, ENV, code, CALLER, ADDR, value, calldata, gas,
                       0, static)
        results.append(res)
        dumps.append(sorted(st.changed_rows())
                     if hasattr(st, "changed_rows") else None)
    n, p = results
    assert n.success == p.success, (n, p)
    assert n.output == p.output, (n.output.hex(), p.output.hex())
    assert n.gas_left == p.gas_left, (n.gas_left, p.gas_left)
    assert [(l.address, l.topics, l.data) for l in n.logs] == \
        [(l.address, l.topics, l.data) for l in p.logs]
    assert n.error == p.error or (not n.success and not p.success)
    return n, p


def asm(*ops) -> bytes:
    """Tiny assembler: ints are opcodes, bytes are literal immediates."""
    out = b""
    for o in ops:
        out += bytes([o]) if isinstance(o, int) else o
    return out


def push(v: int, width: int = 32) -> bytes:
    return bytes([0x5F + width]) + v.to_bytes(width, "big")


def ret_top() -> bytes:
    # store top of stack at mem[0], return 32 bytes
    return asm(push(0, 1), 0x52, push(32, 1), push(0, 1), 0xF3)


M = (1 << 256) - 1


@pytest.mark.parametrize("a,b,op", [
    (3, 5, 0x01), (M, 2, 0x01),                      # ADD wrap
    (7, 9, 0x02), (M, M, 0x02),                      # MUL wrap
    (10, 3, 0x03), (3, 10, 0x03),                    # SUB underflow
    (100, 7, 0x04), (5, 0, 0x04),                    # DIV, div0
    (M, 2, 0x05), (M - 6, 3, 0x05),                  # SDIV negatives
    (100, 7, 0x06), (5, 0, 0x06),                    # MOD
    (M - 6, 5, 0x07),                                # SMOD negative
    (M, M, 0x10), (2, 3, 0x10), (3, 2, 0x11),        # LT/GT
    (M, 1, 0x12), (1, M, 0x13),                      # SLT/SGT signed
    (5, 5, 0x14), (5, 6, 0x14),                      # EQ
    (0xF0, 0x0F, 0x16), (0xF0, 0x0F, 0x17), (0xF0, 0xFF, 0x18),
    (1, 200, 0x1B), (M, 255, 0x1C), (M, 3, 0x1D),    # shifts
])
def test_binary_ops_equivalent(a, b, op):
    run_both(asm(push(b), push(a), op) + ret_top())


@pytest.mark.parametrize("code", [
    asm(push(0, 1), 0x15) + ret_top(),                     # ISZERO
    asm(push(M), 0x19) + ret_top(),                        # NOT
    asm(push(3, 1), push(M - 100), 0x1A) + ret_top(),      # BYTE
    asm(push(2, 1), push(M), 0x0B) + ret_top(),            # SIGNEXTEND
    asm(push(7), push(5), push(3), 0x08) + ret_top(),      # ADDMOD
    asm(push(7), push(5), push(3), 0x09) + ret_top(),      # MULMOD
    asm(push(10), push(3), 0x0A) + ret_top(),              # EXP
    asm(push(0, 1), push(0, 1), 0x20) + ret_top(),         # KECCAK empty
])
def test_unary_and_mod_ops_equivalent(code):
    run_both(code)


def test_context_ops_equivalent():
    for op in (0x30, 0x32, 0x33, 0x34, 0x36, 0x38, 0x3A, 0x41, 0x42, 0x43,
               0x44, 0x45, 0x46, 0x48, 0x58, 0x59, 0x5A):
        run_both(asm(op) + ret_top(), calldata=b"\x01\x02", value=5)


def test_memory_and_calldata_equivalent():
    # CALLDATACOPY + CALLDATALOAD + MLOAD/MSTORE/MSTORE8 + MSIZE
    code = asm(
        push(8, 1), push(1, 1), push(0, 1), 0x37,       # calldatacopy
        push(5, 1), 0x35,                                # calldataload
        push(64, 1), 0x52,                               # mstore
        push(0xAB, 1), push(100, 1), 0x53,               # mstore8
        0x59,                                            # msize
    ) + ret_top()
    run_both(code, calldata=bytes(range(1, 40)))


def test_storage_roundtrip_equivalent():
    code = asm(
        push(0x1234), push(1, 1), 0x55,     # sstore slot1
        push(1, 1), 0x54,                   # sload slot1
        push(0, 1), 0x54, 0x01,             # sload missing + add
    ) + ret_top()
    n, p = run_both(code)
    assert n.success


def test_sstore_gas_cases_equivalent():
    # set-new, overwrite, clear — three distinct gas rows
    pre = [(T_STORE, ADDR + (2).to_bytes(32, "big"), b"\x09" * 32)]
    code = asm(
        push(5, 1), push(1, 1), 0x55,        # fresh set
        push(6, 1), push(2, 1), 0x55,        # overwrite existing
        push(0, 1), push(2, 1), 0x55,        # clear existing
        push(0, 1), push(3, 1), 0x55,        # clear missing
    ) + ret_top()
    run_both(code, extra=pre)


def test_jumps_and_loops_equivalent():
    # sum 100..1 in a loop — exercises JUMP/JUMPI/JUMPDEST/DUP/SWAP heavily
    code = asm(
        push(0, 1),                 # sum
        push(100, 1),               # i          stack: [sum, i]
        0x5B,                       # LOOP @ pc=4
        0x80,                       # DUP1       [sum, i, i]
        0x91,                       # SWAP2      [i, i, sum]
        0x01,                       # ADD        [i, sum+i]
        0x90,                       # SWAP1      [sum', i]
        push(1, 1), 0x90, 0x03,     # i = i-1    [sum', i-1]
        0x80,                       # DUP1       [sum', i', i']
        push(4, 1), 0x57,           # JUMPI loop while i' != 0
        0x50,                       # POP        [sum']
    ) + ret_top()
    n, p = run_both(code)
    assert n.success
    assert int.from_bytes(n.output, "big") == sum(range(1, 101))


def test_bad_jump_and_invalid_equivalent():
    run_both(asm(push(3, 1), 0x56))          # bad dest
    run_both(asm(0xFE))                      # invalid opcode
    run_both(asm(0x01))                      # stack underflow
    run_both(asm(push(1, 1)) * 1025)         # stack overflow
    run_both(asm(0xBB))                      # unknown opcode


def test_oog_equivalent():
    code = asm(push(1, 1), push(1, 1), 0x55)  # SSTORE set costs 20000
    run_both(code + ret_top(), gas=1000)


def test_logs_equivalent():
    code = asm(
        push(0xDEAD, 2), push(0, 1), 0x52,
        push(0x42), push(0x43),
        push(32, 1), push(0, 1), 0xA2,   # LOG2 (leaves an empty stack)
        push(32, 1), push(0, 1), 0xF3,   # return mem[0:32]
    )
    n, p = run_both(code)
    assert len(n.logs) == 1 and len(n.logs[0].topics) == 2


def test_revert_and_return_equivalent():
    run_both(asm(push(0x99, 1), push(0, 1), 0x52,
                 push(1, 1), push(31, 1), 0xFD))   # REVERT 1 byte
    run_both(asm(push(0x99, 1), push(0, 1), 0x52,
                 push(1, 1), push(31, 1), 0xF3))   # RETURN 1 byte


def test_keccak_and_sm3_hash_equivalent():
    code = asm(push(0x6162636465, 5), push(27, 1), 0x52,  # "abcde" @31-27?
               push(5, 1), push(27, 1), 0x20) + ret_top()
    run_both(code)
    # SM suite: KECCAK256 opcode routes to SM3
    st_results = []
    for native in (True, False):
        sm_suite = make_suite(True, backend="host")
        st = _fresh_state(code)
        evm = EVM(sm_suite, native=native)
        res = evm._run(st, ENV, code, CALLER, ADDR, 0, b"", 500000, 0, False)
        st_results.append(res)
    assert st_results[0].output == st_results[1].output
    assert st_results[0].gas_left == st_results[1].gas_left


def test_push_past_code_end_equivalent():
    # PUSH32 with only 2 bytes of immediate left (the documented
    # Python-slice semantics both interpreters must share)
    run_both(bytes([0x7F, 0xAA, 0xBB]) + b"")  # runs off the end: implicit stop
    run_both(bytes([0x7F, 0xAA, 0xBB, 0x00]))


def test_full_transaction_path_native(tmp_path):
    """Counter contract deploy + calls through the full executor with the
    native interpreter enabled — the integration surface."""
    from fisco_bcos_tpu.executor.executor import TransactionExecutor
    from fisco_bcos_tpu.protocol import Transaction

    # runtime: increment slot 0, return its value
    runtime = asm(
        push(0, 1), 0x54, push(1, 1), 0x01, push(0, 1), 0x55,
        push(0, 1), 0x54, push(0, 1), 0x52, push(32, 1), push(0, 1), 0xF3)
    # initcode: codecopy(0, <off>, len(runtime)); return(0, len(runtime))
    prefix_len = len(asm(push(0, 1), push(0, 1), push(0, 1), 0x39,
                         push(0, 1), push(0, 1), 0xF3))
    initcode = asm(
        push(len(runtime), 1), push(prefix_len, 1), push(0, 1), 0x39,
        push(len(runtime), 1), push(0, 1), 0xF3) + runtime
    assert len(initcode) == prefix_len + len(runtime)

    for native in (True, False):
        ex = TransactionExecutor(SUITE)
        ex.evm.native = native
        st = StateStorage(MemoryStorage())
        kp = SUITE.generate_keypair(b"nevm-user")
        deploy = Transaction(to=b"", input=initcode, nonce="d1",
                             block_limit=100).sign(SUITE, kp)
        rec = ex.execute_transaction(deploy, st, 1, ENV.timestamp)
        assert rec.status == 0, (rec.status, rec.message)
        addr = rec.contract_address
        for i in range(3):
            tx = Transaction(to=addr, input=b"", nonce=f"c{i}",
                             block_limit=100).sign(SUITE, kp)
            rec = ex.execute_transaction(tx, st, 2 + i, ENV.timestamp)
            assert rec.status == 0, (rec.status, rec.message)
        assert int.from_bytes(rec.output, "big") == 3


def test_returndatacopy_overflow_equivalent():
    """Huge source offsets must fail identically on both interpreters
    (uint64-wrap here would be a consensus split + native OOB read)."""
    code = asm(push(1, 1), push((1 << 64) - 1), push(0, 1), 0x3E) + ret_top()
    n, p = run_both(code)
    assert not n.success and not p.success


def test_copy_size_u64_wrap_oog_equivalent():
    """CALLDATACOPY/CODECOPY/EXTCODECOPY with size in [2^64-31, 2^64-1]:
    the naive (n+31)/32 wraps to 0 in uint64, undercharging gas and then
    aborting the whole process via std::length_error across the FFI
    boundary (single-tx node DoS + native/Python divergence). Both
    interpreters must return out-of-gas."""
    wrap = (1 << 64) - 1  # words32 wraps to 0 without the overflow fix
    for op in (0x37, 0x39):  # CALLDATACOPY, CODECOPY
        code = asm(push(wrap, 8), push(0, 1), push(0, 1), op)
        n, p = run_both(code)
        assert not n.success and not p.success
        assert n.gas_left == 0 and p.gas_left == 0
    # EXTCODECOPY pops the address first
    code = asm(push(wrap, 8), push(0, 1), push(0, 1), push(0, 1), 0x3C)
    n, p = run_both(code)
    assert not n.success and not p.success


def test_huge_size_gas_sites_oog_equivalent():
    """Every attacker-chosen-size gas multiply (KECCAK256, LOG, CREATE,
    RETURNDATACOPY) must OOG identically for sizes beyond the memory cap
    — including the int64-overflow region (n >= 2^61) where the native
    LOG charge was signed-overflow UB."""
    huge = 1 << 61
    cases = [
        asm(push(huge, 8), push(0, 1), 0x20),             # KECCAK256
        asm(push(huge, 8), push(0, 1), 0xA0),             # LOG0
        asm(push(huge, 8), push(0, 1), push(0, 1), 0xF0),  # CREATE
        asm(push(huge, 8), push(0, 1), push(0, 1), 0x3E),  # RETURNDATACOPY
        # memory-cap in extend itself: MLOAD at off 2^34
        asm(push(1 << 34, 8), 0x51),
    ]
    for code in cases:
        n, p = run_both(code)
        assert not n.success and not p.success, code.hex()
        assert n.gas_left == 0 and p.gas_left == 0


def test_stale_native_binary_refused(tmp_path, monkeypatch):
    """A committed .so that drifts from the checked-in source must fail
    loudly (refuse to load), not execute divergent consensus semantics."""
    import ctypes

    from fisco_bcos_tpu.utils.nativelib import check_src_hash

    lib = ctypes.CDLL(os.environ.get(
        "FBTPU_NEVM_LIB",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "native", "build", "libnevm.so")))
    real_src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native", "nevm", "nevm.cpp")
    assert check_src_hash(lib, "nevm", real_src), \
        "shipped binary should match shipped source"
    drifted = tmp_path / "nevm.cpp"
    drifted.write_bytes(open(real_src, "rb").read() + b"// drifted\n")
    monkeypatch.delenv("FBTPU_NATIVE_ALLOW_STALE", raising=False)
    assert not check_src_hash(lib, "nevm", str(drifted))
    monkeypatch.setenv("FBTPU_NATIVE_ALLOW_STALE", "1")
    assert check_src_hash(lib, "nevm", str(drifted))


def test_block_execution_state_identical_across_interpreters():
    """Consensus safety for mixed fleets: executing the SAME block of
    contract txs with the native and Python interpreters must produce
    identical receipts (encoded) and an identical state changeset —
    stronger than per-frame equality, this covers executor dispatch,
    deploy addresses, logs and gas accounting end to end."""
    from fisco_bcos_tpu.executor.executor import TransactionExecutor
    from fisco_bcos_tpu.protocol import Transaction

    runtime = asm(
        push(0, 1), 0x54, push(1, 1), 0x01, push(0, 1), 0x55,   # slot0 += 1
        push(0x11), push(32, 1), push(0, 1), 0xA1,              # LOG1
        push(0, 1), 0x54, push(0, 1), 0x52, push(32, 1), push(0, 1), 0xF3)
    prefix = asm(push(0, 1), push(0, 1), push(0, 1), 0x39,
                 push(0, 1), push(0, 1), 0xF3)
    init = asm(push(len(runtime), 1), push(len(prefix), 1), push(0, 1), 0x39,
               push(len(runtime), 1), push(0, 1), 0xF3) + runtime

    kp = SUITE.generate_keypair(b"block-eq")
    txs = [Transaction(to=b"", input=init, nonce="bd",
                       block_limit=100).sign(SUITE, kp)]
    outputs = []
    for native in (True, False):
        ex = TransactionExecutor(SUITE)
        ex.evm.native = native
        st = StateStorage(MemoryStorage())
        recs = [ex.execute_transaction(txs[0], st, 1, 1000)]
        addr = recs[0].contract_address
        assert recs[0].status == 0 and addr, "deploy must succeed"
        calls = [Transaction(to=addr, input=b"", nonce=f"bc{i}",
                             block_limit=100).sign(SUITE, kp)
                 for i in range(4)]
        for i, tx in enumerate(calls):
            recs.append(ex.execute_transaction(tx, st, 2, 2000 + i))
        outputs.append((
            [r.encode() for r in recs],
            sorted(st.changeset().items()),
        ))
    native_out, python_out = outputs
    assert native_out[0] == python_out[0], "receipts differ"
    assert native_out[1] == python_out[1], "state changesets differ"


def test_random_bytecode_differential_fuzz():
    """Seeded differential fuzz: arbitrary byte programs (mostly invalid —
    unknown opcodes, stack underflows, wild jumps, truncated PUSHes) must
    produce identical outcomes on both interpreters. Complements the
    per-family equivalence tests with coverage of the weird corners."""
    import numpy as np

    rng = np.random.default_rng(1234)
    # biased byte soup: plenty of real opcodes, some immediates
    pool = list(range(0x00, 0x20)) + list(range(0x30, 0x60)) + \
        [0x60, 0x61, 0x7F, 0x80, 0x90, 0xA0, 0xF3, 0xFD, 0x5B, 0x56, 0x57]
    for trial in range(150):
        n = int(rng.integers(1, 48))
        code = bytes(int(rng.choice(pool)) for _ in range(n))
        run_both(code, calldata=bytes(rng.integers(0, 256, 8, np.uint8)),
                 gas=50_000)


def test_return_revert_memory_expansion_gas_equivalent():
    """RETURN/REVERT whose output window EXPANDS memory must charge the
    expansion in the reported gas_left on both interpreters (caught by
    differential fuzz: C++ argument evaluation order read f.gas before
    read_mem charged it)."""
    for op in (0xF3, 0xFD):
        code = asm(push(90, 1), push(0, 1), op)  # return/revert mem[0:90]
        n, p = run_both(code, gas=10_000)
        assert n.gas_left == p.gas_left
        # expansion to 3 words costs 3*3 + 0 = 9: visible in gas_left
        assert 10_000 - n.gas_left == 3 + 3 + 9


@pytest.mark.slow
def test_deep_differential_fuzz_storage_and_calls():
    """Richer-pool differential fuzz: storage/access/CALL-family/CREATE/
    SELFDESTRUCT opcodes at tight gas budgets. This pool caught a real
    native divergence (RETURN/REVERT memory-expansion gas lost to C++
    argument evaluation order) that the basic fuzz missed for 3 rounds."""
    import numpy as np

    rng = np.random.default_rng(20260730)
    pool = (list(range(0x00, 0x20)) + list(range(0x30, 0x60)) +
            [0x54, 0x55, 0x54, 0x55, 0x31, 0x3B, 0x3C, 0x3F,
             0x5C, 0x5D, 0x5E,
             0x60, 0x61, 0x62, 0x7F, 0x80, 0x81, 0x90, 0x91,
             0xA0, 0xA1, 0xF1, 0xF2, 0xF4, 0xFA, 0xF0, 0xFF,
             0xF3, 0xFD, 0x5B, 0x56, 0x57, 0x20])
    for trial in range(400):
        n = int(rng.integers(1, 96))
        code = bytes(int(rng.choice(pool)) for _ in range(n))
        gas = int(rng.choice([2500, 10_000, 60_000, 400_000]))
        run_both(code, calldata=bytes(rng.integers(0, 256, 16, np.uint8)),
                 gas=gas)
