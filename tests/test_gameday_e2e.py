"""Game day against a REAL daemon cluster (slow tier).

A single-phase schedule — kill -9 under mint-storm load — end to end
through GameDay: boot, RPC prefund, capacity calibration, open-loop
load with a fault mid-window, then every invariant (health within SLO,
converged heads, clean audit, bounded write p99, byte-identical
c_balance offline). The full builtin schedules run in CI via
`tools/sanitize_ci.sh --gameday` and by hand via `tools/gameday.py`."""

import pytest

from fisco_bcos_tpu.testing.gameday import GameDay

pytestmark = pytest.mark.slow

SCHEDULE = {
    "name": "e2e-kill9",
    "nodes": 4,
    "tls": True,
    "recovery_slo_s": 120.0,
    "write_p99_ms": 60_000.0,
    "scenario_accounts": 100,
    "phases": [
        {"name": "kill9-under-mint", "duration_s": 15.0,
         "load": {"scenario": "mint-storm", "intensity": 0.5},
         "events": [{"at_s": 4.0, "action": "sigkill", "node": 3,
                     "restart_after_s": 2.0}]},
    ],
}


def test_gameday_single_phase_kill9(tmp_path):
    rows = []
    day = GameDay(SCHEDULE, str(tmp_path / "gd"), emit=rows.append)
    report = day.run()

    assert report["ok"] and report["height"] >= 1
    assert report["balance_digest"].split(":")[0] != "0", \
        "digest must cover real rows, not a vacuously-empty table"
    (phase,) = report["phases"]
    assert phase["phase"] == "kill9-under-mint"
    assert phase["committed"] > 0 and phase["latency_samples"] > 0
    assert phase["write_p99_ms"] <= SCHEDULE["write_p99_ms"]

    by_metric = {r["metric"] for r in rows}
    assert {"gameday_phase", "gameday_post_soak_tps",
            "gameday_write_p99_ms"} <= by_metric
