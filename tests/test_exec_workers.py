"""Out-of-process execution workers (scheduler/workers.py).

The contract that lets the node trust a subprocess with block execution:
results are BYTE-IDENTICAL to in-process `execute_block_dag` (receipts
AND changeset), a worker SIGKILLed mid-stream degrades the health plane
and falls back in-process (never a wrong block, never a hang), the
respawn probe heals the pool, and a node configured with
`scheduler_workers=1` reaches the exact same state as an in-process node
over the same tx stream.
"""

import os
import signal
import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.executor.executor import TransactionExecutor
from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.scheduler.workers import ExecPool
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage
from fisco_bcos_tpu.utils.health import Health


@pytest.fixture(scope="module")
def suite():
    return make_suite(False, backend="host")


def _chain(suite):
    storage = MemoryStorage()
    Ledger(storage, suite).build_genesis([ConsensusNode(b"\x01" * 64)])
    return storage


def _txs(suite, kp, n, tag="w"):
    out = []
    for i in range(n):
        tx = Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call(
                "register",
                lambda w, i=i: w.blob(b"%s%d" % (tag.encode(), i))
                .u64(100 + i)),
            nonce=f"{tag}-{i}", block_limit=100).sign(suite, kp)
        tx.sender(suite)
        out.append(tx)
    return out


def test_pool_matches_in_process(suite):
    """Receipts and changeset from the worker protocol are byte-identical
    to in-process execution — including with 2 workers and sharding."""
    storage = _chain(suite)
    executor = TransactionExecutor(suite)
    kp = suite.generate_keypair(b"exec-workers")
    txs = _txs(suite, kp, 6)
    ref_state = StateStorage(storage)
    ref_receipts = executor.execute_block_dag(txs, ref_state, 1, 1000)
    ref_changes = ref_state.changeset()

    pool = ExecPool(sm_crypto=False, workers=2)
    pool.start()
    try:
        out = pool.execute(txs, storage, 1, 1000, suite, executor)
        assert out is not None
        receipts, changes = out
        assert [r.encode() for r in receipts] == \
            [r.encode() for r in ref_receipts]
        assert set(changes) == set(ref_changes)
        for k in changes:
            assert changes[k].value == ref_changes[k].value
            assert changes[k].deleted == ref_changes[k].deleted
        stats = pool.stats()
        assert stats["fallbacks"] == 0
        assert sum(w["blocks"] for w in stats["per_worker"]) >= 1
    finally:
        pool.stop()


def test_sender_backfill_over_pipe(suite):
    """Txs with cold sender caches still execute correctly — the pool
    backfills with one batched recover before shipping."""
    storage = _chain(suite)
    executor = TransactionExecutor(suite)
    kp = suite.generate_keypair(b"exec-cold")
    txs = _txs(suite, kp, 3, tag="cold")
    ref_state = StateStorage(storage)
    ref = executor.execute_block_dag(
        [Transaction.decode(t.encode()) for t in txs], ref_state, 1, 1000)
    cold = [Transaction.decode(t.encode()) for t in txs]  # no _sender
    pool = ExecPool(sm_crypto=False, workers=1)
    pool.start()
    try:
        out = pool.execute(cold, storage, 1, 1000, suite, executor)
        assert out is not None
        assert [r.encode() for r in out[0]] == [r.encode() for r in ref]
    finally:
        pool.stop()


def test_sigkill_degrades_falls_back_and_heals(suite):
    """SIGKILL mid-pool: execute() falls back (returns None), the health
    plane degrades with a respawn probe, the probe heals, and the pool
    executes again with fresh workers."""
    storage = _chain(suite)
    executor = TransactionExecutor(suite)
    kp = suite.generate_keypair(b"exec-kill")
    txs = _txs(suite, kp, 4, tag="kill")
    health = Health()
    pool = ExecPool(sm_crypto=False, workers=1, health=health)
    pool.start()
    try:
        victim = pool.pids()[0]
        os.kill(victim, signal.SIGKILL)
        deadline = time.time() + 10
        while time.time() < deadline:
            if pool.execute(txs, storage, 1, 1000, suite, executor) is None:
                break
            time.sleep(0.05)
        else:
            pytest.fail("SIGKILLed worker never produced a fallback")
        assert pool.stats()["fallbacks"] >= 1
        assert health.state() != "ok"  # degraded until the probe heals
        assert pool.probe_respawn() is True
        assert pool.pids() and pool.pids()[0] != victim
        # the health ticker clears the fault via the probe; poke it
        # directly here to avoid timing on the 0.25 s tick
        health.clear("scheduler.exec_worker")
        assert health.sealing_allowed()
        out = pool.execute(txs, storage, 1, 1000, suite, executor)
        assert out is not None and len(out[0]) == len(txs)
    finally:
        pool.stop()
        health.stop()


def test_node_with_workers_matches_in_process_node(suite):
    """Two solo nodes over the same tx stream — one with
    scheduler_workers=1, one in-process — converge to identical heads,
    state roots and balances."""
    from fisco_bcos_tpu.init.node import Node, NodeConfig

    def run(workers):
        node = Node(NodeConfig(consensus="solo", p2p_port=0, rpc_port=0,
                               min_seal_time=0.01,
                               scheduler_workers=workers))
        node.start()
        try:
            kp = node.suite.generate_keypair(b"node-vs-node")
            txs = [Transaction(
                to=pc.BALANCE_ADDRESS,
                input=pc.encode_call(
                    "register",
                    lambda w, i=i: w.blob(b"acct%d" % i).u64(1000 + i)),
                nonce=f"nn-{i}", block_limit=600).sign(node.suite, kp)
                for i in range(8)]
            node.txpool.submit_batch(txs)
            deadline = time.time() + 20
            while (time.time() < deadline
                   and node.ledger.current_number() < 1):
                time.sleep(0.05)
            head = node.ledger.current_number()
            assert head >= 1
            hdr = node.ledger.header_by_number(head)
            st = StateStorage(node.storage)
            balances = [int.from_bytes(
                st.get(pc.T_BALANCE, b"acct%d" % i) or b"", "big")
                for i in range(8)]
            pool_blocks = 0
            if node.exec_pool is not None:
                pool_blocks = sum(
                    w["blocks"]
                    for w in node.exec_pool.stats()["per_worker"])
            return hdr.state_root, balances, pool_blocks
        finally:
            node.stop()

    root_w, balances_w, pool_blocks = run(1)
    root_0, balances_0, _ = run(0)
    assert pool_blocks >= 1  # the worker path actually executed
    assert root_w == root_0
    assert balances_w == balances_0 == [1000 + i for i in range(8)]
