"""Ops tooling: archive-tool, storage-tool, light-monitor, trace recorder.

Reference: tools/archive-tool, tools/storage-tool,
tools/BcosAirBuilder/light_monitor.sh, bcos-scheduler DmcStepRecorder.cpp.
"""

import json
import subprocess
import sys
import time

from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.protocol import Transaction

TOOLS = "tools"


def _run_tool(script, *args):
    r = subprocess.run([sys.executable, f"{TOOLS}/{script}", *args],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (script, args, r.stdout, r.stderr)
    return r.stdout


def _chain_with_blocks(path, n_tx=3):
    node = Node(NodeConfig(crypto_backend="host", storage_path=path,
                           min_seal_time=0.0, tx_count_limit=1))
    node.start()
    kp = node.suite.generate_keypair(b"ops-user")
    hashes = []
    for i in range(n_tx):
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register",
                             lambda w, i=i: w.blob(b"op%d" % i).u64(1)),
                         nonce=f"op{i}", block_limit=100
                         ).sign(node.suite, kp)
        res = node.send_transaction(tx)
        rc = node.txpool.wait_for_receipt(res.tx_hash, 15)
        assert rc is not None and rc.status == 0
        hashes.append(res.tx_hash)
    height = node.ledger.current_number()
    assert height >= n_tx  # tx_count_limit=1 -> one block per tx
    node.stop()
    node.storage.close()
    return hashes, height


def test_storage_tool_inspects_and_repairs(tmp_path):
    path = str(tmp_path / "chain")
    _chain_with_blocks(path)
    tables = json.loads(_run_tool("storage_tool.py", "tables", path))
    assert "s_number_2_header" in tables
    stats = json.loads(_run_tool("storage_tool.py", "stats", path))
    assert stats["s_number_2_header"]["rows"] >= 4  # genesis + 3
    # get the genesis header; write and read back a repair key
    out = _run_tool("storage_tool.py", "get", path, "s_number_2_header",
                    (0).to_bytes(8, "big").hex())
    assert len(out.strip()) > 0
    _run_tool("storage_tool.py", "set", path, "t_repair", "aa", "bb")
    out = _run_tool("storage_tool.py", "get", path, "t_repair", "aa")
    assert out.strip() == "bb"
    _run_tool("storage_tool.py", "compact", path)
    out = _run_tool("storage_tool.py", "get", path, "t_repair", "aa")
    assert out.strip() == "bb"


def test_archive_tool_roundtrip(tmp_path):
    path = str(tmp_path / "chain")
    archive = str(tmp_path / "blocks.archive")
    hashes, height = _chain_with_blocks(path)
    cut = height  # archive blocks [1, height)
    out = json.loads(_run_tool("archive_tool.py", "archive", path, archive,
                               "--until", str(cut)))
    assert out["archived_blocks"] == cut - 1

    # archived tx bodies are gone from hot storage, headers remain
    node = Node(NodeConfig(crypto_backend="host", storage_path=path))
    assert node.ledger.transaction(hashes[0]) is None
    assert node.ledger.header_by_number(1) is not None
    assert node.ledger.current_number() == height
    node.storage.close()

    info = json.loads(_run_tool("archive_tool.py", "info", archive))
    assert info["s_hash_2_tx"] == cut - 1

    json.loads(_run_tool("archive_tool.py", "restore", path, archive))
    node = Node(NodeConfig(crypto_backend="host", storage_path=path))
    assert node.ledger.transaction(hashes[0]) is not None
    assert node.ledger.receipt(hashes[0]) is not None
    node.storage.close()


def test_light_monitor_flags_lag_and_down(tmp_path):
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           rpc_port=0))
    node.start()
    try:
        url = f"http://127.0.0.1:{node.rpc.port}"
        out = subprocess.run(
            [sys.executable, f"{TOOLS}/light_monitor.py", url, "--json"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stdout + out.stderr
        report = json.loads(out.stdout)
        assert report["nodes"][0]["ok"]
        # an unreachable node must flip the exit code
        out = subprocess.run(
            [sys.executable, f"{TOOLS}/light_monitor.py", url,
             "http://127.0.0.1:1", "--json"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 1
        report = json.loads(out.stdout)
        assert report["nodes"][1]["alarm"] == "unreachable"
    finally:
        node.stop()


def test_dmc_step_recorder_matches_across_replicas():
    from fisco_bcos_tpu.utils.trace import BlockTrace, DmcStepRecorder

    def run(messages):
        rec = DmcStepRecorder()
        for round_msgs in messages:
            for m in round_msgs:
                rec.record_message(*m)
            rec.next_round()
        return rec

    msgs = [[(0, 0, b"\xaa" * 20, b"x"), (1, 0, b"\xbb" * 20, b"y")],
            [(0, 1, b"\xbb" * 20, b"z")]]
    a, b = run(msgs), run(msgs)
    assert a.checksums() == b.checksums()
    assert a.summary() == b.summary()
    # intra-round order must NOT matter (parallel executors)
    swapped = [list(reversed(msgs[0])), msgs[1]]
    assert run(swapped).summary() == a.summary()
    # a differing message MUST show up, in the right round
    bad = [msgs[0], [(0, 1, b"\xbb" * 20, b"DIVERGED")]]
    c = run(bad)
    assert c.checksums()[0] == a.checksums()[0]
    assert c.checksums()[1] != a.checksums()[1]

    tr = BlockTrace(7)
    tr.stage("seal")
    time.sleep(0.01)
    tr.stage("execute")
    stages = tr.finish()
    assert set(stages) == {"seal", "execute", "finish"}
    assert stages["execute"] >= 0.01


def test_storage_tool_cluster_mode(tmp_path):
    """storage_tool inspects a LIVE Max shard cluster via max_cluster.json
    (stats/tables/scan/get through the sharded coordinator)."""
    import json as _json
    import subprocess
    import sys as _sys

    from fisco_bcos_tpu.storage.sharded import (
        DurablePrepareStorage, ShardServer, ShardedStorage,
        make_shard_client)
    from fisco_bcos_tpu.storage.wal import WalStorage

    servers = []
    for i in range(3):
        backend = DurablePrepareStorage(
            WalStorage(str(tmp_path / f"s{i}" / "wal")),
            str(tmp_path / f"s{i}" / "prep"))
        srv = ShardServer(backend)
        srv.start()
        servers.append(srv)
    st = ShardedStorage([make_shard_client("127.0.0.1", s.port)
                         for s in servers])
    st.set_batch("t_demo", [(b"k%d" % i, b"v%d" % i) for i in range(8)])

    cluster = {"shards": [{"host": "127.0.0.1", "port": s.port}
                          for s in servers]}
    cpath = tmp_path / "max_cluster.json"
    cpath.write_text(_json.dumps(cluster))

    def run(*args):
        import os as _os
        repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
        r = subprocess.run(
            [_sys.executable, _os.path.join(repo, "tools",
                                            "storage_tool.py"), *args],
            capture_output=True, text=True, timeout=60, cwd=repo)
        assert r.returncode == 0, r.stderr
        return r.stdout

    tables = _json.loads(run("tables", str(cpath)))
    assert "t_demo" in tables
    stats = _json.loads(run("stats", str(cpath)))
    assert stats["t_demo"]["rows"] == 8
    keys = run("scan", str(cpath), "t_demo").split()
    assert len(keys) == 8
    v = run("get", str(cpath), "t_demo", b"k3".hex()).strip()
    assert bytes.fromhex(v) == b"v3"

    st.close()
    for s in servers:
        s.stop()
        s.backend.close()


def test_storage_tool_leveled_disk_and_keypage(tmp_path):
    """storage_tool on a leveled disk-engine directory written through
    the default key-page layout: stats reports per-level segment/byte/
    debt, scan/get address LOGICAL rows through the page layer, and
    `compact` drains all debt offline (operator catch-up)."""
    from fisco_bcos_tpu.storage import make_storage

    path = str(tmp_path / "disk")
    st = make_storage("disk", path, memtable_mb=0, compact_segments=2)
    assert type(st).__name__ == "KeyPageStorage"  # auto default for disk
    engine = st.backend
    engine._compactor.pause()       # leave debt for the tool to drain
    for i in range(8):
        st.set("t_wide", b"row%04d" % i, b"v%d" % i)
    assert engine.compaction_debt_bytes() > 0
    st.close()

    stats = json.loads(_run_tool("storage_tool.py", "stats", path))
    assert stats["t_wide"]["rows"] == 8  # logical rows, not _kp_ pages
    eng = stats["_engine"]
    assert "backend_reads" in eng        # page layer detected
    levels = eng["backend_stats"]["levels"]
    assert levels and all(
        set(lv) >= {"level", "segments", "bytes", "debt_bytes"}
        for lv in levels)
    assert eng["backend_stats"]["compaction_debt_bytes"] > 0

    out = _run_tool("storage_tool.py", "get", path, "t_wide",
                    b"row0003".hex())
    assert out.strip() == b"v3".hex()
    out = _run_tool("storage_tool.py", "compact", path)
    drained = json.loads(out.strip().splitlines()[0])
    assert drained["debt_bytes_before"] > 0
    assert drained["debt_bytes_after"] == 0
    stats = json.loads(_run_tool("storage_tool.py", "stats", path))
    assert stats["_engine"]["backend_stats"]["compaction_debt_bytes"] == 0
    assert stats["t_wide"]["rows"] == 8
