"""End-to-end overload control (ISSUE 12).

Covers the four layers of the plane: txpool watermark admission +
priority eviction + typed drop settling (txpool/txpool.py), the ingest
dispatcher's pre-crypto deadline shed (txpool/ingest.py), the edge's
per-client token buckets / fair-share / -32005 (rpc/admission.py +
rpc/edge.py), the busy-state controller with hysteresis
(utils/overload.py + utils/health.py), gossip import gating under busy
(net/txsync.py), the per-peer p2p send-queue's drop-oldest-gossip policy
(net/p2p.py), and a failpoint-armed brownout/recovery run on a live node.
"""

import threading
import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.txpool import IngestLane, TxPool
from fisco_bcos_tpu.txpool.txpool import TxDropped
from fisco_bcos_tpu.utils.metrics import REGISTRY
from fisco_bcos_tpu.utils.overload import OverloadController


class CountingSuite:
    """Delegating wrapper counting batch-recover calls — the instrument
    behind every 'zero crypto for a shed/reject' assertion."""

    def __init__(self, suite):
        self._suite = suite
        self.recover_calls = 0

    def __getattr__(self, name):
        return getattr(self._suite, name)

    def recover_addresses(self, hashes, sigs):
        self.recover_calls += 1
        return self._suite.recover_addresses(hashes, sigs)


def _make_pool(suite, pool_limit=10, low=0.5, high=0.8):
    ledger = Ledger(MemoryStorage(), suite)
    ledger.build_genesis([ConsensusNode(b"\x01" * 64)])
    return TxPool(suite, ledger, pool_limit=pool_limit,
                  low_watermark=low, high_watermark=high)


def _tx(suite, kp, i, block_limit=100, band=0):
    tx = Transaction(to=pc.BALANCE_ADDRESS, input=b"ov-%d" % i,
                     nonce=f"ov-{i}", block_limit=block_limit)
    tx.attribute = (band & 0xFF) << 24  # priority band: attribute's top byte
    tx.sign(suite, kp)
    # wire round-trip: sign() caches _sender, which would let admission
    # skip the recover — decode strips it, like a real client submission
    return Transaction.decode(tx.encode())


@pytest.fixture(scope="module")
def suite():
    return make_suite(False, backend="host")


@pytest.fixture(scope="module")
def kp(suite):
    return suite.generate_keypair(b"overload-tests")


# -- watermark admission + priority eviction --------------------------------

def test_watermark_admission_and_eviction_ordering(suite, kp):
    pool = _make_pool(suite)  # limit 10, low mark 5, high mark 8
    # below the low watermark: everything admits, even near-deadline
    res = pool.submit_batch([_tx(suite, kp, i, block_limit=2)
                             for i in range(3)])
    assert all(r.status == TransactionStatus.OK for r in res)
    res = pool.submit_batch([_tx(suite, kp, 10 + i, block_limit=50)
                             for i in range(4)])
    assert all(r.status == TransactionStatus.OK for r in res)
    assert pool.status()["pending"] == 7  # between the watermarks now

    # between watermarks: a band-0 tx without deadline slack is shed with
    # the TYPED status; a long-deadline one still admits
    shed = pool.submit_batch([_tx(suite, kp, 20, block_limit=2)])[0]
    assert shed.status == TransactionStatus.DEADLINE_UNMEETABLE
    ok = pool.submit_batch([_tx(suite, kp, 21, block_limit=90)])[0]
    assert ok.status == TransactionStatus.OK
    assert pool.status()["pending"] == 8  # at the high watermark

    # at the high watermark: an equal-priority tx is refused (FULL), a
    # higher-band tx admits by EVICTING the lowest-priority/soonest-
    # expiring pending tx — which settles with TXPOOL_EVICTED
    full = pool.submit_batch([_tx(suite, kp, 30, block_limit=2)])[0]
    assert full.status == TransactionStatus.TXPOOL_FULL
    victims = pool._victims_locked()
    victim_hash = victims[0][2]  # lowest (band, block_limit)
    win = pool.submit_batch([_tx(suite, kp, 31, block_limit=90,
                                 band=1)])[0]
    assert win.status == TransactionStatus.OK
    assert pool.status()["pending"] == 8  # exchanged, not grown
    assert pool.dropped_status(victim_hash) == \
        TransactionStatus.TXPOOL_EVICTED

    # eviction order among the survivors: bands before deadlines — a
    # band-1 incomer must evict a band-0 tx before any band-1 tx
    vb = [v[0] for v in pool._victims_locked()]
    assert vb == sorted(vb)


def test_full_pool_reject_pays_zero_crypto(suite, kp):
    counting = CountingSuite(suite)
    pool = _make_pool(counting)  # high mark 8
    res = pool.submit_batch([_tx(suite, kp, i, block_limit=50)
                             for i in range(8)])
    assert all(r.status == TransactionStatus.OK for r in res)
    before = counting.recover_calls
    # equal-priority txs against a high-watermark pool: rejected in the
    # PRE-crypto phase — zero recover calls for the whole batch
    res = pool.submit_batch([_tx(suite, kp, 100 + i, block_limit=50)
                             for i in range(5)])
    assert all(r.status == TransactionStatus.TXPOOL_FULL for r in res)
    assert counting.recover_calls == before, \
        "full-pool reject must not reach the crypto lane"


def test_consensus_imports_bypass_watermark_admission(suite, kp):
    """fetch-missing (proposal verification) must import into a SATURATED
    pool: a replica refusing the leader's txs would view-change exactly
    while overloaded (found in review)."""
    pool = _make_pool(suite)  # high mark 8
    res = pool.submit_batch([_tx(suite, kp, i, block_limit=50)
                             for i in range(8)])
    assert all(r.status == TransactionStatus.OK for r in res)
    blocked = pool.submit_batch([_tx(suite, kp, 50, block_limit=50)])[0]
    assert blocked.status == TransactionStatus.TXPOOL_FULL
    proposal_tx = _tx(suite, kp, 51, block_limit=50)
    ok = pool.submit_batch([proposal_tx], broadcast=False,
                           consensus=True)[0]
    assert ok.status == TransactionStatus.OK
    # the drop verdict is node-local: the nonce is NOT freed on drop (a
    # peer may still commit the gossiped tx) — same-nonce resubmits stay
    # blocked for the window
    victims = pool._victims_locked()
    vh = victims[0][2]
    vtx = pool._pending[vh]
    pool.submit_batch([_tx(suite, kp, 52, block_limit=90, band=3)])
    assert pool.dropped_status(vh) is not None
    dup = Transaction(to=pc.BALANCE_ADDRESS, input=b"other",
                      nonce=vtx.nonce, block_limit=90).sign(suite, kp)
    r = pool.submit_batch([Transaction.decode(dup.encode())])[0]
    assert r.status == TransactionStatus.NONCE_CHECK_FAIL


def test_evicted_tx_settles_waiters_promptly(suite, kp):
    pool = _make_pool(suite)
    # the eventual victim: unique lowest block_limit, with BOTH kinds of
    # waiter attached (async task + a parked wait_for_receipt thread)
    victim = _tx(suite, kp, 0, block_limit=30)
    task = pool.submit_async(victim)
    h = victim.hash(suite)
    got: dict = {}

    def waiter():
        t0 = time.monotonic()
        try:
            pool.wait_for_receipt(h, timeout=20.0)
            got["result"] = "receipt-or-timeout"
        except TxDropped as exc:
            got["result"] = exc.status
        got["seconds"] = time.monotonic() - t0

    th = threading.Thread(target=waiter, daemon=True)
    th.start()
    time.sleep(0.1)  # let the waiter park on the CV
    for i in range(7):  # fill to the high mark
        pool.submit_batch([_tx(suite, kp, 1 + i, block_limit=60)])
    r = pool.submit_batch([_tx(suite, kp, 50, block_limit=90, band=2)])[0]
    assert r.status == TransactionStatus.OK
    th.join(timeout=5)
    assert not th.is_alive(), "waiter still parked after eviction"
    assert got["result"] == TransactionStatus.TXPOOL_EVICTED
    assert got["seconds"] < 5.0, "settle must be prompt, not timeout-bound"
    with pytest.raises(TxDropped):
        task.result(1.0)
    # wait_for_receipt on the already-recorded drop raises immediately
    with pytest.raises(TxDropped):
        pool.wait_for_receipt(h, timeout=5.0)


def test_seal_drops_expired_for_target_height_with_typed_status(suite, kp):
    pool = _make_pool(suite, pool_limit=50)
    short = _tx(suite, kp, 0, block_limit=3)
    long_ = _tx(suite, kp, 1, block_limit=9)
    pool.submit_batch([short, long_])
    # sealing for height 4: the block_limit=3 tx would be expired INSIDE
    # its own block — dropped with the typed status, zero seal slots
    txs, hashes = pool.seal(10, for_number=4)
    assert [t.nonce for t in txs] == ["ov-1"]
    assert pool.dropped_status(short.hash(suite)) == \
        TransactionStatus.BLOCK_LIMIT_CHECK_FAIL
    # block_limit == target height is still sealable (valid through it)
    pool.unseal(hashes)
    txs, _ = pool.seal(10, for_number=9)
    assert [t.nonce for t in txs] == ["ov-1"]


# -- ingest dispatcher: pre-crypto deadline shed ----------------------------

def test_ingest_dispatcher_sheds_expired_before_crypto(suite, kp):
    from fisco_bcos_tpu.txpool.ingest import _Entry
    from fisco_bcos_tpu.utils.task import Task

    counting = CountingSuite(suite)
    pool = _make_pool(counting, pool_limit=50)
    lane = IngestLane(pool)  # not started: dispatch driven directly
    expired = _tx(suite, kp, 0, block_limit=0)  # <= current height (0)
    live = _tx(suite, kp, 1, block_limit=50)
    e1, e2 = _Entry(expired, Task()), _Entry(live, Task())
    before = counting.recover_calls
    lane._dispatch([e1, e2])
    r1 = e1.task.result(1.0)
    assert r1.status == TransactionStatus.BLOCK_LIMIT_CHECK_FAIL
    assert e2.task.result(1.0).status == TransactionStatus.OK
    # exactly ONE recover: the live tx's batch; the shed entry never
    # reached admission or the lane
    assert counting.recover_calls == before + 1

    # an all-expired batch costs zero crypto and zero submit_batch calls
    e3 = _Entry(_tx(suite, kp, 2, block_limit=0), Task())
    before = counting.recover_calls
    lane._dispatch([e3])
    assert e3.task.result(1.0).status == \
        TransactionStatus.BLOCK_LIMIT_CHECK_FAIL
    assert counting.recover_calls == before


# -- edge admission: token buckets + fairness -------------------------------

def test_token_bucket_fairness_ten_to_one():
    from fisco_bcos_tpu.rpc.admission import ClientAdmission

    clock = [0.0]
    adm = ClientAdmission(write_rate=10.0, write_burst=10.0,
                          clock=lambda: clock[0])
    admits = {"aggr": 0, "polite": 0}
    # 30 simulated seconds in 10 ms steps: the aggressor offers every
    # step (100/s), the polite client every 10th step (10/s) — 10:1
    for step in range(3000):
        clock[0] = step * 0.01
        if adm.try_admit("aggr", True) is None:
            adm.release("aggr")
            admits["aggr"] += 1
        if step % 10 == 0 and adm.try_admit("polite", True) is None:
            adm.release("polite")
            admits["polite"] += 1
    # near-equal admitted share: both are clamped to ~rate * 30s
    ratio = admits["aggr"] / max(1, admits["polite"])
    assert 0.8 <= ratio <= 1.3, admits
    assert admits["polite"] >= 250  # polite traffic passed ~unscathed


def test_fair_share_concurrency_and_retry_hint():
    from fisco_bcos_tpu.rpc.admission import ClientAdmission

    adm = ClientAdmission(fair_capacity=8)  # no token limits: rate 0
    for _ in range(8):
        assert adm.try_admit("hog", True) is None
    retry = adm.try_admit("hog", True)  # past its share (sole client: 8)
    assert isinstance(retry, int) and retry >= 1
    # a second client still admits — the hog's monopoly is bounded
    assert adm.try_admit("newcomer", False) is None
    # with two ACTIVE clients the hog's share halves; it stays rejected
    assert isinstance(adm.try_admit("hog", True), int)
    for _ in range(8):
        adm.release("hog")
    assert adm.try_admit("hog", True) is None  # slots freed -> admitted


def test_batch_bodies_bill_per_entry_not_per_request(suite):
    """A JSON-RPC batch must charge one write token PER sendTransaction
    entry (found in review: per-body billing multiplied the budget by
    max_batch)."""
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.sdk.client import SdkClient

    node = Node(NodeConfig(consensus="solo", crypto_backend="host",
                           min_seal_time=0.0, rpc_port=0,
                           client_write_rate=3.0, client_write_burst=6.0))
    node.start()
    try:
        kp2 = node.suite.generate_keypair(b"batch-bill")
        sdk = SdkClient(f"http://{node.rpc.host}:{node.rpc.port}")

        def call(i):
            tx = Transaction(to=pc.BALANCE_ADDRESS,
                             input=pc.encode_call(
                                 "register",
                                 lambda w: w.blob(b"bb%d" % i).u64(1)),
                             nonce=f"bb-{i}",
                             block_limit=100).sign(node.suite, kp2)
            return ("sendTransaction",
                    ["group0", "", "0x" + tx.encode().hex(), False, False])

        from fisco_bcos_tpu.sdk.client import RpcCallError
        # first 10-write batch: gated at the 6-token burst but CHARGED
        # its full 10-entry cost — the bucket goes into DEBT (per-body
        # billing would have charged 1 token and left 5; the 256x bypass
        # this regression pins)
        out = sdk.request_batch([call(i) for i in range(10)])
        assert all("result" in o for o in out), out
        # an immediate second batch is rejected whole with -32005
        try:
            out = sdk.request_batch([call(100 + i) for i in range(10)])
            raise AssertionError(f"batch admitted: {out[:2]}")
        except RpcCallError as exc:
            assert exc.code == -32005
        # refills pay the 4-token debt FIRST: after ~2.5s (+7.5 tokens)
        # the balance is ~3.5 and a small batch admits again
        time.sleep(2.5)
        out = sdk.request_batch([call(200), call(201)])
        assert all("result" in o for o in out), out
    finally:
        node.stop()


def test_sub_one_burst_paces_instead_of_banning():
    """rate 0.4/s (burst would default to 0.8 < the 1-token gate) must
    throttle, not permanently reject (found in review)."""
    from fisco_bcos_tpu.rpc.admission import ClientAdmission

    clock = [0.0]
    adm = ClientAdmission(write_rate=0.4, clock=lambda: clock[0])
    admits = 0
    for step in range(40):  # 100 simulated seconds in 2.5s steps
        clock[0] = step * 2.5
        if adm.try_admit("slow", True) is None:
            adm.release("slow")
            admits += 1
    assert 30 <= admits <= 45, admits  # ~0.4/s over 100s, not zero


def test_lru_never_evicts_the_just_inserted_client():
    from fisco_bcos_tpu.rpc.admission import ClientAdmission

    adm = ClientAdmission(fair_capacity=10_000)
    adm.MAX_CLIENTS = 4  # shrink the bound for the test
    for i in range(4):  # all tracked clients HOLD inflight slots
        assert adm.try_admit(f"hold{i}", False) is None
    assert adm.try_admit("newcomer", False) is None
    adm.release("newcomer")  # must find its entry: _active returns to 4
    assert adm.stats()["active"] == 4
    for i in range(4):
        adm.release(f"hold{i}")
    assert adm.stats()["active"] == 0


def test_submit_async_settles_when_drop_races_registration(suite, kp):
    """A tx dropped between submit() and the waiter registration must
    still settle the task with TxDropped (found in review)."""
    pool = _make_pool(suite, pool_limit=50)
    tx = _tx(suite, kp, 0, block_limit=30)
    orig_receipt = pool.ledger.receipt
    hooked = {"done": False}

    def racing_receipt(h, _orig=orig_receipt):
        # fire the drop INSIDE submit_async's post-submit window, before
        # the waiter registration's own re-check runs
        if not hooked["done"] and pool.pending_count() == 1:
            hooked["done"] = True
            drops = []
            with pool._lock:
                t = pool._drop_locked(
                    h, TransactionStatus.TXPOOL_EVICTED)
                drops.append((h, TransactionStatus.TXPOOL_EVICTED, t))
            pool._settle_dropped(drops)
        return _orig(h)

    pool.ledger.receipt = racing_receipt
    try:
        task = pool.submit_async(tx)
    finally:
        pool.ledger.receipt = orig_receipt
    with pytest.raises(TxDropped):
        task.result(2.0)


def test_escaped_json_cannot_smuggle_writes_past_the_scan():
    """`"sendTransactio\\u006e"` decodes to the write method but evades
    the byte scan — escaped payloads must bill conservatively as writes
    (found in review)."""
    from fisco_bcos_tpu.rpc.admission import ClientAdmission, admit_payload

    clock = [0.0]
    adm = ClientAdmission(write_rate=1.0, write_burst=1.0,
                          clock=lambda: clock[0])
    smuggled = (b'{"jsonrpc":"2.0","id":1,'
                b'"method":"sendTransactio\\u006e","params":[]}')
    assert admit_payload(adm, "c", smuggled) is None  # burst token
    adm.release("c")
    retry = admit_payload(adm, "c", smuggled)  # billed as a WRITE
    assert isinstance(retry, int) and retry >= 1
    # plain reads stay unmetered (read_rate 0)
    plain = b'{"jsonrpc":"2.0","id":2,"method":"getBlockNumber"}'
    assert admit_payload(adm, "c", plain) is None
    adm.release("c")


def test_busy_shrinks_write_budget_only():
    from fisco_bcos_tpu.rpc.admission import ClientAdmission

    class FakeOverload:
        factor = 1.0

        def write_rate_factor(self):
            return self.factor

    clock = [0.0]
    ov = FakeOverload()
    # bursts of a few tokens: strict per-step refill would alias with
    # float accumulation in the simulated clock
    adm = ClientAdmission(write_rate=100.0, write_burst=5.0,
                          read_rate=100.0, read_burst=5.0,
                          overload=ov, clock=lambda: clock[0])

    def drain(kind_write):
        n = 0
        for step in range(100):  # 1 simulated second, 10ms steps
            clock[0] += 0.01
            if adm.try_admit("c", kind_write) is None:
                adm.release("c")
                n += 1
        return n

    base_w = drain(True)
    ov.factor = 0.25  # brownout: busy shrinks WRITES by 4x...
    busy_w = drain(True)
    busy_r = drain(False)  # ...while READS keep their full budget
    assert busy_w < base_w * 0.5, (base_w, busy_w)
    assert busy_r > base_w * 0.6, (base_w, busy_r)


def test_edge_answers_32005_with_retry_hint(suite):
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.sdk.client import RpcCallError, SdkClient

    node = Node(NodeConfig(consensus="solo", crypto_backend="host",
                           min_seal_time=0.0, rpc_port=0,
                           client_write_rate=1.0, client_write_burst=1.0))
    node.start()
    try:
        kp2 = node.suite.generate_keypair(b"edge-32005")
        sdk = SdkClient(f"http://{node.rpc.host}:{node.rpc.port}")

        def send(i, wait=False):
            tx = Transaction(to=pc.BALANCE_ADDRESS,
                             input=pc.encode_call(
                                 "register",
                                 lambda w: w.blob(b"e%d" % i).u64(1)),
                             nonce=f"edge-{i}",
                             block_limit=100).sign(node.suite, kp2)
            return sdk.send_transaction(tx, wait=wait)

        send(0)  # consumes the single-token burst
        with pytest.raises(RpcCallError) as ei:
            send(1)
        assert ei.value.code == -32005
        # reads ride a SEPARATE (here unlimited) budget: never throttled
        for _ in range(20):
            sdk.get_block_number()
        # the raw reject body carries the retryAfterMs hint
        from fisco_bcos_tpu.rpc.admission import rate_limited_body
        assert b'"retryAfterMs"' in rate_limited_body(123)
    finally:
        node.stop()


def test_ws_edge_shares_the_admission_budget(suite):
    """The WS endpoint must not be an unmetered side door around the
    token buckets (found in review): the same write budget applies."""
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.sdk.client import RpcCallError
    from fisco_bcos_tpu.sdk.ws import WsSdkClient

    node = Node(NodeConfig(consensus="solo", crypto_backend="host",
                           min_seal_time=0.0, ws_port=0,
                           client_write_rate=1.0, client_write_burst=1.0))
    node.start()
    try:
        kp2 = node.suite.generate_keypair(b"ws-32005")
        cli = WsSdkClient("127.0.0.1", node.ws.port)
        try:
            def send(i):
                tx = Transaction(to=pc.BALANCE_ADDRESS,
                                 input=pc.encode_call(
                                     "register",
                                     lambda w: w.blob(b"w%d" % i).u64(1)),
                                 nonce=f"wsov-{i}",
                                 block_limit=100).sign(node.suite, kp2)
                return cli.request("sendTransaction",
                                   ["group0", "", "0x" + tx.encode().hex(),
                                    False, False])

            send(0)  # consumes the single-token burst
            with pytest.raises(RpcCallError) as ei:
                send(1)
            assert ei.value.code == -32005
            # reads stay unmetered (separate budget, here unlimited)
            for _ in range(10):
                cli.get_block_number()
        finally:
            cli.close()
    finally:
        node.stop()


# -- busy-state controller: hysteresis --------------------------------------

def test_busy_hysteresis_no_flapping():
    from fisco_bcos_tpu.utils.health import Health

    clock = [0.0]
    health = Health()
    load = [0.0]
    ctl = OverloadController(health=health, enter=0.8, exit=0.5,
                             hold_s=1.0, alpha=1.0,  # no smoothing: the
                             clock=lambda: clock[0])  # hysteresis alone
    ctl.add_signal("x", lambda: load[0])

    def tick(t, v):
        clock[0], load[0] = t, v
        ctl.sample_once()

    tick(0.0, 1.0)
    assert not ctl.busy()  # crossing seen, hold not yet served
    tick(0.5, 1.0)
    assert not ctl.busy()
    tick(1.1, 1.0)
    assert ctl.busy() and health.state() == "busy"
    assert health.sealing_allowed() and not health.writes_shed()
    # oscillation BETWEEN the thresholds: stays busy, no flapping
    for i, v in enumerate((0.6, 0.9, 0.55, 0.85, 0.6)):
        tick(1.2 + i * 0.3, v)
    assert ctl.busy() and ctl.stats()["transitions"] == 1
    # sustained recovery below exit: leaves busy after the hold
    tick(3.0, 0.2)
    assert ctl.busy()
    tick(3.5, 0.2)
    assert ctl.busy()
    tick(4.1, 0.2)
    assert not ctl.busy() and health.state() == "ok"
    assert ctl.stats()["transitions"] == 2
    # a dip that RECOVERS before the hold never clears busy (and vice
    # versa on entry): re-enter and test the cancelled exit crossing
    tick(5.0, 1.0)
    tick(6.1, 1.0)
    assert ctl.busy()
    tick(6.2, 0.2)   # dip starts
    tick(6.5, 0.9)   # ...but load returns before hold_s elapses
    tick(7.6, 0.9)
    assert ctl.busy() and ctl.stats()["transitions"] == 3


def test_busy_gauge_slots_between_health_levels():
    from fisco_bcos_tpu.utils.health import Health
    from fisco_bcos_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    h = Health(registry=reg)
    assert reg.snapshot()["gauges"]["bcos_node_health"] == 0
    h.busy("overload", "test")
    assert reg.snapshot()["gauges"]["bcos_node_health"] == 0.5
    h.degraded("storage", "worse")  # degraded outranks busy
    assert reg.snapshot()["gauges"]["bcos_node_health"] == 1
    h.clear("storage")
    assert h.state() == "busy"
    h.clear("overload")
    assert reg.snapshot()["gauges"]["bcos_node_health"] == 0


# -- gossip import gating under busy ----------------------------------------

def test_gossip_import_gated_while_busy(suite, kp):
    from fisco_bcos_tpu.net.front import FrontService
    from fisco_bcos_tpu.net.gateway import FakeGateway
    from fisco_bcos_tpu.net.txsync import TransactionSync

    gw = FakeGateway()
    pool_a = _make_pool(suite, pool_limit=100)
    pool_b = _make_pool(suite, pool_limit=100)
    front_a = FrontService(b"\xaa" * 8, gw)
    front_b = FrontService(b"\xbb" * 8, gw)
    gate_open = [False]
    ts_a = TransactionSync(front_a, pool_a, suite,
                           anti_entropy_interval=0.3)
    ts_b = TransactionSync(front_b, pool_b, suite,
                           import_gate=lambda: gate_open[0])
    ts_a.start()
    ts_b.start()
    try:
        gated0 = REGISTRY.snapshot()["counters"].get(
            "bcos_txsync_import_gated_total", 0)
        tx = _tx(suite, kp, 0, block_limit=50)
        pool_a.submit_batch([tx])  # broadcast hook gossips to B
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if REGISTRY.snapshot()["counters"].get(
                    "bcos_txsync_import_gated_total", 0) > gated0:
                break
            time.sleep(0.05)
        assert pool_b.pending_count() == 0, \
            "busy node must not import remote pending txs"
        assert REGISTRY.snapshot()["counters"].get(
            "bcos_txsync_import_gated_total", 0) > gated0
        # recovery: the gate opens and A's anti-entropy sweep re-delivers
        gate_open[0] = True
        deadline = time.monotonic() + 6.0
        while time.monotonic() < deadline and pool_b.pending_count() == 0:
            time.sleep(0.05)
        assert pool_b.pending_count() == 1
    finally:
        ts_a.stop()
        ts_b.stop()
        gw.stop()


# -- p2p send queue: drop-oldest gossip, never consensus --------------------

def _front_frame(module: int, kind: int = 0,
                 payload: bytes = b"x" * 100) -> bytes:
    from fisco_bcos_tpu.codec.wire import Writer
    return Writer().u16(module).u8(kind).u64(0).blob(payload).bytes()


def test_p2p_sendq_drops_oldest_gossip_never_consensus():
    from fisco_bcos_tpu.net.moduleid import ModuleID
    from fisco_bcos_tpu.net.p2p import _Session, _is_gossip

    assert _is_gossip(_front_frame(int(ModuleID.TxsSync)))
    assert not _is_gossip(_front_frame(int(ModuleID.PBFT)))
    # TxsSync REQUEST/RESPONSE = PBFT's fetch-missing path: protected
    assert not _is_gossip(_front_frame(int(ModuleID.TxsSync), kind=1))
    assert not _is_gossip(_front_frame(int(ModuleID.TxsSync), kind=2))
    # mux-tagged frames classify through the group tag
    from fisco_bcos_tpu.net.gateway import MUX_MAGIC
    tagged = bytes([MUX_MAGIC, 2]) + b"g0" + \
        _front_frame(int(ModuleID.TxsSync))
    assert _is_gossip(tagged)

    class BlockedSock:
        def sendall(self, data):
            time.sleep(60)  # writer parks on the first frame it picks up

        def close(self):
            pass

    sess = _Session(b"\xcc" * 8, BlockedSock(), lambda s: None,
                    max_queue=1000)
    sess.start()  # writer thread is no longer started by __init__
    try:
        # park the writer on a sacrificial frame so everything after
        # stays QUEUED deterministically
        assert sess.enqueue(b"p" * 10, droppable=False)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and sess._bytes:
            time.sleep(0.01)
        assert sess._bytes == 0, "writer never picked up the park frame"

        gossip = b"g" * 300
        consensus = b"c" * 300
        assert sess.enqueue(gossip, droppable=True)
        assert sess.enqueue(gossip, droppable=True)
        assert sess.enqueue(gossip, droppable=True)
        # queue 900/1000: a consensus frame evicts the OLDEST gossip
        assert sess.enqueue(consensus, droppable=False)
        assert sess.dropped == 1
        # two more consensus frames: evict remaining gossip, never each
        # other...
        assert sess.enqueue(consensus, droppable=False)
        assert sess.enqueue(consensus, droppable=False)
        assert sess.dropped == 3
        # ...and once only consensus remains, overflow refuses the NEW
        # frame instead of evicting protected backlog
        assert not sess.enqueue(consensus, droppable=False)
        with sess._cv:
            live = [e for e in sess._q if not e[2]]
            assert live and all(not e[1] for e in live), \
                "every surviving live frame is consensus-class"
        counters = REGISTRY.snapshot()["counters"]
        peer = (b"\xcc" * 8)[:8].hex()
        assert counters.get("bcos_p2p_sendq_dropped_total"
                            f"{{'kind': 'gossip', 'peer': '{peer}'}}",
                            0) >= 3
    finally:
        sess.close()


# -- failpoint-armed brownout + recovery on a live node ---------------------

def test_failpoint_commit_stall_triggers_brownout_and_recovery():
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.utils import failpoints as fp

    node = Node(NodeConfig(
        consensus="solo", crypto_backend="host", min_seal_time=0.0,
        tx_count_limit=5, txpool_limit=40,
        overload_enter=0.6, overload_exit=0.3, overload_hold_s=0.2,
        client_write_rate=0.0))
    suite2, kp2 = node.suite, node.suite.generate_keypair(b"brownout")
    node.start()
    try:
        # stall every commit: the pool backlog (the brownout signal here)
        # grows while the sealer keeps sealing through it
        fp.arm("scheduler.2pc.commit", "sleep(250)*40")
        txs = []
        for i in range(36):
            txs.append(Transaction(
                to=pc.BALANCE_ADDRESS,
                input=pc.encode_call(
                    "register", lambda w, i=i: w.blob(b"bo%d" % i).u64(1)),
                nonce=f"bo-{i}", block_limit=200).sign(suite2, kp2))
        res = node.txpool.submit_batch(txs)
        assert all(int(r.status) == 0 for r in res)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and not node.overload.busy():
            time.sleep(0.05)
        assert node.overload.busy(), node.overload.stats()
        assert node.health.state() == "busy"
        # brownout, not blackout: sealing continues, writes NOT shed,
        # remote-tx import IS gated
        assert node.health.sealing_allowed()
        assert not node.health.writes_shed()
        assert not node.accepting_remote_txs()
        extra = Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call("register",
                                 lambda w: w.blob(b"bo-x").u64(1)),
            nonce="bo-x", block_limit=200).sign(suite2, kp2)
        assert int(node.send_transaction(extra).status) == 0
        # recovery: disarm, drain, and the hysteresis exits busy
        fp.disarm("scheduler.2pc.commit")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and (
                node.txpool.pending_count() > 0 or node.overload.busy()):
            time.sleep(0.1)
        assert not node.overload.busy(), node.overload.stats()
        assert node.health.state() == "ok"
        assert node.accepting_remote_txs()
    finally:
        fp.disarm_all()
        node.stop()


# -- ini round-trip of the overload knobs -----------------------------------

def test_overload_config_ini_roundtrip():
    from fisco_bcos_tpu.init.node import NodeConfig
    from fisco_bcos_tpu.tool.config import (node_config_from_ini,
                                            node_config_to_ini)

    cfg = NodeConfig(txpool_low_watermark=0.6, txpool_high_watermark=0.9,
                     overload_enabled=False, overload_enter=0.7,
                     overload_exit=0.4, overload_hold_s=1.5,
                     overload_commit_backlog=9,
                     overload_busy_write_factor=0.5,
                     client_write_rate=123.0, client_write_burst=456.0,
                     client_read_rate=789.0, client_read_burst=1000.0)
    back = node_config_from_ini(node_config_to_ini(cfg))
    for field in ("txpool_low_watermark", "txpool_high_watermark",
                  "overload_enabled", "overload_enter", "overload_exit",
                  "overload_hold_s", "overload_commit_backlog",
                  "overload_busy_write_factor", "client_write_rate",
                  "client_write_burst", "client_read_rate",
                  "client_read_burst"):
        assert getattr(back, field) == getattr(cfg, field), field


# -- compaction-debt backpressure (ISSUE 17) --------------------------------

def test_compaction_debt_backpressure_ok_busy_ok(tmp_path):
    """A compaction-starved node under write load must transition
    ok -> busy on debt (the overload plane's `compaction_debt` signal),
    KEEP serving reads while busy, and drain back to ok once the
    compactor catches up — the contract that keeps a node from silently
    falling behind its own write rate at GB scale."""
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.utils.metrics import REGISTRY

    node = Node(NodeConfig(
        consensus="solo", crypto_backend="host",
        storage_backend="disk", storage_path=str(tmp_path / "data"),
        storage_memtable_mb=0,           # flush on every write batch
        storage_compact_segments=2,
        overload_hold_s=0.0,             # deterministic: no hold window
        overload_compact_debt_mb=1))     # 1 MB of debt saturates the signal
    try:
        engine = node.storage.backend    # key_page_size=auto wraps disk
        assert type(engine).__name__ == "DiskStorage"
        node.overload.sample_once()
        assert "compaction_debt" in node.overload.stats()["signals"]
        assert not node.overload.busy()

        engine._compactor.pause()        # starve compaction deliberately
        rows = [(b"bp%04d-%02d" % (i, j), b"x" * 2048)
                for i in range(24) for j in range(32)]
        for i in range(0, len(rows), 32):
            engine.set_batch("t", rows[i:i + 32])  # one flush per batch
        assert engine.compaction_debt_bytes() > (1 << 20)
        for _ in range(8):               # EWMA convergence over enter=0.85
            node.overload.sample_once()
        assert node.overload.busy()
        status = node.system_status()
        assert status["health"]["state"] == "busy"
        # reads keep serving while writes are being shed
        assert engine.get("t", b"bp0000-00") == b"x" * 2048
        assert engine.get("t", b"bp0023-31") == b"x" * 2048
        assert REGISTRY.snapshot()["gauges"][
            "bcos_storage_compaction_debt_bytes"] > 0

        engine._compactor.resume()       # catch-up drains the backlog
        deadline = time.monotonic() + 60
        while engine.compaction_debt_bytes() > 0:
            assert time.monotonic() < deadline, "debt never drained"
            time.sleep(0.05)
        for _ in range(16):              # EWMA decay below exit=0.5
            node.overload.sample_once()
        assert not node.overload.busy()
        assert node.system_status()["health"]["state"] == "ok"
        assert engine.get("t", b"bp0000-00") == b"x" * 2048
    finally:
        node.stop()
        node.storage.close()
