"""Pipelined block production: off-thread ordered commit stage +
speculative next-height execution over a stacked state view.

Covers the pipeline's correctness contract: speculation reads through the
parent's UNCOMMITTED changeset yet `state_root` stays per-changeset; a
commit failure preserves strict height ordering (N+1 refuses to land
before N) and the retried chain commits byte-identically; an aborted
speculation (view change) discards the speculative tail but never a block
already on the commit stage; a crash between N's commit and N+1's leaves
a durable prefix that replays to the identical root; and — the point —
execute(N+1) demonstrably overlaps commit(N).
"""

import threading
import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.executor.executor import TransactionExecutor
from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
from fisco_bcos_tpu.protocol import Block, BlockHeader, Transaction
from fisco_bcos_tpu.scheduler.scheduler import Scheduler
from fisco_bcos_tpu.storage.interface import Entry
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StackedStorageView, StateStorage
from fisco_bcos_tpu.txpool.txpool import TxPool


def make_stack(storage=None, pipeline=True):
    suite = make_suite(False, backend="host")
    storage = storage if storage is not None else MemoryStorage()
    ledger = Ledger(storage, suite)
    kp = suite.generate_keypair(b"pipe-node")
    ledger.build_genesis([ConsensusNode(kp.pub_bytes)])
    pool = TxPool(suite, ledger)
    sched = Scheduler(storage, ledger, TransactionExecutor(suite), suite,
                      pool, pipeline=pipeline)
    return suite, storage, ledger, pool, sched, kp


def reg_tx(suite, kp, name: bytes, value: int, nonce: str):
    return Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "register",
                           lambda w: w.blob(name).u64(value)),
                       nonce=nonce, block_limit=100).sign(suite, kp)


def transfer_tx(suite, kp, frm: bytes, to: bytes, amount: int, nonce: str):
    return Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "transfer",
                           lambda w: w.blob(frm).blob(to).u64(amount)),
                       nonce=nonce, block_limit=100).sign(suite, kp)


def make_block(number: int, kp, txs=None):
    return Block(header=BlockHeader(number=number,
                                    sealer_list=[kp.pub_bytes]),
                 transactions=list(txs or []))


# -- StackedStorageView ------------------------------------------------------

def test_stacked_view_layering():
    base = MemoryStorage()
    base.set("t", b"a", b"base-a")
    base.set("t", b"b", b"base-b")
    cs1 = {("t", b"a"): Entry(b"cs1-a"), ("t", b"c"): Entry(b"cs1-c")}
    cs2 = {("t", b"b"): Entry(b"", __import__(
        "fisco_bcos_tpu.storage.interface", fromlist=["EntryStatus"]
    ).EntryStatus.DELETED), ("t", b"d"): Entry(b"cs2-d")}
    view = StackedStorageView(base, [cs1, cs2])
    assert view.get("t", b"a") == b"cs1-a"     # older changeset wins base
    assert view.get("t", b"b") is None          # newest tombstone wins
    assert view.get("t", b"c") == b"cs1-c"
    assert view.get("t", b"d") == b"cs2-d"
    assert list(view.keys("t")) == [b"a", b"c", b"d"]
    with pytest.raises(RuntimeError):
        view.set("t", b"x", b"y")
    # an overlay over the view writes without touching it
    st = StateStorage(view)
    st.set("t", b"a", b"overlay")
    assert st.get("t", b"a") == b"overlay"
    assert view.get("t", b"a") == b"cs1-a"


# -- speculative execution ---------------------------------------------------

def test_speculative_execution_reads_uncommitted_parent():
    """Block 2 executes over block 1's NOT-yet-committed changeset: a
    transfer from an account block 1 registered succeeds only if the
    speculative read-through works — and each header's state_root stays
    the root of its OWN changeset."""
    suite, storage, ledger, pool, sched, kp = make_stack()
    b1 = make_block(1, kp, [reg_tx(suite, kp, b"alice", 100, "p1"),
                            reg_tx(suite, kp, b"bob", 1, "p2")])
    r1 = sched.execute_block(b1)
    assert r1 is not None
    b2 = make_block(2, kp, [transfer_tx(suite, kp, b"alice", b"bob", 40,
                                        "p3")])
    r2 = sched.execute_block(b2)  # block 1 is NOT committed yet
    assert r2 is not None
    assert sched.pipeline_stats()["speculative_execs"] == 1
    [rc] = r2.receipts
    assert rc.status == 0, rc.message  # the transfer saw alice's balance
    # per-changeset roots: block 2's changeset must not contain block 1's
    # register rows, and the two roots differ
    assert r1.header.state_root != r2.header.state_root
    b1_keys = set(r1.changes)
    assert all(k not in b1_keys or sched.executor.state_root(
        {k: r2.changes[k]}) for k in r2.changes)
    # commit in order; the durable state reflects both blocks
    assert sched.commit_block(r1.header)
    assert sched.commit_block(r2.header)
    assert ledger.current_number() == 2
    st = StateStorage(storage)
    bal = sched.call(Transaction(
        to=pc.BALANCE_ADDRESS,
        input=pc.encode_call("balanceOf", lambda w: w.blob(b"bob")),
        nonce="q1", block_limit=100).sign(suite, kp))
    from fisco_bcos_tpu.codec.wire import Reader
    assert Reader(bal.output).u64() == 41


def test_speculative_root_matches_serial_root():
    """The speculative N+1 produces the byte-identical header a strictly
    serial execute-after-commit produces (determinism across the two
    scheduling shapes — replicas may mix them freely)."""
    txs1 = lambda s, k: [reg_tx(s, k, b"acct-x", 10, "d1")]  # noqa: E731
    txs2 = lambda s, k: [transfer_tx(s, k, b"acct-x", b"acct-x", 0, "d2"),
                         reg_tx(s, k, b"acct-y", 3, "d3")]  # noqa: E731

    # pipelined: execute 1 and 2 back to back, then commit both
    suite, _, _, _, sp, kp = make_stack()
    r1 = sp.execute_block(make_block(1, kp, txs1(suite, kp)))
    r2 = sp.execute_block(make_block(2, kp, txs2(suite, kp)))
    assert sp.commit_block(r1.header) and sp.commit_block(r2.header)

    # serial: commit 1 before touching 2 (pipeline disabled)
    suite2, _, _, _, ss, kp2 = make_stack(pipeline=False)
    q1 = ss.execute_block(make_block(1, kp2, txs1(suite2, kp2)))
    assert ss.commit_block(q1.header)
    q2 = ss.execute_block(make_block(2, kp2, txs2(suite2, kp2)))
    assert ss.commit_block(q2.header)

    assert r1.header.state_root == q1.header.state_root
    assert r2.header.state_root == q2.header.state_root
    assert r2.header.txs_root == q2.header.txs_root


def test_commit_failure_keeps_strict_order_and_retries():
    """N's transient 2PC failure must not let N+1 land first (strict
    height ordering), and the preserved chain commits on retry — the
    speculative N+1 result stays valid because N's changeset is
    preserved byte-identically."""
    suite, storage, ledger, pool, sched, kp = make_stack()
    r1 = sched.execute_block(make_block(1, kp,
                                        [reg_tx(suite, kp, b"f1", 5, "f1")]))
    r2 = sched.execute_block(make_block(2, kp,
                                        [reg_tx(suite, kp, b"f2", 6, "f2")]))
    fails = {"n": 1}
    orig_prepare = storage.prepare

    def flaky(number, changes):
        if fails["n"]:
            fails["n"] -= 1
            raise RuntimeError("transient storage failure")
        return orig_prepare(number, changes)

    storage.prepare = flaky
    try:
        assert not sched.commit_block(r1.header)   # transient failure
        assert not sched.commit_block(r2.header)   # refused: out of order
        assert ledger.current_number() == 0        # nothing landed
        assert sched.commit_block(r1.header)       # retry succeeds
        assert sched.commit_block(r2.header)       # N+1 still valid
    finally:
        storage.prepare = orig_prepare
    assert ledger.current_number() == 2


def test_abort_speculation_discards_tail_keeps_committing():
    """A view change aborts the speculative chain — but a block already
    handed to the commit stage (checkpoint quorum) is kept and lands."""
    suite, storage, ledger, pool, sched, kp = make_stack()
    r1 = sched.execute_block(make_block(1, kp,
                                        [reg_tx(suite, kp, b"v1", 5, "v1")]))
    r2 = sched.execute_block(make_block(2, kp,
                                        [reg_tx(suite, kp, b"v2", 6, "v2")]))
    assert sched.next_executable() == 3
    # hold block 1's commit open on the commit stage
    gate = threading.Event()
    entered = threading.Event()
    orig_commit = storage.commit

    def gated(number):
        entered.set()
        assert gate.wait(20)
        return orig_commit(number)

    storage.commit = gated
    done = threading.Event()
    results = {}
    try:
        sched.commit_async(r1.header,
                           lambda ok: (results.__setitem__("ok", ok),
                                       done.set()))
        assert entered.wait(10)         # commit of 1 is mid-2PC
        dropped = sched.abort_speculation()
        assert dropped == 1             # block 2 discarded, block 1 kept
        gate.set()
        assert done.wait(10) and results["ok"]
    finally:
        gate.set()
        storage.commit = orig_commit
    assert ledger.current_number() == 1
    assert sched.next_executable() == 2
    # the discarded speculative block can never commit...
    assert not sched.commit_block(r2.header)
    # ...and a fresh block 2 executes against the durable head
    n2 = sched.execute_block(make_block(2, kp,
                                        [reg_tx(suite, kp, b"v3", 7, "v3")]))
    assert n2 is not None and sched.commit_block(n2.header)
    assert ledger.current_number() == 2


def test_execute_genuinely_overlaps_commit():
    """The instrumented overlap assertion: while block 1's 2PC is held
    open on the commit thread, block 2's execution starts AND finishes on
    the caller thread — the pipeline's defining behavior."""
    suite, storage, ledger, pool, sched, kp = make_stack()
    r1 = sched.execute_block(make_block(1, kp,
                                        [reg_tx(suite, kp, b"o1", 5, "o1")]))
    gate = threading.Event()
    entered = threading.Event()
    orig_commit = storage.commit

    def gated(number):
        entered.set()
        assert gate.wait(20)
        return orig_commit(number)

    storage.commit = gated
    done = threading.Event()
    try:
        sched.commit_async(r1.header, lambda ok: done.set())
        assert entered.wait(10)          # commit(1) is in flight
        t0 = time.monotonic()
        r2 = sched.execute_block(make_block(
            2, kp, [reg_tx(suite, kp, b"o2", 6, "o2")]))
        t_exec = time.monotonic() - t0
        assert r2 is not None            # executed WHILE commit(1) ran
        assert not done.is_set(), "commit finished before execute proved overlap"
        stats = sched.pipeline_stats()
        assert stats["overlap_commits"] >= 1
        assert stats["speculative_execs"] >= 1
        gate.set()
        assert done.wait(10)
    finally:
        gate.set()
        storage.commit = orig_commit
    assert sched.commit_block(r2.header)
    assert ledger.current_number() == 2
    assert t_exec < 20  # sanity: execute did not wait for the gate


def test_drop_executed_cascades_to_children():
    suite, storage, ledger, pool, sched, kp = make_stack()
    r1 = sched.execute_block(make_block(1, kp,
                                        [reg_tx(suite, kp, b"c1", 5, "c1")]))
    r2 = sched.execute_block(make_block(2, kp,
                                        [reg_tx(suite, kp, b"c2", 6, "c2")]))
    sched.drop_executed(r1.header)
    assert sched.next_executable() == 1  # both gone: 2 read through 1
    assert not sched.commit_block(r2.header)


def test_crash_between_commits_replays_to_identical_root(tmp_path):
    """kill -9 window: N committed durably (WAL fsync), N+1 executed
    speculatively but NOT committed. Recovery must come up at N exactly,
    and re-executing N+1 must reproduce the identical header — so a
    rejoining node converges on the same chain."""
    from fisco_bcos_tpu.storage.wal import WalStorage

    path = str(tmp_path / "db")
    storage = WalStorage(path)
    suite, _, ledger, pool, sched, kp = make_stack(storage=storage)
    r1 = sched.execute_block(make_block(1, kp,
                                        [reg_tx(suite, kp, b"k1", 5, "k1")]))
    assert sched.commit_block(r1.header)
    b2_txs = [transfer_tx(suite, kp, b"k1", b"k1", 0, "k2")]
    r2 = sched.execute_block(make_block(2, kp, list(b2_txs)))
    assert r2 is not None
    spec_hash = r2.header.hash(suite)
    spec_root = r2.header.state_root
    storage.close()  # the process dies here: block 2 never reached the WAL

    recovered = WalStorage(path)
    led2 = Ledger(recovered, suite)
    assert led2.current_number() == 1  # the speculative block left no trace
    assert led2.header_by_number(2) is None
    assert led2.header_by_number(1).state_root == r1.header.state_root
    sched2 = Scheduler(recovered, led2, TransactionExecutor(suite), suite,
                       None)
    rb2 = sched2.execute_block(make_block(2, kp, list(b2_txs)))
    assert rb2 is not None
    assert rb2.header.hash(suite) == spec_hash
    assert rb2.header.state_root == spec_root
    assert sched2.commit_block(rb2.header)
    assert led2.current_number() == 2
    recovered.close()


def test_last_committed_txs_ordered_eviction():
    suite, storage, ledger, pool, sched, kp = make_stack()
    for i in range(1, 11):
        r = sched.execute_block(make_block(
            i, kp, [reg_tx(suite, kp, b"e%d" % i, 1, "e%d" % i)]))
        assert sched.commit_block(r.header)
    keys = list(sched.last_committed_txs)
    assert keys == list(range(3, 11))  # oldest evicted in commit order


# -- sealer busy-fill --------------------------------------------------------

def test_sealer_keeps_filling_while_pipeline_busy():
    """Driven synchronously (no worker thread): a busy pipeline defers a
    partial proposal up to max_seal_time; an idle one seals at
    min_seal_time; a FULL block seals regardless."""
    from fisco_bcos_tpu.sealer.sealer import Sealer

    suite, storage, ledger, pool, sched, kp = make_stack()
    proposals = []
    busy = {"v": True}
    sealer = Sealer(pool, suite, lambda b: (proposals.append(b), True)[1],
                    max_txs_per_block=10, min_seal_time=0.0,
                    max_seal_time=5.0, pipeline_busy=lambda: busy["v"])
    pool.submit_batch([reg_tx(suite, kp, b"s%d" % i, 1, f"s{i}")
                       for i in range(3)])
    sealer.grant(1, 0)
    sealer.execute_worker()
    assert not proposals, "partial block sealed despite a busy pipeline"
    # pipeline drains -> the same partial block seals immediately
    busy["v"] = False
    sealer.execute_worker()
    assert len(proposals) == 1 and len(proposals[0].transactions) == 3
    # a FULL block never waits, busy or not
    busy["v"] = True
    pool.submit_batch([reg_tx(suite, kp, b"t%d" % i, 1, f"t{i}")
                       for i in range(10)])
    sealer.grant(2, 0)
    sealer.execute_worker()
    assert len(proposals) == 2 and len(proposals[1].transactions) == 10
    # busy-fill is a window, not a wedge: past max_seal_time it seals
    busy_sealer_txs = [reg_tx(suite, kp, b"u%d" % i, 1, f"u{i}")
                       for i in range(2)]
    pool.submit_batch(busy_sealer_txs)
    sealer.grant(3, 0)
    sealer.execute_worker()
    assert len(proposals) == 2  # still filling
    sealer._first_pending_at = time.monotonic() - 6.0  # window elapsed
    sealer.execute_worker()
    assert len(proposals) == 3


# -- live cluster ------------------------------------------------------------

def test_pbft_cluster_pipelines_under_load():
    """4-node chain with a slowed commit on node 0: the next height's
    execution provably runs speculatively while the previous commit is in
    flight, and every node converges on the identical chain."""
    from tests.test_pbft import build_cluster, stop_cluster, wait_until

    suite, gateway, nodes, _ = build_cluster(4, tx_count_limit=25)
    try:
        # slow node 0's storage commit so commit(N) reliably overlaps the
        # consensus+execution of N+1
        orig = nodes[0].storage.commit

        def slow_commit(number, _orig=orig):
            time.sleep(0.15)
            return _orig(number)

        nodes[0].storage.commit = slow_commit
        kp = suite.generate_keypair(b"pipe-load")
        txs = [reg_tx(suite, kp, b"pl%d" % i, 1, f"pl-{i}")
               for i in range(100)]  # 4 blocks of 25
        nodes[0].txpool.submit_batch(txs)
        assert wait_until(
            lambda: all(n.ledger.total_tx_count() >= 100 for n in nodes),
            timeout=60), [n.ledger.total_tx_count() for n in nodes]
        stats = nodes[0].scheduler.pipeline_stats()
        assert stats["speculative_execs"] >= 1, stats
        head = nodes[0].ledger.current_number()
        h0 = nodes[0].ledger.header_by_number(head).hash(suite)
        for n in nodes[1:]:
            assert n.ledger.header_by_number(head).hash(suite) == h0
        for n in nodes:
            assert n.ledger.total_tx_count() == 100
    finally:
        stop_cluster(gateway, nodes)
