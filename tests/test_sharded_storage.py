"""Distributed sharded storage: routing, 2PC, crash recovery, chain use.

Reference counterpart: TiKVStorage.h:50-105 — Max mode's distributed
transactional commit. The suite verifies the Percolator-style commit-point
discipline end to end: durable prepare on shards, primary-decides commit,
recovery converging crashed participants, and a PBFT chain committing
blocks through a 3-shard cluster.
"""

import threading
import time

import pytest

from fisco_bcos_tpu.storage.interface import Entry, EntryStatus
from fisco_bcos_tpu.storage.sharded import (
    COMMIT_META,
    META_KEEP,
    DurablePrepareStorage,
    ShardServer,
    ShardedStorage,
    make_shard_client,
)
from fisco_bcos_tpu.storage.wal import WalStorage


def make_local_cluster(tmp_path, n=3):
    shards = [
        DurablePrepareStorage(WalStorage(str(tmp_path / f"s{i}" / "wal")),
                              str(tmp_path / f"s{i}" / "prep"))
        for i in range(n)
    ]
    return ShardedStorage(shards)


def cs(*items):
    out = {}
    for table, key, value in items:
        out[(table, key)] = (Entry(b"", EntryStatus.DELETED)
                             if value is None else Entry(value))
    return out


ROWS = [("t_acct", f"k{i:03d}".encode(), f"v{i}".encode())
        for i in range(40)]


def test_routing_and_scan_merge(tmp_path):
    st = make_local_cluster(tmp_path)
    st.set_batch("t_acct", [(k, v) for _, k, v in ROWS])
    # every key readable through the coordinator
    for _, k, v in ROWS:
        assert st.get("t_acct", k) == v
    # rows actually spread over all shards (not piled on one)
    counts = [sum(1 for _ in sh.keys("t_acct")) for sh in st.shards]
    assert all(c > 0 for c in counts), counts
    assert sum(counts) == len(ROWS)
    # merged scan is sorted + complete; prefix scans filter
    assert list(st.keys("t_acct")) == sorted(k for _, k, _ in ROWS)
    assert list(st.keys("t_acct", b"k00")) == [
        k for _, k, _ in ROWS if k.startswith(b"k00")]
    got = st.get_batch("t_acct", [k for _, k, _ in ROWS][::-1])
    assert got == [v for _, _, v in ROWS][::-1]
    st.close()


def test_2pc_commit_and_rollback(tmp_path):
    st = make_local_cluster(tmp_path)
    st.prepare(7, cs(("t", b"a", b"1"), ("t", b"b", b"2"),
                     ("t", b"c", b"3"), ("t", b"d", None)))
    # nothing visible before commit
    assert st.get("t", b"a") is None
    st.commit(7)
    assert [st.get("t", k) for k in (b"a", b"b", b"c", b"d")] == \
        [b"1", b"2", b"3", None]
    # commit point durable on the primary (value = attempt id)
    meta = st.get(COMMIT_META, (7).to_bytes(8, "big"))
    assert meta is not None and len(meta) == 8
    st.prepare(8, cs(("t", b"a", b"X")))
    st.rollback(8)
    assert st.get("t", b"a") == b"1"
    st.close()


def test_crash_before_primary_commit_rolls_back(tmp_path):
    st = make_local_cluster(tmp_path)
    st.prepare(5, cs(*[("t", k, v) for _, k, v in ROWS[:10]]))
    st.close()  # coordinator dies before ANY commit
    st2 = make_local_cluster(tmp_path)  # restart: recover() runs in ctor
    for _, k, _ in ROWS[:10]:
        assert st2.get("t", k) is None
    assert all(not sh.pending() for sh in st2.shards)
    st2.close()


def test_crash_after_primary_commit_completes(tmp_path):
    st = make_local_cluster(tmp_path)
    changes = cs(*[("t", k, v) for _, k, v in ROWS[:10]])
    st.prepare(5, changes)
    # simulate coordinator crash between primary and secondary commits
    st.shards[0].commit(5)
    st.close()
    st2 = make_local_cluster(tmp_path)  # recover() runs in ctor
    for _, k, v in ROWS[:10]:
        assert st2.get("t", k) == v, k
    assert all(not sh.pending() for sh in st2.shards)
    st2.close()


def test_durable_prepare_survives_restart(tmp_path):
    inner = WalStorage(str(tmp_path / "wal"))
    d = DurablePrepareStorage(inner, str(tmp_path / "prep"))
    d.prepare(3, cs(("t", b"x", b"y")))
    d.close()  # crash with staged block
    d2 = DurablePrepareStorage(WalStorage(str(tmp_path / "wal")),
                               str(tmp_path / "prep"))
    assert d2.pending() == [(3, b"")]
    d2.commit(3)  # decision arrives from recovery
    assert d2.get("t", b"x") == b"y"
    assert d2.pending() == []
    d2.close()
    # re-restart: nothing pending, data persisted
    d3 = DurablePrepareStorage(WalStorage(str(tmp_path / "wal")),
                               str(tmp_path / "prep"))
    assert d3.pending() == [] and d3.get("t", b"x") == b"y"
    d3.close()


def test_torn_tmp_sidecar_cleaned_on_restart(tmp_path):
    """A crash mid-prepare leaves prepared_<n>.bin.tmp; restart must NOT
    treat it as a staged block (and must delete it)."""
    d = DurablePrepareStorage(WalStorage(str(tmp_path / "wal")),
                              str(tmp_path / "prep"))
    d.prepare(4, cs(("t", b"x", b"y")))
    d.close()
    # fake a crash mid-prepare of block 9: valid-CRC .tmp never renamed
    import os as _os
    from fisco_bcos_tpu.storage.sharded import _SIDE_HDR, _encode_staged
    import zlib as _zlib
    payload = _encode_staged(9, b"deadbeef", cs(("t", b"z", b"w")))
    with open(str(tmp_path / "prep" / "prepared_9.bin.tmp"), "wb") as f:
        f.write(_SIDE_HDR.pack(_zlib.crc32(payload), len(payload)) + payload)
    d2 = DurablePrepareStorage(WalStorage(str(tmp_path / "wal")),
                               str(tmp_path / "prep"))
    assert [n for n, _ in d2.pending()] == [4]
    assert not _os.path.exists(str(tmp_path / "prep" / "prepared_9.bin.tmp"))
    d2.close()


def test_stale_attempt_rolled_back_not_committed(tmp_path):
    """A shard staging attempt A must not be committed by recovery when the
    primary's commit point records attempt B for the same height."""
    st = make_local_cluster(tmp_path)
    st.prepare(6, cs(("t", b"k1", b"old")))
    attempt_a = dict(st.shards[1].pending()).get(6) or \
        dict(st.shards[2].pending()).get(6) or \
        dict(st.shards[0].pending())[6]
    st.rollback(6)
    # stage the same height again with different content; commit it
    st.prepare(6, cs(("t", b"k1", b"new")))
    st.commit(6)
    assert st.get("t", b"k1") == b"new"
    # resurrect a stale staging of height 6 on its owning shard
    owner = st._shard_of("t", b"k1")
    st.shards[owner].prepare(6, cs(("t", b"k1", b"old")),
                             attempt=attempt_a)
    decisions = st.recover()
    assert (owner, 6, False) in decisions  # rolled back, not committed
    assert st.get("t", b"k1") == b"new"
    st.close()


def test_commit_meta_pruned(tmp_path):
    st = make_local_cluster(tmp_path)
    n_blocks = META_KEEP + 20
    for n in range(1, n_blocks + 1):
        st.prepare(n, cs(("t", b"k%d" % n, b"v")))
        st.commit(n)
    metas = list(st.shards[0].keys(COMMIT_META))
    assert len(metas) <= META_KEEP + 1, len(metas)
    # newest rows retained for recovery
    assert (n_blocks).to_bytes(8, "big") in metas
    st.close()


def test_socket_cluster_shard_killed_between_prepare_and_commit(tmp_path):
    """The VERDICT's done-criterion: kill one shard between prepare and
    commit, restart it, and verify block atomicity via recover()."""
    def spawn(i):
        backend = DurablePrepareStorage(
            WalStorage(str(tmp_path / f"s{i}" / "wal")),
            str(tmp_path / f"s{i}" / "prep"))
        srv = ShardServer(backend)
        srv.start()
        return srv

    servers = [spawn(i) for i in range(3)]
    ports = [s.port for s in servers]
    st = ShardedStorage([make_shard_client("127.0.0.1", p) for p in ports])

    changes = cs(*[("t", k, v) for _, k, v in ROWS])
    # find a victim secondary that actually owns rows
    parts = st._split(changes)
    victim = next(i for i in (1, 2) if parts[i])
    st.prepare(11, changes)
    servers[victim].stop()
    servers[victim].backend.close()
    # commit succeeds: the block is decided at the primary; the dead
    # secondary is queued for convergence, NOT surfaced as failure
    st.commit(11)
    assert 11 in st.unresolved
    assert st.get(COMMIT_META, (11).to_bytes(8, "big")) is not None

    # restart the victim on the same directories
    servers[victim] = spawn(victim)
    st.shards[victim] = make_shard_client("127.0.0.1",
                                          servers[victim].port)
    decisions = st.recover()
    assert (victim, 11, True) in decisions
    for _, k, v in ROWS:
        assert st.get("t", k) == v
    st.close()
    for s in servers:
        s.stop()
        s.backend.close()


def test_four_node_pbft_chain_over_socket_shard_cluster(tmp_path):
    """VERDICT r3 done-criterion: a 4-node PBFT chain committing through a
    3-shard storage cluster (real sockets for the sharded node)."""
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.executor import precompiled as pc
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.ledger.ledger import ConsensusNode
    from fisco_bcos_tpu.net.gateway import FakeGateway
    from fisco_bcos_tpu.protocol import Transaction

    servers = []
    for i in range(3):
        backend = DurablePrepareStorage(
            WalStorage(str(tmp_path / f"s{i}" / "wal")),
            str(tmp_path / f"s{i}" / "prep"))
        srv = ShardServer(backend)
        srv.start()
        servers.append(srv)
    sharded = ShardedStorage(
        [make_shard_client("127.0.0.1", s.port) for s in servers])

    suite = make_suite(backend="host")
    gateway = FakeGateway()
    keypairs = [suite.generate_keypair(bytes([i + 1]) * 16)
                for i in range(4)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    nodes = []
    for i, kp in enumerate(keypairs):
        node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0, view_timeout=2.0),
                    keypair=kp, gateway=gateway,
                    storage=sharded if i == 0 else None)
        node.build_genesis(sealers)
        nodes.append(node)
    for node in nodes:
        node.start()
    try:
        kp = suite.generate_keypair(b"shard-pbft-user")
        tx = Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call("register",
                                 lambda w: w.blob(b"acct").u64(55)),
            nonce="n1",
            block_limit=nodes[0].ledger.current_number() + 100,
        ).sign(suite, kp)
        res = nodes[0].send_transaction(tx)
        assert res.status == 0, res
        deadline = time.time() + 30
        while time.time() < deadline:
            if all(n.ledger.current_number() >= 1 for n in nodes):
                break
            time.sleep(0.1)
        assert all(n.ledger.current_number() >= 1 for n in nodes), \
            [n.ledger.current_number() for n in nodes]
        # the sharded node's committed header matches the plain nodes'
        hashes = {n.ledger.header_by_number(1).hash(suite) for n in nodes}
        assert len(hashes) == 1
        rc = nodes[0].ledger.receipt(tx.hash(suite))
        assert rc is not None and rc.status == 0
        # block data really landed across the shard services
        populated = sum(
            1 for s in servers
            if any(any(True for _ in s.backend.keys(t))
                   for t in ("s_number_2_header", "s_hash_2_tx",
                             "s_hash_2_receipt")))
        assert populated >= 2
    finally:
        for node in nodes:
            node.stop()
        gateway.stop()
        sharded.close()
        for s in servers:
            s.stop()
            s.backend.close()


def test_chain_commits_through_sharded_cluster(tmp_path):
    """A node sealing real blocks with a 3-shard storage cluster as its
    transactional backend: ledger schema, receipts and state all live
    partitioned across shards."""
    from fisco_bcos_tpu.executor import precompiled as pc
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.protocol import Transaction

    st = make_local_cluster(tmp_path)
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0),
                storage=st)
    node.start()
    try:
        kp = node.suite.generate_keypair(b"shard-user")
        receipts = []
        for i in range(3):
            tx = Transaction(
                to=pc.BALANCE_ADDRESS,
                input=pc.encode_call(
                    "register", lambda w: w.blob(b"acct%d" % i).u64(100)),
                nonce=f"n{i}",
                block_limit=node.ledger.current_number() + 100,
            ).sign(node.suite, kp)
            r = node.send_transaction(tx)
            assert r.status == 0, r
            rec = node.txpool.wait_for_receipt(r.tx_hash, 15)
            assert rec is not None and rec.status == 0
            receipts.append(rec)
        assert node.ledger.current_number() >= 1
        # data genuinely distributed: >1 shard holds rows
        chain_tables = ("s_number_2_header", "s_hash_2_tx",
                        "s_hash_2_receipt", "s_balance")
        populated = sum(
            1 for sh in st.shards
            if any(any(True for _ in sh.inner.keys(t))
                   for t in chain_tables))
        assert populated >= 2
    finally:
        node.stop()
        st.close()


def test_fencing_rejects_deposed_master(tmp_path):
    """A deposed master (lower fence) must be refused shard-side on every
    2PC op, even across a shard restart (fence is durable)."""
    import pytest as _pytest

    from fisco_bcos_tpu.storage.sharded import StaleFenceError

    shards = [
        DurablePrepareStorage(WalStorage(str(tmp_path / f"s{i}" / "wal")),
                              str(tmp_path / f"s{i}" / "prep"))
        for i in range(3)
    ]
    old_master = ShardedStorage(shards, fence=1)
    old_master.prepare(1, cs(("t", b"a", b"old")))
    old_master.commit(1)

    new_master = ShardedStorage(shards, fence=2)  # failover: higher token
    new_master.prepare(2, cs(("t", b"a", b"new")))
    new_master.commit(2)

    # the deposed master resumes from a pause and tries to write
    with _pytest.raises(StaleFenceError):
        old_master.prepare(3, cs(("t", b"a", b"stale")))
    assert new_master.get("t", b"a") == b"new"

    # shard restart keeps the high-water fence
    for sh in shards:
        sh.close()
    shards2 = [
        DurablePrepareStorage(WalStorage(str(tmp_path / f"s{i}" / "wal")),
                              str(tmp_path / f"s{i}" / "prep"))
        for i in range(3)
    ]
    old2 = ShardedStorage(shards2, fence=1, recover=False)
    with _pytest.raises(StaleFenceError):
        old2.prepare(4, cs(("t", b"b", b"stale")))
    new2 = ShardedStorage(shards2, fence=2)
    assert new2.get("t", b"a") == b"new"
    new2.close()


def test_native_lsm_engine_behind_shards(tmp_path):
    """The native C++ LSM engine (bcoskv) works as a shard backend behind
    DurablePrepareStorage — Max mode on the native runtime."""
    from fisco_bcos_tpu.storage import native as native_mod

    if native_mod._load() is None:
        pytest.skip("libbcoskv.so not built")
    shards = [
        DurablePrepareStorage(
            native_mod.NativeStorage(str(tmp_path / f"s{i}" / "kv")),
            str(tmp_path / f"s{i}" / "prep"))
        for i in range(3)
    ]
    st = ShardedStorage(shards)
    st.prepare(1, cs(*[("t", k, v) for _, k, v in ROWS[:12]]))
    st.commit(1)
    for _, k, v in ROWS[:12]:
        assert st.get("t", k) == v
    # crash one shard between prepare and commit; native engine restarts
    st.prepare(2, cs(("t", b"zz", b"late")))
    st.shards[0].commit(2, fence=0)
    victim = st._shard_of("t", b"zz")
    if victim != 0:
        st.shards[victim].close()
        shards[victim] = DurablePrepareStorage(
            native_mod.NativeStorage(str(tmp_path / f"s{victim}" / "kv")),
            str(tmp_path / f"s{victim}" / "prep"))
        st.shards[victim] = shards[victim]
    st.recover()
    assert st.get("t", b"zz") == b"late"
    st.close()


def test_keypage_layers_over_sharded_cluster(tmp_path):
    """The KeyPage row-packing layer (bcos-table's KeyPageStorage) works
    over the distributed cluster: pages route/commit through the shards,
    rows read back row-level — the reference's Max layering
    (KeyPageStorage over TiKV)."""
    from fisco_bcos_tpu.storage.keypage import KeyPageStorage

    cluster = make_local_cluster(tmp_path)
    kp = KeyPageStorage(cluster, page_size=256)
    for _, k, v in ROWS:
        kp.set("t_kp", k, v)
    for _, k, v in ROWS:
        assert kp.get("t_kp", k) == v
    assert list(kp.keys("t_kp")) == sorted(k for _, k, _ in ROWS)
    # 2PC through the layering
    kp.prepare(3, cs(("t_2pc", b"a", b"1")))
    kp.commit(3)
    assert kp.get("t_2pc", b"a") == b"1"
    # pages (not rows) landed on the shards
    page_rows = sum(1 for sh in cluster.shards
                    for _ in sh.keys("t_kp"))
    assert 0 < page_rows < len(ROWS)  # packed: fewer pages than rows
    cluster.close()


def test_fence_persist_failure_retried_durably(tmp_path):
    """A failed fence persist must NOT leave the in-memory high-water
    ahead of disk: the retry has to re-drive the durable write, or a
    restart re-admits a deposed master (found by code review when the
    storage.sharded.fence_before_rename failpoint landed exactly in
    that window)."""
    from fisco_bcos_tpu.utils import failpoints as fp

    d = DurablePrepareStorage(WalStorage(str(tmp_path / "wal")),
                              str(tmp_path / "prep"))
    with fp.armed("storage.sharded.fence_before_rename", "raise*1"):
        with pytest.raises(Exception):
            d.prepare(1, cs(("t", b"x", b"y")), fence=2)
    # retry with the SAME fence: the durable write must actually run
    d.prepare(1, cs(("t", b"x", b"y")), fence=2)
    d.commit(1, fence=2)
    d.close()
    # restart: the fence high-water survived on disk, a deposed master
    # (fence 1) is refused
    from fisco_bcos_tpu.storage.sharded import StaleFenceError

    d2 = DurablePrepareStorage(WalStorage(str(tmp_path / "wal")),
                               str(tmp_path / "prep"))
    with pytest.raises(StaleFenceError):
        d2.prepare(2, cs(("t", b"a", b"b")), fence=1)
    d2.close()
