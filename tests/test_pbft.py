"""Multi-node PBFT consensus over the in-process FakeGateway transport.

Mirrors the reference's PBFTFixture pattern
(/root/reference/bcos-pbft/test/unittests/pbft/PBFTFixture.h:238-382): N
complete engines with real txpool/sealer/scheduler wired through one fake
gateway, driving full consensus rounds, view changes and late-joiner sync
deterministically in one process.
"""

import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import ConsensusNode
from fisco_bcos_tpu.net.gateway import FakeGateway
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus


def wait_until(pred, timeout=15.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


def make_tx(suite, kp, nonce, name=b"acct", amount=10):
    return Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "register",
                           lambda w: w.blob(name).u64(amount)),
                       nonce=nonce, block_limit=100).sign(suite, kp)


def build_cluster(n=4, view_timeout=2.0, tx_count_limit=1000, **cfg_kw):
    suite = make_suite(backend="host")
    gateway = FakeGateway()
    keypairs = [suite.generate_keypair(bytes([i + 1]) * 16) for i in range(n)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    nodes = []
    for kp in keypairs:
        node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0, view_timeout=view_timeout,
                               tx_count_limit=tx_count_limit, **cfg_kw),
                    keypair=kp, gateway=gateway)
        node.build_genesis(sealers)
        nodes.append(node)
    for node in nodes:
        node.start()
    return suite, gateway, nodes, sealers


def stop_cluster(gateway, nodes):
    for node in nodes:
        node.stop()
    gateway.stop()


@pytest.fixture()
def cluster():
    suite, gateway, nodes, sealers = build_cluster(4)
    yield suite, gateway, nodes, sealers
    stop_cluster(gateway, nodes)


def test_four_node_consensus_commits(cluster):
    suite, gateway, nodes, _ = cluster
    kp = suite.generate_keypair(b"pbft-user")
    tx = make_tx(suite, kp, nonce="n1")
    res = nodes[0].send_transaction(tx)
    assert res.status == TransactionStatus.OK

    assert wait_until(
        lambda: all(n.ledger.current_number() >= 1 for n in nodes)), \
        [n.ledger.current_number() for n in nodes]
    # identical committed header on every node, with a 2f+1 seal quorum
    headers = [n.ledger.header_by_number(1) for n in nodes]
    hashes = {h.hash(suite) for h in headers}
    assert len(hashes) == 1
    h = headers[0]
    assert len(h.signature_list) >= 3
    for idx, seal in h.signature_list:
        assert suite.verify(h.sealer_list[idx], h.hash(suite), seal)
    # the tx landed with a receipt everywhere
    for n in nodes:
        rc = n.ledger.receipt(tx.hash(suite))
        assert rc is not None and rc.status == 0


def test_multi_block_rotating_leaders(cluster):
    suite, gateway, nodes, _ = cluster
    kp = suite.generate_keypair(b"rotate")
    for i in range(3):
        tx = make_tx(suite, kp, nonce=f"r{i}", name=f"acct{i}".encode())
        res = nodes[i % 4].send_transaction(tx)
        assert res.status == TransactionStatus.OK
        assert wait_until(
            lambda i=i: all(n.ledger.current_number() >= i + 1
                            for n in nodes)), \
            [n.ledger.current_number() for n in nodes]
    # different sealer indexes across the three blocks (leader_period=1)
    sealers_used = {nodes[0].ledger.header_by_number(b).sealer
                    for b in (1, 2, 3)}
    assert len(sealers_used) >= 2


def test_view_change_on_leader_failure(cluster):
    suite, gateway, nodes, _ = cluster
    # leader for block 1 in view 0 is index 1 (number//1 + 0) % 4
    engines = {n.consensus.index: n for n in nodes}
    leader = engines[1 % 4]
    gateway.partition(leader.keypair.pub_bytes)

    kp = suite.generate_keypair(b"vc-user")
    tx = make_tx(suite, kp, nonce="vc1")
    live = [n for n in nodes if n is not leader]
    res = live[0].send_transaction(tx)
    assert res.status == TransactionStatus.OK

    assert wait_until(
        lambda: all(n.ledger.current_number() >= 1 for n in live),
        timeout=30.0), [n.ledger.current_number() for n in live]
    assert any(n.consensus.view >= 1 for n in live)
    h = live[0].ledger.header_by_number(1)
    assert h.sealer != leader.consensus.index

    # heal the partition: the failed leader catches up via block sync
    gateway.partition(leader.keypair.pub_bytes, isolated=False)
    assert wait_until(lambda: leader.ledger.current_number() >= 1,
                      timeout=30.0)
    assert leader.ledger.header_by_number(1).hash(suite) == h.hash(suite)


def test_late_joiner_syncs_chain(cluster):
    suite, gateway, nodes, sealers = cluster
    kp = suite.generate_keypair(b"sync-user")
    for i in range(2):
        tx = make_tx(suite, kp, nonce=f"s{i}", name=f"s{i}".encode())
        assert nodes[0].send_transaction(tx).status == TransactionStatus.OK
        assert wait_until(
            lambda i=i: all(n.ledger.current_number() >= i + 1
                            for n in nodes))

    # observer node: same genesis, not in the sealer set
    obs_kp = suite.generate_keypair(b"observer")
    observer = Node(NodeConfig(consensus="pbft", crypto_backend="host"),
                    keypair=obs_kp, gateway=gateway)
    observer.build_genesis(sealers)
    observer.start()
    try:
        assert observer.consensus is None  # not a sealer
        assert wait_until(
            lambda: observer.ledger.current_number()
            >= nodes[0].ledger.current_number(), timeout=30.0)
        target = nodes[0].ledger.current_number()
        for b in range(1, target + 1):
            assert (observer.ledger.header_by_number(b).hash(suite)
                    == nodes[0].ledger.header_by_number(b).hash(suite))
    finally:
        observer.stop()


def test_tx_gossip_reaches_all_pools():
    suite, gateway, nodes, _ = build_cluster(4, view_timeout=60.0)
    try:
        # pause sealing so txs stay pending long enough to observe
        for n in nodes:
            n.sealer.stop()
        kp = suite.generate_keypair(b"gossip")
        txs = [make_tx(suite, kp, nonce=f"g{i}", name=f"g{i}".encode())
               for i in range(5)]
        nodes[2].txpool.submit_batch(txs)
        assert wait_until(
            lambda: all(n.txpool.status()["pending"] >= 5 for n in nodes)), \
            [n.txpool.status() for n in nodes]
    finally:
        stop_cluster(gateway, nodes)


def test_crash_restart_replays_consensus_log(tmp_path):
    """Kill a quorum-breaking set of nodes mid-round (after prepare+commit
    quorum, before checkpoint exchange), restart them on the same storage,
    and the round must finish WITHOUT a view change — the persisted
    consensus log (engine.py _replay_log / storage.py PBFTLog; reference
    bcos-pbft LedgerStorage.cpp + PBFTEngine::initState) carries it."""
    from fisco_bcos_tpu.codec.wire import Reader
    from fisco_bcos_tpu.consensus.pbft.messages import PacketType, PBFTMessage
    from fisco_bcos_tpu.net.moduleid import ModuleID

    suite = make_suite(backend="host")
    gateway = FakeGateway()
    keypairs = [suite.generate_keypair(bytes([i + 1]) * 16) for i in range(4)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]

    def mk_node(i):
        return Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0, view_timeout=60.0,
                               storage_path=str(tmp_path / f"n{i}")),
                    keypair=keypairs[i], gateway=gateway)

    nodes = [mk_node(i) for i in range(4)]
    for n in nodes:
        n.build_genesis(sealers)

    # drop every CHECKPOINT packet so the round stalls after commit quorum
    def drop_checkpoints(src, dst, data):
        r = Reader(data)
        module, _, _ = r.u16(), r.u8(), r.u64()
        if module != int(ModuleID.PBFT):
            return True
        try:
            msg = PBFTMessage.decode(r.blob())
        except Exception:
            return True
        return msg.packet_type != int(PacketType.CHECKPOINT)

    gateway.set_filter(drop_checkpoints)
    try:
        for n in nodes:
            n.start()

        kp = suite.generate_keypair(b"restart-user")
        res = nodes[0].send_transaction(make_tx(suite, kp, nonce="rr1"))
        assert res.status == TransactionStatus.OK

        # every node reaches the executed state (commit quorum passed) but
        # the chain cannot advance: checkpoints are being dropped
        assert wait_until(lambda: all(
            any(c.executed for c in n.consensus._caches.values())
            for n in nodes)), "round did not reach the executed state"
        assert all(n.ledger.current_number() == 0 for n in nodes)

        # crash two nodes (quorum = 3: the survivors cannot finish alone)
        for i in (2, 3):
            nodes[i].stop()
            nodes[i].storage.close()
        gateway.set_filter(None)
        time.sleep(0.3)
        assert all(nodes[i].ledger.current_number() == 0 for i in (0, 1))

        # restart on the same storage: the replayed log finishes the round
        for i in (2, 3):
            nodes[i] = mk_node(i)
            nodes[i].start()

        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes)), \
            [n.ledger.current_number() for n in nodes]
        assert all(n.consensus.view == 0 for n in nodes), \
            "round must complete via log replay, not a view change"
        headers = [n.ledger.header_by_number(1) for n in nodes]
        hashes = {h.hash(suite) for h in headers}
        assert len(hashes) == 1
    finally:
        stop_cluster(gateway, nodes)


def test_live_consensus_membership_change(tmp_path):
    """Governance removes a sealer on-chain: remaining members recompute
    quorum and keep committing WITHOUT any restart; the removed node stops
    participating but keeps following via sync (the reference reloads
    LedgerConfig per block)."""
    suite, gateway, nodes, sealers = build_cluster(4, view_timeout=20.0)
    try:
        kp = suite.generate_keypair(b"member-user")
        res = nodes[0].send_transaction(make_tx(suite, kp, nonce="m1"))
        assert res.status == TransactionStatus.OK
        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes))

        # vote node X out (pick a non-leader for the next heights)
        sorted_ids = sorted(s.node_id for s in sealers)
        victim_id = sorted_ids[3]
        victim = next(n for n in nodes
                      if n.keypair.pub_bytes == victim_id)
        from fisco_bcos_tpu.executor import precompiled as pc
        gov = Transaction(
            to=pc.CONSENSUS_ADDRESS,
            input=pc.encode_call("remove", lambda w: w.blob(victim_id)),
            nonce="gov1", block_limit=100).sign(suite, kp)
        res = nodes[0].send_transaction(gov)
        assert res.status == TransactionStatus.OK
        assert wait_until(
            lambda: all(n.ledger.current_number() >= 2 for n in nodes))

        # remaining engines shrink to n=3 live; victim drops out
        survivors = [n for n in nodes if n is not victim]
        assert wait_until(lambda: all(
            n.consensus.n == 3 for n in survivors)), \
            [n.consensus.n for n in survivors]
        assert wait_until(lambda: victim.consensus.index == -1)

        # chain keeps committing with the reduced set, no restarts
        h0 = nodes[0].ledger.current_number()
        res = nodes[0].send_transaction(make_tx(suite, kp, nonce="m2"))
        assert res.status == TransactionStatus.OK
        assert wait_until(lambda: all(
            n.ledger.current_number() >= h0 + 1 for n in survivors)), \
            [n.ledger.current_number() for n in survivors]
        committed = survivors[0].ledger.header_by_number(h0 + 1)
        # the new block's seal quorum comes from the REDUCED set
        assert len(committed.signature_list) >= 3
        assert all(idx < 3 for idx, _seal in committed.signature_list)
        # the removed node still follows the chain via block sync
        assert wait_until(
            lambda: victim.ledger.current_number() >= h0 + 1, 20)
    finally:
        stop_cluster(gateway, nodes)


def test_observer_promoted_to_sealer_live(tmp_path):
    """addObserver/addSealer governance promotes a RUNNING observer into
    consensus with no restart: peers raise n/quorum and the promoted node
    starts its engine at the enacting commit."""
    suite, gateway, nodes, sealers = build_cluster(4, view_timeout=20.0)
    obs_kp = suite.generate_keypair(b"promotee")
    observer = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0, view_timeout=20.0),
                    keypair=obs_kp, gateway=gateway)
    observer.build_genesis(sealers)
    observer.start()
    nodes = nodes + [observer]
    try:
        assert observer.consensus is None
        kp = suite.generate_keypair(b"promo-user")
        gov = Transaction(
            to=pc.CONSENSUS_ADDRESS,
            input=pc.encode_call("addSealer",
                                 lambda w: w.blob(obs_kp.pub_bytes).u64(1)),
            nonce="pr1", block_limit=100).sign(suite, kp)
        assert nodes[0].send_transaction(gov).status == TransactionStatus.OK

        # the promoted node grows an engine; peers grow to n=5
        assert wait_until(lambda: observer.consensus is not None, 25)
        assert wait_until(lambda: all(
            n.consensus.n == 5 for n in nodes if n.consensus), 25), \
            [n.consensus.n for n in nodes if n.consensus]

        h0 = nodes[0].ledger.current_number()
        tx = make_tx(suite, kp, nonce="pr2", name=b"promo")
        assert nodes[0].send_transaction(tx).status == TransactionStatus.OK
        assert wait_until(lambda: all(
            n.ledger.current_number() >= h0 + 1 for n in nodes), 30), \
            [n.ledger.current_number() for n in nodes]
        hdr = nodes[0].ledger.header_by_number(h0 + 1)
        assert len(hdr.signature_list) >= 4  # n=5 -> quorum = 5 - 1 = 4
    finally:
        stop_cluster(gateway, nodes)


def test_four_node_sm_crypto_consensus(tmp_path):
    """国密 chain through full consensus: SM2 consensus-message signatures,
    SM2 tx recovery at ingest, SM3 Merkle roots in committed headers —
    the ProtocolInitializer's SM suite selection exercised end to end
    (the reference's createSMCryptoSuite path)."""
    suite = make_suite(True, backend="host")
    gateway = FakeGateway()
    keypairs = [suite.generate_keypair(bytes([i + 51]) * 16)
                for i in range(4)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    nodes = []
    for kp in keypairs:
        node = Node(NodeConfig(consensus="pbft", sm_crypto=True,
                               crypto_backend="host", min_seal_time=0.0,
                               view_timeout=3.0),
                    keypair=kp, gateway=gateway)
        node.build_genesis(sealers)
        nodes.append(node)
    for node in nodes:
        node.start()
    try:
        kp = suite.generate_keypair(b"sm-user")
        tx = make_tx(suite, kp, nonce="sm1")
        res = nodes[0].send_transaction(tx)
        assert res.status == TransactionStatus.OK
        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes)), \
            [n.ledger.current_number() for n in nodes]
        headers = [n.ledger.header_by_number(1) for n in nodes]
        assert len({h.hash(suite) for h in headers}) == 1
        h = headers[0]
        assert len(h.signature_list) >= 3
        for idx, seal in h.signature_list:
            assert suite.verify(h.sealer_list[idx], h.hash(suite), seal)
        # the tx root is an SM3 Merkle (bit-parity with the host oracle)
        from fisco_bcos_tpu.ops import merkle as merkle_ops
        want = merkle_ops.merkle_levels_host(
            [tx.hash(suite)], alg="sm3")[-1][0]
        assert h.txs_root == want
        rc = nodes[2].ledger.receipt(tx.hash(suite))
        assert rc is not None and rc.status == 0
    finally:
        stop_cluster(gateway, nodes)


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_verify_overlaps_execute():
    """SURVEY §5 double-buffered staging: while height N executes on the
    execution lane, the engine worker keeps processing consensus packets —
    in particular the PRE-PREPARE of N+1, whose proposal verification (the
    device batch recover on TPU deployments) then runs CONCURRENTLY with
    N's execution instead of waiting behind it."""
    suite, gateway, nodes, _ = build_cluster(4, tx_count_limit=20)
    try:
        kp = suite.generate_keypair(b"overlap-user")
        # slow down execution on node 0 so the overlap window is visible
        exec_spans = []
        verify_times = []
        orig_exec = nodes[0].scheduler.execute_block

        def slow_exec(block, *a, **kw):
            t0 = time.monotonic()
            time.sleep(0.4)
            r = orig_exec(block, *a, **kw)
            exec_spans.append((t0, time.monotonic(), block.header.number))
            return r

        nodes[0].scheduler.execute_block = slow_exec
        orig_verify = nodes[0].txpool.verify_proposal

        def timed_verify(block):
            ok = orig_verify(block)
            verify_times.append((time.monotonic(), block.header.number))
            return ok

        nodes[0].txpool.verify_proposal = timed_verify

        # 40 txs against a 20-tx block limit: at least two heights are in
        # flight back to back regardless of gossip/seal timing
        txs = [make_tx(suite, kp, nonce=f"ov-{i}", name=b"ov%d" % i)
               for i in range(40)]
        nodes[0].txpool.submit_batch(txs[:20])
        nodes[1].txpool.submit_batch(txs[20:])
        assert wait_until(
            lambda: all(n.ledger.total_tx_count() >= 40 for n in nodes),
            timeout=30), [n.ledger.total_tx_count() for n in nodes]

        # node 0 verified a LATER height's proposal before an EARLIER
        # height finished executing — verification is not serialised
        # behind the execution lane (it either overlaps the span or, with
        # eager pipelining, completes before execution even starts)
        overlapped = any(
            vt < t1 and vn > en
            for (_t0, t1, en) in exec_spans
            for (vt, vn) in verify_times)
        assert overlapped, (exec_spans, verify_times)
    finally:
        stop_cluster(gateway, nodes)


def test_compatibility_version_rolling_upgrade():
    """LedgerTypeDef.h:42 rolling-upgrade governance: a chain at genesis
    version 1.0.0 refuses the bn128 pairing precompile; a governance vote
    raises compatibility_version to 1.1.0 on-chain, and the behavior
    switches at the SAME height on all four nodes (on-chain state, not
    node-local config). Downgrades are refused."""
    from fisco_bcos_tpu.executor import precompiled as pcm

    suite = make_suite(backend="host")
    gateway = FakeGateway()
    keypairs = [suite.generate_keypair(bytes([i + 1]) * 16) for i in range(4)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    nodes = []
    for kp in keypairs:
        node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0,
                               compatibility_version="1.0.0"),
                    keypair=kp, gateway=gateway)
        node.build_genesis(sealers)
        nodes.append(node)
    for node in nodes:
        node.start()
    try:
        kp = suite.generate_keypair(b"upgrade-user")

        def submit(to, data, nonce):
            tx = Transaction(to=to, input=data, nonce=nonce,
                             block_limit=100).sign(suite, kp)
            res = nodes[0].send_transaction(tx)
            assert res.status == TransactionStatus.OK
            rc = nodes[0].txpool.wait_for_receipt(res.tx_hash, 30)
            assert rc is not None
            return rc

        # deploy a proxy whose runtime CALLs precompile 8 with its own
        # calldata and returns output(32) || call-success(32)
        runtime = bytes.fromhex(
            "3660006000376020600036600060006008"  # calldatacopy + call args
            "5af16020526040"                      # GAS CALL; mem[32]=ok
            "6000f3")                             # return mem[0:64]
        init = bytes.fromhex("601b600c600039601b6000f3") + runtime
        assert len(runtime) == 0x1b
        rc = submit(b"", init, "deploy-proxy")
        assert rc.status == 0 and rc.contract_address
        proxy = rc.contract_address

        # one-pair input with G1 = infinity: pairing product is vacuously
        # 1 — cheap, but still exercises parsing + the version gate
        g2 = (
            10857046999023057135944570762232829481370756359578518086990519993285655852781,
            11559732032986387107991004021392285783925812861821192530917403151452391805634,
            8495653923123431417604973247489272438418190587263600148770280649306958101930,
            4082367875863433681332203403145435568316851327593401208105741076214120093531)
        # EIP-197 order: x, y, then G2 imag-first
        pair_input = b"".join(v.to_bytes(32, "big")
                              for v in (0, 0, g2[1], g2[0], g2[3], g2[2]))

        # 1.0.0: the inner CALL to address 8 must FAIL (success word 0)
        rc = submit(proxy, pair_input, "pre-upgrade-call")
        assert rc.status == 0
        assert int.from_bytes(rc.output[32:64], "big") == 0

        # governance: raise the chain version
        rc = submit(pcm.SYS_CONFIG_ADDRESS,
                    pcm.encode_call("setValueByKey",
                                    lambda w: w.text("compatibility_version")
                                    .text("1.1.0")),
                    "raise-version")
        assert rc.status == 0
        upgrade_height = nodes[0].ledger.current_number()

        # downgrade attempts are refused on-chain
        rc = submit(pcm.SYS_CONFIG_ADDRESS,
                    pcm.encode_call("setValueByKey",
                                    lambda w: w.text("compatibility_version")
                                    .text("1.0.0")),
                    "downgrade-refused")
        assert rc.status != 0

        # post-upgrade: the same call now succeeds (success word 1, result
        # word 1), committed identically by all four nodes
        rc = submit(proxy, pair_input, "post-upgrade-call")
        assert rc.status == 0
        assert int.from_bytes(rc.output[32:64], "big") == 1
        assert int.from_bytes(rc.output[0:32], "big") == 1

        assert wait_until(lambda: all(
            n.ledger.current_number() >= upgrade_height + 2 for n in nodes))
        for n in nodes:
            # every node reads the same on-chain version and committed the
            # identical post-upgrade receipt
            v = n.ledger.ledger_config().compatibility_version
            assert v == (1, 1, 0), v
        hashes = nodes[0].ledger.tx_hashes_by_number(
            nodes[0].ledger.current_number())
        if hashes:
            receipts = [n.ledger.receipt(hashes[0]) for n in nodes]
            assert len({r.hash(suite) for r in receipts if r}) <= 1
    finally:
        stop_cluster(gateway, nodes)


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_view_change_carries_multiple_pipelined_heights():
    """Waterline + view change: several heights can be PREPARED in flight
    when a view change hits (execution stalled on the leader's lane). The
    VIEW_CHANGE messages must carry ALL prepared rounds and the new view's
    per-height leaders must re-propose them — none of the prepared txs may
    be lost or double-committed."""
    import threading

    suite, gateway, nodes, _ = build_cluster(4, view_timeout=3.0,
                                             tx_count_limit=25)
    try:
        kp = suite.generate_keypair(b"multi-carry")
        # stall EXECUTION on every node so consensus pipelines ahead of it
        # (prepared heights accumulate, nothing commits)
        gates = []
        for n in nodes:
            ev = threading.Event()
            orig = n.scheduler.execute_block

            def slow(block, *a, _orig=orig, _ev=ev, **kw):
                _ev.wait(20)
                return _orig(block, *a, **kw)

            n.scheduler.execute_block = slow
            gates.append(ev)

        txs = [make_tx(suite, kp, nonce=f"mc-{i}", name=b"mc%d" % i)
               for i in range(75)]  # 3 blocks of 25
        nodes[0].txpool.submit_batch(txs)
        # wait until at least two heights hold prepared certificates
        assert wait_until(lambda: any(
            sum(1 for c in n.consensus._caches.values() if c.prepared) >= 2
            for n in nodes), timeout=20), \
            [{h: c.prepared for h, c in n.consensus._caches.items()}
             for n in nodes]

        # force a view change while execution is stalled: the timers are
        # still running (in_flight rounds exist), so the stall itself
        # triggers it once view_timeout expires. Release execution only
        # AFTER the new view has been entered.
        assert wait_until(lambda: any(n.consensus.view >= 1 for n in nodes),
                          timeout=30), [n.consensus.view for n in nodes]
        for ev in gates:
            ev.set()

        # every submitted tx commits exactly once, identically everywhere
        assert wait_until(
            lambda: all(n.ledger.total_tx_count() >= 75 for n in nodes),
            timeout=60), [n.ledger.total_tx_count() for n in nodes]
        for n in nodes:
            assert n.ledger.total_tx_count() == 75  # no double commits
        head = nodes[0].ledger.current_number()
        h0 = nodes[0].ledger.header_by_number(head).hash(suite)
        for n in nodes[1:]:
            assert n.ledger.header_by_number(head).hash(suite) == h0
    finally:
        for ev in gates:
            ev.set()
        stop_cluster(gateway, nodes)


# -- quorum-certificate seal modes ------------------------------------------

def test_cert_mode_cluster_commits_one_certificate_per_block():
    """seal_mode=cert: the committed header carries ONE sentinel entry (a
    QuorumCert), never 2f+1 loose seals, it re-verifies through the shared
    span judge, and it ships fewer wire bytes than the multi-seal form."""
    from fisco_bcos_tpu.consensus import qc
    suite, gateway, nodes, _ = build_cluster(4, seal_mode="cert")
    try:
        kp = suite.generate_keypair(b"cert-user")
        for i in range(2):
            res = nodes[0].send_transaction(
                make_tx(suite, kp, nonce=f"c{i}", name=f"ca{i}".encode()))
            assert res.status == TransactionStatus.OK
            assert wait_until(
                lambda i=i: all(n.ledger.current_number() >= i + 1
                                for n in nodes)), \
                [n.ledger.current_number() for n in nodes]
        import copy
        sealer_set = sorted(n.keypair.pub_bytes for n in nodes)
        for number in (1, 2):
            headers = [n.ledger.header_by_number(number) for n in nodes]
            assert len({h.hash(suite) for h in headers}) == 1
            for h in headers:
                assert len(h.signature_list) == 1
                cert = qc.extract(h)
                assert cert is not None and cert.mode == qc.MODE_CERT
                assert cert.signer_count() >= 3
                assert qc.verify_spans([h], sealer_set, suite)[0]
            # the EXACT same quorum as loose multi-seals costs more wire
            cert = qc.extract(headers[0])
            idxs = qc.idxs_from_bitmap(cert.bitmap, 4)
            ssz = suite.signature_size
            h_multi = copy.copy(headers[0])
            h_multi.signature_list = [
                (i, cert.payload[k * ssz:(k + 1) * ssz])
                for k, i in enumerate(idxs)]
            assert (qc.seal_wire_bytes(headers[0])
                    < qc.seal_wire_bytes(h_multi))
        for n in nodes:
            st = n.consensus.status()
            assert st["sealMode"] == "cert"
            assert st["sealBytesPerBlock"] > 0
    finally:
        stop_cluster(gateway, nodes)


def test_checkpoint_seal_judging_is_one_batch_per_flush():
    """The ONE-lane-call pin at the PBFT checkpoint hop: every committed
    height's quorum rode a flush batch, and flushes never outnumber
    commits (cross-height coalescing can only make them fewer)."""
    suite, gateway, nodes, _ = build_cluster(4, seal_mode="cert")
    try:
        kp = suite.generate_keypair(b"batch-user")
        for i in range(3):
            nodes[i % 4].send_transaction(
                make_tx(suite, kp, nonce=f"b{i}", name=f"ba{i}".encode()))
        assert wait_until(
            lambda: all(n.ledger.current_number() >= 3 for n in nodes),
            timeout=30), [n.ledger.current_number() for n in nodes]
        for n in nodes:
            st = n.consensus.status()
            committed = n.ledger.current_number()
            assert 1 <= st["sealBatches"] <= committed
            # every height judged at least a 2f+1 quorum of seals
            assert st["sealsVerified"] >= 3 * committed
    finally:
        stop_cluster(gateway, nodes)


def test_aggregate_mode_cluster_commits_bls_certificate():
    """seal_mode=aggregate end-to-end: four live nodes mint and accept a
    64-byte BLS aggregate seal (PoP-registered keys), and the committed
    carriage is dramatically smaller than the multi-seal form."""
    from fisco_bcos_tpu.consensus import qc
    from fisco_bcos_tpu.crypto import agg
    suite = make_suite(backend="host")
    keypairs = [suite.generate_keypair(bytes([i + 1]) * 16) for i in range(4)]
    registry = agg.AggKeyRegistry.from_seeds(
        [(kp.pub_bytes, kp.secret.to_bytes(32, "big")) for kp in keypairs])
    gateway = FakeGateway()
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    nodes = []
    for kp in keypairs:
        node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0, view_timeout=30.0,
                               seal_mode="aggregate", agg_registry=registry),
                    keypair=kp, gateway=gateway)
        node.build_genesis(sealers)
        nodes.append(node)
    for node in nodes:
        node.start()
    try:
        kp = suite.generate_keypair(b"agg-user")
        res = nodes[0].send_transaction(make_tx(suite, kp, nonce="a1"))
        assert res.status == TransactionStatus.OK
        assert wait_until(
            lambda: all(n.ledger.current_number() >= 1 for n in nodes),
            timeout=60), [n.ledger.current_number() for n in nodes]
        sealer_set = sorted(kp.pub_bytes for kp in keypairs)
        h = nodes[0].ledger.header_by_number(1)
        cert = qc.extract(h)
        assert cert is not None and cert.mode == qc.MODE_AGGREGATE
        assert len(cert.payload) == agg.G1_BYTES
        assert qc.verify_spans([h], sealer_set, suite,
                               agg_registry=registry)[0]
        # the aggregate carriage beats even ONE loose ECDSA seal entry
        assert qc.seal_wire_bytes(h) < 3 * (8 + 4 + suite.signature_size)
    finally:
        stop_cluster(gateway, nodes)
