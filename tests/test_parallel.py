"""Mesh-sharded crypto plane (fisco_bcos_tpu.parallel).

Runs on the 8-device virtual CPU mesh (conftest forces
xla_force_host_platform_device_count=8) — the same sharding the driver's
dryrun validates, here exercised through the PRODUCT surface: a
CryptoSuite with mesh_devices set must produce bit-identical results to
the host oracle while its arrays live sharded across the mesh.
"""

import numpy as np
import pytest

from fisco_bcos_tpu.crypto import refimpl
from fisco_bcos_tpu.crypto.suite import make_suite


def _workload(suite, n, make_bad=True):
    digests, sigs, pubs = [], [], []
    for i in range(n):
        kp = suite.generate_keypair(bytes([i + 1]) * 16)
        d = suite.hash(b"mesh-tx-%d" % i)
        sigs.append(suite.sign(kp, d))
        digests.append(d)
        pubs.append(kp.pub_bytes)
    if make_bad:  # tamper the last row
        sigs[-1] = sigs[-1][:4] + b"\x5a" + sigs[-1][5:]
    return digests, sigs, pubs


def test_local_mesh_shape():
    from fisco_bcos_tpu.parallel import local_mesh

    mesh = local_mesh(8)
    assert mesh is not None and mesh.devices.size == 8
    assert local_mesh(3).devices.size == 2  # power-of-two prefix
    assert local_mesh(1) is None


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_mesh_suite_verify_and_recover_match_host():
    meshed = make_suite(backend="device", device_min_batch=1,
                        mesh_devices=8)
    host = make_suite(backend="host")
    digests, sigs, pubs = _workload(host, 16)

    ok_m = meshed.verify_batch(digests, sigs, pubs)
    ok_h = host.verify_batch(digests, sigs, pubs)
    assert ok_m.tolist() == ok_h.tolist()
    assert ok_m.tolist() == [True] * 15 + [False]

    pubs_m, okr_m = meshed.recover_batch(digests, sigs)
    pubs_h, okr_h = host.recover_batch(digests, sigs)
    assert okr_m.tolist() == okr_h.tolist()
    assert pubs_m == pubs_h
    assert meshed._mesh_kernels is not None  # the mesh path actually ran


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_mesh_suite_sm2_verify():
    meshed = make_suite(True, backend="device", device_min_batch=1,
                        mesh_devices=8)
    host = make_suite(True, backend="host")
    digests, sigs, pubs = _workload(host, 8)
    ok_m = meshed.verify_batch(digests, sigs, pubs)
    ok_h = host.verify_batch(digests, sigs, pubs)
    assert ok_m.tolist() == ok_h.tolist() == [True] * 7 + [False]


@pytest.mark.slow  # jit-heavy / long round-trip: full-suite tier (VERDICT #7)
def test_mesh_bucket_padding_covers_small_batches():
    """Batches below the mesh size still work (bucket >= mesh width)."""
    meshed = make_suite(backend="device", device_min_batch=1,
                        mesh_devices=8)
    host = make_suite(backend="host")
    digests, sigs, pubs = _workload(host, 3, make_bad=False)
    assert meshed.verify_batch(digests, sigs, pubs).tolist() == [True] * 3


def test_mesh_merkle_root_matches_host():
    """The mesh-sharded Merkle reduction must produce the same root as
    the host oracle for assorted leaf counts (incl. sub-mesh and
    non-power-of-two)."""
    from fisco_bcos_tpu.ops import merkle

    meshed = make_suite(backend="device", device_min_batch=1,
                        mesh_devices=8)
    host = make_suite(backend="host")
    rng = np.random.default_rng(31)
    for n in (1, 3, 8, 17, 40, 64):
        leaves = [rng.bytes(32) for _ in range(n)]
        want = merkle.merkle_levels_host(list(leaves), "keccak256")[-1][0]
        assert meshed.merkle_root(leaves) == want == host.merkle_root(leaves)
