"""Chaos e2e for the snapshot subsystem: disk loss + snap-sync rejoin.

The disaster-recovery claim the in-process suites cannot make: on a REAL
4-node TLS chain of OS processes, every node checkpointing + pruning on a
cadence, a node that dies by kill -9 AND loses its whole data directory
rejoins by fetching a snapshot from a PRUNED peer (which can no longer
serve the early blocks at all), installs it after one batched verify, and
replays only the tail — ending at the survivors' exact head hash and state
root without ever replaying pruned history.

Marked `slow`; `tools/sanitize_ci.sh --chaos` runs the chaos tier in CI.
"""

import re

import pytest

from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.sdk.client import TransactionBuilder
from fisco_bcos_tpu.testing.chaos import ChaosHarness

pytestmark = pytest.mark.slow

SNAP_CFG = {
    # aggressive cadence so a short test crosses several checkpoints
    "snapshot_interval": 2,
    "snapshot_prune": True,
    "snapshot_keep_tail": 0,
    "snapshot_retention": 1,
    "snap_sync_threshold": 3,
    "snapshot_chunk_bytes": 16384,
}


class _Workload:
    def __init__(self, harness):
        self.h = harness
        self.suite = harness.suite()
        self.kp = self.suite.generate_keypair(b"snap-chaos-user")
        self.builder = TransactionBuilder(
            self.suite, None, chain_id=harness.info["chain_id"],
            group_id=harness.info["group_id"])
        self.sent = 0

    def burst(self, n, via):
        for k in range(n):
            node = via[k % len(via)]
            tx = self.builder.build(
                self.kp, pc.BALANCE_ADDRESS,
                pc.encode_call("register",
                               lambda w: w.blob(b"sacct%d" % self.sent)
                               .u64(1)),
                nonce=f"snap-chaos-{self.sent}", block_limit=500)
            self.h.client(node).send_transaction(tx, wait=False)
            self.sent += 1

    def drive_to_height(self, target, via, timeout=300):
        """Commit waves of txs until every node in `via` reports at least
        `target` blocks — fire-and-forget bursts coalesce into few blocks,
        so each wave waits for its commits before the next one."""
        import time as _t
        deadline = _t.monotonic() + timeout
        while min(self.h.block_number(i) for i in via) < target:
            assert _t.monotonic() < deadline, \
                f"chain never reached height {target}"
            self.burst(2, via=via)
            self.h.wait_until(
                lambda: min(self.h.total_txs(i) for i in via) >= self.sent,
                timeout=120, what=f"wave commits toward height {target}")


def _replayed_numbers(log: str) -> list[int]:
    """Block numbers this daemon committed through sync REPLAY."""
    return [int(m) for m in
            re.findall(r"METRIC\|sync\.committed\|\d+\|number=(\d+)", log)]


def test_wiped_node_rejoins_via_snap_sync(tmp_path):
    """Acceptance: kill -9 + data-dir wipe; the node rejoins via snap-sync
    from pruned peers to the identical head hash and state root, without
    replaying pruned history."""
    with ChaosHarness(str(tmp_path / "chain"), tls=True,
                      config_overrides=SNAP_CFG) as h:
        h.start_all()
        for i in range(h.n):
            h.wait_rpc_up(i)
        w = _Workload(h)
        survivors = [0, 1, 2]

        # drive the chain past at least one checkpoint on every node: all
        # four must have pruned (the serving side of the claim) before the
        # victim goes down
        w.drive_to_height(SNAP_CFG["snapshot_interval"] + 2,
                          via=list(range(h.n)))
        h.wait_until(
            lambda: min(h.snapshot_status(i)["prunedBelow"]
                        for i in range(h.n)) > 0,
            timeout=240, what="every node checkpointed + pruned")
        floor0 = h.snapshot_status(0)["prunedBelow"]
        assert h.snapshot_status(0)["lastSnapshotNumber"] >= floor0

        h.kill(3)
        h.wipe_data(3)  # disk loss: WAL, snapshots, consensus log all gone

        # keep the chain moving (and past the snap threshold) while dead,
        # so the wiped node rejoins genuinely FAR behind
        w.drive_to_height(
            h.block_number(0) + SNAP_CFG["snap_sync_threshold"] + 1,
            via=survivors)

        h.start(3)
        h.wait_rpc_up(3)
        # total_txs reflects the installed snapshot the instant its storage
        # commit lands, which is BEFORE the sync worker finishes the
        # install path — also wait for the badge the assertions below grep
        h.wait_until(lambda: h.total_txs(3) >= w.sent
                     and "snap-sync-installed" in h.read_daemon_log(3),
                     timeout=240, what="node3 snap-sync + tail catch-up")

        log3 = h.read_daemon_log(3)
        # wiped: the daemon booted at genesis, NOT from replayed WAL
        boots = re.findall(r"\[DAEMON\]\[up\].*?number=(-?\d+)", log3)
        assert boots and int(boots[-1]) <= 0, \
            f"data dir was not actually wiped (boot heights {boots})"
        assert "snap-sync-installed" in log3, \
            "node3 caught up without the snapshot path"
        status3 = h.snapshot_status(3)
        assert status3["syncMode"] == "snap"
        floor = status3["prunedBelow"]
        assert floor > 0  # adopted snapshot implies adopted pruning floor

        # no pruned block was ever REPLAYED in the REJOINED life: daemon.log
        # survives the data wipe and spans both lives, and pre-kill the node
        # may legitimately have replayed low blocks while lagging under
        # load — only entries after the last boot count
        rejoined_log = log3[log3.rindex("[DAEMON][up]"):]
        replayed = _replayed_numbers(rejoined_log)
        installed = re.findall(
            r"METRIC\|snapshot\.install\|\d+\|number=(\d+)", rejoined_log)
        assert installed, "no snapshot install recorded"
        checkpoint = int(installed[0])
        assert all(n > checkpoint for n in replayed), \
            f"replayed pruned history: {replayed} vs checkpoint {checkpoint}"

        # identical chain: same head hash AND state root on all four
        height = h.wait_converged(range(h.n), min_height=1, timeout=180)
        hashes = {h.block_hash(i, height) for i in range(h.n)}
        assert len(hashes) == 1, f"head hash diverged at {height}: {hashes}"
        roots = {h.state_root(i, height) for i in range(h.n)}
        assert len(roots) == 1, f"state root diverged at {height}: {roots}"

        # and the freshly-rejoined (pruned) node serves the chain onward:
        # its RPC refuses nothing the others serve at the head
        blk3 = h.client(3).get_block_by_number(height, only_header=True)
        assert blk3 is not None and blk3["stateRoot"] in roots
