"""Pro/Max service split: txpool, ledger, gateway/front services.

Reference: fisco-bcos-tars-service/{TxPool,Gateway,Front}Service +
bcos-tars-protocol/client proxies — module surfaces served over RPC so
each subsystem can run in its own process.
"""

import time

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
from fisco_bcos_tpu.net.front import FrontService
from fisco_bcos_tpu.net.gateway import FakeGateway
from fisco_bcos_tpu.protocol import Block, Transaction
from fisco_bcos_tpu.services.gateway_service import FrontServer, RemoteFront
from fisco_bcos_tpu.services.ledger_service import LedgerServer, RemoteLedger
from fisco_bcos_tpu.services.txpool_service import TxPoolServer, RemoteTxPool
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.txpool.txpool import TxPool


def wait_until(pred, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture()
def pool_env():
    suite = make_suite(backend="host")
    ledger = Ledger(MemoryStorage(), suite)
    kp = suite.generate_keypair(b"svc-user")
    ledger.build_genesis([ConsensusNode(kp.pub_bytes)])
    pool = TxPool(suite, ledger, "chain0", "group0", 1000, 600)
    return suite, ledger, pool, kp


def _tx(suite, kp, nonce):
    return Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "register", lambda w: w.blob(nonce.encode())
                           .u64(1)),
                       nonce=nonce, block_limit=100).sign(suite, kp)


def test_txpool_service_roundtrip(pool_env):
    suite, ledger, pool, kp = pool_env
    server = TxPoolServer(pool)
    server.start()
    remote = RemoteTxPool("127.0.0.1", server.port)
    try:
        txs = [_tx(suite, kp, f"svc{i}") for i in range(5)]
        results = remote.submit_batch(txs)
        assert all(r.status == 0 for r in results)
        assert remote.pending_count() == 5

        sealed, hashes = remote.seal(3)
        assert len(sealed) == 3 and len(hashes) == 3
        remote.unseal(hashes)

        filled = remote.fill_block([t.hash(suite) for t in txs[:2]])
        assert filled is not None and len(filled) == 2
        assert remote.fill_block([b"\x00" * 32]) is None

        block = Block(tx_hashes=[t.hash(suite) for t in txs])
        assert remote.verify_proposal(block)
        assert remote.missing_hashes([txs[0].hash(suite), b"\x01" * 32]) \
            == [b"\x01" * 32]

        remote.on_block_committed(1, [t.hash(suite) for t in txs],
                                  [t.nonce for t in txs])
        assert remote.pending_count() == 0
    finally:
        remote.close()
        server.stop()


def test_ledger_service_roundtrip(pool_env):
    suite, ledger, pool, kp = pool_env
    server = LedgerServer(ledger)
    server.start()
    remote = RemoteLedger("127.0.0.1", server.port)
    try:
        assert remote.current_number() == ledger.current_number() == 0
        h0 = remote.header_by_number(0)
        assert h0 is not None
        assert h0.hash(suite) == ledger.header_by_number(0).hash(suite)
        assert remote.header_by_number(99) is None
        assert remote.transaction(b"\x00" * 32) is None
        value, enable = remote.system_config("tx_count_limit")
        assert value is not None and int(value) >= 1
        assert remote.system_config("no_such_key") is None  # drop-in None
        nodes = remote.consensus_nodes()
        assert nodes and nodes[0].node_id == kp.pub_bytes
    finally:
        remote.close()
        server.stop()


def test_front_service_split_dispatch_and_send():
    suite = make_suite(backend="host")
    gateway = FakeGateway()
    kp_a = suite.generate_keypair(b"fsvc-a")
    kp_b = suite.generate_keypair(b"fsvc-b")
    front_a = FrontService(kp_a.pub_bytes, gateway)
    front_b = FrontService(kp_b.pub_bytes, gateway)
    server = FrontServer(front_a)
    server.start()
    remote = RemoteFront("127.0.0.1", server.port, kp_a.pub_bytes)

    got_remote, got_b = [], []
    try:
        MODULE = 4242
        remote.register_module(MODULE, lambda s, p, r: got_remote.append(
            (s, p)))
        front_b.register_module(MODULE, lambda s, p, r: got_b.append((s, p)))

        # network -> split service: B sends to A; the remote module (in the
        # "other process") must receive it via the poll channel
        front_b.send(MODULE, kp_a.pub_bytes, b"to-split-service")
        assert wait_until(lambda: got_remote)
        assert got_remote[0] == (kp_b.pub_bytes, b"to-split-service")

        # split service -> network: remote sends through A's gateway to B
        assert remote.send(MODULE, kp_b.pub_bytes, b"from-split-service")
        assert wait_until(lambda: got_b)
        assert got_b[0] == (kp_a.pub_bytes, b"from-split-service")

        # broadcast + peers
        remote.broadcast(MODULE, b"fanout")
        assert wait_until(lambda: len(got_b) >= 2)
        assert kp_b.pub_bytes in remote.peers()
    finally:
        remote.stop()
        server.stop()
        front_a.stop()
        front_b.stop()
        gateway.stop()


def test_front_service_request_response_bridging():
    """front.request() to a module served by a SPLIT service must round
    trip: the respond channel bridges through the poll protocol."""
    suite = make_suite(backend="host")
    gateway = FakeGateway()
    kp_a = suite.generate_keypair(b"freq-a")
    kp_b = suite.generate_keypair(b"freq-b")
    front_a = FrontService(kp_a.pub_bytes, gateway)
    front_b = FrontService(kp_b.pub_bytes, gateway)
    server = FrontServer(front_a)
    server.start()
    remote = RemoteFront("127.0.0.1", server.port, kp_a.pub_bytes)
    try:
        MODULE = 777

        def handler(src, payload, respond):
            assert respond is not None  # delivered as a request
            respond(b"echo:" + payload)

        remote.register_module(MODULE, handler)
        resp = front_b.request(MODULE, kp_a.pub_bytes, b"ping", timeout=10)
        assert resp == b"echo:ping"
    finally:
        remote.stop()
        server.stop()
        front_a.stop()
        front_b.stop()
        gateway.stop()


def test_scheduler_service_execute_commit_call():
    """Consensus-side proxy executes and commits a block while storage and
    execution state live entirely in the scheduler process (Max split)."""
    from fisco_bcos_tpu.executor.executor import TransactionExecutor
    from fisco_bcos_tpu.scheduler.scheduler import Scheduler
    from fisco_bcos_tpu.services.scheduler_service import (
        RemoteScheduler,
        SchedulerServer,
    )

    suite = make_suite(backend="host")
    storage = MemoryStorage()
    ledger = Ledger(storage, suite)
    kp = suite.generate_keypair(b"sched-svc")
    ledger.build_genesis([ConsensusNode(kp.pub_bytes)])
    sched = Scheduler(storage, ledger, TransactionExecutor(suite), suite,
                      txpool=None)
    server = SchedulerServer(sched)
    server.start()
    remote = RemoteScheduler("127.0.0.1", server.port)
    try:
        txs = [_tx(suite, kp, f"ss{i}") for i in range(3)]
        block = Block(transactions=txs)
        block.header.number = 1
        block.header.timestamp = 1234
        res = remote.execute_block(block, [kp.pub_bytes])
        assert res is not None
        assert len(res.receipts) == 3
        assert all(rc.status == 0 for rc in res.receipts)
        assert res.header.txs_root != b""

        assert remote.commit_block(res.header)
        assert ledger.current_number() == 1
        assert ledger.total_tx_count() == 3

        # read path: remote call for a balance query
        q = Transaction(to=pc.BALANCE_ADDRESS,
                        input=pc.encode_call(
                            "balanceOf", lambda w: w.blob(b"ss0")))
        rc = remote.call(q)
        assert rc.status == 0
        from fisco_bcos_tpu.codec.wire import Reader
        assert Reader(rc.output).u64() == 1

        # out-of-order execution fails cleanly across the wire
        bad = Block(transactions=[_tx(suite, kp, "ss9")])
        bad.header.number = 5
        assert remote.execute_block(bad) is None
    finally:
        remote.close()
        server.stop()
        sched.shutdown()


def test_pro_rpc_service_full_stack():
    """Pro deployment shape: an HTTP JSON-RPC service owning NO chain
    state, backed by txpool/ledger/scheduler/storage service proxies into
    the core node process; the SDK works unchanged against it."""
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.sdk.client import SdkClient
    from fisco_bcos_tpu.services.ledger_service import LedgerServer
    from fisco_bcos_tpu.services.rpc_service import (
        ProNodeConfig,
        make_pro_rpc,
    )
    from fisco_bcos_tpu.services.scheduler_service import SchedulerServer
    from fisco_bcos_tpu.services.storage_service import StorageServer
    from fisco_bcos_tpu.services.txpool_service import TxPoolServer

    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0))
    node.start()
    servers = [TxPoolServer(node.txpool), LedgerServer(node.ledger),
               SchedulerServer(node.scheduler), StorageServer(node.storage)]
    for s in servers:
        s.start()
    rpc_kp = node.suite.generate_keypair(b"pro-rpc-identity")
    rpc_server, facade = make_pro_rpc(
        node.suite, rpc_kp, ProNodeConfig(),
        txpool_addr=("127.0.0.1", servers[0].port),
        ledger_addr=("127.0.0.1", servers[1].port),
        scheduler_addr=("127.0.0.1", servers[2].port),
        storage_addr=("127.0.0.1", servers[3].port))
    rpc_server.start()
    try:
        cli = SdkClient(f"http://127.0.0.1:{rpc_server.port}")
        kp = node.suite.generate_keypair(b"pro-user")
        tx = _tx(node.suite, kp, "pro1")
        rc = cli.send_transaction(tx)  # waits for the receipt via services
        assert int(rc["status"]) == 0
        assert cli.get_block_number() >= 1
        blk = cli.get_block_by_number(1)
        assert blk is not None and int(blk["number"]) == 1
        got = cli.get_transaction("0x" + tx.hash(node.suite).hex(),
                                  require_proof=True)
        assert got is not None and "txProof" in got, got
        # verify the inclusion proof that crossed the service wire (empty
        # proof is valid for a single-tx block: leaf == root)
        from fisco_bcos_tpu.ops.merkle import verify_merkle_proof

        proof = [([bytes.fromhex(s[2:]) for s in lvl["siblings"]],
                  lvl["index"]) for lvl in got["txProof"]]
        root = bytes.fromhex(got["txsRoot"][2:])
        assert verify_merkle_proof(tx.hash(node.suite), proof, root)
        sealers = cli.get_sealer_list()  # needs RemoteLedger.ledger_config
        assert len(sealers) == 1
        cfg = cli.get_system_config("tx_count_limit")
        assert int(cfg["value"]) >= 1
        # read-only call through the scheduler service
        out = cli.call(pc.BALANCE_ADDRESS,
                       pc.encode_call("balanceOf", lambda w: w.blob(b"pro1")))
        assert int(out["status"]) == 0
    finally:
        for s in servers:
            s.stop()
        rpc_server.stop()
        facade.close()
        node.stop()
