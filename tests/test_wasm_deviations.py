"""WASM deviations contract — enumerated and TESTED (VERDICT r3 weak #7).

The bundled interpreter (executor/wasm_interp.py) deliberately narrows
"same capabilities as BCOS-WASM" for determinism and consensus safety.
This file is the authoritative, executable list of those deviations —
each one asserted, so a behavior change here is a conscious consensus
decision, exactly like the EVM deviations list in executor/evm.py.

Deviation contract:
  D1  float CONSTANT opcodes (f32.const/f64.const) trap
  D2  float NUMERIC opcodes (0x8B..0xBF arithmetic/convert) trap
  D3  float MEMORY opcodes (f32/f64 load/store) trap
  D4  linear memory hard cap: 256 pages (16 MiB); memory.grow beyond it
      fails softly (-1) per spec rather than allocating
  D5  call depth capped at 128 (trap, not host recursion error)
  D6  per-instruction gas: default 1, call 5, memory 3 — deterministic
      metering, traps the instant the budget is exceeded
"""

import pytest

from fisco_bcos_tpu.executor.wasm_interp import (
    MAX_CALL_DEPTH,
    MAX_PAGES,
    Instance,
    Module,
    WasmOutOfGas,
    WasmTrap,
)
from tests.test_wasm_vm import _Asm, c32

I32 = 0x7F

# pin the contract's numeric parameters: changing any of these is a
# consensus-divergent decision and must show up as a failing test here
def test_contract_constants_pinned():
    from fisco_bcos_tpu.executor.wasm_interp import (
        COST_CALL, COST_DEFAULT, COST_MEM)
    assert MAX_PAGES == 256          # D4: 16 MiB
    assert MAX_CALL_DEPTH == 128     # D5
    assert (COST_DEFAULT, COST_CALL, COST_MEM) == (1, 5, 3)  # D6


def run_body(body: bytes, gas: int = 100_000, results=(I32,)):
    a = _Asm()
    a.func([], list(results), body)
    a.exports = [("f", 0, 0)]
    return Instance(Module(a.build()), gas=gas).invoke("f", [])


def test_d1_float_consts_trap():
    for op, imm in ((0x43, b"\x00\x00\x00\x00"),
                    (0x44, b"\x00" * 8)):
        with pytest.raises(WasmTrap, match="float"):
            run_body(bytes([op]) + imm + b"\x0b")


def test_d2_float_numeric_ops_trap():
    # f32.add (0x92), f64.mul (0xA2), i32.trunc_f32_s (0xA8): all in the
    # numeric range but float-typed -> deterministic trap
    for op in (0x92, 0xA2, 0xA8):
        with pytest.raises(WasmTrap, match="numeric|float"):
            run_body(c32(1) + c32(2) + bytes([op]) + b"\x0b")


def test_d3_float_memory_ops_trap():
    a = _Asm()
    a.mem_pages = 1
    # f32.load (0x2A): memarg align=2 offset=0
    a.func([], [I32], c32(0) + b"\x2a\x02\x00\x0b")
    a.exports = [("f", 0, 0)]
    with pytest.raises(WasmTrap, match="float memory"):
        Instance(Module(a.build()), gas=10_000).invoke("f", [])


def test_d4_memory_cap_16mib():
    a = _Asm()
    a.mem_pages = 1
    # memory.grow by MAX_PAGES (past the cap) -> -1; then grow by 1 -> ok
    a.func([], [I32], c32(MAX_PAGES) + b"\x40\x00\x0b")
    a.func([], [I32], c32(1) + b"\x40\x00\x0b")
    a.exports = [("grow_big", 0, 0), ("grow_one", 0, 1)]
    inst = Instance(Module(a.build()), gas=1_000_000)
    assert inst.invoke("grow_big", []) == [0xFFFFFFFF]  # -1: refused
    assert inst.invoke("grow_one", []) == [1]  # old size in pages


def test_d5_call_depth_cap():
    a = _Asm()
    # f(): call f()  — infinite recursion must hit the depth cap, with
    # enough gas that the cap (not OOG) is what fires
    a.func([], [], b"\x10\x00\x0b")
    a.exports = [("f", 0, 0)]
    with pytest.raises(WasmTrap) as exc_info:
        Instance(Module(a.build()),
                 gas=MAX_CALL_DEPTH * 1000).invoke("f", [])
    assert not isinstance(exc_info.value, WasmOutOfGas)
    assert "call stack exhausted" in str(exc_info.value)


def test_d6_deterministic_gas_metering():
    # i32.const + i32.const + i32.add + end: every instruction costs 1
    body = c32(1) + c32(2) + b"\x6a\x0b"
    a = _Asm()
    a.func([], [I32], body)
    a.exports = [("f", 0, 0)]
    # measure exact gas, twice: identical (deterministic metering)
    used = []
    for _ in range(2):
        inst = Instance(Module(a.build()), gas=1_000)
        inst.invoke("f", [])
        used.append(1_000 - inst.gas)
    assert used[0] == used[1] > 0
    # one unit less than the exact budget -> out of gas
    inst = Instance(Module(a.build()), gas=used[0] - 1)
    with pytest.raises(WasmOutOfGas):
        inst.invoke("f", [])
