"""Light node: proof-verifying client against a serving full node."""

import time

from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.lightnode import LightNodeClient
from fisco_bcos_tpu.net.front import FrontService
from fisco_bcos_tpu.net.gateway import FakeGateway
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.executor import precompiled as pc


def _setup():
    gw = FakeGateway()
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0),
                gateway=gw)
    node.start()
    lfront = FrontService(b"L" * 32, gw)
    sealers = [n.node_id
               for n in node.ledger.ledger_config().consensus_nodes]
    client = LightNodeClient(lfront, node.suite, sealers)
    return gw, node, client


def test_lightnode_roundtrip():
    gw, node, client = _setup()
    try:
        kp = node.suite.generate_keypair(b"light-user")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"la").u64(9)),
                         nonce="ln1",
                         block_limit=node.ledger.current_number() + 100
                         ).sign(node.suite, kp)
        status, tx_hash = client.send_transaction(tx)
        assert status == 0
        deadline = time.time() + 10
        while (client.status() or 0) < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert client.status() >= 1

        # verified header (solo: one self-seal, quorum=1)
        header = client.header(1)
        assert header is not None and header.number == 1

        # verified tx + receipt via Merkle proofs
        got_tx = client.transaction(tx_hash)
        assert got_tx is not None and got_tx.nonce == "ln1"
        rc = client.receipt(tx_hash)
        assert rc is not None and rc.status == 0

        # read-only call through the full node
        q = Transaction(to=pc.BALANCE_ADDRESS,
                        input=pc.encode_call("balanceOf",
                                             lambda w: w.blob(b"la")))
        st, out = client.call(q)
        assert st == 0
        from fisco_bcos_tpu.codec.wire import Reader
        assert Reader(out).u64() == 9
    finally:
        node.stop()
        gw.stop()


def test_lightnode_rejects_bad_quorum():
    gw, node, client = _setup()
    try:
        kp = node.suite.generate_keypair(b"light-user2")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"lb").u64(1)),
                         nonce="ln2",
                         block_limit=node.ledger.current_number() + 100
                         ).sign(node.suite, kp)
        client.send_transaction(tx)
        deadline = time.time() + 10
        while (client.status() or 0) < 1 and time.time() < deadline:
            time.sleep(0.02)
        # client configured with the WRONG consensus set must reject headers
        rogue = LightNodeClient(client.front, node.suite,
                                [b"\x99" * 64])
        assert rogue.header(1) is None
        assert client.header(1) is not None
    finally:
        node.stop()
        gw.stop()


class _CountingSuite:
    """Delegating wrapper counting the batch crypto entry points — the
    instrument behind the span-verification call-count contract."""

    def __init__(self, suite):
        self._suite = suite
        self.verify_calls = 0
        self.hash_calls = 0
        self.verify_sizes = []

    def __getattr__(self, name):
        return getattr(self._suite, name)

    def verify_batch(self, digests, sigs, pubs):
        self.verify_calls += 1
        self.verify_sizes.append(len(digests))
        return self._suite.verify_batch(digests, sigs, pubs)

    def hash_batch(self, msgs):
        self.hash_calls += 1
        return self._suite.hash_batch(msgs)


def _commit_block(node, kp, tag, n=4):
    """One batch-submitted cohort -> at least one multi-tx block; returns
    the tx hashes."""
    txs = [Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "register",
                           lambda w, i=i: w.blob(b"%s%d" % (tag, i)).u64(1)),
                       nonce=f"{tag.decode()}-{i}",
                       block_limit=node.ledger.current_number() + 100
                       ).sign(node.suite, kp) for i in range(n)]
    for res in node.txpool.submit_batch(txs):
        assert int(res.status) == 0, res
    hashes = [tx.hash(node.suite) for tx in txs]
    for h in hashes:
        assert node.txpool.wait_for_receipt(h, 20) is not None
    return hashes


def test_lightnode_span_verification_call_counts():
    """The ZK-plane contract: a whole request span verifies with ONE
    verify_batch (every header's full seal set) and bounded hash batches
    (one for payload identity, one for every proof level of every item)."""
    from fisco_bcos_tpu.lightnode import LightNodeClient

    gw, node, _ = _setup()
    try:
        kp = node.suite.generate_keypair(b"light-span")
        hashes = _commit_block(node, kp, b"sp", n=4)
        for i in range(2):  # a few more single-tx blocks for the range
            tx = Transaction(to=pc.BALANCE_ADDRESS,
                             input=pc.encode_call(
                                 "register",
                                 lambda w, i=i: w.blob(b"sr%d" % i).u64(1)),
                             nonce=f"sr-{i}",
                             block_limit=node.ledger.current_number() + 100
                             ).sign(node.suite, kp)
            node.send_transaction(tx)
            assert node.txpool.wait_for_receipt(
                tx.hash(node.suite), 20) is not None
        head = node.ledger.current_number()
        counting = _CountingSuite(node.suite)
        lfront = FrontService(b"C" * 32, gw)
        sealers = [n.node_id
                   for n in node.ledger.ledger_config().consensus_nodes]
        client = LightNodeClient(lfront, counting, sealers)

        headers = client.header_range(1, head)
        assert all(h is not None for h in headers)
        assert counting.verify_calls == 1, counting.verify_calls
        assert counting.verify_sizes[0] >= head  # every seal, one call

        counting.verify_calls = 0
        counting.hash_calls = 0
        counting.verify_sizes = []
        got = client.transactions(hashes)
        assert all(tx is not None for tx in got)
        assert [t.nonce for t in got] == [f"sp-{i}" for i in range(4)]
        # one header-quorum batch + exactly three hash batches (payload
        # identity, header-hash prefill, proof levels) for the whole
        # 4-tx span — constant in span size
        assert counting.verify_calls == 1, counting.verify_calls
        assert counting.hash_calls == 3, counting.hash_calls

        counting.hash_calls = 0
        counting.verify_calls = 0
        rcs = client.receipts(hashes)
        assert all(rc is not None for rc in rcs)
        # receipts pay one extra hash batch over transactions(): receipt
        # prefill + tx identity + header prefill + the COMBINED
        # receipt/tx proof batch (the tx proofs ride along to bind each
        # receipt to its tx's tree index)
        assert counting.verify_calls == 1 and counting.hash_calls == 4
    finally:
        node.stop()
        gw.stop()


def test_lightnode_rejects_tampered_proof_root():
    """A peer serving a proof whose root does not match the quorum-sealed
    header is rejected in the span path."""
    gw, node, client = _setup()
    try:
        kp = node.suite.generate_keypair(b"light-tamper")
        hashes = _commit_block(node, kp, b"tp", n=3)
        got = client.transactions(hashes)
        assert all(tx is not None for tx in got)
        # forge the server's root at the level-build seam
        orig = node.lightnode_server._block_levels

        def lying(memo, number, want_tx):
            ctx = orig(memo, number, want_tx)
            if ctx is None:
                return None
            return (ctx[0], ctx[1], b"\x13" * 32)
        node.lightnode_server._block_levels = lying
        got = client.transactions(hashes)
        assert all(tx is None for tx in got)
    finally:
        node.stop()
        gw.stop()


def test_lightnode_pruned_history_is_typed():
    """Body/proof requests against pruned history answer RESP_PRUNED +
    floor — a typed Pruned result, never a decode failure (regression:
    receipt_proof used to raise mid-encode when T_NUM2TXS was swept)."""
    from fisco_bcos_tpu.ledger.ledger import T_NUM2TXS
    from fisco_bcos_tpu.lightnode import Pruned

    gw, node, client = _setup()
    try:
        kp = node.suite.generate_keypair(b"light-prune")
        old = _commit_block(node, kp, b"pr", n=2)
        new = _commit_block(node, kp, b"pn", n=2)
        cut = node.ledger.receipt(new[0]).block_number
        node.ledger.prune_block_data(cut, keep_nonces=0)
        assert node.ledger.pruned_below() == cut

        got = client.transactions(old)
        assert all(isinstance(e, Pruned) and e.below == cut for e in got), got
        rcs = client.receipts(old)
        assert all(isinstance(e, Pruned) and e.below == cut for e in rcs)
        # headers below the floor still serve and verify (they survive)
        assert client.header(1) is not None
        # recent history still fully verifiable
        assert client.transaction(new[0]) is not None

        # crash-window tear: body list swept, receipt row lingering —
        # the server answers typed instead of raising mid-encode
        num = node.ledger.receipt(new[0]).block_number
        node.storage.remove_batch(T_NUM2TXS, [num.to_bytes(8, "big")])
        got = client.receipts([new[0]])
        assert isinstance(got[0], Pruned), got
    finally:
        node.stop()
        gw.stop()


def test_lightnode_quorum_counts_distinct_sealers():
    """Review fix: one compromised sealer's valid seal repeated 2f+1
    times must NOT authenticate a header — quorum counts DISTINCT sealer
    indices."""
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.lightnode import LightNodeClient
    from fisco_bcos_tpu.protocol import BlockHeader

    suite = make_suite(backend="host")
    kps = [suite.generate_keypair(b"q%d" % i) for i in range(4)]
    sealers = [kp.pub_bytes for kp in kps]
    client = LightNodeClient(front=None, suite=suite,
                             consensus_nodes=sealers)
    assert client.quorum == 3
    header = BlockHeader(number=7, extra_data=b"forged")
    hh = header.hash(suite)
    # sealer 0 compromised: its one valid seal replayed under every index
    # slot it controls (same idx repeated)
    idx0 = client.sealers.index(kps[0].pub_bytes)
    seal0 = suite.sign(kps[0], hh)
    header.signature_list = [(idx0, seal0)] * 3
    assert not client.verify_header(header)
    # the honest shape — three distinct sealers — still verifies
    header.signature_list = [
        (client.sealers.index(kp.pub_bytes), suite.sign(kp, hh))
        for kp in kps[:3]]
    assert client.verify_header(header)


def test_lightnode_rejects_garbage_responses():
    """Untrusted peer bytes: truncated/garbage responses reject whole
    (per-request None results), never raise out of the client."""
    gw, node, client = _setup()
    try:
        kp = node.suite.generate_keypair(b"light-garb")
        hashes = _commit_block(node, kp, b"gb", n=2)
        assert client.transaction(hashes[0]) is not None  # sane baseline

        def garbage(module, peer, payload, timeout=5.0):
            return b"\xff\xff\xff\xff\x00\x01garbage"
        orig = client.front.request
        client.front.request = garbage
        try:
            assert client.transactions(hashes) == [None, None]
            assert client.receipts(hashes) == [None, None]
            assert client.header_range(1, 2) == [None, None]
            assert client.header(1) is None
        finally:
            client.front.request = orig
        assert client.transaction(hashes[0]) is not None  # recovered
    finally:
        node.stop()
        gw.stop()


# -- quorum-certificate spans -----------------------------------------------

def _setup_sealmode(seal_mode, **client_kw):
    gw = FakeGateway()
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           seal_mode=seal_mode), gateway=gw)
    node.start()
    lfront = FrontService(b"L" * 32, gw)
    sealers = [n.node_id
               for n in node.ledger.ledger_config().consensus_nodes]
    client = LightNodeClient(lfront, node.suite, sealers, **client_kw)
    return gw, node, client


def test_lightnode_cert_span_is_one_lane_call():
    """Cert-mode chain: a whole header span collapses into ONE
    verify_batch — certificates and any legacy multi-seal headers in the
    same span merge into the same lane call (the 2f+1 fallback is the
    SAME code path, not a second loop)."""
    from fisco_bcos_tpu.consensus import qc

    gw, node, _ = _setup_sealmode("cert")
    try:
        kp = node.suite.generate_keypair(b"light-cert")
        _commit_block(node, kp, b"lc", n=2)
        for i in range(2):
            tx = Transaction(to=pc.BALANCE_ADDRESS,
                             input=pc.encode_call(
                                 "register",
                                 lambda w, i=i: w.blob(b"lc%d" % i).u64(1)),
                             nonce=f"lcr-{i}",
                             block_limit=node.ledger.current_number() + 100
                             ).sign(node.suite, kp)
            node.send_transaction(tx)
            assert node.txpool.wait_for_receipt(
                tx.hash(node.suite), 20) is not None
        head = node.ledger.current_number()
        counting = _CountingSuite(node.suite)
        lfront = FrontService(b"C" * 32, gw)
        sealers = [n.node_id
                   for n in node.ledger.ledger_config().consensus_nodes]
        client = LightNodeClient(lfront, counting, sealers)

        headers = client.header_range(1, head)
        assert all(h is not None for h in headers)
        assert all(qc.extract(h) is not None for h in headers)
        assert counting.verify_calls == 1, counting.verify_calls

        # mixed span through the same judge: re-carry header 1's cert as
        # legacy loose seals (signature_list is outside the header hash)
        legacy = node.ledger.header_by_number(1)
        cert = qc.extract(legacy)
        idxs = qc.idxs_from_bitmap(cert.bitmap, len(sealers))
        ssz = node.suite.signature_size
        legacy.signature_list = [
            (j, cert.payload[k * ssz:(k + 1) * ssz])
            for k, j in enumerate(idxs)]
        counting.verify_calls = 0
        ok = client.verify_headers(
            [legacy] + [node.ledger.header_by_number(b)
                        for b in range(2, head + 1)])
        assert all(ok)
        assert counting.verify_calls == 1, counting.verify_calls
    finally:
        node.stop()
        gw.stop()


def test_lightnode_aggregate_span_skips_the_lane():
    """Aggregate-mode chain: the span judge runs zero verify_batch rows
    (one pairing check per header instead), and a client WITHOUT the PoP
    registry refuses every aggregate header."""
    from fisco_bcos_tpu.crypto import agg

    gw, node, _ = _setup_sealmode("aggregate")
    try:
        registry = agg.AggKeyRegistry.from_seeds(
            [(node.keypair.pub_bytes,
              node.keypair.secret.to_bytes(32, "big"))])
        kp = node.suite.generate_keypair(b"light-agg")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"ag").u64(1)),
                         nonce="ag-1",
                         block_limit=node.ledger.current_number() + 100
                         ).sign(node.suite, kp)
        node.send_transaction(tx)
        assert node.txpool.wait_for_receipt(tx.hash(node.suite), 20)
        counting = _CountingSuite(node.suite)
        lfront = FrontService(b"C" * 32, gw)
        sealers = [n.node_id
                   for n in node.ledger.ledger_config().consensus_nodes]
        with_reg = LightNodeClient(lfront, counting, sealers,
                                   agg_registry=registry)
        h = with_reg.header(1)
        assert h is not None
        assert counting.verify_calls == 0, counting.verify_calls

        without = LightNodeClient(FrontService(b"D" * 32, gw), node.suite,
                                  sealers)
        assert without.header(1) is None
    finally:
        node.stop()
        gw.stop()
