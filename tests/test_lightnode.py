"""Light node: proof-verifying client against a serving full node."""

import time

from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.lightnode import LightNodeClient
from fisco_bcos_tpu.net.front import FrontService
from fisco_bcos_tpu.net.gateway import FakeGateway
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.executor import precompiled as pc


def _setup():
    gw = FakeGateway()
    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0),
                gateway=gw)
    node.start()
    lfront = FrontService(b"L" * 32, gw)
    sealers = [n.node_id
               for n in node.ledger.ledger_config().consensus_nodes]
    client = LightNodeClient(lfront, node.suite, sealers)
    return gw, node, client


def test_lightnode_roundtrip():
    gw, node, client = _setup()
    try:
        kp = node.suite.generate_keypair(b"light-user")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"la").u64(9)),
                         nonce="ln1",
                         block_limit=node.ledger.current_number() + 100
                         ).sign(node.suite, kp)
        status, tx_hash = client.send_transaction(tx)
        assert status == 0
        deadline = time.time() + 10
        while (client.status() or 0) < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert client.status() >= 1

        # verified header (solo: one self-seal, quorum=1)
        header = client.header(1)
        assert header is not None and header.number == 1

        # verified tx + receipt via Merkle proofs
        got_tx = client.transaction(tx_hash)
        assert got_tx is not None and got_tx.nonce == "ln1"
        rc = client.receipt(tx_hash)
        assert rc is not None and rc.status == 0

        # read-only call through the full node
        q = Transaction(to=pc.BALANCE_ADDRESS,
                        input=pc.encode_call("balanceOf",
                                             lambda w: w.blob(b"la")))
        st, out = client.call(q)
        assert st == 0
        from fisco_bcos_tpu.codec.wire import Reader
        assert Reader(out).u64() == 9
    finally:
        node.stop()
        gw.stop()


def test_lightnode_rejects_bad_quorum():
    gw, node, client = _setup()
    try:
        kp = node.suite.generate_keypair(b"light-user2")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"lb").u64(1)),
                         nonce="ln2",
                         block_limit=node.ledger.current_number() + 100
                         ).sign(node.suite, kp)
        client.send_transaction(tx)
        deadline = time.time() + 10
        while (client.status() or 0) < 1 and time.time() < deadline:
            time.sleep(0.02)
        # client configured with the WRONG consensus set must reject headers
        rogue = LightNodeClient(client.front, node.suite,
                                [b"\x99" * 64])
        assert rogue.header(1) is None
        assert client.header(1) is not None
    finally:
        node.stop()
        gw.stop()
