"""Protocol object + wire codec tests (reference: bcos-framework protocol
data model round-trips; TransactionImpl lazy hash/sender semantics)."""

import numpy as np
import pytest

from fisco_bcos_tpu.codec.wire import Reader, Writer
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.protocol import (
    Block,
    BlockHeader,
    LogEntry,
    ParentInfo,
    Receipt,
    Transaction,
    batch_hash,
    batch_recover_senders,
)


@pytest.fixture(scope="module")
def suite():
    return make_suite(backend="host")


@pytest.fixture(scope="module")
def sm_suite():
    return make_suite(sm_crypto=True, backend="host")


def test_wire_roundtrip():
    w = Writer()
    w.u8(7).u16(513).u32(1 << 30).i64(-5).u64(1 << 50).u256(1 << 200)
    w.blob(b"hello").text("world").seq([1, 2, 3], lambda ww, x: ww.u32(x))
    r = Reader(w.bytes())
    assert r.u8() == 7
    assert r.u16() == 513
    assert r.u32() == 1 << 30
    assert r.i64() == -5
    assert r.u64() == 1 << 50
    assert r.u256() == 1 << 200
    assert r.blob() == b"hello"
    assert r.text() == "world"
    assert r.seq(lambda rr: rr.u32()) == [1, 2, 3]
    assert r.done()


def test_wire_truncation_raises():
    w = Writer()
    w.blob(b"abc")
    data = w.bytes()[:-1]
    with pytest.raises(ValueError):
        Reader(data).blob()


def test_transaction_roundtrip_and_identity(suite):
    kp = suite.generate_keypair(b"acct")
    tx = Transaction(chain_id="chain0", group_id="group0", block_limit=100,
                     nonce="n-1", to=b"\x01" * 20, input=b"payload",
                     abi="abi").sign(suite, kp)
    enc = tx.encode()
    tx2 = Transaction.decode(enc)
    assert tx2.nonce == "n-1"
    assert tx2.to == b"\x01" * 20
    assert tx2.signature == tx.signature
    # identity: same unsigned bytes -> same hash; sender recovers to signer
    assert tx2.hash(suite) == tx.hash(suite)
    assert tx2.sender(suite) == kp.address


def test_transaction_tampered_sig_rejected(suite):
    kp = suite.generate_keypair(b"acct2")
    tx = Transaction(nonce="n", block_limit=5).sign(suite, kp)
    bad = bytearray(tx.signature)
    bad[1] ^= 0xFF
    tx2 = Transaction.decode(tx.encode())
    tx2.signature = bytes(bad)
    assert tx2.sender(suite) is None or tx2.sender(suite) != kp.address


def test_batch_recover(suite):
    kps = [suite.generate_keypair(bytes([i])) for i in range(4)]
    txs = [Transaction(nonce=f"n{i}", block_limit=9).sign(suite, kp)
           for i, kp in enumerate(kps)]
    txs[2].signature = txs[1].signature  # wrong sig for tx2's hash
    for t in txs:
        t._sender = None
    senders, ok = batch_recover_senders(txs, suite)
    assert list(ok[:2]) == [True, True]
    assert senders[0] == kps[0].address
    assert senders[1] == kps[1].address
    # recovered-but-wrong or invalid: either way not kps[2]
    assert senders[2] != kps[2].address
    assert ok[3] and senders[3] == kps[3].address


def test_receipt_and_header_roundtrip(suite):
    rc = Receipt(gas_used=21000, status=0, output=b"\x01",
                 logs=[LogEntry(b"\x02" * 20, [b"t1", b"t2"], b"d")],
                 block_number=7)
    rc2 = Receipt.decode(rc.encode())
    assert rc2.gas_used == 21000
    assert rc2.logs[0].topics == [b"t1", b"t2"]
    assert rc2.hash(suite) == rc.hash(suite)

    h = BlockHeader(number=9, parent_info=[ParentInfo(8, b"\xaa" * 32)],
                    txs_root=b"\x01" * 32, sealer=2,
                    sealer_list=[b"pk1", b"pk2"],
                    consensus_weights=[1, 2],
                    signature_list=[(0, b"sig0"), (1, b"sig1")])
    h2 = BlockHeader.decode(h.encode())
    assert h2.number == 9
    assert h2.parent_info[0].hash == b"\xaa" * 32
    assert h2.signature_list == [(0, b"sig0"), (1, b"sig1")]
    # hash covers core only — commit seals don't change identity
    assert h2.hash(suite) == h.hash(suite)
    h2.signature_list = []
    assert BlockHeader.decode(h2.encode()).hash(suite) == h.hash(suite)


def test_block_roots_match_merkle(suite):
    kp = suite.generate_keypair(b"rootacct")
    txs = [Transaction(nonce=f"n{i}", block_limit=3).sign(suite, kp)
           for i in range(5)]
    blk = Block(transactions=txs)
    root = blk.calculate_txs_root(suite)
    assert root == suite.merkle_root([t.hash(suite) for t in txs])
    blk2 = Block.decode(blk.encode())
    assert blk2.calculate_txs_root(suite) == root


def test_sm_suite_transaction(sm_suite):
    kp = sm_suite.generate_keypair(b"smacct")
    tx = Transaction(nonce="sm-n", block_limit=4).sign(sm_suite, kp)
    tx2 = Transaction.decode(tx.encode())
    assert tx2.sender(sm_suite) == kp.address
    assert len(tx.signature) == 128  # r|s|pub per SignatureDataWithPub


def test_structural_concepts_conformance():
    """typing.Protocol contracts (the C++20-concepts analogue) hold for
    both in-process objects and split-service proxies."""
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
    from fisco_bcos_tpu.protocol import concepts
    from fisco_bcos_tpu.services.ledger_service import RemoteLedger
    from fisco_bcos_tpu.services.txpool_service import RemoteTxPool
    from fisco_bcos_tpu.storage.memory import MemoryStorage
    from fisco_bcos_tpu.storage.state import StateStorage
    from fisco_bcos_tpu.storage.wal import WalStorage
    from fisco_bcos_tpu.txpool.txpool import TxPool
    from fisco_bcos_tpu.net.front import FrontService

    suite = make_suite(backend="host")
    ledger = Ledger(MemoryStorage(), suite)
    kp = suite.generate_keypair(b"concept")
    ledger.build_genesis([ConsensusNode(kp.pub_bytes)])
    pool = TxPool(suite, ledger, "chain0", "group0", 10, 600)

    assert isinstance(MemoryStorage(), concepts.KVWritable)
    assert isinstance(StateStorage(MemoryStorage()), concepts.KVWritable)
    assert isinstance(ledger, concepts.LedgerReader)
    assert isinstance(pool, concepts.TxPoolLike)
    # split-service proxies satisfy the SAME structural contracts
    assert issubclass(RemoteLedger, concepts.LedgerReader)
    assert issubclass(RemoteTxPool, concepts.TxPoolLike)
    assert issubclass(FrontService, concepts.FrontLike)
    # wire objects satisfy Serializable/Hashable
    tx = Transaction(nonce="c1", block_limit=9).sign(suite, kp)
    assert isinstance(tx, concepts.Serializable)
    assert isinstance(tx, concepts.Hashable)
