"""Race-stress tests: hammer the concurrency-bearing components from many
threads and check the invariants that data races would break.

Reference counterpart: SURVEY §5 sanitizers/race detection — the reference
relies on cmake SANITIZE_ADDRESS/SANITIZE_THREAD builds plus thread-safe-
by-design structures. The native engine's sanitizer builds exist via
`make -C native SANITIZE=address|thread` (FBTPU_BCOSKV_LIB selects them);
these tests are the Python-side analogue: deterministic invariant checks
under real thread contention.
"""

import queue
import threading

import pytest

from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.ledger.ledger import ConsensusNode, Ledger
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage
from fisco_bcos_tpu.txpool.txpool import TxPool

THREADS = 8


def _hammer(fn, n_threads=THREADS):
    errs: "queue.Queue" = queue.Queue()
    barrier = threading.Barrier(n_threads)

    def run(i):
        try:
            barrier.wait(timeout=10)
            fn(i)
        except Exception as exc:  # noqa: BLE001 — collected for the assert
            errs.put(exc)

    ts = [threading.Thread(target=run, args=(i,)) for i in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    assert errs.empty(), list(errs.queue)


def test_txpool_concurrent_submit_seal_commit():
    """Duplicate-submission races must never double-admit a tx, and
    concurrent seal/unseal must conserve the pending set."""
    suite = make_suite(backend="host")
    ledger = Ledger(MemoryStorage(), suite)
    kp = suite.generate_keypair(b"race-user")
    ledger.build_genesis([ConsensusNode(kp.pub_bytes)])
    pool = TxPool(suite, ledger, "chain0", "group0", 100000, 600)
    txs = [Transaction(to=pc.BALANCE_ADDRESS,
                       input=pc.encode_call(
                           "register",
                           lambda w, i=i: w.blob(b"r%d" % i).u64(1)),
                       nonce=f"race{i}", block_limit=100).sign(suite, kp)
           for i in range(48)]

    # every thread submits the SAME txs; exactly one admission each
    _hammer(lambda i: pool.submit_batch(txs))
    assert pool.pending_count() == len(txs)

    sealed_hashes: list[bytes] = []
    lk = threading.Lock()

    def seal_some(i):
        got, hashes = pool.seal(6)
        with lk:
            sealed_hashes.extend(hashes)

    _hammer(seal_some)
    # no tx sealed twice across concurrent sealers
    assert len(sealed_hashes) == len(set(sealed_hashes))
    pool.unseal(sealed_hashes)

    def commit_disjoint(i):
        chunk = txs[i * 6:(i + 1) * 6]
        pool.on_block_committed(1 + i, [t.hash(suite) for t in chunk],
                                [t.nonce for t in chunk])

    _hammer(commit_disjoint)
    assert pool.pending_count() == 0


def test_state_overlay_parallel_readers_single_writer():
    """Readers racing a writer THROUGH THE OVERLAY must see either the old
    (backend) or a new (overlay) value — never a torn/absent entry."""
    base = MemoryStorage()
    for i in range(64):
        base.set("t", b"k%d" % i, b"old")
    state = StateStorage(base)
    stop = threading.Event()
    bad: list = []

    def writer(_):
        for r in range(100):
            for i in range(64):
                state.set("t", b"k%d" % i, b"new%d" % r)
        stop.set()

    def reader(i):
        if i == 0:
            writer(i)
            return
        while not stop.is_set():
            for j in range(64):
                v = state.get("t", b"k%d" % j)
                if v is None or not (v == b"old" or v.startswith(b"new")):
                    bad.append(v)
                    return

    _hammer(reader)
    assert not bad
    assert all(state.get("t", b"k%d" % i) == b"new99" for i in range(64))


def test_wal_storage_concurrent_direct_writes(tmp_path):
    """Concurrent direct writes to WalStorage must all be durable and the
    log replayable (no interleaved/corrupt records)."""
    from fisco_bcos_tpu.storage.wal import WalStorage

    st = WalStorage(str(tmp_path / "race"))

    def write_mine(i):
        for j in range(50):
            st.set("t%d" % i, b"k%d" % j, b"v%d-%d" % (i, j))

    _hammer(write_mine)
    st.close()

    st2 = WalStorage(str(tmp_path / "race"))
    try:
        for i in range(THREADS):
            for j in range(50):
                assert st2.get("t%d" % i, b"k%d" % j) == b"v%d-%d" % (i, j)
    finally:
        st2.close()


def test_native_bcoskv_concurrent_if_available(tmp_path):
    from fisco_bcos_tpu.storage import native

    if not native.available():
        pytest.skip("native bcoskv not built")
    st = native.NativeStorage(str(tmp_path / "nkv"))

    def write_mine(i):
        for j in range(40):
            st.set("t%d" % i, b"k%d" % j, b"n%d-%d" % (i, j))

    _hammer(write_mine)
    for i in range(THREADS):
        for j in range(40):
            assert st.get("t%d" % i, b"k%d" % j) == b"n%d-%d" % (i, j)
    st.close()
