"""Extended system precompiles: BFS, TableManager/Table, auth plane,
account manager, cast — plus executor-level enforcement (deploy ACL,
method ACLs, frozen contracts/accounts).

Reference semantics: /root/reference/bcos-executor/src/precompiled/
(BFSPrecompiled.cpp, TableManagerPrecompiled.cpp, TablePrecompiled.cpp,
CastPrecompiled.cpp) and extension/ (AuthManagerPrecompiled.cpp,
ContractAuthMgrPrecompiled.cpp, AccountManagerPrecompiled.cpp).
"""

import pytest

from fisco_bcos_tpu.codec.wire import Reader
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.executor.executor import TransactionExecutor
from fisco_bcos_tpu.protocol import Transaction, TransactionStatus
from fisco_bcos_tpu.storage.memory import MemoryStorage
from fisco_bcos_tpu.storage.state import StateStorage


@pytest.fixture()
def env():
    suite = make_suite(backend="host")
    ex = TransactionExecutor(suite)
    state = StateStorage(MemoryStorage())
    kp = suite.generate_keypair(b"pre-admin")
    return suite, ex, state, kp


_N = iter(range(100000))


def run(env, to, method, build=None, kp=None, status=0):
    suite, ex, state, kp0 = env
    tx = Transaction(to=to, input=pc.encode_call(method, build),
                     nonce=f"px{next(_N)}", block_limit=100
                     ).sign(suite, kp or kp0)
    rc = ex.execute_transaction(tx, state, 1, 0)
    assert rc.status == int(status), (method, rc.status, rc.message)
    return rc


# ---------------------------------------------------------------------------
# BFS
# ---------------------------------------------------------------------------

def test_bfs_mkdir_touch_list_link(env):
    rc = run(env, pc.BFS_ADDRESS, "mkdir", lambda w: w.text("/apps/dex/v1"))
    run(env, pc.BFS_ADDRESS, "touch",
        lambda w: w.text("/apps/dex/v1/readme").text("file"))
    rc = run(env, pc.BFS_ADDRESS, "list", lambda w: w.text("/apps/dex/v1"))
    r = Reader(rc.output)
    n = r.u32()
    assert n == 1 and r.text() == "readme" and r.text() == "file"
    # link + readlink round trip
    addr20 = b"\x42" * 20
    run(env, pc.BFS_ADDRESS, "link",
        lambda w: w.text("dex").text("2.0").blob(addr20).blob(b"[]"))
    rc = run(env, pc.BFS_ADDRESS, "readlink",
             lambda w: w.text("/apps/dex/2.0"))
    assert Reader(rc.output).blob() == addr20
    # root listing includes the standard dirs + created ones
    rc = run(env, pc.BFS_ADDRESS, "list", lambda w: w.text("/"))
    names = []
    r = Reader(rc.output)
    for _ in range(r.u32()):
        names.append(r.text())
        r.text()
    assert {"apps", "tables", "sys", "usr"} <= set(names)


def test_bfs_rejects_bad_paths(env):
    run(env, pc.BFS_ADDRESS, "mkdir", lambda w: w.text("relative/x"),
        status=TransactionStatus.PRECOMPILED_ERROR)
    run(env, pc.BFS_ADDRESS, "touch",
        lambda w: w.text("/nonexistent/dir/file").text("file"),
        status=TransactionStatus.PRECOMPILED_ERROR)


# ---------------------------------------------------------------------------
# TableManager / Table
# ---------------------------------------------------------------------------

def _mk_table(env, name="t_test"):
    run(env, pc.TABLE_MANAGER_ADDRESS, "createTable",
        lambda w: (w.text(name).text("id")
                   .seq(["name", "score"], lambda ww, c: ww.text(c))))


def test_table_schema_and_rows(env):
    _mk_table(env)
    rc = run(env, pc.TABLE_MANAGER_ADDRESS, "desc",
             lambda w: w.text("t_test"))
    r = Reader(rc.output)
    assert r.text() == "id"
    assert r.seq(lambda rr: rr.text()) == ["name", "score"]

    run(env, pc.TABLE_ADDRESS, "insert",
        lambda w: w.text("t_test").text("k1")
        .seq(["alice", "90"], lambda ww, v: ww.text(v)))
    rc = run(env, pc.TABLE_ADDRESS, "select",
             lambda w: w.text("t_test").text("k1"))
    r = Reader(rc.output)
    assert r.u8() == 1 and r.seq(lambda rr: rr.text()) == ["alice", "90"]

    run(env, pc.TABLE_ADDRESS, "update",
        lambda w: w.text("t_test").text("k1")
        .seq([("score", "95")], lambda ww, u: ww.text(u[0]).text(u[1])))
    rc = run(env, pc.TABLE_ADDRESS, "select",
             lambda w: w.text("t_test").text("k1"))
    r = Reader(rc.output)
    r.u8()
    assert r.seq(lambda rr: rr.text()) == ["alice", "95"]

    rc = run(env, pc.TABLE_ADDRESS, "remove",
             lambda w: w.text("t_test").text("k1"))
    assert Reader(rc.output).u32() == 1
    rc = run(env, pc.TABLE_ADDRESS, "select",
             lambda w: w.text("t_test").text("k1"))
    assert Reader(rc.output).u8() == 0


def test_table_condition_scan_and_count(env):
    _mk_table(env)
    for i in range(10):
        run(env, pc.TABLE_ADDRESS, "insert",
            lambda w, i=i: w.text("t_test").text(f"k{i}")
            .seq([f"u{i}", str(i)], lambda ww, v: ww.text(v)))
    # select k3 < key <= k7, limit (offset 1, count 2)
    rc = run(env, pc.TABLE_ADDRESS, "selectByCondition",
             lambda w: w.text("t_test")
             .seq([(2, "k3"), (5, "k7")],
                  lambda ww, c: ww.u8(c[0]).text(c[1]))
             .u32(1).u32(2))
    r = Reader(rc.output)
    assert r.u32() == 2
    assert r.text() == "k5"  # k4 skipped by offset
    r.seq(lambda rr: rr.text())
    assert r.text() == "k6"
    rc = run(env, pc.TABLE_ADDRESS, "count",
             lambda w: w.text("t_test")
             .seq([(3, "k5")], lambda ww, c: ww.u8(c[0]).text(c[1])))
    assert Reader(rc.output).u32() == 5  # k5..k9


def test_table_append_columns(env):
    _mk_table(env)
    run(env, pc.TABLE_MANAGER_ADDRESS, "appendColumns",
        lambda w: w.text("t_test").seq(["rank"], lambda ww, c: ww.text(c)))
    rc = run(env, pc.TABLE_MANAGER_ADDRESS, "desc",
             lambda w: w.text("t_test"))
    r = Reader(rc.output)
    r.text()
    assert r.seq(lambda rr: rr.text()) == ["name", "score", "rank"]


# ---------------------------------------------------------------------------
# auth plane: deploy ACL governance round trip + method ACL + freezes
# ---------------------------------------------------------------------------

EVM_COUNTER = bytes.fromhex(  # PUSH1 0 PUSH1 0 RETURN (deploys empty code)
    "60006000f3")


def test_deploy_auth_deny_allow_roundtrip(env):
    suite, ex, state, gov = env
    outsider = suite.generate_keypair(b"outsider-kp")

    # governor bootstraps and switches the chain to whitelist deploys
    run(env, pc.AUTH_MANAGER_ADDRESS, "setDeployAuthType",
        lambda w: w.u8(pc.AUTH_WHITE))
    # outsider cannot change policy now
    run(env, pc.AUTH_MANAGER_ADDRESS, "setDeployAuthType", lambda w: w.u8(0),
        kp=outsider, status=TransactionStatus.PERMISSION_DENIED)

    deploy = Transaction(to=b"", input=EVM_COUNTER, nonce="d1",
                         block_limit=100).sign(suite, outsider)
    rc = ex.execute_transaction(deploy, state, 1, 0)
    assert rc.status == int(TransactionStatus.PERMISSION_DENIED)

    # governor whitelists the outsider -> deploy succeeds
    run(env, pc.AUTH_MANAGER_ADDRESS, "openDeployAuth",
        lambda w: w.blob(outsider.address))
    rc2 = run(env, pc.AUTH_MANAGER_ADDRESS, "hasDeployAuth",
              lambda w: w.blob(outsider.address))
    assert Reader(rc2.output).u8() == 1
    deploy2 = Transaction(to=b"", input=EVM_COUNTER, nonce="d2",
                          block_limit=100).sign(suite, outsider)
    rc = ex.execute_transaction(deploy2, state, 1, 0)
    assert rc.status == 0, rc.message

    # close it again -> denied again
    run(env, pc.AUTH_MANAGER_ADDRESS, "closeDeployAuth",
        lambda w: w.blob(outsider.address))
    deploy3 = Transaction(to=b"", input=EVM_COUNTER, nonce="d3",
                          block_limit=100).sign(suite, outsider)
    rc = ex.execute_transaction(deploy3, state, 1, 0)
    assert rc.status == int(TransactionStatus.PERMISSION_DENIED)


def _deploy_evm(env, kp=None, nonce="m1"):
    suite, ex, state, kp0 = env
    tx = Transaction(to=b"", input=EVM_COUNTER, nonce=nonce,
                     block_limit=100).sign(suite, kp or kp0)
    rc = ex.execute_transaction(tx, state, 1, 0)
    assert rc.status == 0
    return rc.contract_address


def test_method_auth_whitelist(env):
    suite, ex, state, admin = env
    caller = suite.generate_keypair(b"method-caller")
    addr = _deploy_evm(env)
    sel = b"\xde\xad\xbe\xef"

    # whitelist with empty ACL: everyone but the admin is denied
    run(env, pc.CONTRACT_AUTH_ADDRESS, "setMethodAuthType",
        lambda w: w.blob(addr).blob(sel).u8(pc.AUTH_WHITE))
    call = Transaction(to=addr, input=sel + b"\x00", nonce="mc1",
                       block_limit=100).sign(suite, caller)
    rc = ex.execute_transaction(call, state, 1, 0)
    assert rc.status == int(TransactionStatus.PERMISSION_DENIED)

    run(env, pc.CONTRACT_AUTH_ADDRESS, "openMethodAuth",
        lambda w: w.blob(addr).blob(sel).blob(caller.address))
    call2 = Transaction(to=addr, input=sel + b"\x00", nonce="mc2",
                        block_limit=100).sign(suite, caller)
    rc = ex.execute_transaction(call2, state, 1, 0)
    assert rc.status != int(TransactionStatus.PERMISSION_DENIED)

    # non-admin cannot mutate the ACL
    run(env, pc.CONTRACT_AUTH_ADDRESS, "openMethodAuth",
        lambda w: w.blob(addr).blob(sel).blob(caller.address),
        kp=caller, status=TransactionStatus.PERMISSION_DENIED)


def test_contract_freeze_and_account_freeze(env):
    suite, ex, state, admin = env
    addr = _deploy_evm(env, nonce="fz1")
    run(env, pc.CONTRACT_AUTH_ADDRESS, "setContractStatus",
        lambda w: w.blob(addr).u8(1))
    call = Transaction(to=addr, input=b"\x01\x02\x03\x04", nonce="fz2",
                       block_limit=100).sign(suite, admin)
    rc = ex.execute_transaction(call, state, 1, 0)
    assert rc.status == int(TransactionStatus.CONTRACT_FROZEN)
    run(env, pc.CONTRACT_AUTH_ADDRESS, "setContractStatus",
        lambda w: w.blob(addr).u8(0))

    victim = suite.generate_keypair(b"frozen-user")
    run(env, pc.ACCOUNT_MANAGER_ADDRESS, "setAccountStatus",
        lambda w: w.blob(victim.address).u8(pc.ACCOUNT_FROZEN))
    rc2 = run(env, pc.ACCOUNT_MANAGER_ADDRESS, "getAccountStatus",
              lambda w: w.blob(victim.address))
    assert Reader(rc2.output).u8() == pc.ACCOUNT_FROZEN
    tx = Transaction(to=pc.BALANCE_ADDRESS,
                     input=pc.encode_call(
                         "register", lambda w: w.blob(b"v").u64(1)),
                     nonce="fz3", block_limit=100).sign(suite, victim)
    rc = ex.execute_transaction(tx, state, 1, 0)
    assert rc.status == int(TransactionStatus.ACCOUNT_FROZEN)


# ---------------------------------------------------------------------------
# cast
# ---------------------------------------------------------------------------

def test_cast_roundtrips(env):
    rc = run(env, pc.CAST_ADDRESS, "stringToS256", lambda w: w.text("-123"))
    assert int.from_bytes(Reader(rc.output).blob(), "big",
                          signed=True) == -123
    rc = run(env, pc.CAST_ADDRESS, "s256ToString",
             lambda w: w.blob(((1 << 200)).to_bytes(32, "big", signed=True)))
    assert Reader(rc.output).text() == str(1 << 200)
    rc = run(env, pc.CAST_ADDRESS, "stringToS64", lambda w: w.text("-9"))
    assert Reader(rc.output).i64() == -9
    rc = run(env, pc.CAST_ADDRESS, "stringToU256", lambda w: w.text("0xff"))
    assert Reader(rc.output).blob() == (255).to_bytes(32, "big")
    rc = run(env, pc.CAST_ADDRESS, "stringToAddr",
             lambda w: w.text("0x" + "ab" * 20))
    assert Reader(rc.output).blob() == b"\xab" * 20
    rc = run(env, pc.CAST_ADDRESS, "u256ToString",
             lambda w: w.blob((77).to_bytes(32, "big")))
    assert Reader(rc.output).text() == "77"
    run(env, pc.CAST_ADDRESS, "stringToAddr", lambda w: w.text("zz"),
        status=TransactionStatus.PRECOMPILED_ERROR)
