"""PBFT consensus at scale under fault injection (VERDICT r3 #8).

N=7 (f=2) soak: one node crashed from genesis, one node equivocating
(leader sends conflicting pre-prepares), network chaos on a third node's
traffic (random drops, delays, duplicates by ModuleID), a mid-soak leader
partition forcing a view change — 50+ blocks must commit identically on
every live node. Exceeds the reference's PBFTFixture coverage
(bcos-pbft/test/unittests/pbft/PBFTFixture.h:238-382: 4-10 engines, no
network faults).
"""

import os
import random
import time

import pytest

from fisco_bcos_tpu.codec.wire import Reader, Writer
from fisco_bcos_tpu.consensus.pbft.messages import PBFTMessage, PacketType
from fisco_bcos_tpu.crypto.suite import make_suite
from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.init.node import Node, NodeConfig
from fisco_bcos_tpu.ledger.ledger import ConsensusNode
from fisco_bcos_tpu.net.front import ModuleID
from fisco_bcos_tpu.net.gateway import FakeGateway
from fisco_bcos_tpu.protocol import Transaction

N = 7
# A 1-2 core CI host runs all six live nodes on one carousel: rounds
# cost ~10x a dev box, and a mainnet-ish view timeout turns scheduler
# jitter into view-change storms (the same calibration the chaos
# harness applies — testing/chaos.py ctor). Keep the 7-node/f=2
# topology everywhere; shorten the ride and stay in-view on small hosts.
_CORES = os.cpu_count() or 1
TARGET_BLOCKS = 50 if _CORES >= 4 else 20
_VIEW_TIMEOUT = 2.5 if _CORES >= 4 else 6.0
_SOAK_DEADLINE_S = 240 if _CORES >= 4 else 480


def wait_until(pred, timeout=60.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.1)
    return False


@pytest.mark.slow
def test_seven_node_soak_with_faults():
    suite = make_suite(backend="host")
    gateway = FakeGateway()
    keypairs = [suite.generate_keypair(bytes([i + 31]) * 16)
                for i in range(N)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    nodes = []
    for kp in keypairs:
        node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0,
                               view_timeout=_VIEW_TIMEOUT,
                               tx_count_limit=20),
                    keypair=kp, gateway=gateway)
        node.build_genesis(sealers)
        nodes.append(node)

    crashed = 6          # never started: a dead sealer from genesis
    equivocator = 5      # leader-equivocation when its turn comes
    chaotic = 4          # this node's outbound traffic gets chaos
    rng = random.Random(1337)

    def equivocate(data: bytes) -> bytes:
        """Flip a byte inside an outgoing pre-prepare's proposal so
        different peers receive different payloads (signature then fails
        or the hash diverges — honest nodes must reject/ignore)."""
        try:
            r = Reader(data)
            module, flag, seq = r.u16(), r.u8(), r.u64()
            if module != int(ModuleID.PBFT):
                return data
            msg = PBFTMessage.decode(r.blob())
            if msg.packet_type != int(PacketType.PRE_PREPARE) \
                    or not msg.payload:
                return data
            blob = bytearray(msg.payload)
            blob[rng.randrange(len(blob))] ^= 0x41
            msg.payload = bytes(blob)
            msg._hash = None
            return (Writer().u16(module).u8(flag).u64(seq)
                    .blob(msg.encode()).bytes())
        except Exception:
            return data

    sent_mutated = [0]

    def chaos(src, dst, data):
        module = FakeGateway.module_of(data)
        if src == keypairs[equivocator].pub_bytes \
                and module == int(ModuleID.PBFT) and rng.random() < 0.5:
            mutated = equivocate(data)
            if mutated is not data:
                sent_mutated[0] += 1
                # deliver the mutated frame by re-sending directly: return
                # False for the original after enqueueing the fake
                gateway._queues[dst].put((src, mutated))
                return False
        if src == keypairs[chaotic].pub_bytes and \
                module in (int(ModuleID.PBFT), int(ModuleID.BlockSync)):
            p = rng.random()
            if p < 0.05:
                return False          # drop
            if p < 0.20:
                return rng.uniform(0.01, 0.15)  # delay
            if p < 0.25:
                return 2              # duplicate
        return True

    gateway.set_filter(chaos)
    live = [n for i, n in enumerate(nodes) if i != crashed]
    for n in live:
        n.start()

    try:
        kp = suite.generate_keypair(b"soak-user")
        sent = 0
        partitioned_once = False
        deadline = time.time() + _SOAK_DEADLINE_S
        while time.time() < deadline:
            h = max(n.ledger.current_number() for n in live)
            if h >= TARGET_BLOCKS:
                break
            # keep the pool fed so every block seals immediately
            for _ in range(4):
                tx = Transaction(
                    to=pc.BALANCE_ADDRESS,
                    input=pc.encode_call(
                        "register",
                        lambda w: w.blob(b"acct%06d" % sent).u64(1)),
                    nonce=f"s{sent}",
                    block_limit=h + 300).sign(suite, kp)
                try:
                    live[sent % len(live)].send_transaction(tx)
                except Exception:
                    pass
                sent += 1
            if not partitioned_once and h >= TARGET_BLOCKS // 2:
                # partition the CURRENT leader: quorum stays 5/6, view
                # change must fire and the chain must keep moving
                victim = live[1]
                gateway.partition(victim.keypair.pub_bytes)
                # hold past the view timeout so the change actually fires
                time.sleep(_VIEW_TIMEOUT * 2.4)
                gateway.partition(victim.keypair.pub_bytes,
                                  isolated=False)
                partitioned_once = True
            time.sleep(0.15)

        assert wait_until(
            lambda: all(n.ledger.current_number() >= TARGET_BLOCKS
                        for n in live),
            timeout=90 if _CORES >= 4 else 240), \
            [n.ledger.current_number() for n in live]
        assert partitioned_once
        assert sent_mutated[0] > 0, "equivocation never exercised"
        # no fork: identical headers on every live node at several heights
        for h in (1, TARGET_BLOCKS // 2, TARGET_BLOCKS):
            hashes = {n.ledger.header_by_number(h).hash(suite)
                      for n in live}
            assert len(hashes) == 1, f"fork at height {h}"
        # committed headers carry a valid 2f+1 seal quorum
        hdr = live[0].ledger.header_by_number(TARGET_BLOCKS)
        assert len(hdr.signature_list) >= 2 * 2 + 1
        for idx, seal in hdr.signature_list:
            assert suite.verify(hdr.sealer_list[idx], hdr.hash(suite), seal)
    finally:
        for n in live:
            n.stop()
        gateway.stop()


@pytest.mark.slow
def test_liveness_under_sustained_ingest():
    """VERDICT r4 #7: liveness under throughput, not just safety under
    faults. A healthy 4-node chain receives a sustained ingest stream for
    ~30 s; the soak FAILS on regression thresholds:

      * zero view changes (a healthy loaded chain must not time out),
      * mean block interval under 5 s (host-calibrated: measured ~0.6 s on
        the 1-core dev host, 8x slack for CI variance),
      * sustained TPS above 50 (measured ~500+ on the dev host),
      * every submitted tx committed, identically across nodes.

    Emits the measured TPS / interval metrics for the perf log."""
    import threading

    suite = make_suite(backend="host")
    gateway = FakeGateway()
    keypairs = [suite.generate_keypair(bytes([i + 71]) * 16)
                for i in range(4)]
    sealers = [ConsensusNode(kp.pub_bytes) for kp in keypairs]
    nodes = []
    for kp in keypairs:
        node = Node(NodeConfig(consensus="pbft", crypto_backend="host",
                               min_seal_time=0.0, view_timeout=10.0,
                               tx_count_limit=500),
                    keypair=kp, gateway=gateway)
        node.build_genesis(sealers)
        nodes.append(node)
    for node in nodes:
        node.start()
    try:
        kp = suite.generate_keypair(b"ingest-soak")
        # pre-sign outside the measured window (host signing is not the
        # subject); block_limit generous for the whole soak
        batches = []
        for b in range(40):
            batches.append([
                Transaction(to=pc.BALANCE_ADDRESS,
                            input=pc.encode_call(
                                "register",
                                lambda w, b=b, i=i: w.blob(
                                    b"lv%d-%d" % (b, i)).u64(1)),
                            nonce=f"lv-{b}-{i}", block_limit=500
                            ).sign(suite, kp)
                for i in range(100)])
        total = sum(len(b) for b in batches)

        commit_times = {}
        orig = nodes[0].scheduler.commit_block

        def hook(header, _orig=orig):
            ok = _orig(header)
            if ok:
                commit_times[header.number] = time.monotonic()
            return ok

        nodes[0].scheduler.commit_block = hook

        stop_feed = threading.Event()

        rejected = []

        def feeder():
            for i, batch in enumerate(batches):
                if stop_feed.is_set():
                    return
                results = nodes[i % 4].txpool.submit_batch(batch)
                rejected.extend(r.status for r in results
                                if int(r.status) != 0)
                time.sleep(0.05)  # sustained stream, not one burst

        t0 = time.monotonic()
        feed = threading.Thread(target=feeder, daemon=True)
        feed.start()
        ok = wait_until(
            lambda: all(n.ledger.total_tx_count() >= total for n in nodes),
            timeout=180)
        t1 = time.monotonic()
        stop_feed.set()
        feed.join(timeout=10)
        assert not rejected, f"admission rejections: {rejected[:5]}"
        assert ok, [n.ledger.total_tx_count() for n in nodes]

        # -- regression thresholds ----------------------------------------
        views = [n.consensus.view for n in nodes]
        assert all(v == 0 for v in views), f"spurious view change: {views}"
        ordered = [commit_times[k] for k in sorted(commit_times)]
        intervals = [b - a for a, b in zip(ordered, ordered[1:])]
        mean_interval = sum(intervals) / len(intervals) if intervals else 0.0
        tps = total / (t1 - t0)
        print(f"\nsoak: tps={tps:.0f} blocks={len(ordered)} "
              f"mean_interval={mean_interval * 1000:.0f}ms views={views}")
        assert mean_interval < 5.0, f"block interval {mean_interval:.1f}s"
        assert tps > (50 if _CORES >= 4 else 25), f"sustained TPS {tps:.0f}"
        # identical heads everywhere
        head = nodes[0].ledger.current_number()
        h0 = nodes[0].ledger.header_by_number(head).hash(suite)
        for n in nodes[1:]:
            assert n.ledger.header_by_number(head).hash(suite) == h0
    finally:
        for node in nodes:
            node.stop()
        gateway.stop()
