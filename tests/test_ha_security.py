"""Leader election (lease/fence semantics) + disk-encryption tests."""

import time

import pytest

from fisco_bcos_tpu.crypto.symm import (BlockCipher, aes128_encrypt_block,
                                        aes128_key_schedule,
                                        sm4_encrypt_block, sm4_key_schedule)
from fisco_bcos_tpu.ha import FileLeaseElection
from fisco_bcos_tpu.security import (DataEncryption, EncryptedStorage,
                                     KeyCenter)
from fisco_bcos_tpu.storage.interface import Entry
from fisco_bcos_tpu.storage.wal import WalStorage


# ---------------------------------------------------------------------------
# cipher golden vectors (public standards)
# ---------------------------------------------------------------------------

def test_sm4_standard_vector():
    key = bytes.fromhex("0123456789abcdeffedcba9876543210")
    pt = bytes.fromhex("0123456789abcdeffedcba9876543210")
    rks = sm4_key_schedule(key)
    ct = sm4_encrypt_block(rks, pt)
    assert ct.hex() == "681edf34d206965e86b3e94f536e4246"


def test_aes128_nist_vector():
    key = bytes.fromhex("000102030405060708090a0b0c0d0e0f")
    pt = bytes.fromhex("00112233445566778899aabbccddeeff")
    rks = aes128_key_schedule(key)
    ct = aes128_encrypt_block(rks, pt)
    assert ct.hex() == "69c4e0d86a7b0430d8cdb78070b4c55a"


@pytest.mark.parametrize("alg", ["sm4", "aes"])
def test_seal_roundtrip_and_tamper(alg):
    c = BlockCipher(alg, b"some-passphrase")
    msg = b"node.key material" * 7
    blob = c.seal(msg)
    assert c.open_sealed(blob) == msg
    bad = bytearray(blob)
    bad[20] ^= 1
    with pytest.raises(ValueError):
        c.open_sealed(bytes(bad))


def test_data_encryption_files_and_storage(tmp_path):
    enc = DataEncryption(KeyCenter(b"pw"), algorithm="aes")
    src = tmp_path / "node.key"
    src.write_bytes(b"secret-key-bytes")
    out = enc.encrypt_file(str(src))
    assert out.endswith(".enc")
    assert b"secret-key-bytes" not in (tmp_path / "node.key.enc").read_bytes()
    assert enc.decrypt_file(out) == b"secret-key-bytes"

    st = EncryptedStorage(WalStorage(str(tmp_path / "db")), enc)
    st.set("t", b"k", b"plaintext-value")
    assert st.get("t", b"k") == b"plaintext-value"
    # at rest it is sealed
    assert st.backend.get("t", b"k") != b"plaintext-value"
    st.prepare(1, {("t", b"k2"): Entry(b"v2")})
    st.commit(1)
    assert st.get("t", b"k2") == b"v2"
    st.close()

    # wrong passphrase cannot read values back
    st2 = EncryptedStorage(WalStorage(str(tmp_path / "db")),
                           DataEncryption(KeyCenter(b"wrong")))
    with pytest.raises(ValueError):
        st2.get("t", b"k")
    st2.close()


# ---------------------------------------------------------------------------
# leader election
# ---------------------------------------------------------------------------

def test_election_failover(tmp_path):
    lease = str(tmp_path / "leader.lease")
    a = FileLeaseElection(lease, "node-a", lease_ttl=0.6, heartbeat=0.1)
    b = FileLeaseElection(lease, "node-b", lease_ttl=0.6, heartbeat=0.1)
    events = []
    a.on_elected(lambda: events.append("a-up"))
    a.on_seized(lambda: events.append("a-down"))
    b.on_elected(lambda: events.append("b-up"))

    a.start()
    deadline = time.time() + 5
    while not a.is_leader() and time.time() < deadline:
        time.sleep(0.02)
    assert a.is_leader() and a.leader() == "node-a"
    fence_a = a.fence_token()

    b.start()
    time.sleep(0.5)
    assert not b.is_leader()  # lease held and renewed by a

    a.stop()  # clean release
    deadline = time.time() + 5
    while not b.is_leader() and time.time() < deadline:
        time.sleep(0.02)
    assert b.is_leader()
    assert b.fence_token() > fence_a  # fencing token advanced
    assert "a-up" in events and "b-up" in events
    b.stop()
