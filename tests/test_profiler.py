"""Continuous-profiling plane (analysis/profiler.py + tools/perf_gate.py).

Covers the ISSUE-15 test checklist: disarmed-cost structure (no sampler
thread, plain-branch stage markers), folded-stack correctness against a
synthetic known-shape workload, per-thread role classification, CPU
attribution, burst-on-slow-span on a live node, the /profile route on
both the RPC edge and the [monitor] ops server, ring boundedness, the
host-weather sampler, and the perf gate's injected-regression /
identical-rerun behaviour.
"""

from __future__ import annotations

import http.client
import json
import threading
import time

import pytest

from fisco_bcos_tpu.analysis import hostweather, profiler


# -- structure / disarmed contract ----------------------------------------
def test_disarmed_has_no_sampler_thread():
    p = profiler.SamplingProfiler()
    assert not p.armed and p._thread is None
    p.configure(hz=50)
    assert p.armed and p._thread is not None and p._thread.is_alive()
    t = p._thread
    p.configure(hz=0)
    # disarm joins the thread: the disarmed state has NO thread, not a
    # parked one
    assert not p.armed and p._thread is None
    t.join(timeout=5)
    assert not t.is_alive()


def test_stage_marker_scopes_and_restores():
    ident = threading.get_ident()
    assert profiler.current_stage(ident) is None
    with profiler.stage("execute"):
        assert profiler.current_stage(ident) == "execute"
        with profiler.stage("commit"):
            assert profiler.current_stage(ident) == "commit"
        assert profiler.current_stage(ident) == "execute"
    # fully unwound: no residue in the stage map (bounded by live scopes)
    assert profiler.current_stage(ident) is None
    assert ident not in profiler._THREAD_STAGE


def test_role_classification():
    assert profiler.classify("tx-ingest") == "ingest"
    assert profiler.classify("sched-commit") == "commit"
    assert profiler.classify("sched-notify") == "commit"
    assert profiler.classify("pbft") == "pbft"
    assert profiler.classify("pbft-exec_0") == "pbft"
    assert profiler.classify("sealer") == "seal"
    assert profiler.classify("crypto-lane") == "lane"
    assert profiler.classify("crypto-lane-w_1") == "lane"
    assert profiler.classify("storage-compact") == "compaction"
    assert profiler.classify("rpc-worker-3") == "edge"
    assert profiler.classify("ops-http") == "edge"
    assert profiler.classify("gw-ab12") == "net"
    assert profiler.classify("MainThread") == "main"
    assert profiler.classify("never-heard-of-it") == "other"


def test_ring_bounded():
    fold = profiler._Folded(cap=64)
    for i in range(1000):
        fold.add(f"main;mod.py:f{i}")
    assert len(fold.counts) <= 64
    assert fold.overflow == 1000 - len(fold.counts)
    assert fold.samples == 1000
    text = profiler._folded_text(fold.counts, fold.overflow)
    assert "(overflow)" in text
    assert len(text.splitlines()) <= 65


# -- folded-stack correctness against a known-shape workload --------------
def _known_shape_leaf(stop):
    x = 1
    while not stop.is_set():
        # burn in a long inner chunk so samples land in THIS frame, not
        # in the Event.is_set call
        for _ in range(20000):
            x = (x * 31 + 7) & 0xFFFFFFFF


def _known_shape_mid(stop):
    _known_shape_leaf(stop)


def _known_shape_root(stop):
    _known_shape_mid(stop)


def test_folded_stacks_synthetic_shape():
    p = profiler.SamplingProfiler()
    stop = threading.Event()
    t = threading.Thread(target=_known_shape_root, args=(stop,),
                         name="synthetic-burn", daemon=True)
    t.start()
    try:
        p.configure(hz=150, ring=1024)
        time.sleep(0.7)
        p.configure(hz=0)
    finally:
        stop.set()
        t.join(5)
    folded = p.folded()
    line = next((ln for ln in folded.splitlines()
                 if "_known_shape_leaf" in ln), None)
    assert line is not None, folded[:800]
    # root-first order with the full call chain intact
    i_root = line.index("_known_shape_root")
    i_mid = line.index("_known_shape_mid")
    i_leaf = line.index("_known_shape_leaf")
    assert i_root < i_mid < i_leaf
    # the unknown-prefix thread classifies as `other` at the stack root
    assert line.startswith("other;")
    # the spinning leaf dominates the synthetic thread's samples
    count = int(line.rsplit(" ", 1)[1])
    assert count >= 10


def test_cpu_attribution_names_the_burner():
    p = profiler.SamplingProfiler()
    stop = threading.Event()
    t = threading.Thread(target=_known_shape_root, args=(stop,),
                         name="synthetic-burn", daemon=True)
    t.start()
    try:
        p.configure(hz=100, ring=1024)
        time.sleep(1.0)
        attrib = p.attribution()
        p.configure(hz=0)
    finally:
        stop.set()
        t.join(5)
    assert attrib["total_cpu_seconds"] > 0.1, attrib
    # the burner's CPU lands on a named function, and coverage of the
    # process total is high (only CPU on never-sampled threads escapes)
    assert attrib["attributed_pct"] is not None
    assert attrib["attributed_pct"] > 50.0, attrib
    # the burner is named among the top holders (the Event.is_set leaf is
    # an acceptable alias for the same loop)
    top2 = list(attrib["by_func"])[:2]
    assert any("test_profiler.py" in f for f in top2), attrib["by_func"]


# -- burst mode ------------------------------------------------------------
def test_burst_on_slow_span_and_trace_linking():
    from fisco_bcos_tpu.utils import otrace

    p = profiler.PROFILER
    old = (p.hz, p.ring, p.burst_hz, p.burst_s)
    tr_stats = otrace.TRACER.stats()
    try:
        p.configure(hz=50, ring=1024, burst_hz=97, burst_s=0.2)
        p._burst_next_ok = 0.0  # the storm guard is not under test
        otrace.TRACER.configure(sample_rate=1.0, slow_ms=1.0)
        root = otrace.TRACER.new_root()
        with otrace.TRACER.span("slow.unit", parent=root):
            time.sleep(0.01)
        tid = root.trace_id.hex()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and tid not in p.burst_ids():
            time.sleep(0.02)
        rec = p.burst_profile(tid)
        assert rec is not None, p.burst_ids()
        assert rec["traceId"] == tid and rec["reason"] == "slow.unit"
        assert rec["samples"] > 0 and rec["folded"].strip()
        # bounded retention: the burst dict never outgrows its keep
        for i in range(profiler.SamplingProfiler._BURST_KEEP + 4):
            with p._lock:
                p._bursts[f"{i:032x}"] = {"traceId": f"{i:032x}",
                                          "folded": ""}
                while len(p._bursts) > p._BURST_KEEP:
                    p._bursts.popitem(last=False)
        assert len(p.burst_ids()) <= profiler.SamplingProfiler._BURST_KEEP
    finally:
        with p._lock:
            p._bursts.clear()
        p.configure(hz=old[0], ring=old[1], burst_hz=old[2],
                    burst_s=old[3])
        otrace.TRACER.configure(sample_rate=tr_stats["sample_rate"],
                                slow_ms=tr_stats["slow_ms"])


# -- live node: /profile on both edges + getTrace profile member ----------
@pytest.fixture
def solo_node():
    from fisco_bcos_tpu.init.node import Node, NodeConfig

    node = Node(NodeConfig(crypto_backend="host", min_seal_time=0.0,
                           rpc_port=0, metrics_port=0,
                           trace_sample_rate=1.0, trace_slow_ms=2.0,
                           profile_hz=47.0, profile_burst_hz=97.0,
                           profile_burst_s=0.2))
    node.start()
    yield node
    node.stop()


def _commit_one(node, i: int):
    from fisco_bcos_tpu.executor import precompiled as pc
    from fisco_bcos_tpu.protocol import Transaction

    tx = Transaction(to=pc.BALANCE_ADDRESS,
                     input=pc.encode_call(
                         "register",
                         lambda w: w.blob(b"pf%d" % i).u64(10 + i)),
                     nonce=f"pf{i}", block_limit=100).sign(
        node.suite, node.suite.generate_keypair(b"prof-test"))
    res = node.send_transaction(tx)
    rc = node.txpool.wait_for_receipt(res.tx_hash, 30)
    assert rc is not None and rc.status == 0
    return res


def test_profile_route_on_rpc_edge_and_monitor_server(solo_node):
    node = solo_node
    _commit_one(node, 0)
    for host, port in ((node.rpc.host, node.rpc.port),
                      ("127.0.0.1", node.metrics.port)):
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/profile?seconds=0.3")
        r = conn.getresponse()
        body = r.read().decode()
        assert r.status == 200, (port, r.status, body[:200])
        assert body.strip(), "empty folded capture"
        # role-classified roots from the node's own threads
        assert any(ln.split(";")[0] in
                   ("ingest", "commit", "seal", "edge", "main", "other",
                    "control", "net", "execute")
                   for ln in body.splitlines()), body[:400]
        conn.request("GET", "/profile?fmt=flame")
        r = conn.getresponse()
        html = r.read().decode()
        assert r.status == 200 and "<html" in html and "FOLDED" in html
        conn.close()


def test_burst_linked_via_get_trace_on_live_node(solo_node):
    node = solo_node
    from fisco_bcos_tpu.utils import otrace

    root = otrace.TRACER.new_root()
    tid = root.trace_id.hex()
    with otrace.ctx_scope(root):
        _commit_one(node, 1)  # well over the 2 ms slow threshold
    # the live node's OWN pipeline spans compete for the single burst
    # slot; keep firing genuine slow spans under OUR root (the storm
    # guard is reset each try) until the burst lands on this trace
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline \
            and tid not in profiler.PROFILER.burst_ids():
        profiler.PROFILER._burst_next_ok = 0.0
        with otrace.TRACER.span("slow.retry", parent=root):
            time.sleep(0.005)
        time.sleep(0.05)
    impl = node.make_rpc_impl()
    doc = impl.get_trace("group0", "", tid)
    assert doc.get("profile"), profiler.PROFILER.burst_ids()
    assert doc["profile"]["traceId"] == tid
    assert doc["profile"]["folded"].strip()
    lst = impl.list_traces("group0", "")
    ours = [t for t in lst["traces"] if t["traceId"] == tid]
    assert ours and ours[0]["profiled"] is True
    # getSystemStatus aggregates the plane
    st = node.system_status()
    assert st["profile"]["armed"] and tid in st["profile"]["bursts"]


def test_system_status_has_profile_when_disarmed():
    from fisco_bcos_tpu.init.node import Node, NodeConfig

    node = Node(NodeConfig(crypto_backend="host", profile_hz=0.0))
    try:
        st = node.system_status()
        assert st["profile"]["armed"] is False
    finally:
        node.stop()


# -- host weather ----------------------------------------------------------
def test_host_weather_sample_shape():
    w = hostweather.sample(spin_ms=20)
    assert w["spin_score"] > 0
    assert w["cores"] >= 1
    # PSI/steal may be unavailable on exotic kernels, but the keys exist
    assert "psi_cpu" in w and "steal_pct" in w
    # PSI alone must NOT trip the predicate: a saturating bench elevates
    # /proc/pressure/cpu with its own load (the stamp keeps it for humans)
    noisy, _why = hostweather.noisy(
        {"psi_cpu": {"avg10": 50.0, "avg60": 0.0}, "steal_pct": 0.0})
    assert not noisy
    # hypervisor steal — the signal our own process cannot fake — does
    noisy, _why = hostweather.noisy(
        {"psi_cpu": {"avg10": 0.0, "avg60": 0.0}, "steal_pct": 5.0})
    assert noisy
    noisy, _why = hostweather.noisy(
        {"psi_cpu": {"avg10": 0.0, "avg60": 0.0}, "steal_pct": 0.0,
         "spin_score": 100}, reference_spin=1000)
    assert noisy
    noisy, _why = hostweather.noisy(
        {"psi_cpu": {"avg10": 0.0, "avg60": 0.0}, "steal_pct": 0.0,
         "spin_score": 1000}, reference_spin=1000)
    assert not noisy


# -- perf gate -------------------------------------------------------------
def _gate():
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(os.path.dirname(__file__), "..",
                                  "tools", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


_BASE = {"metric": "chain_tps", "chain_tps_4node_host": 1000.0,
         "rpc_read_qps": 5000.0, "trace_e2e_p50_ms": 30.0}


def _jitter(line, f):
    out = dict(line)
    for k in ("chain_tps_4node_host", "rpc_read_qps", "trace_e2e_p50_ms"):
        out[k] = round(out[k] * f, 2)
    return out


def test_perf_gate_passes_identical_rerun_and_catches_2x():
    pg = _gate()
    # history reflecting the documented 1.45x run-to-run swings: the
    # derived band must absorb a dip INSIDE that recorded spread
    history = [_jitter(_BASE, f) for f in (0.76, 1.0, 1.1)]
    # identical rerun: candidate == a recorded run -> PASS
    rep = pg.gate([dict(_BASE)], history, {}, min_runs=3)
    assert rep["ok"], rep
    # a dip within the recorded noise: still PASS (bands from spread)
    rep = pg.gate([_jitter(_BASE, 0.80)], history, {}, min_runs=3)
    assert rep["ok"], rep
    # injected 2x regression on a chain row: FAIL, named
    rep = pg.gate([_jitter(_BASE, 0.5)], history, {}, min_runs=3)
    assert not rep["ok"]
    assert "chain_tps_4node_host" in rep["failed"]
    # lower-better direction: a 2x slowdown in latency also FAILs
    bad = dict(_BASE)
    bad["trace_e2e_p50_ms"] = _BASE["trace_e2e_p50_ms"] * 2.1
    rep = pg.gate([bad], history, {}, min_runs=3)
    assert "trace_e2e_p50_ms" in rep["failed"]


def test_perf_gate_catastrophic_trips_thin_history():
    pg = _gate()
    history = [dict(_BASE)]  # ONE recorded run: everything is advisory...
    rep = pg.gate([_jitter(_BASE, 0.85)], history, {}, min_runs=3)
    assert rep["ok"], rep  # ...so a marginal dip stays advisory
    rep = pg.gate([_jitter(_BASE, 0.5)], history, {}, min_runs=3)
    assert not rep["ok"]  # ...but a halved metric is fatal regardless


def test_perf_gate_noise_widens_bands():
    pg = _gate()
    history = [_jitter(_BASE, f) for f in (0.98, 1.0, 1.02)]
    cand = _jitter(_BASE, 0.84)  # just under the quiet-host band (12%)
    quiet = pg.gate([cand], history, {}, min_runs=3, weather_now=None)
    assert not quiet["ok"]
    noisy_weather = {"psi_cpu": {"avg10": 30.0, "avg60": 10.0},
                     "steal_pct": 5.0, "spin_score": 1}
    loud = pg.gate([cand], history, {}, min_runs=3,
                   weather_now=noisy_weather)
    assert loud["ok"], loud  # the widened band absorbs the dip
    assert loud["noisy"]


def test_perf_gate_interleaved_medians():
    pg = _gate()
    history = [_jitter(_BASE, f) for f in (0.95, 1.0, 1.05)]
    # 3 interleaved candidate runs: one noisy outlier must not fail the
    # gate when the median is healthy
    cands = [_jitter(_BASE, 0.55), _jitter(_BASE, 1.0),
             _jitter(_BASE, 1.02)]
    rep = pg.gate(cands, history, {}, min_runs=3)
    assert rep["ok"], rep
    assert rep["candidate_runs"] == 3
