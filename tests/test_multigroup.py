"""Multi-group: independent chains on a shared gateway + routed RPC."""

import json
import time
import urllib.request

from fisco_bcos_tpu.init.group import GroupManager, GroupedJsonRpc
from fisco_bcos_tpu.init.node import NodeConfig
from fisco_bcos_tpu.net.gateway import FakeGateway, GroupGateway
from fisco_bcos_tpu.net.front import FrontService
from fisco_bcos_tpu.net.moduleid import ModuleID
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.executor import precompiled as pc


def test_group_gateway_isolation():
    shared = FakeGateway()
    g1 = GroupGateway(shared, "g1")
    g2 = GroupGateway(shared, "g2")
    got = {"g1": [], "g2": []}

    def front(tag):
        class F:
            def on_network_message(self, src, data):
                got[tag].append((src, data))
        return F()

    g1.register_front(b"A" * 32, front("g1"))
    g1.register_front(b"B" * 32, front("g1"))
    g2.register_front(b"A" * 32, front("g2"))  # same node id, other group
    time.sleep(0.05)
    assert g1.peers(b"A" * 32) == [b"B" * 32]
    assert g2.peers(b"A" * 32) == []  # no cross-group peers
    g1.broadcast(b"A" * 32, b"hello-g1")
    deadline = time.time() + 5
    while not got["g1"] and time.time() < deadline:
        time.sleep(0.01)
    assert got["g1"] == [(b"A" * 32, b"hello-g1")]
    assert got["g2"] == []
    shared.stop()


def test_two_groups_independent_chains_and_rpc():
    mgr = GroupManager()
    n1 = mgr.add_group(NodeConfig(group_id="group0", crypto_backend="host",
                                  min_seal_time=0.0))
    n2 = mgr.add_group(NodeConfig(group_id="group1", crypto_backend="host",
                                  min_seal_time=0.0))
    mgr.start()
    try:
        kp = n1.suite.generate_keypair(b"mg-user")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"a").u64(42)),
                         nonce="n1", group_id="group0",
                         block_limit=n1.ledger.current_number() + 100
                         ).sign(n1.suite, kp)
        r = n1.send_transaction(tx)
        rc = n1.txpool.wait_for_receipt(r.tx_hash, 15)
        assert rc is not None and rc.status == 0
        deadline = time.time() + 5
        while n1.ledger.current_number() < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert n1.ledger.current_number() >= 1
        assert n2.ledger.current_number() == 0  # other group untouched

        rpc = GroupedJsonRpc(mgr)
        resp = rpc.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "getGroupList", "params": []})
        assert resp["result"]["groupList"] == ["group0", "group1"]
        resp = rpc.handle({"jsonrpc": "2.0", "id": 2,
                           "method": "getBlockNumber", "params": ["group0"]})
        assert resp["result"] >= 1
        resp = rpc.handle({"jsonrpc": "2.0", "id": 3,
                           "method": "getBlockNumber", "params": ["group1"]})
        assert resp["result"] == 0
        resp = rpc.handle({"jsonrpc": "2.0", "id": 4,
                           "method": "getBlockNumber", "params": ["nope"]})
        assert "error" in resp

        # served over HTTP too
        srv = rpc.serve(port=0)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/",
                data=json.dumps({"jsonrpc": "2.0", "id": 9,
                                 "method": "getBlockNumber",
                                 "params": ["group0"]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as f:
                body = json.load(f)
            assert body["result"] >= 1
        finally:
            srv.stop()
    finally:
        mgr.stop()
        n1.storage.close() if hasattr(n1.storage, "close") else None
