"""Multi-group: independent chains on a shared gateway + routed RPC."""

import json
import time
import urllib.request

import pytest

from fisco_bcos_tpu.init.group import GroupManager, GroupedJsonRpc
from fisco_bcos_tpu.init.node import NodeConfig
from fisco_bcos_tpu.net.gateway import FakeGateway, GroupGateway
from fisco_bcos_tpu.net.front import FrontService
from fisco_bcos_tpu.net.moduleid import ModuleID
from fisco_bcos_tpu.protocol import Transaction
from fisco_bcos_tpu.rpc.server import JSONRPC_GROUP_NOT_FOUND
from fisco_bcos_tpu.executor import precompiled as pc


def test_group_gateway_isolation():
    shared = FakeGateway()
    g1 = GroupGateway(shared, "g1")
    g2 = GroupGateway(shared, "g2")
    got = {"g1": [], "g2": []}

    def front(tag):
        class F:
            def on_network_message(self, src, data):
                got[tag].append((src, data))
        return F()

    g1.register_front(b"A" * 32, front("g1"))
    g1.register_front(b"B" * 32, front("g1"))
    g2.register_front(b"A" * 32, front("g2"))  # same node id, other group
    time.sleep(0.05)
    assert g1.peers(b"A" * 32) == [b"B" * 32]
    assert g2.peers(b"A" * 32) == []  # no cross-group peers
    g1.broadcast(b"A" * 32, b"hello-g1")
    deadline = time.time() + 5
    while not got["g1"] and time.time() < deadline:
        time.sleep(0.01)
    assert got["g1"] == [(b"A" * 32, b"hello-g1")]
    assert got["g2"] == []
    shared.stop()


def test_two_groups_independent_chains_and_rpc():
    mgr = GroupManager()
    n1 = mgr.add_group(NodeConfig(group_id="group0", crypto_backend="host",
                                  min_seal_time=0.0))
    n2 = mgr.add_group(NodeConfig(group_id="group1", crypto_backend="host",
                                  min_seal_time=0.0))
    mgr.start()
    try:
        kp = n1.suite.generate_keypair(b"mg-user")
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"a").u64(42)),
                         nonce="n1", group_id="group0",
                         block_limit=n1.ledger.current_number() + 100
                         ).sign(n1.suite, kp)
        r = n1.send_transaction(tx)
        rc = n1.txpool.wait_for_receipt(r.tx_hash, 15)
        assert rc is not None and rc.status == 0
        deadline = time.time() + 5
        while n1.ledger.current_number() < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert n1.ledger.current_number() >= 1
        assert n2.ledger.current_number() == 0  # other group untouched

        rpc = GroupedJsonRpc(mgr)
        resp = rpc.handle({"jsonrpc": "2.0", "id": 1,
                           "method": "getGroupList", "params": []})
        assert resp["result"]["groupList"] == ["group0", "group1"]
        resp = rpc.handle({"jsonrpc": "2.0", "id": 2,
                           "method": "getBlockNumber", "params": ["group0"]})
        assert resp["result"] >= 1
        resp = rpc.handle({"jsonrpc": "2.0", "id": 3,
                           "method": "getBlockNumber", "params": ["group1"]})
        assert resp["result"] == 0
        resp = rpc.handle({"jsonrpc": "2.0", "id": 4,
                           "method": "getBlockNumber", "params": ["nope"]})
        assert "error" in resp

        # served over HTTP too
        srv = rpc.serve(port=0)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/",
                data=json.dumps({"jsonrpc": "2.0", "id": 9,
                                 "method": "getBlockNumber",
                                 "params": ["group0"]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as f:
                body = json.load(f)
            assert body["result"] >= 1
        finally:
            srv.stop()
    finally:
        mgr.stop()
        n1.storage.close() if hasattr(n1.storage, "close") else None


@pytest.fixture()
def grouped_pair():
    from fisco_bcos_tpu.storage.memory import MemoryStorage

    mgr = GroupManager(storage=MemoryStorage())
    n1 = mgr.add_group(NodeConfig(group_id="group0", crypto_backend="host",
                                  min_seal_time=0.0))
    n2 = mgr.add_group(NodeConfig(group_id="group1", crypto_backend="host",
                                  min_seal_time=0.0))
    mgr.start()
    yield mgr, n1, n2
    mgr.stop()


def _http_rpc(port, method, params, rid=1):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/",
        data=json.dumps({"jsonrpc": "2.0", "id": rid, "method": method,
                         "params": params}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as f:
        return json.load(f)


def test_group_methods_enumerate_real_registry(grouped_pair):
    """getGroupList/getGroupInfo/getGroupInfoList answer from the live
    registry on EVERY group's impl (rpc/server.py), not a hardcoded
    single group."""
    from fisco_bcos_tpu.rpc.server import JsonRpcImpl

    mgr, n1, n2 = grouped_pair
    impl = JsonRpcImpl(n1)  # a single group's impl, registry-aware
    assert impl.get_group_list() == {"groupList": ["group0", "group1"]}
    infos = impl.get_group_info_list()
    assert [i["groupID"] for i in infos] == ["group0", "group1"]
    # info for a SIBLING group renders from that group's node
    info = impl.get_group_info("group1")
    assert info["groupID"] == "group1"
    assert info["genesisHash"] == "0x" + n2.ledger.header_by_number(0).hash(
        n2.suite).hex()
    # a node WITHOUT a registry still reports only itself
    lone = mgr.node("group0")
    reg, lone.group_registry = lone.group_registry, None
    try:
        assert JsonRpcImpl(lone).get_group_list() == \
            {"groupList": ["group0"]}
    finally:
        lone.group_registry = reg


def test_unknown_group_error_parity_http_and_ws(grouped_pair):
    """Every group-routed method answers an unknown group with the SAME
    dedicated error object (code -32004) over HTTP and WS."""
    from fisco_bcos_tpu.rpc.ws_server import WsRpcServer
    from fisco_bcos_tpu.sdk.client import RpcCallError
    from fisco_bcos_tpu.sdk.ws import WsSdkClient

    mgr, n1, n2 = grouped_pair
    grouped = GroupedJsonRpc(mgr)
    srv = grouped.serve(port=0)
    ws = WsRpcServer(grouped, port=0)
    ws.start()
    try:
        for method, params in [
            ("getBlockNumber", ["nope"]),
            ("getGroupInfo", ["nope"]),
            ("sendTransaction", ["nope", "", "0x00"]),
            ("getGroupPeers", ["nope"]),
        ]:
            body = _http_rpc(srv.port, method, params)
            assert body["error"]["code"] == JSONRPC_GROUP_NOT_FOUND, \
                (method, body)
            assert "nope" in body["error"]["message"]
        # known groups still route per group over the one edge
        assert _http_rpc(srv.port, "getBlockNumber", ["group1"])[
            "result"] == 0
        client = WsSdkClient("127.0.0.1", ws.port)
        try:
            assert client.request("getBlockNumber", ["group0"]) >= 0
            with pytest.raises(RpcCallError) as exc:
                client.request("getBlockNumber", ["nope"])
            assert exc.value.code == JSONRPC_GROUP_NOT_FOUND
            with pytest.raises(RpcCallError) as exc:
                client.request("getGroupInfo", ["nope"])
            assert exc.value.code == JSONRPC_GROUP_NOT_FOUND
            assert client.request("getGroupList", [])[
                "groupList"] == ["group0", "group1"]
        finally:
            client.close()
    finally:
        ws.stop()
        srv.stop()


def test_per_group_query_caches_behind_one_edge(grouped_pair):
    """The shared edge wires one commit-coherent QueryCache PER group:
    hot responses never cross groups and invalidation stays local."""
    mgr, n1, n2 = grouped_pair
    grouped = GroupedJsonRpc(mgr)
    kp = n1.suite.generate_keypair(b"mg-cache")
    tx = Transaction(to=pc.BALANCE_ADDRESS,
                     input=pc.encode_call(
                         "register", lambda w: w.blob(b"c").u64(1)),
                     nonce="c1", group_id="group0",
                     block_limit=100).sign(n1.suite, kp)
    r = n1.send_transaction(tx)
    assert n1.txpool.wait_for_receipt(r.tx_hash, 15) is not None
    # route a block query through the edge twice: second serves cached
    req = {"jsonrpc": "2.0", "id": 1, "method": "getBlockByNumber",
           "params": ["group0", "", 1, False, False]}
    r1 = grouped.handle(dict(req))
    r2 = grouped.handle(dict(req))
    assert r1["result"] is not None
    assert r1["result"] is r2["result"]  # same cached object
    # group1's cache wires on its first routed request (lazy per group)
    grouped.handle({"jsonrpc": "2.0", "id": 2, "method": "getBlockNumber",
                    "params": ["group1"]})
    assert n1.query_cache is not None and n2.query_cache is not None
    assert n1.query_cache is not n2.query_cache
    assert n1.query_cache.stats()["hits"] >= 1
    assert n2.query_cache.stats()["hits"] == 0


def test_metrics_carry_group_label_and_keep_totals(grouped_pair):
    """bcos_* series from per-group subsystems carry a {group=...} label
    ALONGSIDE the unlabeled totals (dashboard compatibility)."""
    from fisco_bcos_tpu.utils.metrics import REGISTRY

    mgr, n1, n2 = grouped_pair
    kp = n1.suite.generate_keypair(b"mg-metrics")
    for node, gid in ((n1, "group0"), (n2, "group1")):
        tx = Transaction(to=pc.BALANCE_ADDRESS,
                         input=pc.encode_call(
                             "register", lambda w: w.blob(b"m").u64(1)),
                         nonce=f"m-{gid}", group_id=gid,
                         block_limit=100).sign(node.suite, kp)
        r = node.send_transaction(tx)
        assert node.txpool.wait_for_receipt(r.tx_hash, 15) is not None
    text = REGISTRY.prometheus_text()
    assert 'bcos_txpool_pending{group="group0"}' in text
    assert 'bcos_txpool_pending{group="group1"}' in text
    # the unlabeled series survives for existing dashboards
    assert any(line.startswith("bcos_txpool_pending ")
               for line in text.splitlines())
