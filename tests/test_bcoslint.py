"""bcoslint (tools/bcoslint.py): per-rule positive/negative fixtures,
suppression comments, and the baseline round-trip."""

from __future__ import annotations

import importlib.util
import os
import sys
import textwrap

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "bcoslint", os.path.join(_REPO, "tools", "bcoslint.py"))
bcoslint = importlib.util.module_from_spec(_spec)
sys.modules.setdefault("bcoslint", bcoslint)
_spec.loader.exec_module(bcoslint)


def lint(src: str, relpath: str = "fisco_bcos_tpu/example.py"):
    return bcoslint.lint_source(textwrap.dedent(src), relpath)


def rules_of(violations):
    return sorted(v.rule for v in violations)


# -- raw-lock --------------------------------------------------------------

def test_raw_lock_flagged_in_hot_module():
    src = """
    import threading
    class Pool:
        def __init__(self):
            self._lock = threading.RLock()
    """
    vs = lint(src, "fisco_bcos_tpu/txpool/txpool.py")
    assert "raw-lock" in rules_of(vs)


def test_raw_lock_ignored_outside_hot_modules_and_in_lockcheck():
    src = """
    import threading
    lock = threading.Lock()
    """
    assert "raw-lock" not in rules_of(lint(src, "fisco_bcos_tpu/tool/x.py"))
    assert "raw-lock" not in rules_of(
        lint(src, "fisco_bcos_tpu/analysis/lockcheck.py"))


# -- lock-order ------------------------------------------------------------

def test_lock_order_lexical_inversion_flagged():
    # in scheduler.py, _lock (scheduler.state) ranks INSIDE _commit_2pc:
    # nesting the 2PC inside the state lock is the inversion
    src = """
    class S:
        def bad(self):
            with self._lock:
                with self._commit_2pc:
                    pass
        def good(self):
            with self._commit_2pc:
                with self._lock:
                    pass
    """
    vs = lint(src, "fisco_bcos_tpu/scheduler/scheduler.py")
    order = [v for v in vs if v.rule == "lock-order"]
    assert len(order) == 1
    assert order[0].scope == "S.bad"


def test_lock_order_ignores_closures_under_with():
    # a def inside a with runs LATER, not under the lock
    src = """
    class S:
        def ok(self):
            with self._lock:
                def cb():
                    with self._commit_2pc:
                        pass
                return cb
    """
    vs = lint(src, "fisco_bcos_tpu/scheduler/scheduler.py")
    assert "lock-order" not in rules_of(vs)


# -- blocking-under-lock ---------------------------------------------------

def test_blocking_under_hot_lock_flagged_and_allow_respected():
    src = """
    import os
    class E:
        def bad(self):
            with self._lock:
                self.suite.verify_batch([], [], [])
        def fine(self):
            with self._lock:
                os.fsync(3)
    """
    # engine.state allows fsync but not suite_batch
    vs = lint(src, "fisco_bcos_tpu/storage/engine.py")
    blocking = [v for v in vs if v.rule == "blocking-under-lock"]
    assert len(blocking) == 1 and blocking[0].scope == "E.bad"


def test_sleep_and_sendall_under_no_blocking_lock():
    src = """
    import time
    class P:
        def bad(self):
            with self._cv:
                self.sock.sendall(b"x")
                time.sleep(0.1)
    """
    vs = lint(src, "fisco_bcos_tpu/net/p2p.py")  # _cv -> p2p.session, allow=∅
    kinds = [v for v in vs if v.rule == "blocking-under-lock"]
    assert len(kinds) == 2


# -- bare-except / swallowed-worker-exception ------------------------------

def test_bare_except_flagged():
    src = """
    def f():
        try:
            g()
        except:
            pass
    """
    assert "bare-except" in rules_of(lint(src))


def test_swallowed_worker_exception():
    src = """
    class W:
        def _run(self):
            while True:
                try:
                    self.step()
                except Exception:
                    pass
    """
    assert "swallowed-worker-exception" in rules_of(lint(src))


def test_logged_worker_exception_is_fine():
    src = """
    class W:
        def _run(self):
            while True:
                try:
                    self.step()
                except Exception:
                    LOG.exception("step failed")
    """
    assert "swallowed-worker-exception" not in rules_of(lint(src))


def test_swallow_outside_worker_loop_not_flagged():
    src = """
    def lookup(d):
        try:
            return d["k"]
        except Exception:
            pass
    """
    assert "swallowed-worker-exception" not in rules_of(lint(src))


# -- wallclock-deadline ----------------------------------------------------

def test_wallclock_deadline_flagged():
    src = """
    import time
    def f():
        deadline = time.time() + 5
        while time.time() < deadline:
            pass
    """
    vs = [v for v in lint(src) if v.rule == "wallclock-deadline"]
    assert len(vs) == 2


def test_wallclock_timestamp_not_flagged():
    src = """
    import time
    def f():
        return int(time.time() * 1000)  # wire timestamp: wall clock is right
    """
    assert "wallclock-deadline" not in rules_of(lint(src))


# -- fsync-no-failpoint ----------------------------------------------------

def test_fsync_without_failpoint_flagged_in_storage():
    src = """
    import os
    def persist(f):
        os.fsync(f.fileno())
    """
    assert "fsync-no-failpoint" in rules_of(
        lint(src, "fisco_bcos_tpu/storage/newfile.py"))
    # same code outside the durability scope: not this rule's business
    assert "fsync-no-failpoint" not in rules_of(
        lint(src, "fisco_bcos_tpu/ha/election.py"))


def test_fsync_with_failpoint_is_fine():
    src = """
    import os
    from ..utils import failpoints as fp
    def persist(f):
        fp.fire("storage.newfile.persist")
        os.fsync(f.fileno())
    """
    assert "fsync-no-failpoint" not in rules_of(
        lint(src, "fisco_bcos_tpu/storage/newfile.py"))


# -- metrics-cardinality ---------------------------------------------------

def test_metrics_cardinality_hex_and_fstring():
    src = """
    def f(reg, tx_hash, stage):
        reg.inc("bcos_x_total", labels={"tx": tx_hash.hex()})
        reg.observe("bcos_y_seconds", 1.0, labels={"id": f"req-{stage}"})
        reg.inc("bcos_z_total", labels={"stage": stage})
    """
    vs = [v for v in lint(src) if v.rule == "metrics-cardinality"]
    assert len(vs) == 2  # the bounded Name label is fine


# -- mutable-default / dict-iter-mutation ----------------------------------

def test_mutable_default_flagged():
    src = """
    def f(x=[]):
        return x
    def g(y=None):
        return y
    """
    vs = [v for v in lint(src) if v.rule == "mutable-default"]
    assert len(vs) == 1


def test_dict_iter_mutation_flagged_and_safe_idiom_not():
    src = """
    def bad(d):
        for k in d:
            d.pop(k)
    def good(d):
        for k in [k for k in d if k]:
            d.pop(k)
    def also_good(d):
        for k in list(d):
            del d[k]
    """
    vs = [v for v in lint(src) if v.rule == "dict-iter-mutation"]
    assert len(vs) == 1 and vs[0].scope == "bad"


# -- unused-import ---------------------------------------------------------

def test_unused_import_flagged_and_usage_forms_respected():
    src = """
    import os
    import json
    from typing import Optional

    __all__ = ["Optional"]

    def f(p) -> None:
        return os.path.basename(p)
    """
    vs = [v for v in lint(src) if v.rule == "unused-import"]
    assert [v.message for v in vs] == ["import 'json' is never used"]


def test_class_scope_import_is_attribute_usage():
    src = """
    class C:
        from .evm import T_CODE
        def f(self, state):
            state.set(self.T_CODE, b"k", b"v")
    """
    assert "unused-import" not in rules_of(lint(src))


def test_init_py_reexports_exempt():
    src = "from .front import FrontService\n"
    assert "unused-import" not in rules_of(
        lint(src, "fisco_bcos_tpu/net/__init__.py"))


# -- thread-start-in-ctor --------------------------------------------------

def test_thread_start_in_ctor_flagged():
    # all three shapes: inline, via self-attr, via local
    src = """
    import threading
    class A:
        def __init__(self):
            threading.Thread(target=self._run, daemon=True).start()
    class B:
        def __init__(self):
            self._t = threading.Thread(target=self._run)
            self._t.start()
    class C:
        def __init__(self):
            t = threading.Thread(target=self._run)
            t.start()
    """
    vs = [v for v in lint(src) if v.rule == "thread-start-in-ctor"]
    assert sorted(v.scope for v in vs) == \
        ["A.__init__", "B.__init__", "C.__init__"]


def test_thread_start_in_ctor_self_start_on_worker_subclass():
    src = """
    class Miner(Worker):
        def __init__(self):
            super().__init__("miner")
            self.start()
    """
    vs = [v for v in lint(src) if v.rule == "thread-start-in-ctor"]
    assert len(vs) == 1


def test_thread_start_outside_ctor_ok():
    # the fixed p2p shape: build in __init__, start from an owner-called
    # start() — and self.start() on a NON-thread class is not a spawn
    src = """
    import threading
    class A:
        def __init__(self):
            self._t = threading.Thread(target=self._run)
        def start(self):
            self._t.start()
    class B:
        def __init__(self):
            self.start()
        def start(self):
            pass
    """
    assert "thread-start-in-ctor" not in rules_of(lint(src))


# -- log-in-hot-loop -------------------------------------------------------

def test_log_in_hot_loop_fstring_flagged():
    src = """
    from ..utils.log import LOG
    def dispatch(entries):
        for e in entries:
            LOG.debug(f"dispatching {e}")
    """
    vs = [v for v in lint(src, "fisco_bcos_tpu/txpool/ingest.py")
          if v.rule == "log-in-hot-loop"]
    assert len(vs) == 1 and vs[0].scope == "dispatch"


def test_log_in_hot_loop_lazy_args_and_cold_modules_ok():
    lazy = """
    from ..utils.log import LOG
    def dispatch(entries):
        for e in entries:
            LOG.debug("dispatching %s", e)
        LOG.info(f"done: {len(entries)}")
    """
    assert "log-in-hot-loop" not in rules_of(
        lint(lazy, "fisco_bcos_tpu/txpool/ingest.py"))
    hot = """
    from ..utils.log import LOG
    def dispatch(entries):
        for e in entries:
            LOG.debug(f"dispatching {e}")
    """
    # same f-string loop OUTSIDE the hot-path scope: connection plumbing
    # logs per connection, not per item
    assert "log-in-hot-loop" not in rules_of(
        lint(hot, "fisco_bcos_tpu/net/p2p.py"))


def test_log_in_hot_loop_closure_inside_loop_ok():
    src = """
    from ..utils.log import LOG
    def dispatch(entries):
        for e in entries:
            def cb():
                LOG.debug(f"later {e}")
            e.on_done(cb)
    """
    assert "log-in-hot-loop" not in rules_of(
        lint(src, "fisco_bcos_tpu/txpool/ingest.py"))


# -- suppression -----------------------------------------------------------

def test_suppression_same_line_and_line_above():
    src = """
    def f(x=[]):  # bcoslint: disable=mutable-default
        return x
    # bcoslint: disable=mutable-default
    def g(y={}):
        return y
    def h(z=set()):
        return z
    """
    vs = [v for v in lint(src) if v.rule == "mutable-default"]
    assert len(vs) == 1 and vs[0].scope == "h"


def test_disable_all_suppresses_every_rule():
    src = """
    def f(x=[]):  # bcoslint: disable=all
        return x
    """
    assert lint(src) == []


def test_suppressing_one_rule_keeps_others():
    src = """
    import time
    def f(x=[]):  # bcoslint: disable=mutable-default
        return time.time() + 1
    """
    assert rules_of(lint(src)) == ["wallclock-deadline"]


# -- baseline round-trip ---------------------------------------------------

BAD = textwrap.dedent("""
    def f(x=[]):
        return x
""")


def test_baseline_round_trip(tmp_path):
    target = tmp_path / "victim.py"
    target.write_text(BAD)
    base = tmp_path / "baseline.txt"

    # 1) no baseline: the violation fails the gate
    assert bcoslint.main([str(target), "--baseline", str(base)]) == 1
    # 2) update-baseline grandfathers it
    assert bcoslint.main([str(target), "--baseline", str(base),
                          "--update-baseline"]) == 0
    assert bcoslint.main([str(target), "--baseline", str(base)]) == 0
    # justification column survives a rewrite
    text = base.read_text()
    text = text.replace("TODO: justify or fix", "fixture: kept on purpose")
    base.write_text(text)
    assert bcoslint.main([str(target), "--baseline", str(base),
                          "--update-baseline"]) == 0
    assert "fixture: kept on purpose" in base.read_text()

    # 3) a NEW violation still fails while the old one stays grandfathered
    target.write_text(BAD + "\ndef g(y={}):\n    return y\n")
    assert bcoslint.main([str(target), "--baseline", str(base)]) == 1
    # 4) fixing the new one returns the gate to clean
    target.write_text(BAD)
    assert bcoslint.main([str(target), "--baseline", str(base)]) == 0
    # 5) fixing the BASELINED one leaves a stale entry (warned, still 0)
    target.write_text("def f(x=None):\n    return x\n")
    assert bcoslint.main([str(target), "--baseline", str(base)]) == 0
    # 6) --update-baseline prunes it
    assert bcoslint.main([str(target), "--baseline", str(base),
                          "--update-baseline"]) == 0
    assert "mutable-default" not in base.read_text()


def test_fingerprint_survives_line_moves(tmp_path):
    target = tmp_path / "victim.py"
    target.write_text(BAD)
    base = tmp_path / "baseline.txt"
    assert bcoslint.main([str(target), "--baseline", str(base),
                          "--update-baseline"]) == 0
    # shift the offending line down 20 lines: key is content, not lineno
    target.write_text("# pad\n" * 20 + BAD)
    assert bcoslint.main([str(target), "--baseline", str(base)]) == 0


# -- the repo itself gates clean -------------------------------------------

def test_repo_lints_clean_against_committed_baseline():
    assert bcoslint.main([]) == 0


def test_list_rules_names_every_rule():
    # stable rule ids are the suppression/baseline API — pin them
    assert set(bcoslint.RULES) == {
        "raw-lock", "lock-order", "bare-except",
        "swallowed-worker-exception", "wallclock-deadline",
        "fsync-no-failpoint", "metrics-cardinality", "mutable-default",
        "dict-iter-mutation", "unused-import", "thread-start-in-ctor",
        "log-in-hot-loop",
    }
