"""Crash/fault e2e: REAL OS processes, real TCP p2p (SM-TLS), real JSON-RPC.

The robustness claims the in-process suites cannot make: a node that dies
by kill -9 mid-stream restarts from its data directory, replays its WAL and
consensus log, rejoins over block sync and reaches the SAME block hash and
state root as the survivors; a crashed leader triggers a view change that
keeps the chain live; a slow/flapping link does not wedge consensus.

Each test boots a fresh 4-node chain via tools/build_chain.py and drives it
only through the public surfaces (daemon CLI, JSON-RPC HTTP) — the shape of
the reference's process-level integration tests. Marked `slow` (multi-
process, ~1-2 min each); `tools/sanitize_ci.sh --chaos` runs them in CI.
"""

import re

import pytest

from fisco_bcos_tpu.executor import precompiled as pc
from fisco_bcos_tpu.sdk.client import TransactionBuilder
from fisco_bcos_tpu.testing.chaos import ChaosHarness

pytestmark = pytest.mark.slow


class _Workload:
    """Register-call traffic signed once, submitted via JSON-RPC wait=False."""

    def __init__(self, harness: ChaosHarness):
        self.h = harness
        self.suite = harness.suite()
        self.kp = self.suite.generate_keypair(b"chaos-user")
        self.builder = TransactionBuilder(
            self.suite, None, chain_id=harness.info["chain_id"],
            group_id=harness.info["group_id"])
        self.sent = 0

    def burst(self, n: int, via: list[int]) -> None:
        for k in range(n):
            node = via[k % len(via)]
            tx = self.builder.build(
                self.kp, pc.BALANCE_ADDRESS,
                pc.encode_call("register",
                               lambda w: w.blob(b"acct%d" % self.sent)
                               .u64(1)),
                nonce=f"chaos-{self.sent}", block_limit=500)
            self.h.client(node).send_transaction(tx, wait=False)
            self.sent += 1


def _daemon_boot_height(log: str) -> int:
    """Height the daemon reported at its LAST '[DAEMON][up]' line — what the
    WAL replay restored BEFORE any block sync ran."""
    heights = re.findall(r"\[DAEMON\]\[up\].*?number=(-?\d+)", log)
    return int(heights[-1]) if heights else -1


def test_kill9_rejoin_catches_up(tmp_path):
    """Acceptance: 4 processes with TLS on, blocks committing via JSON-RPC;
    kill -9 one node mid-stream; it restarts from its data dir, replays its
    WAL, rejoins via sync, and matches the survivors' head hash/state root."""
    with ChaosHarness(str(tmp_path / "chain"), tls=True) as h:
        h.start_all()
        for i in range(h.n):
            h.wait_rpc_up(i)
        w = _Workload(h)
        survivors = [0, 1, 2]
        w.burst(8, via=survivors)
        # the victim must have committed blocks BEFORE the crash, so the
        # restart genuinely replays a non-empty WAL
        h.wait_until(lambda: min(h.total_txs(i) for i in range(h.n)) >= 4,
                     timeout=180, what="pre-kill commits on every node")
        h.kill(3)  # mid-stream: traffic keeps flowing while node3 is dead
        w.burst(8, via=survivors)
        h.wait_until(
            lambda: min(h.total_txs(i) for i in survivors) >= w.sent,
            timeout=180, what="survivor commits after kill -9")
        assert min(h.block_number(i) for i in survivors) >= 1

        h.start(3)  # same data dir: WAL replay + recovery + sync catch-up
        h.wait_rpc_up(3)
        log3 = h.read_daemon_log(3)
        assert "stale-pidfile" in log3, \
            "kill -9 left no pid file, or the daemon missed it"
        assert _daemon_boot_height(log3) >= 1, \
            "restart came up at genesis — WAL replay restored nothing"
        h.wait_until(lambda: h.total_txs(3) >= w.sent, timeout=180,
                     what="node3 sync catch-up")
        height = h.wait_converged(range(h.n), min_height=1, timeout=120)
        hashes = {h.block_hash(i, height) for i in range(h.n)}
        assert len(hashes) == 1, f"head hash diverged at {height}: {hashes}"
        roots = {h.state_root(i, height) for i in range(h.n)}
        assert len(roots) == 1, f"state root diverged at {height}: {roots}"


def test_leader_crash_view_change_keeps_liveness(tmp_path):
    """Crash the next-height leader: the survivors' view change must elect
    a new leader and keep committing; the old leader rejoins on restart."""
    with ChaosHarness(str(tmp_path / "chain"), tls=True,
                      view_timeout=4.0) as h:
        h.start_all()
        for i in range(h.n):
            h.wait_rpc_up(i)
        status = h.client(0).get_consensus_status()
        leader_idx = status["leaderIndex"]
        # engine indices follow the sorted node-id order
        by_id = sorted(range(h.n),
                       key=lambda i: bytes.fromhex(
                           h.info["nodes"][i]["node_id"]))
        leader_node = by_id[leader_idx]
        survivors = [i for i in range(h.n) if i != leader_node]

        h.kill(leader_node)
        w = _Workload(h)
        w.burst(8, via=survivors)
        h.wait_until(
            lambda: min(h.total_txs(i) for i in survivors) >= w.sent,
            timeout=180, what="commits after leader crash")
        views = [h.client(i).request("getPbftView",
                                     [h.info["group_id"], ""])
                 for i in survivors]
        assert max(views) >= 1, f"no view change happened: views={views}"

        h.start(leader_node)
        h.wait_rpc_up(leader_node)
        h.wait_until(lambda: h.total_txs(leader_node) >= w.sent,
                     timeout=180, what="old leader catch-up")
        height = h.wait_converged(range(h.n), min_height=1, timeout=120)
        assert len({h.block_hash(i, height) for i in range(h.n)}) == 1


def test_delayed_flaky_link_keeps_liveness(tmp_path):
    """Bounded delay + periodic connection cuts on ONE link must not wedge
    the chain: reconnect-with-backoff re-establishes the session and every
    node still commits everything identically."""
    h = ChaosHarness(str(tmp_path / "chain"), tls=True)
    proxy = h.inject_link(0, 1, delay=0.03, drop_every=25)
    with h:
        h.start_all()
        for i in range(h.n):
            h.wait_rpc_up(i)
        w = _Workload(h)
        w.burst(12, via=list(range(h.n)))
        h.wait_until(
            lambda: min(h.total_txs(i) for i in range(h.n)) >= w.sent,
            timeout=240, what="commits across the degraded link")
        assert proxy._chunks > 0, "link traffic never crossed the proxy"
        height = h.wait_converged(range(h.n), min_height=1, timeout=120)
        assert len({h.block_hash(i, height) for i in range(h.n)}) == 1
