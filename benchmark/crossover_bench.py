#!/usr/bin/env python3
"""Measure the host/device batch crossover for the crypto suite.

VERDICT flagged `device_min_batch=64` as an unmeasured guess. This harness
measures host-oracle and device-kernel verify throughput across batch
sizes and reports the crossover — run it on the deployment's real
accelerator to pick the node's `device_min_batch` (NodeConfig).

Usage: python benchmark/crossover_bench.py [--sizes 1,4,16,64,256,1024]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="1,4,16,64,256,1024")
    ap.add_argument("--iters", type=int, default=3)
    args = ap.parse_args()
    sizes = [int(s) for s in args.sizes.split(",")]

    import jax

    from fisco_bcos_tpu.crypto.suite import make_suite

    host = make_suite(backend="host")
    dev = make_suite(backend="device", device_min_batch=1)
    kp = host.generate_keypair(b"crossover")
    backend = jax.devices()[0].platform

    rows = []
    crossover = None
    for n in sizes:
        ds = [host.hash(b"x%d" % i) for i in range(n)]
        sigs = [host.sign(kp, d) for d in ds]
        pubs = [kp.pub_bytes] * n

        t0 = time.perf_counter()
        for _ in range(args.iters):
            host.verify_batch(ds, sigs, pubs)
        host_dt = (time.perf_counter() - t0) / args.iters

        dev.verify_batch(ds, sigs, pubs)  # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.iters):
            dev.verify_batch(ds, sigs, pubs)
        dev_dt = (time.perf_counter() - t0) / args.iters

        rows.append({"batch": n,
                     "host_ms": round(host_dt * 1000, 2),
                     "device_ms": round(dev_dt * 1000, 2),
                     "winner": "device" if dev_dt < host_dt else "host"})
        if crossover is None and dev_dt < host_dt:
            crossover = n
    print(json.dumps({"backend": backend, "rows": rows,
                      "device_min_batch_suggestion": crossover}, indent=1))


if __name__ == "__main__":
    main()
