#!/usr/bin/env python3
"""Per-kernel scan-step cost breakdown for the EC verify ladder.

VERDICT r3 #1/#2: the gap from the measured 2.95x (r2, 16k batch) to the
10x target needs EVIDENCE about where a verify's time goes. This harness
times the ladder's building blocks in isolation on the live backend and
prints a breakdown (all per-batch-element-step, amortized):

  field_mul        one Montgomery/Solinas field multiply
  jac_double       point doubling (the 136 per verify)
  jac_add_affine   mixed add (the 4x34 per GLV verify)
  select_const     G-table one-hot tensordot select
  select_batch     per-element Q-table select
  table_build      per-element window table + batch normalization
  inv_batch        the scalar-field inversion tree (s^-1)
  glv_ladder       the full 34-step scan (everything combined)
  verify_e2e       whole ecdsa_verify_batch

The ladder model cost (doublings + adds + selects) vs the measured
glv_ladder/verify time shows whether the kernel is compute-bound or
losing time to fusion/layout overheads.

Usage: python benchmark/profile_kernels.py [--batch 16384] [--iters 5]
Called by tools/tpu_watcher.py after a successful sweep; results merge
into BENCH_LAST_GOOD.json under "profile".
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=16384)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--json", action="store_true",
                    help="print one JSON line instead of a table")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    import bench as bench_mod
    from fisco_bcos_tpu.crypto import refimpl
    from fisco_bcos_tpu.ops import ec, fp

    backend = jax.devices()[0].platform
    B = args.batch
    cv = ec.SECP256K1
    f = cv.fp

    e, r, s, v, qx, qy = bench_mod.build_sig_args(refimpl.SECP256K1, B)
    # lane-major operands for the sub-kernels
    exm = jnp.transpose(jnp.asarray(e))
    qxm, qym = jnp.transpose(jnp.asarray(qx)), jnp.transpose(jnp.asarray(qy))
    qxr, qyr = f.to_rep(qxm), f.to_rep(qym)
    P = jnp.stack([qxr, qyr, f.one_rep(qxr.shape)])
    dig = jnp.asarray(np.random.default_rng(7).integers(
        0, ec.TBL, B, dtype=np.uint32))

    def timed(fn, *a):
        g = jax.jit(fn)
        out = g(*a)
        bench_mod.sync_device(out)  # block_until_ready is a no-op on axon
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = g(*a)
        bench_mod.sync_device(out)
        return (time.perf_counter() - t0) / args.iters

    rows: dict[str, float] = {}

    rows["field_mul"] = timed(lambda a, b: f.mul(a, b), qxr, qyr)
    rows["jac_double"] = timed(lambda p: ec.jac_double(cv, p), P)
    rows["jac_add_affine"] = timed(
        lambda p, x, y: ec.jac_add_affine(cv, p, x, y), P, qxr, qyr)
    rows["select_const"] = timed(
        lambda d: ec._take_const(cv.g_table, d), dig)
    tq2 = jax.jit(lambda x, y: ec._q_window_affine(cv, x, y))(qxr, qyr)
    bench_mod.sync_device(tq2)
    rows["select_batch"] = timed(lambda t, d: ec._take_batch(t, d), tq2, dig)
    rows["table_build"] = timed(
        lambda x, y: ec._q_window_affine(cv, x, y), qxr, qyr)
    rows["inv_batch_n"] = timed(
        lambda a: cv.fn.inv_batch(cv.fn.to_rep(a)), exm)
    u1 = cv.fn.reduce_loose(exm)
    rows["glv_ladder"] = timed(
        lambda a, b, x, y: ec.glv_shamir_mult(cv, a, b, x, y),
        u1, u1, qxr, qyr)
    rows["verify_e2e"] = timed(
        lambda *a: ec.ecdsa_verify_batch(cv, *a), e, r, s, qx, qy)

    # fused-kernel units (pallas path; fall back silently if disabled)
    if fp._use_pallas():
        from fisco_bcos_tpu.ops import pallas_fp

        rows["pl_mul"] = timed(lambda a, b: pallas_fp.mul(f, a, b),
                               qxr, qyr)
        rows["pl_pow_sqrt"] = timed(
            lambda a: pallas_fp.pow_const(f, a, (f.n_int + 1) // 4), qxr)
        rows["glv_split"] = timed(
            lambda k: jnp.stack(ec._glv_split_device(cv, k)[::2]), u1)
        from fisco_bcos_tpu.ops import merkle as _mk
        leaves = jnp.asarray(np.random.default_rng(9).integers(
            0, 256, (10000, 32), dtype=np.uint8))
        rows["merkle_10k"] = timed(lambda l: _mk.merkle_root(l), leaves)

    # ladder cost model at WINDOW=4/GLV_DIGITS=34: does measured time
    # match the sum of its parts? (mismatch => fusion/layout overhead)
    model = (ec.GLV_DIGITS * ec.WINDOW * rows["jac_double"]
             + ec.GLV_DIGITS * 4 * rows["jac_add_affine"]
             + ec.GLV_DIGITS * 2 * rows["select_const"]
             + ec.GLV_DIGITS * 2 * rows["select_batch"]
             + rows["table_build"])
    out = {
        "backend": backend,
        "batch": B,
        "ms": {k: round(v * 1e3, 3) for k, v in rows.items()},
        "ladder_model_ms": round(model * 1e3, 3),
        "ladder_measured_ms": round(rows["glv_ladder"] * 1e3, 3),
        "model_ratio": round(rows["glv_ladder"] / model, 3) if model else 0,
        "verify_sigs_per_sec": round(B / rows["verify_e2e"], 1),
    }
    if args.json:
        print(json.dumps(out))
        return
    print(f"backend={backend} batch={B}")
    for k, ms in out["ms"].items():
        print(f"  {k:<16} {ms:>10.3f} ms")
    print(f"  ladder model {out['ladder_model_ms']:.3f} ms vs measured "
          f"{out['ladder_measured_ms']:.3f} ms "
          f"(ratio {out['model_ratio']})")
    print(f"  verify: {out['verify_sigs_per_sec']} sigs/s")


if __name__ == "__main__":
    main()
