#!/usr/bin/env python3
"""Full BASELINE device sweep with incremental persistence.

Runs the complete BASELINE.md config grid — secp256k1 verify+recover at
1k/16k/64k, SM2 verify at 1k/16k/64k, Keccak256 Merkle root at 10k/64k
leaves, plus small-batch points (64/256/1024) for the host/device
crossover (VERDICT r3 weak #2) — and writes results to --out after EVERY
config via atomic rename, so a tunnel wedge mid-sweep keeps everything
measured so far.

Configs are ordered headline-first (64k secp verify/recover, 64k SM2)
so the most valuable numbers land even if the healthy window is short.

Intended caller: tools/tpu_watcher.py, which probes the default backend
(bounded) before launching this in a bounded child. Do NOT run bare on a
host with a wedged tunnel — it will hang at jax import.

Reference counterpart: benchmark/merkleBench.cpp + bcos-crypto/demo/
perf_demo.cpp (the reference's CPU harnesses for the same grid).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(_REPO, "BENCH_LAST_GOOD.json"))
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--skip-done", action="store_true",
                    help="skip configs already recorded for this backend")
    args = ap.parse_args()

    import jax

    import bench as bench_mod
    from fisco_bcos_tpu.crypto import refimpl
    from fisco_bcos_tpu.ops import ec, merkle

    backend = jax.devices()[0].platform
    bench_mod._LAST_GOOD = args.out  # save() routes through the shared lock
    record: dict = {"backend": backend, "updated_at": _now(), "configs": {}}
    if os.path.exists(args.out):
        try:
            prev = json.load(open(args.out))
            if prev.get("backend") == backend:
                record["configs"] = prev.get("configs", {})
        except Exception:
            pass

    print(f"sweep: backend={backend} out={args.out}", flush=True)

    def build_args(params, batch_n, sm=False):
        return bench_mod.build_sig_args(params, batch_n, sm=sm)

    def timed(fn, *fargs):
        return bench_mod.timed_device(fn, *fargs, iters=args.iters)

    def save(name: str, payload: dict) -> None:
        payload["measured_at"] = _now()
        record["configs"][name] = payload

        def _merge(rec):
            if rec.get("backend") != backend:
                rec["configs"] = {}
            rec["backend"] = backend
            rec["updated_at"] = _now()
            rec.setdefault("configs", {})[name] = dict(payload)
            return rec

        bench_mod.update_last_good(_merge)
        print(f"sweep: {name}: {payload}", flush=True)

    # CPU OpenSSL divisor for vs_baseline (same measurement as bench.py)
    if not (args.skip_done and "cpu_baseline" in record["configs"]):
        base, cores, src = bench_mod._measure_cpu_baseline()
        save("cpu_baseline", {"sigs_per_sec": round(base, 1),
                              "cores": cores, "source": src})

    # -- EC configs, headline-first ----------------------------------------
    ec_grid = [
        ("secp_verify_65536", "secp", "verify", 65536),
        ("secp_recover_65536", "secp", "recover", 65536),
        ("sm2_verify_65536", "sm2", "verify", 65536),
        ("secp_verify_16384", "secp", "verify", 16384),
        ("sm2_verify_16384", "sm2", "verify", 16384),
        ("secp_recover_16384", "secp", "recover", 16384),
        ("secp_verify_1024", "secp", "verify", 1024),
        ("sm2_verify_1024", "sm2", "verify", 1024),
        ("secp_recover_1024", "secp", "recover", 1024),
        # small batches: locate the host/device crossover
        ("secp_verify_256", "secp", "verify", 256),
        ("secp_verify_64", "secp", "verify", 64),
    ]
    failures = []
    for name, curve, op, batch in ec_grid:
        if args.skip_done and name in record["configs"]:
            continue
        try:
            sm = curve == "sm2"
            params = refimpl.SM2P256V1 if sm else refimpl.SECP256K1
            cv = ec.SM2P256V1 if sm else ec.SECP256K1
            e, r, s, v, qx, qy = build_args(params, batch, sm=sm)
            if op == "verify":
                fn = ec.sm2_verify_batch if sm else ec.ecdsa_verify_batch
                dt, ok = timed(fn, cv, e, r, s, qx, qy)
                assert bool(np.asarray(ok).all()), \
                    f"{name}: kernel rejected sigs"
                # negative: a tampered digest must be rejected (guards a
                # kernel defect that weakens a check into always-true)
                e_bad = np.asarray(e).copy()
                e_bad[0, 0] ^= 1
                okb = np.asarray(fn(cv, e_bad, r, s, qx, qy))
                assert (not okb[0]) and bool(okb[1:].all()), \
                    f"{name}: tampered sig accepted"
            else:
                dt, rec = timed(ec.ecdsa_recover_batch, cv, e, r, s, v)
                assert bool(np.asarray(rec[2]).all()), \
                    f"{name}: recover failed"
                # value-level: recovered keys must equal the signers'
                assert (np.asarray(rec[0]) == np.asarray(qx)).all() and \
                       (np.asarray(rec[1]) == np.asarray(qy)).all(), \
                    f"{name}: recovered wrong public keys"
            save(name, {"sigs_per_sec": round(batch / dt, 1),
                        "batch": batch, "ms": round(dt * 1e3, 2)})
        except Exception as exc:  # keep sweeping: one bad config (or a
            failures.append(name)  # lowering gap) must not erase the rest
            print(f"sweep: {name} FAILED: {exc!r}", flush=True)

    # -- Merkle configs ----------------------------------------------------
    rng = np.random.default_rng(11)
    for name, nleaves in [("merkle_keccak_10000", 10000),
                          ("merkle_keccak_65536", 65536),
                          ("merkle_sm3_10000", 10000)]:
        if args.skip_done and name in record["configs"]:
            continue
        try:
            alg = "sm3" if "sm3" in name else "keccak256"
            leaves = rng.integers(0, 256, (nleaves, 32), dtype=np.uint8)
            leaves_d = jax.device_put(leaves)
            dt, root = timed(merkle.merkle_root, leaves_d, alg)
            # parity vs host oracle at FULL size (guards the fused tree)
            host_root = merkle.merkle_levels_host(
                [bytes(x) for x in leaves[:64]], alg)[-1][0]
            dev_small = bytes(np.asarray(merkle.merkle_root(leaves[:64],
                                                            alg)))
            assert dev_small == host_root, \
                f"{name}: device/host root mismatch"
            save(name, {"ms_per_root": round(dt * 1e3, 2),
                        "leaves": nleaves,
                        "leaves_per_sec": round(nleaves / dt, 1)})
        except Exception as exc:
            failures.append(name)
            print(f"sweep: {name} FAILED: {exc!r}", flush=True)

    # -- derived: crossover estimate ---------------------------------------
    cfgs = record["configs"]
    floor = 5391.3  # native/ncrypto 1-core measured floor (BENCH_r03)
    crossover = None
    for b in (64, 256, 1024, 16384, 65536):
        c = cfgs.get(f"secp_verify_{b}")
        if c and c["sigs_per_sec"] > floor:
            crossover = b
            break
    save("crossover", {"device_min_batch_suggest": crossover,
                       "native_floor_sigs_per_sec": floor})
    print(f"sweep: DONE (failures: {failures or 'none'})", flush=True)
    if failures:
        sys.exit(3)


if __name__ == "__main__":
    main()
