#!/usr/bin/env python3
"""EVM interpreter throughput — the number VERDICT flagged as unmeasured.

The reference executes contracts on evmone (vm/VMFactory.h:46-64, an
analysis-based C++ interpreter, ~1e9 simple ops/s/core); this framework's
EVM is a Python interpreter, so its budget matters for chain-level TPS
once crypto is batch-accelerated. This harness reports:

  * raw opcode throughput (tight arithmetic loop),
  * storage-touching contract calls/s (counter contract: SLOAD/SSTORE),
  * plain value-transfer receipts/s through the executor dispatch.

Usage: python benchmark/evm_bench.py [-n 200]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=200, help="calls per config")
    args = ap.parse_args()

    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.executor.evm import T_CODE
    from fisco_bcos_tpu.executor.executor import TransactionExecutor
    from fisco_bcos_tpu.protocol import Transaction
    from fisco_bcos_tpu.storage.memory import MemoryStorage
    from fisco_bcos_tpu.storage.state import StateStorage

    suite = make_suite(backend="host")
    ex = TransactionExecutor(suite)
    state = StateStorage(MemoryStorage())
    kp = suite.generate_keypair(b"evm-bench")

    # 1) tight loop: 255 iterations x ~8 ops (PUSH/DUP/SUB/JUMPI...)
    loop_addr = b"\xe1" * 20
    # PUSH1 255; JUMPDEST; PUSH1 1; SWAP1; SUB; DUP1; PUSH1 2; JUMPI; STOP
    loop_code = bytes.fromhex("60ff5b600190038060025700")
    state.set(T_CODE, loop_addr, loop_code)
    # 2) counter: SLOAD slot0, +1, SSTORE
    ctr_addr = b"\xe2" * 20
    ctr_code = bytes.fromhex("5f54600101805f5500")  # slot0 += 1; STOP
    state.set(T_CODE, ctr_addr, ctr_code)

    def bench(addr: bytes, nonce_prefix: str) -> tuple[float, int]:
        txs = [Transaction(to=addr, input=b"", nonce=f"{nonce_prefix}{i}",
                           block_limit=100).sign(suite, kp)
               for i in range(args.n)]
        for tx in txs:
            tx.sender(suite)  # pre-recover: crypto is benched elsewhere
        t0 = time.perf_counter()
        gas = 0
        for tx in txs:
            rc = ex.execute_transaction(tx, state, 1, 0)
            assert rc.status == 0, rc.message
            gas += rc.gas_used
        return time.perf_counter() - t0, gas

    from fisco_bcos_tpu.executor import nevm

    ops_per_call = 255 * 8
    out = {"metric": "evm_interpreter"}
    variants = [("python", False)]
    if nevm.available():
        variants.append(("native", True))
    for label, use_native in variants:
        ex.evm.native = use_native
        dt_loop, gas_loop = bench(loop_addr, f"lp-{label}")
        dt_ctr, _ = bench(ctr_addr, f"ct-{label}")
        out[f"{label}_opcode_throughput_ops_per_sec"] = round(
            args.n * ops_per_call / dt_loop, 1)
        out[f"{label}_loop_calls_per_sec"] = round(args.n / dt_loop, 1)
        out[f"{label}_counter_calls_per_sec"] = round(args.n / dt_ctr, 1)
        out[f"{label}_gas_per_sec"] = round(gas_loop / dt_loop, 1)
    if nevm.available():
        out["native_vs_python_loop"] = round(
            out["native_loop_calls_per_sec"]
            / out["python_loop_calls_per_sec"], 1)
        out["note"] = ("native/nevm frame interpreter (the evmone "
                       "analogue) vs the pure-Python fallback")
    else:
        out["note"] = ("pure-Python interpreter only — build native/ "
                       "(make -C native) for the evmone-class path")
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
