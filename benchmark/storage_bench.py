#!/usr/bin/env python3
"""Storage benchmark — counterpart of the reference's
tests/perf/benchmark.cpp:26-43 (StateStorage vs KeyPageStorage read/write
throughput over a configurable dataset). Adds the native C++ engine.

Usage: python benchmark/storage_bench.py [-n 20000] [--value-size 64]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_backend(name, factory, n, vsize):
    st = factory()
    val = b"v" * vsize
    keys = [b"key%08d" % i for i in range(n)]
    t0 = time.perf_counter()
    for k in keys:
        st.set("t", k, val)
    w = n / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    for k in keys:
        st.get("t", k)
    r = n / (time.perf_counter() - t0)
    if hasattr(st, "close"):
        st.close()
    return {"backend": name, "writes_per_sec": round(w), "reads_per_sec": round(r)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=20_000)
    ap.add_argument("--value-size", type=int, default=64)
    args = ap.parse_args()

    from fisco_bcos_tpu.storage.engine import DiskStorage
    from fisco_bcos_tpu.storage.keypage import KeyPageStorage
    from fisco_bcos_tpu.storage.memory import MemoryStorage
    from fisco_bcos_tpu.storage.state import StateStorage
    from fisco_bcos_tpu.storage.wal import WalStorage
    from fisco_bcos_tpu.storage import native

    tmp = tempfile.mkdtemp(prefix="bcos-bench-")
    results = [
        bench_backend("state_over_memory",
                      lambda: StateStorage(MemoryStorage()),
                      args.n, args.value_size),
        bench_backend("wal", lambda: WalStorage(os.path.join(tmp, "wal")),
                      args.n, args.value_size),
        bench_backend("keypage_over_wal",
                      lambda: KeyPageStorage(
                          WalStorage(os.path.join(tmp, "kp"))),
                      args.n, args.value_size),
        # the log-structured engine, sized so the dataset spills out of
        # the memtable into segments (reads hit bloom+index, not RAM)
        bench_backend("disk_engine",
                      lambda: DiskStorage(os.path.join(tmp, "disk"),
                                          memtable_bytes=1 << 20),
                      args.n, args.value_size),
        bench_backend("keypage_over_disk",
                      lambda: KeyPageStorage(
                          DiskStorage(os.path.join(tmp, "kpd"),
                                      memtable_bytes=1 << 20)),
                      args.n, args.value_size),
    ]
    if native.available():
        results.append(bench_backend(
            "native_bcoskv",
            lambda: native.NativeStorage(os.path.join(tmp, "native")),
            args.n, args.value_size))
    shutil.rmtree(tmp, ignore_errors=True)
    print(json.dumps({"metric": f"storage_rw_{args.n}", "results": results}))


if __name__ == "__main__":
    main()
