#!/usr/bin/env python3
"""Device validation + timing for the FBTPU_FUSED_VERIFY kernels.

Run on a healthy tunnel window. Compares the fused end-to-end verify /
recover / SM2-verify kernels against the default (fused-ladder) path by
VALUE on the same batch, then times both. Exit 0 = fused kernels are
bit-correct; the printed JSON says whether they are also faster (the
signal for flipping the dispatch default).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main() -> None:
    import jax

    import bench as bench_mod
    from fisco_bcos_tpu.crypto import refimpl
    from fisco_bcos_tpu.ops import ec, pallas_verify

    B = int(os.environ.get("FUSED_CHECK_BATCH", "16384"))
    out = {"batch": B, "backend": jax.devices()[0].platform}

    e, r, s, v, qx, qy = bench_mod.build_sig_args(refimpl.SECP256K1, B)
    el, rl, sl = (np.asarray(x).T for x in (e, r, s))
    qxl, qyl = np.asarray(qx).T, np.asarray(qy).T

    # default path (fused-ladder dispatch)
    dt_def, ok_def = bench_mod.timed_device(
        ec.ecdsa_verify_batch, ec.SECP256K1, e, r, s, qx, qy)
    assert bool(np.asarray(ok_def).all()), "default verify rejected sigs"

    # fused end-to-end kernel, same inputs
    t0 = time.perf_counter()
    ok_f = bench_mod.sync_device(pallas_verify.ecdsa_verify_fused(
        ec.SECP256K1, el, rl, sl, qxl, qyl))
    compile_s = time.perf_counter() - t0
    dt_f, ok_f2 = bench_mod.timed_device(
        pallas_verify.ecdsa_verify_fused, ec.SECP256K1, el, rl, sl,
        qxl, qyl)
    assert (np.asarray(ok_f) == np.asarray(ok_def)).all(), \
        "fused verify disagrees with default on valid sigs"
    # negative parity
    e_bad = el.copy()
    e_bad[0, 0] ^= 1
    okb = np.asarray(bench_mod.sync_device(pallas_verify.ecdsa_verify_fused(
        ec.SECP256K1, e_bad, rl, sl, qxl, qyl)))
    assert (not okb[0]) and bool(okb[1:].all()), "fused tamper check failed"
    out["verify"] = {"default_ms": round(dt_def * 1e3, 1),
                     "fused_ms": round(dt_f * 1e3, 1),
                     "fused_compile_s": round(compile_s, 1),
                     "fused_sigs_per_sec": round(B / dt_f, 1),
                     "speedup": round(dt_def / dt_f, 2)}

    # recover
    dt_rd, rec_d = bench_mod.timed_device(
        ec.ecdsa_recover_batch, ec.SECP256K1, e, r, s, v)
    dt_rf, rec_f = bench_mod.timed_device(
        pallas_verify.ecdsa_recover_fused, ec.SECP256K1, el, rl, sl,
        np.asarray(v))
    assert (np.asarray(rec_f[0]).T == np.asarray(rec_d[0])).all(), \
        "fused recover qx mismatch"
    assert (np.asarray(rec_f[1]).T == np.asarray(rec_d[1])).all(), \
        "fused recover qy mismatch"
    out["recover"] = {"default_ms": round(dt_rd * 1e3, 1),
                      "fused_ms": round(dt_rf * 1e3, 1),
                      "fused_sigs_per_sec": round(B / dt_rf, 1),
                      "speedup": round(dt_rd / dt_rf, 2)}

    # sm2
    es, rs, ss, _vs, qxs, qys = bench_mod.build_sig_args(
        refimpl.SM2P256V1, B, sm=True)
    esl, rsl, ssl = (np.asarray(x).T for x in (es, rs, ss))
    qxsl, qysl = np.asarray(qxs).T, np.asarray(qys).T
    dt_sd, ok_sd = bench_mod.timed_device(
        ec.sm2_verify_batch, ec.SM2P256V1, es, rs, ss, qxs, qys)
    dt_sf, ok_sf = bench_mod.timed_device(
        pallas_verify.sm2_verify_fused, ec.SM2P256V1, esl, rsl, ssl,
        qxsl, qysl)
    assert (np.asarray(ok_sf) == np.asarray(ok_sd)).all(), \
        "fused sm2 disagrees"
    out["sm2_verify"] = {"default_ms": round(dt_sd * 1e3, 1),
                         "fused_ms": round(dt_sf * 1e3, 1),
                         "fused_sigs_per_sec": round(B / dt_sf, 1),
                         "speedup": round(dt_sd / dt_sf, 2)}

    out["flip_default"] = all(out[k]["speedup"] > 1.0
                              for k in ("verify", "recover", "sm2_verify"))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
