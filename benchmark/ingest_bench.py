#!/usr/bin/env python3
"""Txpool ingest benchmark — the BASELINE.json "TxValidator ingest: 50k-tx
block" config (reference hot path: TransactionSync.cpp:516-537 tbb batch
verify; txpool.verify_worker_num). Measures end-to-end batch submit:
decode -> batch ecrecover (device) -> pool insert.

Usage: python benchmark/ingest_bench.py [-n 50000] [--backend auto|host]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=50_000)
    ap.add_argument("--backend", default="auto",
                    choices=["auto", "host", "device"])
    ap.add_argument("--sign-workers", type=int, default=os.cpu_count() or 4)
    args = ap.parse_args()

    from concurrent.futures import ProcessPoolExecutor

    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.init.node import Node, NodeConfig

    node = Node(NodeConfig(crypto_backend=args.backend, min_seal_time=3600))
    node.build_genesis()
    suite = node.suite
    kp = suite.generate_keypair(b"ingest")

    # host-side signing is not the benchmark; parallelise it
    from fisco_bcos_tpu.protocol import Transaction
    from fisco_bcos_tpu.executor import precompiled as pc

    def mk(i):
        return Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call("balanceOf",
                                 lambda w: w.blob(b"a%d" % i)),
            nonce="n%d" % i, block_limit=100).sign(suite, kp)

    t0 = time.perf_counter()
    txs = [mk(i) for i in range(args.n)]
    # wire round-trip: drop the signer's cached sender so ingest really
    # performs ecrecover, as it would for txs arriving from the network
    txs = [Transaction.decode(t.encode()) for t in txs]
    sign_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    results = node.txpool.submit_batch(txs)
    dt = time.perf_counter() - t0
    ok = sum(1 for r in results if int(r.status) == 0)
    print(json.dumps({
        "metric": f"txpool_ingest_{args.n}",
        "value": round(args.n / dt, 1),
        "unit": "txs/sec",
        "accepted": ok,
        "sign_prep_s": round(sign_s, 1),
    }))


if __name__ == "__main__":
    main()
