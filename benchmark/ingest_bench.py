#!/usr/bin/env python3
"""Txpool ingest benchmark — the BASELINE.json "TxValidator ingest: 50k-tx
block" config (reference hot path: TransactionSync.cpp:516-537 tbb batch
verify; txpool.verify_worker_num). Measures end-to-end batch submit:
decode -> batch ecrecover/verify (device) -> pool insert.

Modes:
  plain (default): one suite, -n txs, single ingest measurement.
  --mixed:         BASELINE row 4 — n/2 secp256k1 + n/2 SM2 txs (a secp
                   chain node and an SM chain node sharing the host/
                   device), --trials ingest repetitions into FRESH pools,
                   block-verify latency reported as p50/p95.

Usage:
  python benchmark/ingest_bench.py [-n 50000] [--backend auto|host]
  python benchmark/ingest_bench.py --mixed [-n 50000] [--trials 3]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sign_one(args):
    """Worker: build + sign one tx batch slice (spawn-pool friendly)."""
    sm, seed, lo, hi = args
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.executor import precompiled as pc
    from fisco_bcos_tpu.protocol import Transaction

    suite = make_suite(sm, backend="host")
    kp = suite.generate_keypair(seed)
    out = []
    for i in range(lo, hi):
        tx = Transaction(
            to=pc.BALANCE_ADDRESS,
            input=pc.encode_call("balanceOf",
                                 lambda w: w.blob(b"a%d" % i)),
            nonce="%s%d" % ("s" if sm else "e", i),
            block_limit=100).sign(suite, kp)
        out.append(tx.encode())
    return out


def _sign_batch(sm: bool, n: int, workers: int) -> list[bytes]:
    seed = b"ingest-sm" if sm else b"ingest-secp"
    if workers <= 1 or n < 256:
        return _sign_one((sm, seed, 0, n))
    import multiprocessing
    from concurrent.futures import ProcessPoolExecutor

    ctx = multiprocessing.get_context("spawn")
    step = (n + workers - 1) // workers
    chunks = [(sm, seed, lo, min(lo + step, n))
              for lo in range(0, n, step)]
    with ProcessPoolExecutor(workers, mp_context=ctx) as ex:
        parts = list(ex.map(_sign_one, chunks))
    return [raw for part in parts for raw in part]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=50_000)
    ap.add_argument("--backend", default="auto",
                    choices=["auto", "host", "device"])
    ap.add_argument("--mixed", action="store_true")
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--sign-workers", type=int, default=os.cpu_count() or 4)
    args = ap.parse_args()

    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.protocol import Transaction

    if not args.mixed:
        node = Node(NodeConfig(crypto_backend=args.backend,
                               min_seal_time=3600))
        node.build_genesis()
        t0 = time.perf_counter()
        raws = _sign_batch(False, args.n, args.sign_workers)
        # wire round-trip: decode drops the signer's cached sender so
        # ingest really performs ecrecover, as for network arrivals
        txs = [Transaction.decode(r) for r in raws]
        sign_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        results = node.txpool.submit_batch(txs)
        dt = time.perf_counter() - t0
        ok = sum(1 for r in results if int(r.status) == 0)
        print(json.dumps({
            "metric": f"txpool_ingest_{args.n}",
            "value": round(args.n / dt, 1),
            "unit": "txs/sec",
            "accepted": ok,
            "sign_prep_s": round(sign_s, 1),
        }))
        return

    # -- mixed secp+SM2 (BASELINE row 4) ----------------------------------
    half = args.n // 2
    t0 = time.perf_counter()
    secp_raws = _sign_batch(False, half, args.sign_workers)
    sm_raws = _sign_batch(True, half, args.sign_workers)
    sign_s = time.perf_counter() - t0

    latencies = []
    accepted = 0
    for _ in range(args.trials):
        # fresh pools per trial: same txs are virgin again
        secp_node = Node(NodeConfig(crypto_backend=args.backend,
                                    min_seal_time=3600))
        secp_node.build_genesis()
        sm_node = Node(NodeConfig(sm_crypto=True,
                                  crypto_backend=args.backend,
                                  min_seal_time=3600))
        sm_node.build_genesis()
        secp_txs = [Transaction.decode(r) for r in secp_raws]
        sm_txs = [Transaction.decode(r) for r in sm_raws]
        t0 = time.perf_counter()
        r1 = secp_node.txpool.submit_batch(secp_txs)
        r2 = sm_node.txpool.submit_batch(sm_txs)
        latencies.append(time.perf_counter() - t0)
        accepted = sum(1 for r in (*r1, *r2) if int(r.status) == 0)

    latencies.sort()
    p50 = latencies[len(latencies) // 2]
    p95 = latencies[min(len(latencies) - 1,
                        int(len(latencies) * 0.95))]
    print(json.dumps({
        "metric": f"txpool_ingest_mixed_{args.n}",
        "value": round(args.n / p50, 1),
        "unit": "txs/sec",
        "p50_s": round(p50, 3),
        "p95_s": round(p95, 3),
        "trials": args.trials,
        "secp_txs": half,
        "sm2_txs": half,
        "accepted": accepted,
        "sign_prep_s": round(sign_s, 1),
    }))


if __name__ == "__main__":
    main()
