#!/usr/bin/env python3
"""Profile the single-node block pipeline: seal -> execute -> commit.

Isolates the per-stage host cost of one N-tx block on ONE node (no
consensus, no gossip) so the chain-TPS work targets the real hot spots.
Run with --profile to get a cProfile breakdown of the execute+commit path.

NOTE: cProfile instruments every call (10-30% distortion) and needs a
dev checkout. For the question "which functions hold the GIL on a LIVE
chain" use the always-on sampling plane instead: `chain_bench
--profile-attrib` (per-function CPU vs an independent rusage meter) or
`GET /profile?fmt=flame` on any running node (analysis/profiler.py).
This script stays for micro-level call-graph drilling where call counts
matter more than wall fidelity.
"""

from __future__ import annotations

import argparse
import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("-n", type=int, default=1000)
    ap.add_argument("--profile", action="store_true")
    ap.add_argument("--sm", action="store_true")
    args = ap.parse_args()

    from benchmark.chain_bench import _build_workload
    from fisco_bcos_tpu.crypto.suite import make_suite
    from fisco_bcos_tpu.init.node import Node, NodeConfig
    from fisco_bcos_tpu.ledger.ledger import ConsensusNode
    from fisco_bcos_tpu.net.gateway import FakeGateway
    from fisco_bcos_tpu.protocol import Block, BlockHeader, Transaction

    suite = make_suite(args.sm, backend="host")
    kp = suite.generate_keypair(b"\x01" * 16)
    node = Node(NodeConfig(consensus="pbft", sm_crypto=args.sm,
                           crypto_backend="host", min_seal_time=0.0,
                           tx_count_limit=args.n),
                keypair=kp, gateway=FakeGateway())
    node.build_genesis([ConsensusNode(kp.pub_bytes)])

    t0 = time.perf_counter()
    wire = _build_workload(args.sm, args.n, block_limit=100)
    t_sign = time.perf_counter() - t0

    txs = [Transaction.decode(raw) for raw in wire]
    t0 = time.perf_counter()
    node.txpool.submit_batch(txs)
    t_submit = time.perf_counter() - t0

    header = BlockHeader(number=1, timestamp=int(time.time() * 1000))
    block = Block(header=header, transactions=list(txs))

    prof = cProfile.Profile() if args.profile else None
    if prof:
        prof.enable()
    t0 = time.perf_counter()
    result = node.scheduler.execute_block(block)
    t_exec = time.perf_counter() - t0
    assert result is not None
    t0 = time.perf_counter()
    ok = node.scheduler.commit_block(result.header)
    t_commit = time.perf_counter() - t0
    assert ok
    if prof:
        prof.disable()

    n = args.n
    print(f"sign:    {t_sign:8.3f}s  ({1e3*t_sign/n:6.3f} ms/tx)")
    print(f"submit:  {t_submit:8.3f}s  ({1e3*t_submit/n:6.3f} ms/tx)")
    print(f"execute: {t_exec:8.3f}s  ({1e3*t_exec/n:6.3f} ms/tx)")
    print(f"commit:  {t_commit:8.3f}s  ({1e3*t_commit/n:6.3f} ms/tx)")
    print(f"exec+commit rate: {n/(t_exec+t_commit):,.0f} tx/s (1 node)")
    if prof:
        s = io.StringIO()
        pstats.Stats(prof, stream=s).sort_stats("cumulative").print_stats(40)
        print(s.getvalue())


if __name__ == "__main__":
    main()
