#!/usr/bin/env python3
"""Crypto throughput benchmark — counterpart of the reference's
bcos-crypto/demo/perf_demo.cpp (sign/verify/hash ops/sec) extended with the
BASELINE.json batch configs: secp256k1 + SM2 batch verify/recover at
1k/16k/64k signatures on the device kernels.

Usage: python benchmark/crypto_bench.py [--batches 1024,16384,65536]
       [--suite ecdsa|sm|both] [--recover]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mk_batch(params, refimpl, batch, with_pub):
    import numpy as np
    rng = np.random.default_rng(11)
    base = []
    for i in range(8):
        sk, pub = refimpl.keygen(params, bytes([i + 3]) * 32)
        digest = refimpl.keccak256(rng.bytes(64))
        if params.name.startswith("sm2"):
            r, s = refimpl.sm2_sign(sk, digest)
            v = 0
        else:
            r, s, v = refimpl.ecdsa_sign(params, sk, digest)
        base.append((int.from_bytes(digest, "big"), r, s, v, pub))
    cols = list(zip(*(base[i % 8] for i in range(batch))))
    return cols


def bench_kernel(name, fn, args_dev, batch, iters=3):
    import bench as bench_mod
    out = fn(*args_dev)
    bench_mod.sync_device(out)  # block_until_ready is a no-op on axon
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args_dev)
    bench_mod.sync_device(out)
    dt = (time.perf_counter() - t0) / iters
    return {"kernel": name, "batch": batch, "sigs_per_sec": round(batch / dt, 1),
            "ms": round(dt * 1000, 2)}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="1024,16384,65536")
    ap.add_argument("--suite", default="both",
                    choices=["ecdsa", "sm", "both"])
    ap.add_argument("--recover", action="store_true")
    ap.add_argument("--host-ops", action="store_true",
                    help="also time host-side single sign/verify/hash")
    args = ap.parse_args()

    import jax
    import numpy as np

    from fisco_bcos_tpu.crypto import refimpl
    from fisco_bcos_tpu.ops import bigint, ec

    batches = [int(b) for b in args.batches.split(",")]
    results = []

    for batch in batches:
        if args.suite in ("ecdsa", "both"):
            e, r, s, v, pubs = _mk_batch(refimpl.SECP256K1, refimpl, batch,
                                         True)
            el = jax.device_put(bigint.batch_to_limbs(e))
            rl = jax.device_put(bigint.batch_to_limbs(r))
            sl = jax.device_put(bigint.batch_to_limbs(s))
            qx = jax.device_put(bigint.batch_to_limbs([p[0] for p in pubs]))
            qy = jax.device_put(bigint.batch_to_limbs([p[1] for p in pubs]))
            results.append(bench_kernel(
                "secp256k1_verify",
                lambda *a: ec.ecdsa_verify_batch(ec.SECP256K1, *a),
                (el, rl, sl, qx, qy), batch))
            if args.recover:
                vl = jax.device_put(np.asarray(v, np.uint32))
                results.append(bench_kernel(
                    "secp256k1_recover",
                    lambda *a: ec.ecdsa_recover_batch(ec.SECP256K1, *a),
                    (el, rl, sl, vl), batch))
        if args.suite in ("sm", "both"):
            e, r, s, v, pubs = _mk_batch(refimpl.SM2P256V1, refimpl, batch,
                                         True)
            el = jax.device_put(bigint.batch_to_limbs(e))
            rl = jax.device_put(bigint.batch_to_limbs(r))
            sl = jax.device_put(bigint.batch_to_limbs(s))
            qx = jax.device_put(bigint.batch_to_limbs([p[0] for p in pubs]))
            qy = jax.device_put(bigint.batch_to_limbs([p[1] for p in pubs]))
            results.append(bench_kernel(
                "sm2_verify",
                lambda *a: ec.sm2_verify_batch(ec.SM2P256V1, *a),
                (el, rl, sl, qx, qy), batch))

    if args.host_ops:
        params = refimpl.SECP256K1
        sk, pub = refimpl.keygen(params, b"x" * 32)
        digest = refimpl.keccak256(b"bench")
        t0 = time.perf_counter()
        n = 20
        for _ in range(n):
            refimpl.ecdsa_sign(params, sk, digest)
        results.append({"kernel": "host_sign",
                        "ops_per_sec": round(n / (time.perf_counter() - t0), 1)})
        t0 = time.perf_counter()
        n = 2000
        for _ in range(n):
            refimpl.keccak256(b"x" * 256)
        results.append({"kernel": "host_keccak256_256B",
                        "ops_per_sec": round(n / (time.perf_counter() - t0), 1)})

    print(json.dumps({"metric": "crypto_throughput", "results": results}))


if __name__ == "__main__":
    main()
